"""Worker-side versioned pull cache: the delta-pull plane's shadow.

ISSUE 20: the rows workers re-pull step after step are exactly the
rows that rarely change (Parallax's sparsity observation, PAPERS.md) —
bytes we can elide entirely with version tracking, the same insight
the PR-17 delta shipper exploits for serving replicas.  The table
stamps every tail row with a per-shard-monotonic version at apply
(parameter/sparse_table.py ``@rowver``); the worker keeps this bounded
direct-mapped cache of ``(slot, version)`` tags and sends its per-row
watermark with each pull; the server ships value bytes only for rows
newer than the watermark plus a hit bitmap, and the worker splices
cached rows for the rest.

What makes the "splice" free of device work: a version-exact hit's
cached row is BIT-IDENTICAL to the server row — the version changed
iff the row did — so the spliced result equals the fresh gather and
only the LEDGER changes (miss rows book value bytes, hits book
``pull_cache_hits`` / ``pull_bytes_saved``).  The pull interpreter in
transfer/api.py therefore runs this cache as a host-side *shadow* fed
per compiled execution (``jax.debug.callback``, the ledger's
established tracer discipline) while the device keeps the plain
gather; byte counts are modeled exactly the way the push ledger
already models its wire.  ``store_rows=True`` drops the modeling
shortcut and stores actual row values, asserting cached == fresh on
every hit — the oracle the version-invalidation tests run to prove
every apply path bumps its rows.

Invalidation contract:

* a hit requires BOTH the slot tag and the version stamp to match the
  line — any apply bumps the row's version, so stale lines miss and
  refill;
* the cache keys on table capacity: a ``grow`` re-strides tail row
  ids, so a capacity change flushes everything (versions are per-shard
  monotonic, not globally unique — a moved row could otherwise alias a
  stale line);
* repartition keeps tail ids stable and bumps demoted rows, so no
  flush is needed;
* restart/resume flushes (``Transfer.pull_shadow_flush``): a restore
  can rewind versions, after which a warm cache could false-hit on a
  re-used stamp.  A resumed worker always starts cold.
"""

from __future__ import annotations

from typing import Dict, Optional

import numpy as np


class PullCache:
    """Bounded direct-mapped ``slot -> version`` cache.

    ``lines`` bounds the footprint (one int64 tag + int64 version per
    line; ~16B/line).  Direct-mapped: slot ``s`` lives only at line
    ``s % lines``, so lookup and fill are one vectorized gather/scatter
    each — no LRU bookkeeping on the hot pull path, and conflict
    evictions are deterministic (last writer in batch order wins).
    """

    def __init__(self, lines: int, store_rows: bool = False):
        if lines <= 0:
            raise ValueError(f"PullCache: lines must be > 0, got {lines}")
        self.lines = int(lines)
        self.store_rows = bool(store_rows)
        self.capacity: Optional[int] = None
        self.tags = np.full(self.lines, -1, np.int64)
        self.vers = np.zeros(self.lines, np.int64)
        self._rows: Dict[int, dict] = {}
        # counters are cumulative over the cache's lifetime; the
        # transfer ledger books the per-interval view
        self.hits = 0
        self.misses = 0
        self.flushes = 0
        self.mismatches = 0

    def flush(self) -> None:
        self.tags.fill(-1)
        self.vers.fill(0)
        self._rows.clear()
        self.flushes += 1

    def lookup(self, slots, versions, capacity: int,
               rows: Optional[dict] = None) -> np.ndarray:
        """One pull's worth of watermark traffic: returns the boolean
        hit mask over ``slots`` (True = cached row is current, no value
        bytes needed), then fills every valid miss line with the fresh
        ``(slot, version)`` tag.

        Hits are decided against the PRE-request cache state, so
        duplicate slots in one batch hit or miss together — matching
        the ledger's existing per-occurrence booking.  ``rows`` (field
        -> (B, d) host array) is required in ``store_rows`` mode: hit
        lines are value-compared against the fresh rows and any
        mismatch (an apply path that forgot to bump) raises.
        """
        slots = np.asarray(slots, np.int64).ravel()
        versions = np.asarray(versions, np.int64).ravel()
        if int(capacity) != self.capacity:
            # grow re-strided the slot space (or first use): start cold
            if self.capacity is not None:
                self.flush()
            self.capacity = int(capacity)
        valid = slots >= 0
        line = np.where(valid, slots % self.lines, 0)
        hit = valid & (self.tags[line] == slots) \
            & (self.vers[line] == versions)
        if self.store_rows:
            if rows is None:
                raise ValueError("PullCache(store_rows=True) needs the "
                                 "fresh rows to verify hits against")
            self._verify_and_store(slots, line, hit, valid, rows)
        miss = valid & ~hit
        self.tags[line[miss]] = slots[miss]
        self.vers[line[miss]] = versions[miss]
        self.hits += int(hit.sum())
        self.misses += int(miss.sum())
        return hit

    def _verify_and_store(self, slots, line, hit, valid, rows) -> None:
        host = {f: np.asarray(v) for f, v in rows.items()}
        for i in np.flatnonzero(hit):
            cached = self._rows.get(int(line[i]))
            if cached is None or cached["slot"] != int(slots[i]):
                continue  # line stored before store_rows toggled on
            for f, v in cached.items():
                if f == "slot":
                    continue
                # equal_nan: an injected-NaN row (testing/faults.py
                # _poison_row) re-pulled at an unchanged version is a
                # legitimate hit, not a missed bump
                eq_nan = np.issubdtype(np.asarray(v).dtype, np.inexact)
                if not np.array_equal(host[f][i], v, equal_nan=eq_nan):
                    self.mismatches += 1
                    raise AssertionError(
                        f"PullCache oracle: slot {int(slots[i])} hit at "
                        f"an unchanged version but field {f!r} differs "
                        "from the server row — some apply path did not "
                        "bump the row version")
        for i in np.flatnonzero(valid & ~hit):
            entry = {"slot": int(slots[i])}
            for f in host:
                entry[f] = host[f][i].copy()
            self._rows[int(line[i])] = entry
