"""Ok-Topk-style sparse allreduce collective for the dense/hot planes.

ROADMAP item 4 deferred "near-optimal sparse allreduce (Ok-Topk,
PAPERS.md) as an alternative collective for the dense/hot planes" —
this module is that collective.  The hybrid backend's hot-plane
reconcile and the tpu window path's ``dense`` rung both reconcile a
replicated/capacity-shaped buffer with ONE dense reduction per push
(``psum`` / ``psum_scatter``), paying O(capacity·d) wire bytes even
when only a fraction of the rows were touched in the window.  Ok-Topk's
split-and-exchange shape fixes the wire model: each shard contributes
its **touched-row (index, value) set**, a balanced reduce-scatter over
row-hash buckets merges duplicate indices with scatter-add, and a
sparse allgather rebroadcasts the reduced rows.

The pieces here are deliberately small and backend-free:

* :func:`merge_rows` — the scatter-add merge kernel (duplicate indices
  summed into their row), the reduce half every backend primitive
  shares and the thing the numpy merge oracle in
  tests/test_sparse_allreduce.py pins.
* :func:`bucket_layout` / :func:`bucket_permute` /
  :func:`bucket_unpermute` — the balanced row-hash bucketing.  Row
  ``r``'s bucket owner is ``r % n_shards`` (round-robin): hot slots are
  frequency-RANKED, so contiguous blocks would pile the whole Zipf head
  onto shard 0 — the modular hash spreads ranks evenly, which is what
  makes the reduce-scatter balanced.
* :func:`sparse_ar_bytes` / :func:`dense_psum_bytes` — the shared wire
  byte models.  The pricer (``parameter.key_index.
  price_hot_collectives``), the ledger booking (api.py's interpreter)
  and the budget gate all read these two functions, so the crossover
  evidence and the booked bytes can never drift apart.

Shapes stay static (XLA): the exchanged buffers are capacity-shaped
like the tpu backend's ``(n, C)`` request buckets, and — exactly like
that backend's routed ledger — the wire ledger books the SEMANTIC
sparse payload (touched rows × (index + value bytes)), not the padded
buffer, because that is what a variable-length wire implementation
ships.  SparCML (arXiv:1802.08021) supplies the density threshold the
crossover prices by; the plan compiler (transfer/plan.py) turns the
decision into a ``TrafficPlan.collective`` row the api.py interpreter
executes via ``_prim_sparse_allreduce`` / the hybrid hot-plane
primitive — backends never compare collective names (the PLAN-DISPATCH
lint rule covers the collective strings too as of this PR).
"""

from __future__ import annotations

from typing import Tuple

import jax.numpy as jnp

#: one int32 row id per touched row on the sparse wire
ROW_ID_BYTES = 4

#: collective decisions the hot-plane pricer can return; mirrored by
#: ``transfer.plan.COLLECTIVES`` (which adds the window dense rung's
#: ``psum_scatter``).
HOT_COLLECTIVES = ("psum", "sparse_allreduce")


def sparse_ar_bytes(touched_rows: float, width_bytes: int) -> float:
    """Modeled wire volume of one sparse allreduce reconcile:
    ``touched`` (index, value) rows through the split-and-exchange.
    Booked per exchange like the dense psum's single
    ``capacity * width`` booking — the ring/bidirectional factor is
    identical for both collectives, so it cancels out of the crossover
    and is left out of both models."""
    return float(touched_rows) * (ROW_ID_BYTES + float(width_bytes))


def dense_psum_bytes(capacity: int, width_bytes: int) -> float:
    """Modeled wire volume of the dense reconcile it replaces: the full
    replicated/capacity-shaped buffer, no index stream."""
    return float(capacity) * float(width_bytes)


def bucket_layout(n_rows: int, n_shards: int) -> Tuple[int, int]:
    """``(cap_bucket, n_padded)`` for the balanced row-hash bucketing of
    ``n_rows`` rows over ``n_shards`` reduce-scatter buckets: each shard
    owns ``cap_bucket = ceil(n_rows / n_shards)`` rows and the padded
    row space is ``n_shards * cap_bucket`` (pad rows are never touched,
    contribute exact zeros, and are dropped by the unpermute)."""
    n_shards = max(int(n_shards), 1)
    cap_bucket = -(-int(n_rows) // n_shards) if n_rows else 0
    return cap_bucket, n_shards * cap_bucket


def bucket_permute(dense, n_shards: int):
    """Reorder a ``(n_padded, ...)`` row-major buffer into bucket-major
    order ``[shard0's rows | shard1's rows | ...]`` where row ``r``
    belongs to shard ``r % n_shards`` at bucket-local index
    ``r // n_shards``.  A pure reshape/transpose — after it, a tiled
    ``psum_scatter`` over the leading axis IS the balanced reduce-
    scatter over row-hash buckets."""
    n_pad = dense.shape[0]
    cap_bucket = n_pad // int(n_shards)
    rest = dense.shape[1:]
    return jnp.transpose(
        dense.reshape((cap_bucket, int(n_shards)) + rest),
        (1, 0) + tuple(range(2, dense.ndim + 1))
    ).reshape((n_pad,) + rest)


def bucket_unpermute(bucketed, n_shards: int):
    """Inverse of :func:`bucket_permute`: bucket-major (the allgather's
    concatenation of per-shard reduced buckets) back to row-major."""
    n_pad = bucketed.shape[0]
    cap_bucket = n_pad // int(n_shards)
    rest = bucketed.shape[1:]
    return jnp.transpose(
        bucketed.reshape((int(n_shards), cap_bucket) + rest),
        (1, 0) + tuple(range(2, bucketed.ndim + 1))
    ).reshape((n_pad,) + rest)


def merge_rows(slots, values, capacity: int):
    """Scatter-add merge of duplicate row indices — the reduce half of
    the sparse allreduce: every contribution ``values[i]`` lands in row
    ``slots[i]`` of a ``(capacity, width)`` accumulator, duplicates
    summed, ``slot < 0`` (padding / non-representative dedup rows) and
    ``slot >= capacity`` contributions dropped.  The numpy oracle in
    tests/test_sparse_allreduce.py pins this against ``np.add.at``."""
    slots = jnp.asarray(slots, jnp.int32)
    values = jnp.asarray(values)
    valid = (slots >= 0) & (slots < capacity)
    safe = jnp.where(valid, slots, capacity)
    acc = jnp.zeros((capacity,) + values.shape[1:], values.dtype)
    mask = valid.reshape((-1,) + (1,) * (values.ndim - 1))
    return acc.at[safe].add(values * mask.astype(values.dtype),
                            mode="drop")


def merge_counts(slots, counts, capacity: int):
    """Width-0 twin of :func:`merge_rows` for the contribution-count
    plane (``mean`` normalization divides by these post-merge)."""
    slots = jnp.asarray(slots, jnp.int32)
    counts = jnp.asarray(counts, jnp.float32)
    valid = (slots >= 0) & (slots < capacity)
    safe = jnp.where(valid, slots, capacity)
    return jnp.zeros((capacity,), jnp.float32).at[safe].add(
        counts * valid, mode="drop")
