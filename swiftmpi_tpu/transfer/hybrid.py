"""``hybrid`` transfer backend: replicated hot head + sharded cold tail.

Zipf-aware placement (Parallax, arXiv:1808.02621): real vocabularies put
most of the per-step traffic on a tiny frequency head, which under the pure
``tpu`` backend inflates the routed-row count and skews bucket occupancy
(the overflow counter measures exactly this).  The hybrid backend splits
the unified slot space the ``HotColdPartition`` defines:

* **hot** (``slot < n_hot``): rows live REPLICATED on every device as the
  ``field + "@hot"`` state arrays.  Pull is a local ``take`` — zero
  cross-chip bytes.  Push scatter-adds the local batch slice into an
  ``(n_hot, width)`` dense buffer and reconciles with a SINGLE dense
  ``psum`` over the whole mesh — no routing, no dedup sort (SparCML's
  "densify once occupancy crosses the threshold", arXiv:1802.08021,
  applied per-partition via ``calibrate_hot_k``).
* **tail** (``slot >= n_hot``): rows stay in the hash-sharded table and
  route through the unmodified :class:`TpuTransfer` all_to_all path,
  re-based by ``-n_hot``.

The composition sits behind the same ``pull``/``push``/``push_span`` API
(including the PR-2 stencil span wire format), so models consume the split
transparently.  Per-step traffic (routed tail rows, hot rows, psum bytes,
bucket overflow) is accounted with the same tracer/eager discipline as the
tpu backend's overflow counter and read via :meth:`traffic`.

A state dict with no ``@hot`` fields (n_hot == 0, e.g. the LR loop, which
has no upfront frequency histogram) degenerates to the pure tail path —
``hybrid`` is then bit-identical to ``tpu``.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, PartitionSpec as P

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu.cluster.mesh import SHARD_AXIS
from swiftmpi_tpu.parameter.sparse_table import (base_field, hot_name,
                                                 is_hot_field)
from swiftmpi_tpu.transfer.api import Transfer
from swiftmpi_tpu.transfer.tpu import TpuTransfer


class HybridTransfer(Transfer):
    name = "hybrid"

    def __init__(self, mesh: Mesh, axis: str = SHARD_AXIS,
                 bucket_capacity: Optional[int] = None,
                 debug_overflow: bool = False,
                 data_plane: str = "auto"):
        self.mesh = mesh
        self.axis = axis
        self.tail = TpuTransfer(mesh, axis, bucket_capacity, debug_overflow,
                                data_plane=data_plane)
        self._hot_push_cache: Dict = {}
        self._hot_total = 0
        self._psum_bytes_total = 0
        self._hot_pending: list = []

    def on_membership(self, epoch: int, live_ranks) -> None:
        """Elastic membership (api.py): the tail backend owns most of
        the world-shaped compiled state, so it is told FIRST (its own
        epoch guard runs there), then the hybrid books the epoch and
        drops its hot-psum cache."""
        self.tail.on_membership(epoch, live_ranks)
        super().on_membership(epoch, live_ranks)

    def _membership_changed(self) -> None:
        self._hot_push_cache.clear()

    # -- attribute forwarding to the tail backend --------------------------
    @property
    def metrics(self):
        return self.tail.metrics

    @metrics.setter
    def metrics(self, m):
        self.tail.metrics = m

    @property
    def count_traffic(self) -> bool:
        return self.tail.count_traffic

    @count_traffic.setter
    def count_traffic(self, flag: bool):
        self.tail.count_traffic = bool(flag)

    @property
    def bucket_capacity(self):
        return self.tail.bucket_capacity

    @property
    def window_expected_unique(self):
        """Expected-unique-rows hint for the window wire-format crossover
        (see TpuTransfer); lives on the tail, which makes the decision."""
        return self.tail.window_expected_unique

    @window_expected_unique.setter
    def window_expected_unique(self, v):
        self.tail.window_expected_unique = v

    @property
    def wire_quant(self) -> str:
        """Window value-quantization mode (``off|int8|bf16``); lives on
        the tail, which makes the wire-format decision and owns the EF
        drain.  Hot rows are untouched — their dense psum never
        quantizes."""
        return self.tail.wire_quant

    @wire_quant.setter
    def wire_quant(self, v: str):
        self.tail.wire_quant = v

    @property
    def wire_quant_guard(self) -> float:
        return self.tail.wire_quant_guard

    @wire_quant_guard.setter
    def wire_quant_guard(self, v: float):
        self.tail.wire_quant_guard = float(v)

    @property
    def wire_sketch(self) -> bool:
        """Counting-sketch wire rung arm (``sparse_sketch``); lives on
        the tail, whose window plan prices the ladder.  Hot rows are
        untouched — their dense psum ships no index stream at all."""
        return self.tail.wire_sketch

    @wire_sketch.setter
    def wire_sketch(self, v: bool):
        self.tail.wire_sketch = bool(v)

    @property
    def pull_quant(self) -> str:
        """Pull value-quantization mode (``off|int8|bf16``); lives on
        the tail, whose pull plan prices the format.  Hot rows are
        untouched — replica reads ship nothing and are never
        quantized."""
        return self.tail.pull_quant

    @pull_quant.setter
    def pull_quant(self, v: str):
        self.tail.pull_quant = v

    @property
    def pull_quant_guard(self) -> float:
        return self.tail.pull_quant_guard

    @pull_quant_guard.setter
    def pull_quant_guard(self, v: float):
        self.tail.pull_quant_guard = float(v)

    @property
    def pull_cache(self) -> int:
        """Versioned pull-cache line count (0 = off); lives on the
        tail, which runs the cache shadow — hot-replica hits are
        already 0 bytes and never enter the cache."""
        return self.tail.pull_cache

    @pull_cache.setter
    def pull_cache(self, v: int):
        self.tail.pull_cache = int(v)

    @property
    def pull_cache_oracle(self) -> bool:
        return self.tail.pull_cache_oracle

    @pull_cache_oracle.setter
    def pull_cache_oracle(self, v: bool):
        self.tail.pull_cache_oracle = bool(v)

    def pull_shadow_flush(self) -> None:
        # the tail owns the live shadow (tail pulls book the cache);
        # flush both for symmetry with the knob forwarding above
        self.tail.pull_shadow_flush()
        super().pull_shadow_flush()

    @property
    def collective_mode(self) -> str:
        """Hot/dense collective selection mode (``psum | auto |
        sparse_allreduce``); storage lives on the tail so the tail's
        window plan (dense rung) and the hybrid's hot plan — both
        compiled via transfer/plan.py — read the same knob."""
        return self.tail.collective_mode

    @collective_mode.setter
    def collective_mode(self, v: str):
        self.tail.collective_mode = v

    @property
    def hot_touched_fraction(self):
        return self.tail.hot_touched_fraction

    @hot_touched_fraction.setter
    def hot_touched_fraction(self, v):
        self.tail.hot_touched_fraction = v

    @property
    def sparse_ar_ratio(self) -> float:
        return self.tail.sparse_ar_ratio

    @sparse_ar_ratio.setter
    def sparse_ar_ratio(self, v: float):
        self.tail.sparse_ar_ratio = float(v)

    def wire_dense_ratio(self, family=None):
        return self.tail.wire_dense_ratio(family)

    def set_wire_dense_ratio(self, ratio, family=None):
        # the tail backend asks the wire-format question (its
        # _push_window_flat), so the tunable ratio state lives there
        self.tail.set_wire_dense_ratio(ratio, family)

    def overflow_count(self) -> int:
        return self.tail.overflow_count()

    # -- hot/tail split helpers --------------------------------------------
    @staticmethod
    def _n_hot(state) -> int:
        for f, v in state.items():
            if is_hot_field(f):
                return int(v.shape[0])
        return 0

    @staticmethod
    def _split_state(state):
        tail = {f: v for f, v in state.items() if not is_hot_field(f)}
        hot = {base_field(f): v for f, v in state.items()
               if is_hot_field(f)}
        return tail, hot

    # -- traffic accounting ------------------------------------------------
    def _accum_hot(self, psum_bytes: int, hot) -> None:
        self._hot_total += int(hot)
        self._psum_bytes_total += int(psum_bytes)
        self._obs_inc("hot_rows", int(hot))
        self._obs_inc("psum_bytes", int(psum_bytes))

    def _record_hot(self, hot, psum_bytes: int) -> None:
        cb = partial(self._accum_hot, int(psum_bytes))
        if isinstance(hot, jax.core.Tracer):
            jax.debug.callback(cb, hot)
        else:
            self._hot_pending.append((int(psum_bytes), hot))
            if len(self._hot_pending) >= 1024:
                pending, self._hot_pending = self._hot_pending, []
                for b, h in pending:
                    self._accum_hot(b, h)

    def _accum_hot_sparse(self, row_bytes: int, hot) -> None:
        # sparse-allreduce twin of _accum_hot: the byte volume depends
        # on the TRACED touched-row count (touched * per-row bytes),
        # not the static head size, so it is computed in the callback
        self._accum_hot(int(hot) * int(row_bytes), hot)

    def _record_hot_sparse(self, hot, row_bytes: int) -> None:
        cb = partial(self._accum_hot_sparse, int(row_bytes))
        if isinstance(hot, jax.core.Tracer):
            jax.debug.callback(cb, hot)
        else:
            self._accum_hot_sparse(int(row_bytes), hot)

    def traffic(self) -> Dict[str, int]:
        """Cumulative per-step traffic counters (counted while
        ``count_traffic`` is set): ``routed_rows`` (tail rows through
        all_to_all), ``hot_rows`` (head hits served dense), ``psum_bytes``
        (dense reconciliation volume), ``overflow_dropped``."""
        jax.effects_barrier()
        pending, self._hot_pending = self._hot_pending, []
        for b, h in pending:
            self._accum_hot(b, h)
        t = self.tail.traffic()
        w = self.wire_traffic()       # own ledger: hot-psum exchanges
        out = {"routed_rows": t["routed_rows"],
               "hot_rows": self._hot_total,
               "psum_bytes": self._psum_bytes_total,
               "overflow_dropped": t["overflow_dropped"]}
        for k in ("wire_bytes", "dispatches", "window_sparse",
                  "window_dense", "window_fmt_dense", "window_fmt_sparse",
                  "window_fmt_q", "window_fmt_bitmap", "window_fmt_sketch",
                  "collective_psum", "collective_sparse_ar",
                  "hot_psum_bytes_saved",
                  "plan_compiles", "plan_cache_hits",
                  "coalesced_rows_in", "coalesced_rows_out",
                  "pull_bytes", "pull_rows", "pull_hot_rows",
                  "pull_cache_hits", "pull_delta_rows",
                  "pull_bytes_saved",
                  "pull_fmt_full", "pull_fmt_bf16", "pull_fmt_q"):
            out[k] = t.get(k, 0) + w.get(k, 0)
        if self.metrics is not None:
            self.metrics.set("transfer_hot_rows", out["hot_rows"])
            self.metrics.set("transfer_psum_bytes", out["psum_bytes"])
        return out

    def _batch_divisor(self) -> int:
        """The tail path shard_maps the batch dim over the mesh's data and
        shard axes; request lengths must divide their product."""
        div = int(self.mesh.shape[self.axis])
        if self.tail.dp_axis:
            div *= int(self.mesh.shape[self.tail.dp_axis])
        return div

    def _pad_batch(self, slots, grads=None, counts=None):
        """Pad the batch dim to the next mesh multiple with -1 slots
        (dropped by both the routed and dense paths) and zero grad rows.
        Stencil spans are B + 2W rows — almost never mesh-aligned — so
        the backend absorbs the alignment instead of every caller.
        Returns ``(slots, grads, counts, orig_len)``."""
        B = slots.shape[0]
        pad = (-B) % self._batch_divisor()
        if pad == 0:
            return slots, grads, counts, B
        slots = jnp.concatenate(
            [slots, jnp.full((pad,) + slots.shape[1:], -1, slots.dtype)])
        if grads is not None:
            grads = {f: jnp.concatenate(
                [g, jnp.zeros((pad,) + g.shape[1:], g.dtype)])
                for f, g in ((f, jnp.asarray(g)) for f, g in grads.items())}
        if counts is not None:
            counts = jnp.concatenate(
                [jnp.asarray(counts, jnp.float32),
                 jnp.zeros((pad,), jnp.float32)])
        return slots, grads, counts, B

    # -- pull --------------------------------------------------------------
    # No override: the base-class pull interpreter (api.Transfer.pull)
    # drives this backend through its ``hot_split`` placement stage
    # (``_interpret_pull_hot_split``), composing `_pad_batch`,
    # `_split_state` and the tail backend's own pull — replica hits
    # resolve locally at 0 bytes, tail rows book (and cache/quantize)
    # on the tail's ledger and merge in traffic().

    # -- push --------------------------------------------------------------
    def push(self, state, slots, grads, access, mean=False, counts=None):
        slots = jnp.asarray(slots, jnp.int32)
        slots, grads, counts, _ = self._pad_batch(slots, grads, counts)
        tail_state, hot_state = self._split_state(state)
        n_hot = self._n_hot(state)
        if n_hot == 0:
            return self.tail.push(tail_state, slots, grads, access,
                                  mean=mean, counts=counts)
        is_hot = (slots >= 0) & (slots < n_hot)
        tail_slots = jnp.where(slots >= n_hot, slots - n_hot, -1)
        new_tail = self.tail.push(tail_state, tail_slots, grads, access,
                                  mean=mean, counts=counts)
        if self.count_traffic:
            width_bytes = sum(
                np.dtype(jnp.asarray(g).dtype).itemsize * g.shape[1]
                for g in grads.values()) + 4        # + f32 counts column
            self._record_hot(jnp.sum(is_hot), n_hot * width_bytes)
            # wire ledger: the hot psum is one dispatch shipping the full
            # replicated head (dense; token keeps the rows value traced)
            self._record_exchange(jnp.sum(is_hot) * 0 + n_hot, width_bytes)
        new_hot = self._hot_push(hot_state, slots, grads, access,
                                 mean, counts)
        out = dict(new_tail)
        out.update({hot_name(f): v for f, v in new_hot.items()})
        return out

    def push_span(self, state, slots, grads, counts, access, mean=False):
        """Span push (stencil wire format): rows carry window-overlap
        gradient SUMS with per-row data counts; both paths normalize by
        the summed data counts, matching ``XlaTransfer.push_span``."""
        return self.push(state, slots, grads, access, mean=mean,
                         counts=counts)

    # -- window-coalesced push ---------------------------------------------
    # No override: the base-class TrafficPlan interpreter
    # (api.Transfer.push_window) drives the window path through its
    # ``hot_split`` placement stage, which composes this backend's
    # structural primitives — `_pad_batch`, `_split_state`, the tail's
    # dedup/exchange primitives, and `_hot_push` below.

    def _hot_push(self, hot_state, slots, grads, access, mean, counts):
        with_counts = counts is not None
        sig = (self.tail._signature(hot_state, slots, grads),
               mean, with_counts)
        fn = self._hot_push_cache.get(sig)
        if fn is None:
            from swiftmpi_tpu.obs import costs as obs_costs
            fn = self._hot_push_cache.setdefault(
                sig, obs_costs.track("hybrid_hot_push", jax.jit(
                    self._build_hot_push(
                        hot_state, access, tuple(sorted(grads)), mean,
                        with_counts))))
        if with_counts:
            return fn(hot_state, slots, grads,
                      jnp.asarray(counts, jnp.float32))
        return fn(hot_state, slots, grads)

    def _build_hot_push(self, hot_state, access, grad_fields, mean,
                        with_counts):
        n_hot = next(iter(hot_state.values())).shape[0]
        bspec = self.tail._batch_spec()
        axes = (self.tail.dp_axis, self.axis) if self.tail.dp_axis \
            else (self.axis,)
        state_specs = {f: P() for f in hot_state}
        grad_specs = {f: bspec for f in grad_fields}
        in_specs = (state_specs, bspec, grad_specs)
        if with_counts:
            in_specs += (bspec,)

        @partial(jax.shard_map, mesh=self.mesh, in_specs=in_specs,
                 out_specs=state_specs, check_vma=False)
        def _hot(hot_l, slots_l, grads_l, *maybe_counts):
            valid = (slots_l >= 0) & (slots_l < n_hot)
            # tail and padding slots scatter out-of-bounds and drop
            safe = jnp.where(valid, slots_l, n_hot)
            if with_counts:
                c = maybe_counts[0] * valid
            else:
                c = valid.astype(jnp.float32)
            acc = {}
            for f in grad_fields:
                g = jnp.asarray(grads_l[f])
                acc[f] = jnp.zeros((n_hot, g.shape[1]), g.dtype).at[
                    safe].add(g, mode="drop")
            csum = jnp.zeros((n_hot,), jnp.float32).at[safe].add(
                c, mode="drop")
            # the whole reconciliation is this one dense psum: no
            # routing, no dedup sort — duplicate hot slots summed by the
            # scatter, cross-device duplicates summed by the reduction
            acc, csum = jax.lax.psum((acc, csum), axes)
            if mean:
                inv = (1.0 / jnp.maximum(csum, 1.0))[:, None]
                acc = {f: a * inv for f, a in acc.items()}
            new_fields = access.apply_push(hot_l, acc)
            out = dict(hot_l)
            out.update(new_fields)
            return out

        return _hot

    def _hot_push_sparse(self, hot_state, slots, grads, access, mean,
                         counts):
        """Sparse-allreduce hot-plane reconcile (the plan interpreter
        dispatches here when the hot TrafficPlan's collective says so —
        this backend never reads the collective name itself)."""
        with_counts = counts is not None
        sig = (self.tail._signature(hot_state, slots, grads),
               mean, with_counts, "sparse_ar")
        fn = self._hot_push_cache.get(sig)
        if fn is None:
            from swiftmpi_tpu.obs import costs as obs_costs
            fn = self._hot_push_cache.setdefault(
                sig, obs_costs.track("hybrid_hot_push_sparse", jax.jit(
                    self._build_hot_push_sparse(
                        hot_state, access, tuple(sorted(grads)), mean,
                        with_counts))))
        if with_counts:
            return fn(hot_state, slots, grads,
                      jnp.asarray(counts, jnp.float32))
        return fn(hot_state, slots, grads)

    def _build_hot_push_sparse(self, hot_state, access, grad_fields,
                               mean, with_counts):
        """Ok-Topk split-and-exchange for the replicated hot head
        (transfer/sparse_allreduce): each shard scatter-adds its local
        touched rows into a bucket-PERMUTED dense accumulator (row r →
        bucket r % n, so the frequency-ranked Zipf head spreads evenly
        over shards), a tiled ``psum_scatter`` over the permuted layout
        is the balanced reduce-scatter merging duplicate indices, and
        an ``all_gather`` + unpermute is the sparse allgather
        rebroadcasting the reduced rows to every replica.  Semantically
        identical to the dense psum up to float reduction order (the
        parity test pins allclose, not bit-identity); the wire ledger
        books the touched-row payload a variable-length wire ships —
        see the module docstring of transfer/sparse_allreduce."""
        from swiftmpi_tpu.transfer.sparse_allreduce import (
            bucket_layout, bucket_permute, bucket_unpermute)
        n_hot = next(iter(hot_state.values())).shape[0]
        n = int(self.mesh.shape[self.axis])
        cap_bucket, n_pad = bucket_layout(n_hot, n)
        bspec = self.tail._batch_spec()
        dp_axis = self.tail.dp_axis
        state_specs = {f: P() for f in hot_state}
        grad_specs = {f: bspec for f in grad_fields}
        in_specs = (state_specs, bspec, grad_specs)
        if with_counts:
            in_specs += (bspec,)

        def _reduce_bucketed(plane):
            # permuted layout → tiled psum_scatter IS the balanced
            # reduce-scatter over row-hash buckets; the all_gather is
            # the sparse allgather back to the replicated head
            b = bucket_permute(plane, n)
            b = jax.lax.psum_scatter(b, self.axis, scatter_dimension=0,
                                     tiled=True)
            if dp_axis:
                b = jax.lax.psum(b, dp_axis)
            g = jax.lax.all_gather(b, self.axis, axis=0, tiled=True)
            return bucket_unpermute(g, n)[:n_hot]

        @partial(jax.shard_map, mesh=self.mesh, in_specs=in_specs,
                 out_specs=state_specs, check_vma=False)
        def _hot_sparse(hot_l, slots_l, grads_l, *maybe_counts):
            valid = (slots_l >= 0) & (slots_l < n_hot)
            # tail and padding slots scatter out-of-bounds and drop;
            # pad rows [n_hot, n_pad) are never touched and contribute
            # exact zeros through the exchange
            safe = jnp.where(valid, slots_l, n_pad)
            if with_counts:
                c = maybe_counts[0] * valid
            else:
                c = valid.astype(jnp.float32)
            acc = {}
            for f in grad_fields:
                g = jnp.asarray(grads_l[f])
                local = jnp.zeros((n_pad, g.shape[1]), g.dtype).at[
                    safe].add(g * valid[:, None].astype(g.dtype),
                              mode="drop")
                with jax.named_scope("wire_exchange"):
                    acc[f] = _reduce_bucketed(local)
            csum = _reduce_bucketed(
                jnp.zeros((n_pad,), jnp.float32).at[safe].add(
                    c, mode="drop"))
            if mean:
                inv = (1.0 / jnp.maximum(csum, 1.0))[:, None]
                acc = {f: a * inv for f, a in acc.items()}
            new_fields = access.apply_push(hot_l, acc)
            out = dict(hot_l)
            out.update(new_fields)
            return out

        return _hot_sparse
