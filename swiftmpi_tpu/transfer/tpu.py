"""``tpu`` transfer backend: explicit SPMD routing via shard_map.

The literal TPU-native rendering of the reference pull/push RPC
(SURVEY.md §3.2-3.3): on a 1-D ``shard`` mesh every device plays both roles
— worker (holds a batch slice) and server (holds a table shard) — exactly
like every reference MPI rank hosting both endpoints
(`/root/reference/src/cluster/cluster.h:65-71`).  One pull is:

  1. bucket my local slot requests by owning shard   (arrange_local_vals,
     global_pull_access.h:46-60)
  2. ``all_to_all`` request buckets over ICI          (Transfer::send +
     main_loop recv, transfer.h:86-192)
  3. owners gather rows from their local shard slice  (PullAccessAgent,
     accessmethod.h:63-70)
  4. ``all_to_all`` rows back, unpermute to request order
     (response callbacks + StateBarrier, global_pull_access.h:80-101)

and the barrier is implicit in program order.  Push routes (slot, grad)
pairs the same way; owners segment-sum what they receive and apply the
access method once per row (see api.py for the sum-vs-sequential semantic
note).  All shapes are static: request buckets are fixed-capacity
``(n_shards, C)`` with ``-1`` padding routed to out-of-bounds scatter drops.

Requires: table row-sharded and batch sharded over the same mesh axis, and
``KeyIndex.num_shards`` == axis size so slot ranges align with device rows.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, PartitionSpec as P

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu.cluster.mesh import DATA_AXIS, SHARD_AXIS
from swiftmpi_tpu.obs import costs as obs_costs
from swiftmpi_tpu.ops import (calibration, pallas_gather, pallas_ring,
                              pallas_scatter)
from swiftmpi_tpu.parameter.sparse_table import ROWVER_KEY
from swiftmpi_tpu.transfer.api import Transfer, grad_row_bytes


def _shard_gather(arr: jax.Array, flat_idx: jax.Array) -> jax.Array:
    """Per-shard row gather; routes through the VMEM-resident Pallas
    kernel when the single-chip verdict says it wins.  ``manual=True``:
    this is called inside ``shard_map``, where ``arr`` is the device-
    local shard — no partitioner hazard, and the per-core shard is even
    smaller than the single-chip table the verdict was measured on."""
    if calibration.gated("vmem_gather", "SMTPU_PALLAS_GATHER",
                         pallas_gather.fits_vmem(arr), manual=True):
        return pallas_gather.masked_vmem_gather(
            arr, flat_idx, jnp.ones(flat_idx.shape, bool))
    return jnp.take(arr, flat_idx, axis=0)


def _bucketize(slots_l: jax.Array, n: int, cap_per_shard: int, C: int):
    """Group local slot requests by owner shard into an (n, C) matrix.

    Returns (req, order, so, idx_in_bucket) where ``req[o, j]`` is the
    owner-local row id of my j-th request to shard o (-1 padding), and the
    rest reconstructs request order on the way back.
    """
    B = slots_l.shape[0]
    valid = slots_l >= 0
    owner = jnp.where(valid, slots_l // cap_per_shard, n)  # n == "invalid"
    order = jnp.argsort(owner)
    so = owner[order]                       # sorted owners, invalid last
    local_row = jnp.where(valid, slots_l % cap_per_shard, 0)[order]
    # position within each owner group: arange - group start
    group_start = jnp.searchsorted(so, jnp.arange(n + 1))
    idx_in_bucket = jnp.arange(B) - group_start[jnp.clip(so, 0, n)]
    in_bounds = (so < n) & (idx_in_bucket < C)
    row_idx = jnp.where(in_bounds, so, n)          # OOB row -> dropped
    col_idx = jnp.where(in_bounds, idx_in_bucket, 0)
    req = jnp.full((n, C), -1, jnp.int32).at[row_idx, col_idx].set(
        local_row.astype(jnp.int32), mode="drop")
    return req, order, so, idx_in_bucket


class TpuTransfer(Transfer):
    name = "tpu"

    def __init__(self, mesh: Mesh, axis: str = SHARD_AXIS,
                 bucket_capacity: Optional[int] = None,
                 debug_overflow: bool = False,
                 data_plane: str = "auto"):
        """``bucket_capacity``: per-destination request slots; defaults to
        the full local batch (no overflow possible).  Smaller values cut
        all_to_all volume ~proportionally but drop overflow requests —
        only safe when keys are known to spread (reference demo configs
        rely on the same spread via frag_num >> server_num).

        When a capacity is set, every pull/push also counts globally how
        many valid requests overflowed their bucket; the running total is
        readable via :meth:`overflow_count` (and mirrored into ``metrics``
        if one is attached).  With ``debug_overflow=True`` each call
        synchronously checks the count and raises — slow, but turns silent
        training corruption into an immediate failure.

        ``data_plane``: the ``[cluster] data_plane:`` knob (``auto`` /
        ``pallas`` / ``xla``) steering the push wire exchange between
        ``all_to_all`` and the Pallas DMA ring
        (ops/pallas_ring.py) — resolved per measured calibration
        verdict by :func:`pallas_ring.use_ring_push`."""
        self.mesh = mesh
        self.axis = axis
        self.n = int(mesh.shape[axis])
        if data_plane not in calibration.DATA_PLANE_MODES:
            raise ValueError(
                f"data_plane must be one of "
                f"{calibration.DATA_PLANE_MODES}, got {data_plane!r}")
        self.data_plane = data_plane
        # hybrid multi-host mesh (ps_mesh(hybrid=True)): a leading data
        # axis across processes/DCN.  Each data group holds a full table
        # replica and routes requests over its own shard axis (ICI); the
        # groups reconcile per push with the only traffic that crosses
        # DCN — batch-proportional (slot, grad) pair gathers in the
        # sparse regime, a dense-grad psum at table-scale batches (the
        # static crossover is in _build_push).
        self.dp_axis = DATA_AXIS if DATA_AXIS in mesh.axis_names else None
        self.bucket_capacity = bucket_capacity
        self.debug_overflow = debug_overflow
        self.metrics = None              # optional utils.timers.Metrics
        self._overflow_total = 0
        self._overflow_pending: list = []   # eager-path device scalars
        # optional routed-row accounting (off by default: one extra
        # reduce per call) — the denominator of the hybrid backend's
        # "N× fewer cross-shard rows" golden checks
        self.count_traffic = False
        self._routed_total = 0
        self._routed_pending: list = []
        # jitted shard_map closures, keyed by static shape signature —
        # without this every pull/push call would re-trace and recompile.
        self._pull_cache: Dict = {}
        self._push_cache: Dict = {}
        # window-coalesced push (push_window): per-signature caches for
        # the pre-exchange dedup pass and the dense psum program, plus
        # an optional expected-unique-rows hint (set from the vocab
        # frequency histogram via cluster.hashfrag.expected_unique_rows)
        # that sharpens the static sparse/dense wire-format crossover
        self._dedup_cache: Dict = {}
        self._window_dense_cache: Dict = {}
        self.window_expected_unique: Optional[float] = None

    def _membership_changed(self) -> None:
        """Elastic membership (api.py): every compiled program here is
        specialized to a signature that embeds the world's shard
        layout, so an epoch change drops all four caches — the next
        call recompiles against the new shape instead of routing rows
        to a dead peer's address."""
        self._pull_cache.clear()
        self._push_cache.clear()
        self._dedup_cache.clear()
        self._window_dense_cache.clear()

    # -- overflow accounting ----------------------------------------------
    def _accum_overflow(self, op: str, count) -> None:
        c = int(count)
        self._overflow_total += c
        self._obs_inc("overflow_dropped", c)
        if self.debug_overflow and c:
            raise RuntimeError(
                f"TpuTransfer.{op}: {c} request(s) overflowed "
                f"bucket_capacity={self.bucket_capacity} and were "
                "DROPPED — raise bucket_capacity (or leave it unset "
                "for the overflow-free default)")

    def _record_overflow(self, op: str, count) -> None:
        """Accumulate a per-call overflow count on the host.

        Under an outer trace (the model's jitted/scanned training step)
        the count is a tracer: it is staged via ``jax.debug.callback`` so
        it fires on every compiled execution — a plain Python side effect
        would leak the tracer and count only the trace-time call.  Called
        eagerly, the concrete device scalar is queued and materialized
        only in :meth:`overflow_count`, so the async-dispatch pipeline is
        never stalled by a per-push D2H sync.  ``debug_overflow`` opts
        into the synchronous (slow, loud) eager check; from compiled code
        its raise surfaces at the next sync point."""
        if isinstance(count, jax.core.Tracer):
            jax.debug.callback(partial(self._accum_overflow, op), count)
        elif self.debug_overflow:
            self._accum_overflow(op, count)     # synchronous, documented slow
        else:
            self._overflow_pending.append(count)
            if len(self._overflow_pending) >= 1024:
                # drain so the list (and its pinned device scalars) can't
                # grow unboundedly when overflow_count() is never called;
                # by now these executions have long completed, so the
                # int() materialization is not a pipeline stall
                pending, self._overflow_pending = self._overflow_pending, []
                drained = sum(int(c) for c in pending)
                self._overflow_total += drained
                self._obs_inc("overflow_dropped", drained)

    def overflow_count(self) -> int:
        """Total requests dropped by bucket overflow since construction
        (flushes queued eager counts and pending traced callbacks); 0 when
        no capacity is set (overflow impossible by construction)."""
        jax.effects_barrier()
        pending, self._overflow_pending = self._overflow_pending, []
        drained = sum(int(c) for c in pending)
        self._overflow_total += drained
        self._obs_inc("overflow_dropped", drained)
        total = self._overflow_total
        if self.metrics is not None:
            self.metrics.set("transfer_overflow_dropped", total)
        return total

    # -- traffic accounting ------------------------------------------------
    def _accum_routed(self, count) -> None:
        self._routed_total += int(count)
        self._obs_inc("routed_rows", int(count))

    def _record_routed(self, count) -> None:
        """Same tracer/eager discipline as :meth:`_record_overflow`."""
        if isinstance(count, jax.core.Tracer):
            jax.debug.callback(self._accum_routed, count)
        else:
            self._routed_pending.append(count)
            if len(self._routed_pending) >= 1024:
                pending, self._routed_pending = self._routed_pending, []
                drained = sum(int(c) for c in pending)
                self._routed_total += drained
                self._obs_inc("routed_rows", drained)

    def routed_rows(self) -> int:
        """Total rows routed through all_to_all bucket routing since
        construction (counted only while ``count_traffic`` is set)."""
        jax.effects_barrier()
        pending, self._routed_pending = self._routed_pending, []
        drained = sum(int(c) for c in pending)
        self._routed_total += drained
        self._obs_inc("routed_rows", drained)
        if self.metrics is not None:
            self.metrics.set("transfer_routed_rows", self._routed_total)
        return self._routed_total

    def traffic(self) -> Dict[str, int]:
        """Per-backend traffic counters in the hybrid-comparable shape,
        merged with the base wire ledger (wire_bytes / dispatches /
        window decision counters — see Transfer.wire_traffic)."""
        out = {"routed_rows": self.routed_rows(),
               "hot_rows": 0, "psum_bytes": 0,
               "overflow_dropped": self.overflow_count()}
        out.update(self.wire_traffic())
        return out

    def _signature(self, state, slots, grads=None):
        sig = (tuple(sorted((f, v.shape, str(v.dtype))
                            for f, v in state.items())),
               tuple(slots.shape))
        if grads is not None:
            sig += (tuple(sorted((f, tuple(v.shape))
                                 for f, v in grads.items())),)
        return sig

    # -- pull --------------------------------------------------------------
    def _prim_pull(self, state, slots, fields):
        """Structural routed gather — wire-format / cache / byte-ledger
        decisions live in the base-class pull interpreter
        (api.Transfer.pull).  The routed-row and overflow counters stay
        with the primitive: they are properties of THIS backend's bucket
        routing, not of the wire format."""
        fields = tuple(fields)
        slots = jnp.asarray(slots, jnp.int32)
        if self.count_traffic:
            self._record_routed(jnp.sum(slots >= 0))
        sig = self._signature(state, slots) + (fields,)
        fn = self._pull_cache.get(sig)
        if fn is None:
            fn = self._pull_cache.setdefault(
                sig, obs_costs.track("tpu_pull", jax.jit(
                    self._build_pull(state, fields))))
        if self.bucket_capacity is None:
            return fn(state, slots)
        out, ovf = fn(state, slots)
        self._record_overflow("pull", ovf)
        return out

    def _batch_spec(self):
        """Request/response arrays: sharded over every device (the data
        groups each carry their own slice of the global batch)."""
        return P((self.dp_axis, self.axis)) if self.dp_axis \
            else P(self.axis)

    def _build_pull(self, state, fields):
        capacity = next(iter(state.values())).shape[0]
        cap_per_shard = capacity // self.n
        bspec = self._batch_spec()
        state_specs = {f: P(self.axis) for f in state}
        pull_specs = {f: bspec for f in fields}
        counted = self.bucket_capacity is not None
        out_specs = (pull_specs, P()) if counted else pull_specs

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(state_specs, bspec),
                 out_specs=out_specs, check_vma=False)
        def _pull(state_l, slots_l):
            B = slots_l.shape[0]
            C = self.bucket_capacity or B
            req, order, so, idx = _bucketize(
                slots_l, self.n, cap_per_shard, C)
            # telemetry phase name carried into the device trace
            with jax.named_scope("wire_exchange"):
                got = jax.lax.all_to_all(req, self.axis, 0, 0, tiled=True)
            ok = got >= 0
            safe = jnp.where(ok, got, 0)
            out = {}
            for f in fields:
                rows = _shard_gather(state_l[f], safe.reshape(-1))
                rows = rows.reshape(self.n, C, -1) * ok[..., None]
                resp = jax.lax.all_to_all(rows, self.axis, 0, 0, tiled=True)
                vals = resp[jnp.clip(so, 0, self.n - 1),
                            jnp.clip(idx, 0, C - 1)]
                vals = vals * ((so < self.n) & (idx < C))[:, None]
                out[f] = jnp.zeros((B, vals.shape[1]),
                                   vals.dtype).at[order].set(vals)
            if not counted:
                return out
            axes = (self.dp_axis, self.axis) if self.dp_axis \
                else (self.axis,)
            ovf = jax.lax.psum(
                jnp.sum((so < self.n) & (idx >= C)), axes)
            return out, ovf

        return _pull

    # -- push --------------------------------------------------------------
    def push(self, state, slots, grads, access, mean=False, counts=None,
             _wire=None):
        """``counts`` (non-None) marks a position-indexed span family (the
        stencil wire format): per-row contribution counts ship as a
        synthetic width-1 grad field through the same bucket routing, so
        ``mean`` normalization at the owner divides by DATA counts rather
        than 1-per-request — matching ``XlaTransfer.push_span``.

        ``_wire`` (internal, ``(row_bytes, base_bytes)``) overrides the
        ledger's per-row byte model: the window path books its
        quantized/bitmap exchanges at ENCODED size while the routed
        payload itself stays dequantized f32 (the format decision
        changes bytes, not semantics)."""
        slots = jnp.asarray(slots, jnp.int32)
        with_counts = counts is not None
        if self.count_traffic:
            rows = jnp.sum(slots >= 0)
            self._record_routed(rows)
            # wire ledger: sparse (index, value) rows; counts ride as an
            # extra 4-byte column on span families (computed BEFORE the
            # synthetic field is attached so it isn't double-counted)
            if _wire is not None:
                self._record_exchange(rows, _wire[0], base_bytes=_wire[1])
            else:
                self._record_exchange(
                    rows, grad_row_bytes(grads, with_counts=with_counts))
        if with_counts:
            grads = dict(grads)
            grads["__counts__"] = jnp.asarray(
                counts, jnp.float32).reshape(-1, 1)
        sig = self._signature(state, slots, grads) + (mean, with_counts)
        fn = self._push_cache.get(sig)
        if fn is None:
            fn = self._push_cache.setdefault(
                sig, obs_costs.track("tpu_push", jax.jit(
                    self._build_push(state, access,
                                     tuple(sorted(grads)), mean,
                                     with_counts))))
        if self.bucket_capacity is None:
            return fn(state, slots, grads)
        out, ovf = fn(state, slots, grads)
        self._record_overflow("push", ovf)
        return out

    def push_span(self, state, slots, grads, counts, access, mean=False):
        """Sort-free span push (PR-2 stencil wire format) over the same
        all_to_all routing; see :meth:`push` ``counts``."""
        return self.push(state, slots, grads, access, mean=mean,
                         counts=counts)

    # -- window-plan primitives --------------------------------------------
    # The window push lives in ONE place — the TrafficPlan interpreter
    # (api.Transfer.push_window).  This backend contributes the sharded
    # primitives below: the shard_map dedup pre-pass, the bucket-routed
    # exchange, the dense psum program, and the shard-owner metadata
    # for the key tracer.  No wire-format question is asked here.

    def _trace_shard_args(self, capacity):
        """This backend knows its slot -> shard owner mapping, so
        window trace records carry the per-destination row split."""
        return {"cap_per_shard": capacity // self.n, "n_shards": self.n}

    def _prim_window_dedup(self, flat, fgrads, fcounts, capacity):
        return self._window_dedup(flat, fgrads, fcounts, capacity)

    def _prim_window_exchange(self, state, ded_slots, ded_grads,
                              ded_counts, access, mean, need_counts,
                              wire):
        """Routed exchange of the deduped window: the surviving rows go
        through the existing bucket routing ONCE, booked at the plan's
        encoded size when a ``wire`` override is supplied."""
        return self.push(state, ded_slots, ded_grads, access, mean=mean,
                         counts=ded_counts if need_counts else None,
                         _wire=wire)

    def _window_dedup(self, flat, fgrads, fcounts, capacity):
        """Device-local positional dedup of the flattened window: each
        device collapses repeats WITHIN its own batch slice (cross-device
        repeats still sum correctly at the owning shard).  Returns
        (slots, grads, counts) of the same sharded shapes with non-first
        occurrences marked -1 and their grads/counts folded into the
        representative row."""
        counts_in = fcounts if fcounts is not None else jnp.ones(
            flat.shape, jnp.float32)
        sig = (capacity, tuple(flat.shape),
               tuple(sorted((f, tuple(v.shape), str(v.dtype))
                            for f, v in fgrads.items())))
        fn = self._dedup_cache.get(sig)
        if fn is None:
            fn = self._dedup_cache.setdefault(
                sig, obs_costs.track("tpu_window_dedup", jax.jit(
                    self._build_window_dedup(
                        capacity, tuple(sorted(fgrads))))))
        return fn(flat, fgrads, counts_in)

    def _build_window_dedup(self, capacity, grad_fields):
        bspec = self._batch_spec()
        grad_specs = {f: bspec for f in grad_fields}

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(bspec, grad_specs, bspec),
                 out_specs=(bspec, grad_specs, bspec), check_vma=False)
        @jax.named_scope("window_dedup")
        def _dedup(slots_l, grads_l, counts_l):
            B = slots_l.shape[0]
            valid = slots_l >= 0
            pos = jnp.arange(B, dtype=jnp.int32)
            safe = jnp.where(valid, slots_l, capacity)
            # rep[k] = first window position holding slot k — sort-free
            # scatter-min into a (capacity+1,) plane, exactly the
            # XlaTransfer.push_span representative trick
            rep = jnp.full((capacity + 1,), B, jnp.int32).at[safe].min(
                jnp.where(valid, pos, B), mode="drop")
            owner = jnp.where(valid, rep[safe], B)   # B == dropped
            is_owner = valid & (owner == pos)
            out_grads = {}
            for f in grad_fields:
                g = grads_l[f]
                out_grads[f] = jnp.zeros_like(g).at[owner].add(
                    g * valid[:, None].astype(g.dtype), mode="drop")
            csum = jnp.zeros(counts_l.shape, counts_l.dtype).at[owner].add(
                counts_l * valid, mode="drop")
            return jnp.where(is_owner, slots_l, -1), out_grads, csum

        return _dedup

    def _push_window_dense(self, state, flat, fgrads, access, mean,
                           fcounts):
        capacity = next(iter(state.values())).shape[0]
        with_counts = fcounts is not None
        counts_in = fcounts if with_counts else jnp.ones(
            flat.shape, jnp.float32)
        sig = self._signature(state, flat, fgrads) + (
            mean, with_counts, "window_dense")
        fn = self._window_dense_cache.get(sig)
        if fn is None:
            fn = self._window_dense_cache.setdefault(
                sig, obs_costs.track("tpu_window_dense", jax.jit(
                    self._build_push_window_dense(
                        state, access, tuple(sorted(fgrads)), mean))))
        # ledger booking (an interpreter concern) fires from
        # api.Transfer._interpret_window_flat before this primitive runs
        return fn(state, flat, fgrads, counts_in)

    def _prim_sparse_allreduce(self, state, flat, fgrads, access, mean,
                               fcounts):
        """Sparse-allreduce primitive for the sharded table: the dense
        rung's tiled ``psum_scatter`` already IS the balanced
        reduce-scatter — each shard's summed slice lands directly on
        its owner, and a SHARDED target needs no allgather leg at all
        (Ok-Topk's rebroadcast only exists for replicated state, the
        hybrid hot head).  The compute is therefore identical to the
        dense collective and the flip is bit-identical on this backend;
        what changes is the WIRE MODEL — the interpreter books the
        touched-row (index, value) payload instead of the full
        capacity-shaped buffer (see transfer/sparse_allreduce)."""
        return self._push_window_dense(state, flat, fgrads, access,
                                       mean, fcounts)

    def _build_push_window_dense(self, state, access, grad_fields, mean):
        capacity = next(iter(state.values())).shape[0]
        bspec = self._batch_spec()
        state_specs = {f: P(self.axis) for f in state}
        grad_specs = {f: bspec for f in grad_fields}

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(state_specs, bspec, grad_specs, bspec),
                 out_specs=state_specs, check_vma=False)
        def _push_dense(state_l, slots_l, grads_l, counts_l):
            valid = slots_l >= 0
            safe = jnp.where(valid, slots_l, capacity)  # OOB -> dropped
            dense = {}
            for f in grad_fields:
                g = jnp.asarray(grads_l[f])
                width = g.shape[1]
                if calibration.gated(
                        "vmem_scatter", "SMTPU_PALLAS_SCATTER",
                        pallas_scatter.fits_vmem(capacity, width),
                        manual=True):
                    acc = pallas_scatter.masked_vmem_scatter_add(
                        slots_l, valid, g, capacity)
                else:
                    acc = jnp.zeros((capacity, width), g.dtype).at[
                        safe].add(g * valid[:, None].astype(g.dtype),
                                  mode="drop")
                # the ONE exchange of the window: tiled reduce-scatter
                # lands each shard's summed slice on its owner directly
                with jax.named_scope("wire_exchange"):
                    acc = jax.lax.psum_scatter(acc, self.axis,
                                               scatter_dimension=0,
                                               tiled=True)
                if self.dp_axis:
                    acc = jax.lax.psum(acc, self.dp_axis)
                dense[f] = acc
            if mean:
                cplane = jnp.zeros((capacity,), jnp.float32).at[safe].add(
                    counts_l * valid, mode="drop")
                cplane = jax.lax.psum_scatter(
                    cplane, self.axis, scatter_dimension=0, tiled=True)
                if self.dp_axis:
                    cplane = jax.lax.psum(cplane, self.dp_axis)
                inv = (1.0 / jnp.maximum(cplane, 1.0))[:, None]
                dense = {f: a * inv for f, a in dense.items()}
            with jax.named_scope("apply"):
                new_fields = access.apply_push(state_l, dense)
            out = dict(state_l)
            out.update(new_fields)
            if ROWVER_KEY in state_l:
                # delta-pull version stamp: global-slot occupancy
                # reduce-scattered onto its owning shard tile (the same
                # wire the grads ride), psum'd over the data axis so
                # replicas stamp the identical union of touched rows
                touched = jnp.zeros((capacity,), jnp.int32).at[safe].add(
                    valid.astype(jnp.int32), mode="drop")
                touched = jax.lax.psum_scatter(
                    touched, self.axis, scatter_dimension=0, tiled=True)
                if self.dp_axis:
                    touched = jax.lax.psum(touched, self.dp_axis)
                ver = state_l[ROWVER_KEY]
                newv = jnp.max(ver) + jnp.int32(1)
                out[ROWVER_KEY] = jnp.where(
                    (touched > 0)[:, None], newv, ver)
            return out

        return _push_dense

    def _build_push(self, state, access, grad_fields, mean=False,
                    with_counts=False):
        capacity = next(iter(state.values())).shape[0]
        cap_per_shard = capacity // self.n
        bspec = self._batch_spec()
        state_specs = {f: P(self.axis) for f in state}
        grad_specs = {f: bspec for f in grad_fields}
        counted = self.bucket_capacity is not None
        out_specs = (state_specs, P()) if counted else state_specs

        dp = int(self.mesh.shape[self.dp_axis]) if self.dp_axis else 1
        # wire-exchange routing, resolved at trace time: the Pallas DMA
        # ring replaces both all_to_all rounds when the data_plane knob
        # / measured ring_push verdict says so (1-D mesh only — see
        # ops/pallas_ring.py on LOGICAL device ids)
        use_ring = pallas_ring.use_ring_push(
            self.n, self.dp_axis is None, self.data_plane)

        def _wire_exchange(x, ring=None):
            if use_ring if ring is None else ring:
                with jax.named_scope("pallas_ring_push"):
                    return pallas_ring.ring_exchange(x, self.axis, self.n)
            with jax.named_scope("wire_exchange"):
                return jax.lax.all_to_all(x, self.axis, 0, 0, tiled=True)

        @partial(jax.shard_map, mesh=self.mesh,
                 in_specs=(state_specs, bspec, grad_specs),
                 out_specs=out_specs, check_vma=False)
        def _push(state_l, slots_l, grads_l):
            B = slots_l.shape[0]
            C = self.bucket_capacity or B
            req, order, so, idx = _bucketize(
                slots_l, self.n, cap_per_shard, C)
            # phase names match obs.span()/telemetry: the collectives are
            # "wire_exchange" (or "pallas_ring_push" when the DMA ring
            # is routed), the owner-side access update is "apply" —
            # host timing is meaningless inside jit, so the device trace
            # carries the names instead (docs/ARCHITECTURE.md).
            got = _wire_exchange(req)
            ok = got >= 0
            # received (slot, grad) pairs -> dense per-shard grad sums;
            # untouched rows get exact zero and the access rule is a no-op.
            safe_rows = jnp.where(ok, got, cap_per_shard).reshape(-1)
            # DCN reconciliation strategy (static, from shapes): the data
            # groups must agree on one global update.  Sparse: all_gather
            # the received (row, grad) PAIRS across the data axis and
            # scatter-add locally — DCN bytes scale with the batch
            # (dp*n*C rows), not the table.  Dense: one capacity-sized
            # psum — fewer bytes only once the batch approaches table
            # scale (round-2 verdict Weak #4: the dense psum alone is
            # O(capacity*d) per push, ~400MB/field at 1M-row scale).
            sparse_dcn = bool(self.dp_axis) and (
                dp * self.n * C < cap_per_shard // 2)
            rows_g = None
            if sparse_dcn:
                rows_g = jax.lax.all_gather(
                    safe_rows, self.dp_axis).reshape(-1)
            inv = None
            if mean and not with_counts:
                # contribution counts accumulate at the owning shard from
                # the received requests themselves — no extra collective
                if sparse_dcn:
                    counts = jnp.zeros((cap_per_shard,), jnp.float32).at[
                        rows_g].add(
                        (rows_g < cap_per_shard).astype(jnp.float32),
                        mode="drop")
                else:
                    counts = jnp.zeros((cap_per_shard,), jnp.float32).at[
                        safe_rows].add(ok.reshape(-1).astype(jnp.float32),
                                       mode="drop")
                    if self.dp_axis:
                        counts = jax.lax.psum(counts, self.dp_axis)
                inv = (1.0 / jnp.maximum(counts, 1.0))[:, None]
            dense = {}
            for f in grad_fields:
                g = jnp.asarray(grads_l[f])
                width = g.shape[1]
                # forward my buckets' grads in the same (n, C) layout
                bucket = jnp.zeros((self.n, C, width), g.dtype)
                row_idx = jnp.where((so < self.n) & (idx < C), so, self.n)
                col_idx = jnp.clip(idx, 0, C - 1)
                bucket = bucket.at[row_idx, col_idx].set(
                    g[order], mode="drop")
                # the width-1 counts bucket always rides all_to_all: its
                # bytes are noise next to the d-wide grad buckets, and
                # inv-scaling ring-fed grad sums by a ring-fed counts
                # column trips an XLA reshape CHECK during the interpret
                # discharge (jaxlib 0.4.x, array.h new_num_elements)
                recv = _wire_exchange(
                    bucket, ring=use_ring and f != "__counts__")
                if sparse_dcn:
                    # batch-proportional DCN traffic: every group's
                    # received pairs, applied by everyone identically
                    recv_g = jax.lax.all_gather(
                        recv.reshape(-1, width), self.dp_axis)
                    acc = jnp.zeros((cap_per_shard, width), g.dtype)
                    acc = acc.at[rows_g].add(
                        recv_g.reshape(-1, width), mode="drop")
                else:
                    acc = jnp.zeros((cap_per_shard, width), g.dtype)
                    acc = acc.at[safe_rows].add(
                        recv.reshape(-1, width), mode="drop")
                    if self.dp_axis:
                        # capacity-sized psum: the right call only at
                        # batch ~ table scale (see strategy note above)
                        acc = jax.lax.psum(acc, self.dp_axis)
                dense[f] = acc
            if with_counts:
                # span families: per-row DATA counts rode along as the
                # synthetic field and summed at the owner like any grad
                csum = dense.pop("__counts__")
                if mean:
                    inv = 1.0 / jnp.maximum(csum[:, :1], 1.0)
            if mean:
                dense = {f: a * inv for f, a in dense.items()}
            with jax.named_scope("apply"):
                new_fields = access.apply_push(state_l, dense)
            out = dict(state_l)
            out.update(new_fields)
            if ROWVER_KEY in state_l:
                # delta-pull version stamp: bump every row touched by
                # THIS apply past the shard's current max (per-shard
                # monotonic — sparse_table.py).  The plane is replicated
                # across data groups, so the bump must cover the UNION
                # of touched rows: an occupancy plane psum'd over the
                # data axis, exactly like the grads themselves.
                touched = jnp.zeros((cap_per_shard,), jnp.int32).at[
                    safe_rows].add(ok.reshape(-1).astype(jnp.int32),
                                   mode="drop")
                if self.dp_axis:
                    touched = jax.lax.psum(touched, self.dp_axis)
                ver = state_l[ROWVER_KEY]
                newv = jnp.max(ver) + jnp.int32(1)
                out[ROWVER_KEY] = jnp.where(
                    (touched > 0)[:, None], newv, ver)
            if not counted:
                return out
            axes = (self.dp_axis, self.axis) if self.dp_axis \
                else (self.axis,)
            ovf = jax.lax.psum(
                jnp.sum((so < self.n) & (idx >= C)), axes)
            return out, ovf

        return _push
