"""``local`` transfer backend: numpy golden model.

Single-process, loop-free-of-collectives reference implementation of the
transfer semantics in api.py, used to property-test the ``xla`` and ``tpu``
backends against each other.  Mirrors the role of the reference's
single-rank ``mpirun -np 1`` deployment as the implicit test story
(SURVEY.md §4) — except here it is an actual oracle, not a smoke run.
"""

from __future__ import annotations

import numpy as np

from swiftmpi_tpu import obs
from swiftmpi_tpu.parameter.access import AccessMethod
from swiftmpi_tpu.parameter.sparse_table import ROWVER_KEY, ef_name
from swiftmpi_tpu.transfer.api import (Transfer, grad_row_bytes,
                                       numerics_quant_err,
                                       quantize_dequantize)


def _bump_versions(out, rows) -> None:
    """Stamp ``rows`` of the row-version plane (present iff the
    delta-pull cache is armed) past the current max — the eager numpy
    twin of the device backends' per-shard ``max + 1`` bump.  Any apply
    that changes a row MUST pass through here (or a device twin): the
    PullCache's version-exact hit contract depends on it."""
    if ROWVER_KEY not in out:
        return
    ver = out[ROWVER_KEY]
    ver[np.asarray(rows, np.int64)] = np.int32(ver.max() + 1)


class LocalTransfer(Transfer):
    name = "local"

    def __init__(self):
        # wire ledger parity with the device backends: local has no
        # actual wire, so wire_bytes counts the NOTIONAL sparse payload
        # (valid rows x grad_row_bytes) the same exchange would ship —
        # the oracle for cross-backend traffic goldens
        self.count_traffic = False
        # elastic membership (api.py): nothing compiled to invalidate;
        # keep the adoption history so tests can assert the hook fired
        self.membership_log: list = []

    def _membership_changed(self) -> None:
        self.membership_log.append(
            (self._membership_epoch, self._live_ranks))

    def _prim_pull(self, state, slots, fields):
        # structural gather only — the ledger/format/cache logic lives
        # in the base-class pull interpreter (api.Transfer.pull)
        slots = np.asarray(slots, np.int64)
        valid = slots >= 0
        out = {}
        for f in fields:
            arr = np.asarray(state[f])
            rows = arr[np.where(valid, slots, 0)]
            rows[~valid] = 0
            out[f] = rows
        return out

    def push(self, state, slots, grads, access, mean=False):
        slots = np.asarray(slots, np.int64)
        valid = slots >= 0
        self._record_exchange(int(valid.sum()), grad_row_bytes(grads))
        uniq, counts = np.unique(slots[valid], return_counts=True)
        combined = {}
        for f in grads:
            g = np.asarray(grads[f], np.float32)
            width = g.shape[1]
            acc = np.zeros((len(uniq), width), np.float32)
            pos = np.searchsorted(uniq, slots[valid])
            np.add.at(acc, pos, g[valid])
            if mean:
                acc /= np.maximum(counts, 1)[:, None]
            combined[f] = acc
        current = {f: np.asarray(state[f])[uniq]
                   for f in access.touched_fields(grads)}
        updated = access.apply_push(current, combined)
        out = {f: np.asarray(state[f]).copy() for f in state}
        for f in updated:
            out[f][uniq] = np.asarray(updated[f])
        _bump_versions(out, uniq)
        return out

    def push_span(self, state, slots, grads, counts, access, mean=False,
                  _wire=None):
        """Span-family oracle (stencil wire format): rows carry window-
        overlap gradient SUMS with per-row DATA counts; ``mean`` divides
        each unique key's gradient sum by its summed data count —
        matching ``XlaTransfer.push_span`` exactly."""
        slots = np.asarray(slots, np.int64)
        counts = np.asarray(counts, np.float32)
        valid = slots >= 0
        if _wire is not None:
            self._record_exchange(int(valid.sum()), _wire[0],
                                  base_bytes=_wire[1])
        else:
            self._record_exchange(int(valid.sum()),
                                  grad_row_bytes(grads, with_counts=True))
        uniq = np.unique(slots[valid])
        pos = np.searchsorted(uniq, slots[valid])
        csum = np.zeros((len(uniq),), np.float32)
        np.add.at(csum, pos, counts[valid])
        combined = {}
        for f in grads:
            g = np.asarray(grads[f], np.float32)
            acc = np.zeros((len(uniq), g.shape[1]), np.float32)
            np.add.at(acc, pos, g[valid])
            if mean:
                acc /= np.maximum(csum, 1.0)[:, None]
            combined[f] = acc
        current = {f: np.asarray(state[f])[uniq]
                   for f in access.touched_fields(grads)}
        updated = access.apply_push(current, combined)
        out = {f: np.asarray(state[f]).copy() for f in state}
        for f in updated:
            out[f][uniq] = np.asarray(updated[f])
        _bump_versions(out, uniq)
        return out

    # -- window-plan primitives --------------------------------------------
    # The window push itself lives in ONE place — the TrafficPlan
    # interpreter (api.Transfer.push_window).  The oracle contributes
    # only eager numpy primitives; it never sees the wire-format
    # question, which is what makes it the exactness reference the
    # envelope tests diff the device backends against.

    def _prim_window_dedup(self, flat, fgrads, fcounts, capacity):
        """Eager oracle dedup: compact the flattened window to sorted
        unique rows with summed grads/counts (``np.unique`` +
        ``np.add.at`` — the numpy twin of the representative trick)."""
        valid = flat >= 0
        uniq = np.unique(flat[valid])
        pos = np.searchsorted(uniq, flat[valid])
        csum = np.zeros((len(uniq),), np.float32)
        np.add.at(csum, pos, fcounts[valid])
        sums = {}
        for f, g in fgrads.items():
            acc = np.zeros((len(uniq), g.shape[1]), np.float32)
            np.add.at(acc, pos, g[valid])
            sums[f] = acc
        return uniq, sums, csum

    def _prim_sparse_allreduce(self, state, flat, fgrads, access, mean,
                               fcounts):
        """Eager numpy sparse-allreduce twin: on the one-"shard" oracle
        world the split-and-exchange degenerates to merging duplicate
        indices (``np.unique`` + ``np.add.at``) and applying the touched
        rows — the exactness reference the device collective's merge is
        diffed against in tests/test_sparse_allreduce.py."""
        flat = np.asarray(flat, np.int64)
        capacity = next(iter(state.values())).shape[0]
        valid = (flat >= 0) & (flat < capacity)
        uniq = np.unique(flat[valid])
        pos = np.searchsorted(uniq, flat[valid])
        counts = (np.asarray(fcounts, np.float32)
                  if fcounts is not None
                  else np.ones(flat.shape, np.float32))
        csum = np.zeros((len(uniq),), np.float32)
        np.add.at(csum, pos, counts[valid])
        combined = {}
        for f, g in fgrads.items():
            g = np.asarray(g, np.float32)
            acc = np.zeros((len(uniq), g.shape[1]), np.float32)
            np.add.at(acc, pos, g[valid])
            if mean:
                acc /= np.maximum(csum, 1.0)[:, None]
            combined[f] = acc
        current = {f: np.asarray(state[f])[uniq]
                   for f in access.touched_fields(fgrads)}
        updated = access.apply_push(current, combined)
        out = {f: np.asarray(state[f]).copy() for f in state}
        for f in updated:
            out[f][uniq] = np.asarray(updated[f])
        _bump_versions(out, uniq)
        return out

    def _prim_ef_drain(self, state, uniq, sums, capacity, quant):
        """Eager EF drain: residual in, quantize the SUM, bank the new
        error — same order of operations as api.ef_quantize_window,
        spelled out in numpy with the same numerics/trace taps."""
        state = dict(state)
        sums = dict(sums)
        err_sq = 0.0
        drained = rebanked = 0.0
        banked = False
        for f in list(sums):
            efk = ef_name(f)
            if efk not in state:
                continue
            ef = np.asarray(state[efk], np.float32).copy()
            tot = sums[f] + ef[uniq]
            drained += float(np.sum(np.abs(ef[uniq])))
            deq = np.asarray(quantize_dequantize(tot, quant), np.float32)
            ef[uniq] = tot - deq
            state[efk] = ef
            sums[f] = deq
            err_sq += float(np.sum((tot - deq) ** 2))
            rebanked += float(np.sum(np.abs(tot - deq)))
            banked = True
        if banked:
            numerics_quant_err(err_sq)
            tracer = obs.get_tracer()
            if tracer is not None:
                tracer.stage_ef(self.name, drained, rebanked)
        return state, sums
