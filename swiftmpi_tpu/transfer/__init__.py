"""Transfer layer: the pull/push data plane over XLA collectives.

TPU-native equivalent of `/root/reference/src/transfer/` +
`/root/reference/src/parameter/global_{pull,push}_access.h` — see api.py.
"""

from swiftmpi_tpu.transfer.api import (PushSpec, Transfer,
                                       get_transfer)

__all__ = ["PushSpec", "Transfer", "get_transfer"]
