"""``sparse_sketch`` wire codec: counting-sketch index compression.

S2 Reducer (arXiv:2110.02140) observes that a sparse gradient exchange
spends a large fraction of its bytes on the *index stream* — 4 bytes per
surviving row under the legacy ``sparse`` format, ``capacity / 8`` bytes
of occupancy mask under ``bitmap`` — and replaces it with a counting
sketch of the index set.  This module is the swiftmpi_tpu rendering of
that idea, shaped to slot between the ``bitmap`` and ``sparse`` rungs of
the window wire-format ladder (parameter/key_index.py):

* the slot space ``[0, capacity)`` is cut into buckets of
  :data:`BUCKET_WIDTH` consecutive slots;
* the **counting sketch** is one uint16 occupancy count per bucket —
  ``2 * ceil(capacity / 256)`` bytes, 16x below the bitmap mask's
  ``capacity / 8``;
* each surviving row ships a single uint8 **in-bucket offset** (its
  slot modulo the bucket width) in slot-sorted order, plus its packed
  values.

Decode is exact, not probabilistic: rows arrive slot-sorted, so bucket
``b``'s ``counts[b]`` rows are contiguous and each row's slot is
``b * BUCKET_WIDTH + offset``.  The rung is therefore LOSSLESS on both
indices and values (EF-compatible by vacuity: residual planes are never
touched), and its byte model

    ``sketch_base_bytes(capacity) + rows * (1 + value_bytes)``

beats ``bitmap`` whenever 1 byte/row of offsets undercuts the mask's
amortized ``capacity / (8 * rows)`` bytes/row, and beats ``sparse``
whenever rows are dense enough that 3 index bytes/row matter — the
mid-density band the pricer (``price_window_formats``) resolves per
window.

Host-side codec only: the device payload rides the unchanged f32
routing (the ``bitmap`` precedent — the format decision changes what
the ledger *books*, not the routed math), while this module is the
byte-exact encode/decode oracle the goldens and the serving/delta
planes can call.
"""

from __future__ import annotations

from typing import Dict, Tuple

import numpy as np

#: consecutive slots per sketch bucket.  256 keeps the in-bucket offset
#: in one uint8; per-bucket occupancy can reach 256 (every slot of a
#: bucket surviving), which overflows uint8 by exactly one — hence the
#: uint16 counts plane.
BUCKET_WIDTH = 256

#: per-row index cost of the format: one uint8 in-bucket offset.
OFFSET_BYTES = 1

#: per-bucket cost of the counting sketch: one uint16 occupancy count.
COUNT_BYTES = 2


def n_buckets(capacity: int) -> int:
    return -(-int(capacity) // BUCKET_WIDTH)


def sketch_base_bytes(capacity: int) -> int:
    """Row-count-independent bytes of one encoded exchange: the uint16
    counting-sketch plane, the analogue of ``bitmap``'s ``capacity / 8``
    mask."""
    return COUNT_BYTES * n_buckets(capacity)


def sketch_wire_bytes(capacity: int, rows: float, value_bytes: float) -> float:
    """Modeled encoded bytes of one window: the pricing twin of
    :func:`encode` (``parameter.key_index.price_window_formats`` calls
    this, so the plan pricer and the codec can never disagree on the
    byte model)."""
    return (float(sketch_base_bytes(capacity))
            + float(rows) * (OFFSET_BYTES + float(value_bytes)))


def encode_index(slots, capacity: int) -> Tuple[np.ndarray, np.ndarray]:
    """Encode a set of distinct slots in ``[0, capacity)`` as
    ``(counts, offsets)``: the uint16 per-bucket occupancy sketch and
    the slot-sorted uint8 in-bucket offsets.  ``-1`` padding is
    dropped."""
    slots = np.asarray(slots).reshape(-1)
    slots = np.sort(slots[slots >= 0]).astype(np.int64)
    if slots.size and int(slots[-1]) >= int(capacity):
        raise ValueError(
            f"sketch.encode_index: slot {int(slots[-1])} out of range "
            f"for capacity {capacity}")
    if slots.size != np.unique(slots).size:
        raise ValueError("sketch.encode_index: slots must be distinct "
                         "(encode AFTER the window dedup)")
    counts = np.bincount(slots // BUCKET_WIDTH,
                         minlength=n_buckets(capacity)).astype(np.uint16)
    offsets = (slots % BUCKET_WIDTH).astype(np.uint8)
    return counts, offsets


def decode_index(counts, offsets) -> np.ndarray:
    """Exact inverse of :func:`encode_index`: slot-sorted int64 slots."""
    counts = np.asarray(counts, np.int64)
    offsets = np.asarray(offsets, np.int64)
    if int(counts.sum()) != offsets.size:
        raise ValueError("sketch.decode_index: counts/offsets mismatch "
                         f"({int(counts.sum())} != {offsets.size})")
    base = np.repeat(np.arange(counts.size, dtype=np.int64),
                     counts) * BUCKET_WIDTH
    return base + offsets


def encode(slots, values: Dict[str, np.ndarray], capacity: int) -> bytes:
    """Byte-exact encode of one deduped window: the counting sketch,
    the offset stream, then each field's rows packed in slot-sorted
    order (fields in sorted name order; widths/dtypes are the
    receiver's static plan metadata, not shipped)."""
    raw = np.asarray(slots).reshape(-1)
    keep = raw >= 0
    order = np.argsort(raw[keep], kind="stable")
    counts, offsets = encode_index(raw[keep], capacity)
    parts = [counts.tobytes(), offsets.tobytes()]
    for f in sorted(values):
        v = np.ascontiguousarray(np.asarray(values[f])[keep][order])
        parts.append(v.tobytes())
    return b"".join(parts)


def decode(payload: bytes, capacity: int,
           fields: Dict[str, Tuple[int, np.dtype]]
           ) -> Tuple[np.ndarray, Dict[str, np.ndarray]]:
    """Inverse of :func:`encode` given the static field metadata
    ``{name: (width, dtype)}``; returns slot-sorted ``(slots, values)``."""
    m = n_buckets(capacity)
    counts = np.frombuffer(payload[:COUNT_BYTES * m], np.uint16)
    rows = int(counts.sum())
    pos = COUNT_BYTES * m
    offsets = np.frombuffer(payload[pos:pos + rows], np.uint8)
    pos += rows
    slots = decode_index(counts, offsets)
    out: Dict[str, np.ndarray] = {}
    for f in sorted(fields):
        width, dtype = fields[f]
        nbytes = rows * width * np.dtype(dtype).itemsize
        out[f] = np.frombuffer(payload[pos:pos + nbytes],
                               dtype).reshape(rows, width)
        pos += nbytes
    if pos != len(payload):
        raise ValueError(f"sketch.decode: {len(payload) - pos} trailing "
                         "bytes (field metadata mismatch?)")
    return slots, out
