"""Transfer layer: the pull/push data plane, with backend selection.

This is the TPU-native replacement for the reference's entire RPC stack —
``Transfer``/``Listener``/``Route`` over ZeroMQ plus the
``GlobalPullAccess::pull_with_barrier`` / ``GlobalPushAccess::
push_with_barrier`` clients (`/root/reference/src/transfer/transfer.h:86-241`,
`/root/reference/src/parameter/global_pull_access.h:28-43`,
`global_push_access.h:26-43`).  Per the BASELINE north star, the interface
survives and the wire disappears: a backend is selected by the ``transfer``
config key and turns pull/push into XLA collectives.

Backends:

* ``xla``   — gather/scatter with sharding constraints; XLA chooses the
              collectives.  Works under any mesh (or none).  Default.
* ``tpu``   — explicit SPMD routing via ``shard_map``: keys are bucketed by
              owning shard, ``all_to_all`` ships requests over ICI, owners
              gather/apply locally, ``all_to_all`` ships rows back.  The
              literal TPU translation of the reference pull/push RPC
              (SURVEY.md §3.2-3.3) on a 1-D ``shard`` mesh.
* ``hybrid`` — Zipf-aware composition: frequency-hot rows replicated on
              every device and reconciled with one dense ``psum`` per
              push, cold-tail rows through the ``tpu`` routing above
              (transfer/hybrid.py; requires a ``HotColdPartition`` on
              the KeyIndex to be more than an alias of ``tpu``).
* ``local`` — numpy golden model of the same semantics, for tests.

Shared semantics (all backends, property-tested against each other):

* ``pull(state, slots) -> rows``: per-position row gather of the access
  method's pull-visible fields; ``slot == -1`` padding yields zero rows.
* ``push(state, slots, grads) -> state'``: duplicate slots' gradients are
  **summed**, then the access method's update is applied **once** per
  unique row.  ``slot == -1`` contributions are dropped.

The reference instead applies one sequential AdaGrad step per *worker* per
key (server.h:159-176) — order-dependent and racy (SURVEY.md §3.3).  The
sum-then-apply-once rule is the deliberate synchronous-SPMD semantic; the
async flavor is recovered at the model layer by taking several local steps
between pushes.

Within-worker mean normalization (the reference's ``grad /= count`` at
serialization, word2vec.h:120-132) stays the caller's job via
``LocalParamCache.normalized_grads`` or the models' count scaling.
"""

from __future__ import annotations

from typing import Optional

import jax

from swiftmpi_tpu.parameter.access import AccessMethod
from swiftmpi_tpu.parameter.sparse_table import TableState
from swiftmpi_tpu.utils.config import ConfigParser


@jax.tree_util.register_pytree_node_class
class PushSpec:
    """One gradient-family push: ``(slots, grads, mean)``.

    A pytree whose ``mean``/``dense`` flags are static aux data, so a
    jitted function taking pushes as an argument (e.g. the async
    snapshot mode's ``jit(apply_fn)(state, pushes)``) sees concrete
    Python bools, not traced scalars.  Iterates like the plain 3-tuple
    it replaces.

    ``dense=True`` marks grads that are ALREADY capacity-shaped and
    normalized (e.g. the dense-logits w2v mode computes the h-grad as
    a (capacity, d) matmul output): the apply step feeds them straight
    to the access method, skipping the transfer's scatter/dedup —
    ``slots`` is unused and should be None.

    ``counts`` (non-None) marks a POSITION-INDEXED span family (the
    stencil w2v rendering): each row already carries the sum of its
    window-overlap contributions and ``counts[i]`` says how many, so
    ``mean`` normalization needs the data counts rather than
    1-per-row, and the apply step routes through the sort-free
    ``push_span`` dedup instead of the generic sorted push."""

    def __init__(self, slots, grads, mean: bool = False,
                 dense: bool = False, counts=None):
        self.slots = slots
        self.grads = grads
        self.mean = bool(mean)
        self.dense = bool(dense)
        self.counts = counts

    def __iter__(self):
        return iter((self.slots, self.grads, self.mean))

    def tree_flatten(self):
        return (self.slots, self.grads, self.counts), (self.mean, self.dense)

    @classmethod
    def tree_unflatten(cls, aux, children):
        mean, dense = aux
        return cls(children[0], children[1], mean, dense, children[2])


class Transfer:
    """Backend interface: pure device-level pull/push."""

    name: str = "?"

    def pull(self, state: TableState, slots, access: AccessMethod,
             fields=None) -> TableState:
        """Gather rows for ``slots``.  ``fields`` restricts the pull to a
        subset of ``access.pull_fields`` — a caller whose slot groups
        need different fields (w2v: h for targets, v for contexts)
        splits its pulls rather than gathering every field for every
        slot and discarding half the bytes."""
        raise NotImplementedError

    def push(self, state: TableState, slots, grads: TableState,
             access: AccessMethod, mean: bool = False) -> TableState:
        """Apply ``grads`` at ``slots``.  ``mean=True`` divides each
        unique key's gradient sum by its contribution count before the
        access rule runs — the reference's ``grad /= count``
        normalization at push serialization (word2vec.h:120-132,
        lr.cpp:32-38), folded into the backend's own dedup pass.  Doing
        it here instead of pre-scaling each contribution saves a
        capacity-sized scatter + a batch-sized gather + a (B, d)
        multiply per push on the worker side (measured at ~25% of the
        w2v step, docs/ARCHITECTURE.md), and matches the reference's
        sum-then-divide order of operations bit-for-bit."""
        raise NotImplementedError


def get_transfer(name: Optional[str] = None,
                 config: Optional[ConfigParser] = None,
                 **kwargs) -> Transfer:
    """Resolve a backend by name or by the ``[cluster] transfer`` config key
    (the BASELINE.json ``transfer=tpu`` flag)."""
    if name is None:
        if config is not None and config.has("cluster", "transfer"):
            name = config.get("cluster", "transfer").to_string()
        else:
            name = "xla"
    if name == "xla":
        from swiftmpi_tpu.transfer.xla import XlaTransfer
        return XlaTransfer(**kwargs)
    if name == "tpu":
        from swiftmpi_tpu.transfer.tpu import TpuTransfer
        return TpuTransfer(**kwargs)
    if name == "hybrid":
        from swiftmpi_tpu.transfer.hybrid import HybridTransfer
        return HybridTransfer(**kwargs)
    if name == "local":
        from swiftmpi_tpu.transfer.local import LocalTransfer
        return LocalTransfer(**kwargs)
    raise ValueError(f"unknown transfer backend {name!r} "
                     "(expected xla|tpu|hybrid|local)")
