"""Transfer layer: the pull/push data plane, with backend selection.

This is the TPU-native replacement for the reference's entire RPC stack —
``Transfer``/``Listener``/``Route`` over ZeroMQ plus the
``GlobalPullAccess::pull_with_barrier`` / ``GlobalPushAccess::
push_with_barrier`` clients (`/root/reference/src/transfer/transfer.h:86-241`,
`/root/reference/src/parameter/global_pull_access.h:28-43`,
`global_push_access.h:26-43`).  Per the BASELINE north star, the interface
survives and the wire disappears: a backend is selected by the ``transfer``
config key and turns pull/push into XLA collectives.

Backends:

* ``xla``   — gather/scatter with sharding constraints; XLA chooses the
              collectives.  Works under any mesh (or none).  Default.
* ``tpu``   — explicit SPMD routing via ``shard_map``: keys are bucketed by
              owning shard, ``all_to_all`` ships requests over ICI, owners
              gather/apply locally, ``all_to_all`` ships rows back.  The
              literal TPU translation of the reference pull/push RPC
              (SURVEY.md §3.2-3.3) on a 1-D ``shard`` mesh.
* ``hybrid`` — Zipf-aware composition: frequency-hot rows replicated on
              every device and reconciled with one dense ``psum`` per
              push, cold-tail rows through the ``tpu`` routing above
              (transfer/hybrid.py; requires a ``HotColdPartition`` on
              the KeyIndex to be more than an alias of ``tpu``).
* ``local`` — numpy golden model of the same semantics, for tests.

Shared semantics (all backends, property-tested against each other):

* ``pull(state, slots) -> rows``: per-position row gather of the access
  method's pull-visible fields; ``slot == -1`` padding yields zero rows.
* ``push(state, slots, grads) -> state'``: duplicate slots' gradients are
  **summed**, then the access method's update is applied **once** per
  unique row.  ``slot == -1`` contributions are dropped.

The reference instead applies one sequential AdaGrad step per *worker* per
key (server.h:159-176) — order-dependent and racy (SURVEY.md §3.3).  The
sum-then-apply-once rule is the deliberate synchronous-SPMD semantic; the
async flavor is recovered at the model layer by taking several local steps
between pushes.

Within-worker mean normalization (the reference's ``grad /= count`` at
serialization, word2vec.h:120-132) stays the caller's job via
``LocalParamCache.normalized_grads`` or the models' count scaling.
"""

from __future__ import annotations

from typing import Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu import obs
from swiftmpi_tpu.parameter.access import AccessMethod
from swiftmpi_tpu.parameter.sparse_table import ROWVER_KEY, TableState
from swiftmpi_tpu.utils.config import ConfigParser


def bump_row_versions(out, state, safe_rows):
    """Device twin of the row-version bump (delta-pull plane): stamp
    the touched rows of the ``@rowver`` plane past the array's current
    max — per-shard monotonic with no host counter, since inside a
    ``shard_map`` the max runs over the local shard slice.  ``out`` is
    the post-apply state dict being built; ``safe_rows`` may carry
    out-of-bounds padding (``== capacity``), which drops.  A no-op
    (and trace-identical) when the plane is absent — the static dict
    check keeps ``pull_cache: off`` programs untouched.  Every push
    apply path MUST route its touched rows through here (or the local
    oracle's numpy twin): the PullCache's version-exact hit contract
    depends on it."""
    if ROWVER_KEY not in state:
        return out
    ver = state[ROWVER_KEY]
    newv = jnp.max(ver) + jnp.int32(1)
    out[ROWVER_KEY] = ver.at[safe_rows].set(newv, mode="drop")
    return out


def grad_row_bytes(grads, with_index: bool = True,
                   with_counts: bool = False) -> int:
    """Wire bytes per pushed row: the grad fields' widths at their dtypes,
    plus an int32 index in the sparse representation and an f32 counts
    column when a span family ships data counts.  One shared formula so
    every backend's ``wire_bytes`` counter measures the same thing."""
    total = 4 if with_index else 0
    for g in grads.values():
        g = jnp.asarray(g)
        total += int(np.dtype(g.dtype).itemsize) * int(g.shape[-1])
    if with_counts:
        total += 4
    return total


def quant_grad_row_bytes(grads, quant: str,
                         with_counts: bool = False) -> int:
    """Encoded wire bytes per pushed row under the ``sparse_q`` format:
    the int32 index survives, each grad field ships its values quantized
    — int8 (1 byte/element plus a 4-byte per-(row, field) scale bucket)
    or bf16 (2 bytes/element, no scale) — and the counts column, when a
    span family ships one, stays f32.  The sparse_q twin of
    :func:`grad_row_bytes`, used both by the crossover model and by the
    ledger's encoded-size booking."""
    if quant not in ("int8", "bf16"):
        raise ValueError(f"quant_grad_row_bytes: unknown quant {quant!r}")
    total = 4
    for g in grads.values():
        d = int(jnp.asarray(g).shape[-1])
        total += d + 4 if quant == "int8" else 2 * d
    if with_counts:
        total += 4
    return total


def quantize_dequantize(g, quant: str):
    """Round-trip one grad block through the ``sparse_q`` value encoding
    (what the receiver would reconstruct): ``int8`` scales each bucket
    (last axis) by max|g|/127 and rounds symmetrically; ``bf16`` is a
    dtype round-trip.  Always returns f32 — the quantization lives in
    the VALUES; downstream routing/apply is unchanged, which is what
    keeps the format decision bit-path-exact outside the documented
    envelope."""
    g = jnp.asarray(g, jnp.float32)
    if quant == "bf16":
        return g.astype(jnp.bfloat16).astype(jnp.float32)
    if quant != "int8":
        raise ValueError(f"quantize_dequantize: unknown quant {quant!r}")
    scale = jnp.max(jnp.abs(g), axis=-1, keepdims=True) * (1.0 / 127.0)
    safe = jnp.where(scale > 0, scale, 1.0)
    q = jnp.clip(jnp.round(g / safe), -127.0, 127.0)
    return q * jnp.where(scale > 0, scale, 0.0)


# -- numerics health tap (obs/numerics.py, ISSUE 13) ------------------------
# When the numerics plane is armed it installs a tap here; every
# EF/quantize path (ef_quantize_window for xla/tpu/hybrid, the local
# oracle's numpy twin) books its pre-vs-post quantization error
# sum-of-squares through it.  None (the default) traces NOTHING extra,
# which is what keeps `[obs] numerics: off` bit-identical — callers
# must rebuild/retrace their jitted steps when arming or clearing.
_NUMERICS_TAP = None


def set_numerics_tap(fn) -> None:
    global _NUMERICS_TAP
    _NUMERICS_TAP = fn


def clear_numerics_tap() -> None:
    set_numerics_tap(None)


def numerics_quant_err(err_sq) -> None:
    """Book one quantized window's error sum-of-squares (traced tracer
    or eager scalar) into the armed numerics tap; no-op when off."""
    tap = _NUMERICS_TAP
    if tap is not None:
        tap(err_sq)


def ef_quantize_window(state, ded_slots, ded_grads, capacity: int,
                       quant: str, trace_backend: Optional[str] = None):
    """Error-feedback quantize of one deduped window: drain each touched
    slot's residual into its gradient sum, quantize-dequantize, and
    store the new per-slot quantization error back into the ``<f>@ef``
    residual planes.  Returns ``(state', grads')`` with the residual
    planes replaced and the grads dequantized (f32, ready for the
    unchanged routing/apply path).  Fields without an ``@ef`` plane in
    ``state`` pass through untouched.

    Written to be correct under the tpu backend's DEVICE-LOCAL dedup,
    where the same slot can survive as owner in several devices' batch
    slices: the residual is drained into the globally FIRST occurrence
    only (representative trick over the full flattened batch), and the
    write-back is clear-then-scatter-ADD, which commutes under
    duplicates — the EF identity sum(applied_deq) + residual' ==
    sum(true grads) + residual holds exactly per slot either way.
    Plain traced jnp ops on the global arrays (GSPMD routes them), so
    the same code serves the xla oracle and the tpu/hybrid windows."""
    from swiftmpi_tpu.parameter.sparse_table import ef_name

    ded_slots = jnp.asarray(ded_slots, jnp.int32)
    B = ded_slots.shape[0]
    valid = ded_slots >= 0
    pos = jnp.arange(B, dtype=jnp.int32)
    safe = jnp.where(valid, ded_slots, capacity)
    rep = jnp.full((capacity + 1,), B, jnp.int32).at[safe].min(
        jnp.where(valid, pos, B), mode="drop")
    first = valid & (jnp.take(rep, safe) == pos)
    touched = jnp.zeros((capacity,), jnp.bool_).at[safe].set(
        True, mode="drop")
    gather_idx = jnp.clip(safe, 0, capacity - 1)
    out_state = dict(state)
    out_grads = dict(ded_grads)
    err_sq = None
    # tracer armed at trace time adds two |.|-sum reads per EF field —
    # pure reads, values untouched; same rebuild-to-arm contract as the
    # numerics tap above
    tracer = obs.get_tracer()
    drained = rebanked = None
    for f, g in ded_grads.items():
        efk = ef_name(f)
        if efk not in state:
            continue
        ef = state[efk]
        g = jnp.asarray(g, jnp.float32)
        res = jnp.take(ef, gather_idx, axis=0) * first[:, None]
        tot = g + res
        deq = quantize_dequantize(tot, quant) * valid[:, None]
        err = (tot - deq) * valid[:, None]
        cleared = ef * (~touched)[:, None]
        out_state[efk] = cleared.at[safe].add(err, mode="drop")
        out_grads[f] = deq
        if _NUMERICS_TAP is not None:
            fsq = jnp.sum(err ** 2)
            err_sq = fsq if err_sq is None else err_sq + fsq
        if tracer is not None:
            dsum = jnp.sum(jnp.abs(res))
            esum = jnp.sum(jnp.abs(err))
            drained = dsum if drained is None else drained + dsum
            rebanked = esum if rebanked is None else rebanked + esum
    if err_sq is not None:
        numerics_quant_err(err_sq)
    if tracer is not None and drained is not None:
        from functools import partial
        cb = partial(tracer.stage_ef, trace_backend or "?")
        if isinstance(drained, jax.core.Tracer):
            jax.debug.callback(cb, drained, rebanked)
        else:
            cb(float(drained), float(rebanked))
    return out_state, out_grads


def pull_row_bytes(state, fields) -> int:
    """Wire bytes per pulled row: int32 request index plus the pulled
    fields' widths at the table's stored dtypes.  The pull-side twin of
    :func:`grad_row_bytes` so ``pull_bytes`` means the same thing on
    every backend."""
    total = 4
    for f in fields:
        arr = state[f]
        total += int(np.dtype(arr.dtype).itemsize) * int(arr.shape[-1])
    return total


def quant_pull_row_bytes(state, fields, quant: str) -> int:
    """Encoded wire bytes per pulled row under the quantized pull
    formats: the int32 request index survives, each field ships its
    values int8 (1 byte/element plus a 4-byte per-(row, field) scale —
    the PR-10 delta codec's scheme, transfer/delta.py) or bf16 (2
    bytes/element, no scale).  The pull-side twin of
    :func:`quant_grad_row_bytes`, used by the pull pricer
    (transfer/plan.price_pull_formats) and the ledger's encoded
    booking — note a 1-wide int8 field prices at 4+1+4 = 9 > 8 bytes
    and correctly loses to ``full_f32``."""
    if quant not in ("int8", "bf16"):
        raise ValueError(f"quant_pull_row_bytes: unknown quant {quant!r}")
    total = 4
    for f in fields:
        d = int(state[f].shape[-1])
        total += d + 4 if quant == "int8" else 2 * d
    return total


@jax.tree_util.register_pytree_node_class
class PushSpec:
    """One gradient-family push: ``(slots, grads, mean)``.

    A pytree whose ``mean``/``dense`` flags are static aux data, so a
    jitted function taking pushes as an argument (e.g. the async
    snapshot mode's ``jit(apply_fn)(state, pushes)``) sees concrete
    Python bools, not traced scalars.  Iterates like the plain 3-tuple
    it replaces.

    ``dense=True`` marks grads that are ALREADY capacity-shaped and
    normalized (e.g. the dense-logits w2v mode computes the h-grad as
    a (capacity, d) matmul output): the apply step feeds them straight
    to the access method, skipping the transfer's scatter/dedup —
    ``slots`` is unused and should be None.

    ``counts`` (non-None) marks a POSITION-INDEXED span family (the
    stencil w2v rendering): each row already carries the sum of its
    window-overlap contributions and ``counts[i]`` says how many, so
    ``mean`` normalization needs the data counts rather than
    1-per-row, and the apply step routes through the sort-free
    ``push_span`` dedup instead of the generic sorted push."""

    def __init__(self, slots, grads, mean: bool = False,
                 dense: bool = False, counts=None):
        self.slots = slots
        self.grads = grads
        self.mean = bool(mean)
        self.dense = bool(dense)
        self.counts = counts

    def __iter__(self):
        return iter((self.slots, self.grads, self.mean))

    def tree_flatten(self):
        return (self.slots, self.grads, self.counts), (self.mean, self.dense)

    @classmethod
    def tree_unflatten(cls, aux, children):
        mean, dense = aux
        return cls(children[0], children[1], mean, dense, children[2])


class Transfer:
    """Backend interface: pure device-level pull/push."""

    name: str = "?"

    # -- wire traffic ledger (shared by every backend) ---------------------
    # ``wire_bytes`` counts push-side exchange PAYLOAD bytes (sparse:
    # valid rows x grad_row_bytes; dense: capacity x row bytes) and
    # ``dispatches`` the number of push-side exchanges — pulls are
    # ledgered separately (``pull_bytes``/``pull_rows``), so a window
    # that coalesces W pushes into one exchange shows a W-fold dispatch
    # drop regardless of the pull schedule.
    # Counting is off until ``count_traffic`` is set (one extra reduce
    # per push otherwise).  The counts are data-dependent under jit, so
    # the same tracer/eager discipline as the tpu backend's overflow
    # counter applies: traced values are staged via jax.debug.callback
    # (fires per compiled execution), eager device scalars queue and
    # materialize in :meth:`traffic`.

    def _wire_state(self) -> dict:
        st = self.__dict__.get("_wire_ledger")
        if st is None:
            st = self.__dict__["_wire_ledger"] = {
                "wire_bytes": 0, "dispatches": 0,
                "window_sparse": 0, "window_dense": 0,
                "window_fmt_dense": 0, "window_fmt_sparse": 0,
                "window_fmt_q": 0, "window_fmt_bitmap": 0,
                "window_fmt_sketch": 0,
                "collective_psum": 0, "collective_sparse_ar": 0,
                "hot_psum_bytes_saved": 0,
                "plan_compiles": 0, "plan_cache_hits": 0,
                "coalesced_rows_in": 0, "coalesced_rows_out": 0,
                "pull_bytes": 0, "pull_rows": 0, "pull_hot_rows": 0,
                "pull_cache_hits": 0, "pull_delta_rows": 0,
                "pull_bytes_saved": 0,
                "pull_fmt_full": 0, "pull_fmt_bf16": 0, "pull_fmt_q": 0,
                "pending": [], "pull_pending": [],
                "pull_hot_pending": []}
        return st

    #: decision string -> fine-grained format counter.  The legacy
    #: 2-way counters keep counting (dense -> window_dense, everything
    #: sparse-shaped -> window_sparse) so pre-4-way dashboards and
    #: goldens stay valid; the fmt counters record which format WON.
    _WINDOW_FMT_KEY = {"dense": "window_fmt_dense",
                       "sparse": "window_fmt_sparse",
                       "sparse_q": "window_fmt_q",
                       "bitmap": "window_fmt_bitmap",
                       "sparse_sketch": "window_fmt_sketch"}

    def _obs_inc(self, key: str, n, **labels) -> None:
        """Mirror a ledger increment into the telemetry registry as
        ``transfer/<key>{backend=<name>, **labels}``.  Telemetry off
        costs one branch; handles are cached per instance and re-fetched
        if the global registry was swapped (tests reset it)."""
        reg = obs.get_registry()
        if not reg.enabled:
            return
        cache = self.__dict__.get("_obs_cache")
        if cache is None or cache[0] is not reg:
            cache = self.__dict__["_obs_cache"] = (reg, {})
        ck = (key,) + tuple(sorted(labels.items())) if labels else key
        c = cache[1].get(ck)
        if c is None:
            # the one legit dynamic transfer/ name: TELEMETRY-CATALOG
            # validates `key` at every _obs_inc call site instead
            c = cache[1][ck] = reg.counter(  # smtpu-lint: disable=TELEMETRY-CATALOG
                "transfer/" + key, backend=self.name, **labels)
        c.inc(n)

    def _count_decision(self, st: dict, decision: str) -> None:
        """Book one window's wire-format decision: the legacy 2-way
        counter plus the 4-way ``window_fmt_*`` split, mirrored as a
        single fmt-labeled telemetry series
        ``transfer/window_fmt{backend=, fmt=}``."""
        legacy = "window_dense" if decision == "dense" else "window_sparse"
        st[legacy] += 1
        self._obs_inc(legacy, 1)
        fmt_key = self._WINDOW_FMT_KEY[decision]
        st[fmt_key] += 1
        self._obs_inc("window_fmt", 1,
                      fmt=fmt_key[len("window_fmt_"):])

    #: pull-format decision -> ledger counter (the pull family's
    #: sibling of ``_WINDOW_FMT_KEY``), mirrored as the fmt-labeled
    #: telemetry series ``transfer/pull_fmt{backend=, fmt=}``.
    _PULL_FMT_KEY = {"full_f32": "pull_fmt_full",
                     "bf16": "pull_fmt_bf16",
                     "sparse_q": "pull_fmt_q"}

    def _count_pull_decision(self, decision: str) -> None:
        """Book one pull's wire-format decision.  Host-side eager like
        :meth:`_count_collective` — the decision is plan-static per
        compiled pull program, so this fires once per ``pull`` CALL
        (trace time under jit), mirroring when the plan decision itself
        is made.  Only armed pulls reach here: with ``pull_quant`` and
        ``pull_cache`` both off the pull never compiles a plan and the
        ledger stays byte-for-byte the legacy one."""
        if not getattr(self, "count_traffic", False):
            return
        key = self._PULL_FMT_KEY[decision]
        self._wire_state()[key] += 1
        self._obs_inc("pull_fmt", 1, fmt=key[len("pull_fmt_"):])

    #: collective decision -> ledger counter (the dense/hot reconcile's
    #: sibling of ``_WINDOW_FMT_KEY``), mirrored as the kind-labeled
    #: telemetry series ``transfer/collective{backend=, kind=}``.
    _COLLECTIVE_KEY = {"psum": "collective_psum",
                       "psum_scatter": "collective_psum",
                       "sparse_allreduce": "collective_sparse_ar"}

    def _count_collective(self, collective: str) -> None:
        """Book one reconcile's collective decision.  Host-side eager —
        the decision is plan-static per compiled window program, and
        this fires once per push_window CALL (trace time under jit),
        mirroring when the plan decision itself is made."""
        if not getattr(self, "count_traffic", False):
            return
        key = self._COLLECTIVE_KEY[collective]
        self._wire_state()[key] += 1
        self._obs_inc("collective", 1, kind=key[len("collective_"):])

    def _accum_saved(self, nbytes) -> None:
        st = self._wire_state()
        st["hot_psum_bytes_saved"] += int(nbytes)
        self._obs_inc("hot_psum_bytes_saved", int(nbytes))

    def _record_saved(self, nbytes) -> None:
        """Record the wire bytes a sparse-allreduce reconcile saved over
        the dense collective it replaced (``dense model - booked``);
        traced values land via callback, same discipline as
        :meth:`_record_exchange`."""
        if not getattr(self, "count_traffic", False):
            return
        if isinstance(nbytes, jax.core.Tracer):
            jax.debug.callback(self._accum_saved, nbytes)
        else:
            self._accum_saved(nbytes)

    def _accum_wire(self, row_bytes, rows, ndisp: int = 1,
                    decision: Optional[str] = None,
                    base_bytes: int = 0) -> None:
        st = self._wire_state()
        nbytes = int(rows) * int(row_bytes) + int(base_bytes)
        st["wire_bytes"] += nbytes
        st["dispatches"] += ndisp
        self._obs_inc("wire_bytes", nbytes)
        self._obs_inc("dispatches", ndisp)
        if decision:
            self._count_decision(st, decision)
        # wire-tracing plane (obs/trace.py): the tracer reads the SAME
        # host landing point the ledger books through, so its records
        # agree with the counters by construction and arming it changes
        # nothing in the traced program
        tr = obs.get_tracer()
        if tr is not None:
            tr.on_exchange(self.name, int(rows), int(row_bytes),
                           base_bytes=int(base_bytes), decision=decision)

    def _record_exchange(self, rows, row_bytes: int,
                         decision: Optional[str] = None,
                         base_bytes: int = 0) -> None:
        """Record one push exchange of ``rows`` (traced or eager count)
        at ``row_bytes`` per row, plus ``base_bytes`` of per-exchange
        overhead independent of the row count (the bitmap format's
        capacity/8-byte occupancy mask)."""
        if not getattr(self, "count_traffic", False):
            return
        from functools import partial
        cb = partial(self._accum_wire, int(row_bytes), decision=decision,
                     base_bytes=int(base_bytes))
        if isinstance(rows, jax.core.Tracer):
            jax.debug.callback(cb, rows)
        elif obs.get_tracer() is not None:
            # armed tracer: land eagerly (program order) so the window
            # state machine attributes bytes to the RIGHT open record —
            # the batching queue would park this exchange past the next
            # window's open.  Ledger totals are identical either way.
            self._accum_wire(int(row_bytes), rows, decision=decision,
                             base_bytes=int(base_bytes))
        else:
            st = self._wire_state()
            st["pending"].append((int(row_bytes), rows, decision,
                                  int(base_bytes)))
            if len(st["pending"]) >= 1024:
                pending, st["pending"] = st["pending"], []
                for rb, r, d, bb in pending:
                    self._accum_wire(rb, r, decision=d, base_bytes=bb)

    def _accum_pull(self, row_bytes, rows) -> None:
        st = self._wire_state()
        nbytes = int(rows) * int(row_bytes)
        st["pull_bytes"] += nbytes
        st["pull_rows"] += int(rows)
        self._obs_inc("pull_bytes", nbytes)
        self._obs_inc("pull_rows", int(rows))

    def _record_pull(self, rows, row_bytes: int) -> None:
        """Record one pull exchange of ``rows`` (traced or eager count)
        at ``row_bytes`` per row.  ``row_bytes == 0`` still counts rows
        — the hybrid backend's hot hits are local replica reads that
        ship nothing but should show up in ``pull_rows`` so hit ratios
        can be derived from the ledger alone."""
        if not getattr(self, "count_traffic", False):
            return
        from functools import partial
        cb = partial(self._accum_pull, int(row_bytes))
        if isinstance(rows, jax.core.Tracer):
            jax.debug.callback(cb, rows)
        else:
            st = self._wire_state()
            st["pull_pending"].append((int(row_bytes), rows))
            if len(st["pull_pending"]) >= 1024:
                pending, st["pull_pending"] = st["pull_pending"], []
                for rb, r in pending:
                    self._accum_pull(rb, r)

    def _accum_pull_hot(self, rows) -> None:
        st = self._wire_state()
        st["pull_hot_rows"] += int(rows)
        self._obs_inc("pull_hot_rows", int(rows))

    def _record_pull_hot(self, rows) -> None:
        """Record ``rows`` pull hits answered by a local replica (the
        hybrid backend's hot head).  These rows are INCLUDED in
        ``pull_rows`` (so row totals stay comparable across backends)
        but ship zero wire bytes; this explicit series lets miss-ratio
        math separate replica hits from actually-shipped tail rows
        instead of inferring it from ``pull_bytes == 0`` rows."""
        if not getattr(self, "count_traffic", False):
            return
        if isinstance(rows, jax.core.Tracer):
            jax.debug.callback(self._accum_pull_hot, rows)
        else:
            st = self._wire_state()
            st["pull_hot_pending"].append(rows)
            if len(st["pull_hot_pending"]) >= 1024:
                pending, st["pull_hot_pending"] = \
                    st["pull_hot_pending"], []
                for r in pending:
                    self._accum_pull_hot(r)

    def _pull_shadow_get(self):
        """This worker's versioned :class:`~swiftmpi_tpu.transfer.
        pull_cache.PullCache` shadow, (re)built lazily when the
        ``pull_cache`` knob (line count) or the oracle mode moved.
        Host-side state — it never appears in a traced program, which
        is what keeps ``pull_cache`` a pure ledger/wire-model plane:
        a version-exact hit's cached row is bit-identical to the fresh
        gather, so device values need no splice."""
        from swiftmpi_tpu.transfer.pull_cache import PullCache
        sh = self.__dict__.get("_pull_shadow")
        lines = int(self.pull_cache)
        oracle = bool(self.pull_cache_oracle)
        if sh is None or sh.lines != lines or sh.store_rows != oracle:
            sh = self.__dict__["_pull_shadow"] = PullCache(
                lines, store_rows=oracle)
        return sh

    def pull_shadow_flush(self) -> None:
        """Drop every cached (slot, version) tag: the worker starts
        cold.  Called on membership changes and by the model's
        restore/resume path — a rewound table can re-issue version
        stamps, after which a warm cache could false-hit (the
        invalidation contract in transfer/pull_cache.py)."""
        sh = self.__dict__.get("_pull_shadow")
        if sh is not None:
            sh.flush()

    def _accum_pull_cached(self, val_bytes, full_row_bytes, capacity,
                           fields, slots, versions, *rows) -> None:
        """Host landing point for one watermarked pull execution: run
        the cache shadow over ``(slots, versions)`` and book the
        delta-pull wire model —

          request   8 bytes/valid row (int32 key + int32 watermark)
          response  ceil(valid/8) hit-bitmap bytes, plus the plan's
                    encoded value bytes per MISS row only

        against the ``full_row_bytes`` baseline the uncached wire
        would have booked; the difference lands on
        ``pull_bytes_saved``.  ``rows`` (oracle mode only) are the
        fresh field arrays the shadow value-checks hits against.
        Fires per compiled execution via ``jax.debug.callback`` —
        (slots, versions, rows) are gathered at one program point, so
        the shadow's stored (version, value) pairs are always mutually
        consistent even if the runtime reorders callbacks."""
        sh = self._pull_shadow_get()
        slots = np.asarray(slots).ravel()
        rowmap = dict(zip(fields, rows)) if rows else None
        hit = sh.lookup(slots, versions, int(capacity), rows=rowmap)
        n_valid = int((slots >= 0).sum())
        n_hit = int(hit.sum())
        n_miss = n_valid - n_hit
        booked = 8 * n_valid + (n_valid + 7) // 8 + n_miss * int(val_bytes)
        saved = max(0, n_valid * int(full_row_bytes) - booked)
        st = self._wire_state()
        st["pull_bytes"] += booked
        st["pull_rows"] += n_valid
        st["pull_cache_hits"] += n_hit
        st["pull_delta_rows"] += n_miss
        st["pull_bytes_saved"] += saved
        self._obs_inc("pull_bytes", booked)
        self._obs_inc("pull_rows", n_valid)
        self._obs_inc("pull_cache_hits", n_hit)
        self._obs_inc("pull_delta_rows", n_miss)
        self._obs_inc("pull_bytes_saved", saved)

    def _accum_coalesce(self, decision, rows_in, rows_out) -> None:
        st = self._wire_state()
        st["coalesced_rows_in"] += int(rows_in)
        st["coalesced_rows_out"] += int(rows_out)
        self._obs_inc("coalesced_rows_in", int(rows_in))
        self._obs_inc("coalesced_rows_out", int(rows_out))
        if decision:
            self._count_decision(st, decision)
            tr = obs.get_tracer()
            if tr is not None:
                # a decision-carrying dedup opens this backend's window
                # record; the following exchange callback seals it
                tr.on_window(self.name, decision, int(rows_in),
                             int(rows_out))

    def _record_coalesce(self, rows_in, rows_out,
                         decision: Optional[str] = None) -> None:
        """Record one window's pre-exchange dedup (rows before/after) and
        its wire-format decision; fires per compiled execution under an
        outer trace, same discipline as :meth:`_record_exchange`."""
        if not getattr(self, "count_traffic", False):
            return
        from functools import partial
        cb = partial(self._accum_coalesce, decision)
        if isinstance(rows_in, jax.core.Tracer) \
                or isinstance(rows_out, jax.core.Tracer):
            jax.debug.callback(cb, rows_in, rows_out)
        else:
            self._accum_coalesce(decision, rows_in, rows_out)

    def wire_traffic(self) -> Dict[str, int]:
        """Cumulative wire ledger (flushes traced callbacks and queued
        eager scalars): ``wire_bytes``, ``dispatches``, the window
        path's ``window_sparse``/``window_dense`` decision counts plus
        ``coalesced_rows_in``/``coalesced_rows_out`` (rows before/after
        the per-window dedup), and the pull side's
        ``pull_bytes``/``pull_rows``.

        Reset semantics (contract for all backends, enforced by
        tests/test_telemetry.py): every value is a **monotonically
        non-decreasing total** over the Transfer instance's lifetime.
        There is no reset method on purpose — a reader wanting
        per-interval numbers snapshots twice and subtracts (exactly what
        the telemetry StepRecorder does with the registry mirror of
        these counters).  Calling this method never perturbs the
        ledger."""
        jax.effects_barrier()
        st = self._wire_state()
        pending, st["pending"] = st["pending"], []
        for rb, r, d, bb in pending:
            self._accum_wire(rb, r, decision=d, base_bytes=bb)
        pulls, st["pull_pending"] = st["pull_pending"], []
        for rb, r in pulls:
            self._accum_pull(rb, r)
        hots, st["pull_hot_pending"] = st["pull_hot_pending"], []
        for r in hots:
            self._accum_pull_hot(r)
        return {k: v for k, v in st.items()
                if k not in ("pending", "pull_pending",
                             "pull_hot_pending")}

    def traffic(self) -> Dict[str, int]:
        """Cumulative traffic counters; every backend reports at least
        the wire ledger so cross-backend goldens compare like with
        like.  Backends with routed/hot paths extend this dict.

        Same contract as :meth:`wire_traffic`: monotonic totals, no
        reset, deltas are the caller's job.  The identical numbers are
        mirrored live into the telemetry registry as
        ``transfer/<key>{backend=<name>}`` counters (when telemetry is
        on), so per-step deltas come from ``telemetry.jsonl`` without
        ever calling this (and without its ``jax.effects_barrier``)."""
        return self.wire_traffic()

    def traffic_delta(self, since: Optional[Dict[str, int]] = None
                      ) -> Dict[str, int]:
        """Per-interval traffic: :meth:`traffic` minus an earlier
        snapshot ``since`` (itself a ``traffic()`` return value).

        This is the helper side of the monotonic-totals contract: the
        ledger never resets, so interval numbers are always
        snapshot-and-subtract — done HERE once instead of hand-rolled
        at every call site.  ``since=None`` (or a key absent from
        ``since``, e.g. a snapshot taken before a counter existed)
        subtracts zero, so the result degrades to the totals."""
        cur = self.traffic()
        if not since:
            return cur
        return {k: v - since.get(k, 0) for k, v in cur.items()}

    # -- elastic membership (ISSUE 16) -------------------------------------
    #: last adopted membership epoch; -1 = never told (static world).
    #: Class-level DEFAULTS — the guarded mutation path is
    #: :meth:`on_membership` only.
    _membership_epoch = -1
    _live_ranks: Optional[Tuple[int, ...]] = None

    def on_membership(self, epoch: int, live_ranks) -> None:
        """Adopt an elastic membership change (cluster/membership.py):
        the world's live-rank set or shard ownership moved, so anything
        this backend compiled or estimated against the old world shape
        is suspect.  Raises
        :class:`~swiftmpi_tpu.cluster.membership.StaleEpochError` if
        ``epoch`` regresses below what was already adopted (acting on a
        stale world view is the split-brain the epoch protocol
        prevents); adopting the SAME epoch again is a no-op, so every
        component in a process can be told independently.  Backends
        override :meth:`_membership_changed` to invalidate their
        compiled caches — the base books the epoch and mirrors the
        change into telemetry."""
        from swiftmpi_tpu.cluster.membership import StaleEpochError
        epoch = int(epoch)
        if epoch < self._membership_epoch:
            raise StaleEpochError(
                f"{self.name}: membership epoch {epoch} regressed "
                f"below adopted {self._membership_epoch}")
        if epoch == self._membership_epoch:
            return
        # epoch-guard: regression raised StaleEpochError above — the
        # membership state below only ever moves forward
        self._membership_epoch = epoch
        self._live_ranks = tuple(int(r) for r in live_ranks)
        self._obs_inc("membership_changes", 1)
        # shard ownership moved: cached (slot, version) tags describe
        # rows that may now live elsewhere — start cold
        self.pull_shadow_flush()
        self._membership_changed()

    def _membership_changed(self) -> None:
        """Backend hook, called once per adopted epoch: drop whatever
        was specialized to the old world shape.  Default: nothing (a
        backend with no world-shaped state)."""

    # -- wire-format decision hook ----------------------------------------
    #: post-dedup unique-row estimate for the window crossover (set by
    #: the model from the vocab histogram; retuned online by the
    #: control plane).  None = use the raw pre-dedup row count.
    window_expected_unique = None

    #: value quantization for the window push's sparse formats:
    #: ``"off"`` (default — 2-way decision, bit-identical to the
    #: pre-quantization wire) | ``"int8"`` | ``"bf16"``.  Set from
    #: ``[cluster] wire_quant`` by the model, which also arms the
    #: ``@ef`` residual planes; flipping it mid-run requires a step
    #: rebuild (the decision is baked at trace time).
    wire_quant = "off"

    #: safety factor pricing the lossy rung: ``sparse_q`` wins only
    #: when its volume times this still beats the best lossless format
    #: (key_index.window_wire_format).  Raise toward 2.0 to keep
    #: quantization off marginal windows, lower toward 1.0 to compress
    #: aggressively.  Host-side like the dense ratio — takes effect on
    #: the next decision.
    wire_quant_guard = 1.25

    #: value quantization for the pull wire (``transfer.plan.
    #: PULL_QUANT_MODES``): ``"off"`` (default — pulls ship ``full_f32``
    #: and stay bit-identical to the legacy wire) | ``"int8"`` (the
    #: ``sparse_q`` rung, PR-10 codec scheme) | ``"bf16"``.  Set from
    #: ``[cluster] pull_quant``.  Quantized pulls perturb the FORWARD
    #: READ, not the server state, so parity holds to the PR-10
    #: trajectory envelope rather than bit-exactness.
    pull_quant = "off"

    #: safety factor pricing the encoded pull rungs: an encoded format
    #: wins only when its volume times this still beats ``full_f32``
    #: (transfer.plan.price_pull_formats).  Same semantics and default
    #: as ``wire_quant_guard``.
    pull_quant_guard = 1.25

    #: versioned pull-cache size in LINES (direct-mapped,
    #: transfer/pull_cache.py); 0 = off.  Set from ``[cluster]
    #: pull_cache``.  Arming requires the table's row-version plane
    #: (``SparseTable.ensure_row_versions``) — the model arms both
    #: together.  The cache is a host-side wire-model shadow: values
    #: are unchanged by construction, only the pull ledger moves.
    pull_cache = 0

    #: test-only oracle mode: the shadow stores actual row values and
    #: asserts cached == fresh on every version-exact hit — proving
    #: every apply path bumps its rows' versions.
    pull_cache_oracle = False

    #: arm the ``sparse_sketch`` wire rung (transfer/sketch.py):
    #: counting-sketch index compression between the ``bitmap`` and
    #: ``sparse`` rungs.  Lossless, so unlike ``wire_quant`` it needs no
    #: EF planes — but with both knobs off the decision stays the exact
    #: legacy 2-way (bit-identity guarantee), so arming requires the
    #: usual step rebuild.  Set from ``[cluster] wire_sketch``.
    wire_sketch = False

    #: collective selection mode for the dense/hot reconcile planes
    #: (``transfer.plan.COLLECTIVE_MODES``): ``"psum"`` (default — the
    #: legacy dense collective, bit-identical to the pre-PR wire),
    #: ``"sparse_allreduce"`` (pin the Ok-Topk split-and-exchange), or
    #: ``"auto"`` (price by touched-fraction crossover,
    #: key_index.price_hot_collectives).  Set from ``[cluster]
    #: collective``; flipping it mid-run requires a step rebuild (the
    #: collective is baked into the compiled reconcile).
    collective_mode = "psum"

    #: live hot-touch density signal for the ``auto`` crossover:
    #: expected fraction of the hot/dense capacity touched per window.
    #: Seeded by the model from the vocab histogram; retuned online by
    #: the Controller from the DecayedSketch's hot-touch counts.
    #: ``None`` = unknown → ``auto`` conservatively keeps the dense
    #: collective.
    hot_touched_fraction = None

    #: SparCML-style safety factor on the sparse collective: the dense
    #: collective wins while ``sparse_bytes * ratio >= dense_bytes``
    #: (sparse must beat dense by this margin to pay for its irregular
    #: index stream).  Host-side like wire_dense_ratio — takes effect
    #: on the next plan compile.
    sparse_ar_ratio = 2.0

    def _ratio_state(self) -> dict:
        st = self.__dict__.get("_wire_ratios")
        if st is None:
            st = self.__dict__["_wire_ratios"] = {}
        return st

    def wire_dense_ratio(self, family: Optional[str] = None) -> float:
        """Current sparse/dense crossover ratio for a push family
        (``None`` = the default family): dense wins when
        ``sparse_volume * ratio >= dense_volume``.  2.0 is the
        SparCML-derived seed default (see key_index.window_wire_format);
        the control plane retunes it per family at runtime."""
        st = self._ratio_state()
        return float(st.get(family, st.get(None, 2.0)))

    def set_wire_dense_ratio(self, ratio: float,
                             family: Optional[str] = None) -> None:
        """Set the crossover ratio (per ``family``, or the default when
        ``family=None``).  Takes effect on the NEXT decision — decisions
        are made host-side per call, so no recompile is needed."""
        self._ratio_state()[family] = float(ratio)

    def _window_plan(self, rows: int, capacity: int, row_bytes: int,
                     quant_row_bytes: Optional[int] = None,
                     family: Optional[str] = "window",
                     with_counts: bool = True):
        """Compile (or fetch) this instance's :class:`TrafficPlan` for
        one window shape (transfer/plan.py) and fire the plan's
        observation side-channels: compile/hit counters on the wire
        ledger, and — armed — the full candidate pricing on the wire
        tracer, so each runtime window record can say WHY its format
        won (obs/trace.py).  The on_decision tap fires per CALL, not
        per compile: trace streams see every window, cached or not."""
        from swiftmpi_tpu.transfer.plan import compile_window_plan
        plan, hit = compile_window_plan(
            self, int(rows), int(capacity), int(row_bytes),
            quant_row_bytes, with_counts, family=family)
        if getattr(self, "count_traffic", False):
            key = "plan_cache_hits" if hit else "plan_compiles"
            self._wire_state()[key] += 1
            self._obs_inc(key, 1)
        tr = obs.get_tracer()
        if tr is not None:
            tr.on_decision(self.name, plan.wire_format, plan.prices,
                           plan.rows, plan.capacity, plan.row_bytes,
                           quant=plan.quant)
        return plan

    def _pull_plan(self, rows: int, capacity: int, row_bytes: int,
                   quant_row_bytes: Optional[int] = None):
        """Compile (or fetch) this instance's :class:`PullPlan`
        (transfer/plan.py's ``compile_pull_plan``) — the pull sibling
        of :meth:`_window_plan`, with the same observation discipline:
        compile/hit counters on the wire ledger, the format decision
        on the ``pull_fmt`` counters, and the pricing evidence on the
        armed wire tracer (decision key ``pull_<format>`` so pulls
        don't collide with the window formats in the trace price
        cache)."""
        from swiftmpi_tpu.transfer.plan import compile_pull_plan
        plan, hit = compile_pull_plan(self, int(rows), int(capacity),
                                      int(row_bytes), quant_row_bytes)
        if getattr(self, "count_traffic", False):
            key = "plan_cache_hits" if hit else "plan_compiles"
            self._wire_state()[key] += 1
            self._obs_inc(key, 1)
        self._count_pull_decision(plan.wire_format)
        tr = obs.get_tracer()
        if tr is not None:
            tr.on_decision(self.name, "pull_" + plan.wire_format,
                           plan.prices, plan.rows, plan.capacity,
                           plan.row_bytes, quant=plan.quant)
        return plan

    def _hot_plan(self, n_hot: int, width_bytes: int):
        """Compile (or fetch) the hot-plane reconcile's
        :class:`TrafficPlan` (transfer/plan.py's ``compile_hot_plan``) —
        the hot sibling of :meth:`_window_plan`, with the same
        observation discipline: compile/hit counters on the wire ledger,
        and the collective's pricing evidence on the armed wire tracer
        (decision key ``hot_<collective>`` so hot rows don't collide
        with the window formats in the trace price cache)."""
        from swiftmpi_tpu.transfer.plan import compile_hot_plan
        plan, hit = compile_hot_plan(self, int(n_hot), int(width_bytes))
        if getattr(self, "count_traffic", False):
            key = "plan_cache_hits" if hit else "plan_compiles"
            self._wire_state()[key] += 1
            self._obs_inc(key, 1)
        tr = obs.get_tracer()
        if tr is not None:
            tr.on_decision(self.name, "hot_" + plan.collective,
                           plan.prices, plan.rows, plan.capacity,
                           plan.row_bytes)
        return plan

    def decide_wire_format(self, rows: int, capacity: int,
                           row_bytes: int,
                           family: Optional[str] = None,
                           quant_row_bytes: Optional[int] = None) -> str:
        """``"sparse" | "dense"`` — or, with ``wire_quant`` /
        ``wire_sketch`` armed and a ``quant_row_bytes`` estimate
        supplied, the full 5-way ``"sparse" | "dense" | "bitmap" |
        "sparse_q" | "sparse_sketch"`` — for one exchange of ``rows``
        candidate rows against a ``capacity``-row dense alternative.
        The ONE place the wire-format question is asked — call sites no
        longer read config/module constants directly, so the control
        plane can steer the crossover (ratio and expected-unique
        estimate) without touching compiled code.

        Thin shim over :meth:`_window_plan`: the pricing, caching and
        trace taps all live in the TrafficPlan compiler now; this keeps
        the historical ask-for-a-string entry point for the control
        plane and the calibration tools."""
        return self._window_plan(rows, capacity, row_bytes,
                                 quant_row_bytes=quant_row_bytes,
                                 family=family).wire_format

    def _trace_keys(self, ded_slots, cap_per_shard: Optional[int] = None,
                    n_shards: Optional[int] = None) -> None:
        """Ship a bounded strided reservoir of the surviving (deduped,
        ``-1``-padded) slot array — and, when the backend knows its
        ``slot // cap_per_shard`` owner mapping, the surviving-row count
        per destination shard — to the armed wire tracer.  Pure reads
        plus one host callback, added to the traced program only when a
        tracer with a key reservoir is installed at trace time (values
        are untouched, so trajectories stay bit-identical either way;
        arming mid-run requires the usual step rebuild)."""
        tr = obs.get_tracer()
        if tr is None or tr.keys <= 0:
            return
        from functools import partial
        ded_slots = jnp.asarray(ded_slots)
        B = int(ded_slots.shape[0])
        if B == 0:
            return
        stride = max(B // int(tr.keys), 1)
        sample = ded_slots[::stride][:int(tr.keys)]
        cb = partial(tr.stage_keys, self.name)
        shard_rows = None
        if cap_per_shard and n_shards:
            valid = ded_slots >= 0
            own = jnp.where(valid,
                            ded_slots // jnp.int32(cap_per_shard),
                            jnp.int32(n_shards))
            shard_rows = jnp.zeros((int(n_shards) + 1,), jnp.int32).at[
                own].add(1, mode="drop")[:int(n_shards)]
        if isinstance(sample, jax.core.Tracer) or \
                isinstance(shard_rows, jax.core.Tracer):
            if shard_rows is None:
                jax.debug.callback(cb, sample)
            else:
                jax.debug.callback(cb, sample, shard_rows)
        elif shard_rows is None:
            cb(np.asarray(sample))
        else:
            cb(np.asarray(sample), np.asarray(shard_rows))

    def pull(self, state: TableState, slots, access: AccessMethod,
             fields=None) -> TableState:
        """Gather rows for ``slots``.  ``fields`` restricts the pull to a
        subset of ``access.pull_fields`` — a caller whose slot groups
        need different fields (w2v: h for targets, v for contexts)
        splits its pulls rather than gathering every field for every
        slot and discarding half the bytes.

        This method is THE pull-family TrafficPlan interpreter (the
        single dispatch point the PLAN-DISPATCH lint rule pins, the
        pull sibling of :meth:`push_window`): it compiles a
        :class:`PullPlan` (transfer/plan.py) when the ``pull_quant`` /
        ``pull_cache`` knobs are armed and executes it over the
        backend's ONE structural primitive — :meth:`_prim_pull`, a
        plain masked row gather — with every ledger/cache/quant tap
        fired from HERE.  Backends never ask the pull-format question
        and never book the pull ledger.  With both knobs off the pull
        books and gathers exactly the legacy wire — bit-identical by
        construction."""
        from swiftmpi_tpu.transfer.plan import pull_route
        fields = tuple(fields or access.pull_fields)
        route = pull_route(self.name)
        if route.placement == "hot_split":
            return self._interpret_pull_hot_split(state, slots, access,
                                                  fields)
        return self._interpret_pull_flat(state, slots, fields)

    def _prim_pull(self, state: TableState, slots, fields) -> TableState:
        """Backend pull primitive: masked row gather of ``fields`` at
        ``slots`` (``-1`` padding yields zero rows), NO ledger booking
        and no format logic — the interpreter owns both.  Structural
        routing accounting (the tpu backend's routed-row and overflow
        counters) stays with the primitive, like the push executors'."""
        raise NotImplementedError

    def _interpret_pull_flat(self, state: TableState, slots,
                             fields) -> TableState:
        """Execute one pull on a ``flat`` route.  Armed, the plan's
        format prices the wire (encoded rungs round-trip the pulled
        values through :func:`quantize_dequantize` — the forward read
        perturbs, the server state does not) and the versioned cache
        shadow books the delta wire: the row-version plane rides the
        SAME routed gather as the value rows (the watermark protocol's
        4 bytes/row), then lands host-side via the ledger's callback
        discipline."""
        from swiftmpi_tpu.parameter.sparse_table import ROWVER_KEY
        from swiftmpi_tpu.transfer.plan import pull_route
        route = pull_route(self.name)
        capacity = next(iter(state.values())).shape[0]
        row_bytes = pull_row_bytes(state, fields)
        quant = self.pull_quant
        armed = quant != "off" or bool(self.pull_cache)
        if route.eager:
            slots_h = np.asarray(slots, np.int64)
            n_valid = int((slots_h >= 0).sum())
            if not armed:
                self._record_pull(n_valid, row_bytes)
                return self._prim_pull(state, slots, fields)
            qrb = (quant_pull_row_bytes(state, fields, quant)
                   if quant != "off" else None)
            plan = self._pull_plan(int(slots_h.size), capacity,
                                   row_bytes, qrb)
            cached = plan.cached and ROWVER_KEY in state
            if cached:
                out = self._prim_pull(state, slots,
                                      fields + (ROWVER_KEY,))
                vers = np.asarray(out.pop(ROWVER_KEY)).ravel()
                if self.count_traffic:
                    rows = (tuple(np.asarray(out[f]) for f in fields)
                            if self.pull_cache_oracle else ())
                    self._accum_pull_cached(
                        plan.wire_row_bytes - 4, row_bytes, capacity,
                        fields, slots_h.ravel(), vers, *rows)
            else:
                out = self._prim_pull(state, slots, fields)
                self._record_pull(n_valid, plan.wire_row_bytes)
            if plan.wire_format != "full_f32":
                for f in fields:
                    out[f] = np.asarray(
                        quantize_dequantize(out[f], plan.quant))
            return out
        slots_j = jnp.asarray(slots, jnp.int32)
        if not armed:
            self._record_pull(jnp.sum(slots_j >= 0), row_bytes)
            return self._prim_pull(state, slots_j, fields)
        qrb = (quant_pull_row_bytes(state, fields, quant)
               if quant != "off" else None)
        plan = self._pull_plan(int(slots_j.size), capacity, row_bytes,
                               qrb)
        cached = plan.cached and ROWVER_KEY in state
        if cached:
            out = self._prim_pull(state, slots_j,
                                  fields + (ROWVER_KEY,))
            vers = out.pop(ROWVER_KEY)
            if self.count_traffic:
                from functools import partial
                cb = partial(self._accum_pull_cached,
                             plan.wire_row_bytes - 4, row_bytes,
                             capacity, fields)
                rows = (tuple(out[f] for f in fields)
                        if self.pull_cache_oracle else ())
                if isinstance(slots_j, jax.core.Tracer) \
                        or isinstance(vers, jax.core.Tracer):
                    jax.debug.callback(cb, slots_j, vers, *rows)
                else:
                    cb(np.asarray(slots_j), np.asarray(vers),
                       *(np.asarray(r) for r in rows))
        else:
            out = self._prim_pull(state, slots_j, fields)
            self._record_pull(jnp.sum(slots_j >= 0),
                              plan.wire_row_bytes)
        if plan.wire_format != "full_f32":
            out = {f: quantize_dequantize(out[f], plan.quant)
                   for f in fields}
        return out

    def _interpret_pull_hot_split(self, state: TableState, slots, access,
                                  fields) -> TableState:
        """Execute the ``hot_split`` pull placement (hybrid): replica
        hits resolved from the local hot head at 0 bytes exactly as the
        legacy wire books them, tail rows re-based by ``-n_hot`` and
        re-interpreted through the tail backend's ``pull`` — so the
        tail's cache/quant/ledger compose exactly as they do
        standalone, and hot reads are never quantized (the replica is
        reconciled losslessly by the hot psum).  Uses the hybrid
        backend's structural primitives (``_pad_batch``,
        ``_split_state``, ``_n_hot``) — only reachable on routes
        declaring ``placement="hot_split"``."""
        slots = jnp.asarray(slots, jnp.int32)
        slots, _, _, B = self._pad_batch(slots)
        tail_state, hot_state = self._split_state(state)
        n_hot = self._n_hot(state)
        if n_hot == 0:
            out = self.tail.pull(tail_state, slots, access,
                                 fields=fields)
            return {f: v[:B] for f, v in out.items()}
        is_hot = (slots >= 0) & (slots < n_hot)
        tail_slots = jnp.where(slots >= n_hot, slots - n_hot, -1)
        out = self.tail.pull(tail_state, tail_slots, access,
                             fields=fields)
        n_hot_rows = jnp.sum(is_hot)
        if self.count_traffic:
            # replica hits ship nothing: rows counted, zero bytes —
            # the 0-byte hot booking the cross-backend goldens pin
            self._record_hot(n_hot_rows, 0)
            self._record_pull(n_hot_rows, 0)
            self._record_pull_hot(n_hot_rows)
        safe_hot = jnp.clip(slots, 0, n_hot - 1)
        return {
            f: jnp.where(is_hot[..., None],
                         jnp.take(hot_state[f], safe_hot, axis=0),
                         out[f])[:B]
            for f in fields}

    def push(self, state: TableState, slots, grads: TableState,
             access: AccessMethod, mean: bool = False) -> TableState:
        """Apply ``grads`` at ``slots``.  ``mean=True`` divides each
        unique key's gradient sum by its contribution count before the
        access rule runs — the reference's ``grad /= count``
        normalization at push serialization (word2vec.h:120-132,
        lr.cpp:32-38), folded into the backend's own dedup pass.  Doing
        it here instead of pre-scaling each contribution saves a
        capacity-sized scatter + a batch-sized gather + a (B, d)
        multiply per push on the worker side (measured at ~25% of the
        w2v step, docs/ARCHITECTURE.md), and matches the reference's
        sum-then-divide order of operations bit-for-bit."""
        raise NotImplementedError

    def push_window(self, state: TableState, slots, grads: TableState,
                    access: AccessMethod, mean: bool = False,
                    counts=None) -> TableState:
        """Window-coalesced push: ``slots`` is ``(W, B)``, ``grads``
        ``{f: (W, B, d)}``, ``counts`` (optional) ``(W, B)`` — W steps'
        pushes accumulated into one buffer and exchanged ONCE.

        Semantics are push's sum-then-apply-once rule extended across
        the window: every (step, position) contribution to a key is
        summed, ``mean=True`` divides by the TOTAL window contribution
        count, and the access rule runs once per unique row per window.
        At ``W == 1`` this is the flatten of a unit axis followed by the
        per-step ``push``/``push_span`` — bit-identical to the per-step
        path by construction, so every existing parity oracle applies.
        At ``W > 1`` the update differs from W sequential applies by the
        optimizer's window staleness (bounded by W-1 steps; envelope
        documented in docs/ARCHITECTURE.md "Window-coalesced push").

        This method is THE TrafficPlan interpreter (the single dispatch
        point the PLAN-DISPATCH lint rule pins): it compiles a plan
        (transfer/plan.py) per window family and executes it over the
        backend's primitives — ``_prim_window_dedup``, ``_prim_ef_drain``,
        ``_prim_window_exchange``, ``_push_window_dense`` — with every
        obs/trace/numerics tap fired from HERE.  Backends never ask the
        wire-format question and never branch on a format name.  W == 1
        windows (and local/xla windows with every compression knob off)
        take :meth:`_push_window_passthrough` untouched — bit-identical
        to the pre-plan wire by construction."""
        from swiftmpi_tpu.transfer.plan import window_route
        route = window_route(self.name)
        if route.eager:
            shaped = np.asarray(slots, np.int64)
        else:
            shaped = slots = jnp.asarray(slots, jnp.int32)
        if shaped.ndim < 2 or shaped.shape[0] == 1:
            return self._push_window_passthrough(state, slots, grads,
                                                 access, mean=mean,
                                                 counts=counts)
        armed = self.wire_quant != "off" or bool(self.wire_sketch)
        if not route.always_decide and not armed:
            return self._push_window_passthrough(state, slots, grads,
                                                 access, mean=mean,
                                                 counts=counts)
        # flatten the (W, B) window ONCE, in the route's element space
        if route.eager:
            flat = shaped.reshape(-1)
            fgrads = {}
            for f, g in grads.items():
                g = np.asarray(g, np.float32)
                fgrads[f] = g.reshape((-1,) + g.shape[2:])
            fcounts = None if counts is None else np.asarray(
                counts, np.float32).reshape(-1)
        else:
            flat = shaped.reshape(-1)
            fgrads = {f: jnp.asarray(g).reshape(
                (-1,) + jnp.asarray(g).shape[2:])
                for f, g in grads.items()}
            fcounts = None if counts is None else jnp.asarray(
                counts, jnp.float32).reshape(-1)
        if not route.counts_follow_data and fcounts is None:
            # oracle routes price and ship with_counts rows regardless
            # (legacy local/xla behavior, kept bit-identical)
            fcounts = (np.ones(flat.shape, np.float32) if route.eager
                       else jnp.ones(flat.shape, jnp.float32))
        if route.placement == "hot_split":
            return self._interpret_window_hot_split(
                state, flat, fgrads, fcounts, access, mean,
                counts_present=counts is not None)
        return self._interpret_window_flat(
            state, flat, fgrads, access, mean, fcounts,
            passthrough=(slots, grads, counts))

    def _push_window_passthrough(self, state: TableState, slots, grads,
                                 access: AccessMethod, mean: bool = False,
                                 counts=None) -> TableState:
        """The no-plan window executor: flatten and delegate to the
        per-step ``push``/``push_span``.  Taken for W == 1 windows on
        every backend and for whole W > 1 windows on the non-
        ``always_decide`` routes with all compression knobs off — the
        paths whose bit-identity the parity goldens pin."""
        slots = jnp.asarray(slots)
        flat = slots.reshape(-1)
        fgrads = {f: jnp.asarray(g).reshape((-1,) + jnp.asarray(g).shape[2:])
                  for f, g in grads.items()}
        if counts is not None:
            return self.push_span(state, flat, fgrads,
                                  jnp.asarray(counts).reshape(-1),
                                  access, mean=mean)
        return self.push(state, flat, fgrads, access, mean=mean)

    def _trace_shard_args(self, capacity: int) -> dict:
        """Keyword arguments the interpreter forwards to
        :meth:`_trace_keys` — a backend that knows its slot -> shard
        owner mapping (tpu) returns ``cap_per_shard``/``n_shards`` so
        window records carry the per-destination row split."""
        return {}

    def _prim_window_dedup(self, flat, fgrads, fcounts, capacity: int):
        """Backend dedup primitive: collapse repeated slots of the
        flattened window into their first occurrence, summing grads and
        counts.  Returns ``(ded_slots, ded_grads, ded_counts)`` — same
        leading shape with non-representatives marked ``-1`` (device
        routes) or compacted unique rows (the eager oracle).

        Default: the single-device representative trick (sort-free
        positional scatter-min over a (capacity+1,) plane — exactly the
        ``XlaTransfer.push_span`` machinery), which any one-program
        device backend can use as-is."""
        B = flat.shape[0]
        valid = flat >= 0
        pos = jnp.arange(B, dtype=jnp.int32)
        safe = jnp.where(valid, flat, capacity)
        rep = jnp.full((capacity + 1,), B, jnp.int32).at[safe].min(
            jnp.where(valid, pos, B), mode="drop")
        owner = jnp.where(valid, jnp.take(rep, safe), B)
        is_owner = valid & (owner == pos)
        ded_grads = {}
        for f, g in fgrads.items():
            g = jnp.asarray(g)
            ded_grads[f] = jnp.zeros_like(g).at[owner].add(
                g * valid[:, None].astype(g.dtype), mode="drop")
        ded_counts = jnp.zeros(fcounts.shape, jnp.float32).at[owner].add(
            fcounts * valid, mode="drop")
        return jnp.where(is_owner, flat, -1), ded_grads, ded_counts

    def _prim_ef_drain(self, state, ded_slots, ded_grads, capacity: int,
                       quant: str):
        """Backend EF primitive: drain residual planes into the deduped
        sums, quantize-dequantize the values, bank the new error.
        Returns ``(state', ded_grads')``.  The numerics/trace taps fire
        inside :func:`ef_quantize_window` (device twin) or the local
        oracle's numpy override — both under the interpreter's plan."""
        return ef_quantize_window(state, ded_slots, ded_grads, capacity,
                                  quant, trace_backend=self.name)

    def _prim_window_exchange(self, state, ded_slots, ded_grads,
                              ded_counts, access, mean: bool,
                              need_counts: bool, wire):
        """Backend exchange primitive for a deduped, encoded window:
        ship the surviving rows, booking the exchange at the plan's
        encoded ``(row_bytes, base_bytes)``.  Default: the span family
        (counts always ride — the oracle routes' legacy contract)."""
        return self.push_span(state, ded_slots, ded_grads, ded_counts,
                              access, mean=mean, _wire=wire)

    def _prim_sparse_allreduce(self, state, flat, fgrads, access,
                               mean: bool, fcounts):
        """Backend sparse-allreduce primitive: reconcile the window's
        touched-row (index, value) set into the full table — the
        ``sparse_allreduce`` collective of the window ``dense`` rung
        (Ok-Topk's split-and-exchange; see transfer/sparse_allreduce).
        Default: the single-program twin — scatter-add merge of
        duplicate indices + full-table apply, exactly what the
        reduce-scatter/allgather degenerates to on a one-program world
        (serves the xla backend and the base class).  Distributed
        backends override with the real exchange (tpu: the tiled
        ``psum_scatter`` already IS the balanced reduce-scatter landing
        each reduced slice on its sharded owner, so no allgather is
        needed — only the ledger booking differs from the dense
        collective there)."""
        from swiftmpi_tpu.transfer.sparse_allreduce import (merge_counts,
                                                            merge_rows)
        capacity = next(iter(state.values())).shape[0]
        dense = {f: merge_rows(flat, jnp.asarray(g), capacity)
                 for f, g in fgrads.items()}
        if mean:
            counts = (fcounts if fcounts is not None
                      else jnp.ones(flat.shape, jnp.float32))
            csum = merge_counts(flat, counts, capacity)
            inv = (1.0 / jnp.maximum(csum, 1.0))[:, None]
            dense = {f: a * inv for f, a in dense.items()}
        new_fields = access.apply_push(state, dense)
        out = dict(state)
        out.update(new_fields)
        ok = (flat >= 0) & (flat < capacity)
        return bump_row_versions(out, state,
                                 jnp.where(ok, flat, capacity))

    def _interpret_window_flat(self, state, flat, fgrads, access,
                               mean: bool, fcounts, pre_deduped=False,
                               passthrough=None):
        """Execute one compiled plan over a flattened W > 1 window.

        ``pre_deduped``: the rows already went through a unified-space
        dedup (the hybrid hot-split stage) — skip the dedup primitive
        and book a traced-zero coalesce so the decision still lands on
        this backend's ledger/trace.  ``passthrough``: the original
        ``(slots, grads, counts)`` triple, supplied by routes whose
        dense/sparse decisions execute as the legacy passthrough."""
        from swiftmpi_tpu.transfer.plan import window_route
        route = window_route(self.name)
        capacity = next(iter(state.values())).shape[0]
        with_counts = ((fcounts is not None) if route.counts_follow_data
                       else True)
        row_bytes = grad_row_bytes(fgrads, with_counts=with_counts)
        quant = self.wire_quant
        qrb = (quant_grad_row_bytes(fgrads, quant,
                                    with_counts=with_counts)
               if quant != "off" else None)
        plan = self._window_plan(flat.shape[0], capacity, row_bytes,
                                 quant_row_bytes=qrb, family="window",
                                 with_counts=with_counts)
        spec = plan.spec
        decision = plan.wire_format
        if decision == "dense" and route.always_decide:
            self._count_collective(plan.collective)
            if plan.collective == "sparse_allreduce":
                if getattr(self, "count_traffic", False):
                    from swiftmpi_tpu.transfer.sparse_allreduce import \
                        ROW_ID_BYTES
                    val_bytes = grad_row_bytes(fgrads, with_index=False,
                                               with_counts=mean)
                    # semantic sparse payload: touched (index, value)
                    # rows by occupancy — duplicate slots merge for
                    # free in the local scatter-add, so only unique
                    # rows pay wire (the booking the budget gate and
                    # price_hot_collectives both model)
                    valid = (flat >= 0) & (flat < capacity)
                    safe = jnp.where(valid, flat, capacity)
                    occ = jnp.zeros((capacity + 1,), jnp.int32).at[
                        safe].add(1, mode="drop")
                    touched = jnp.sum(occ[:capacity] > 0)
                    self._record_exchange(touched,
                                          ROW_ID_BYTES + val_bytes,
                                          decision="dense")
                    self._record_saved(
                        capacity * val_bytes
                        - touched * (ROW_ID_BYTES + val_bytes))
                return self._prim_sparse_allreduce(
                    state, flat, fgrads, access, mean, fcounts)
            if getattr(self, "count_traffic", False):
                # wire volume is the static table size, not the row
                # count — the `flat[0] * 0 + capacity` token keeps the
                # value traced so the callback fires once per compiled
                # execution
                self._record_exchange(
                    flat[0].astype(jnp.int32) * 0 + capacity,
                    grad_row_bytes(fgrads, with_index=False,
                                   with_counts=mean),
                    decision="dense")
            return self._push_window_dense(state, flat, fgrads, access,
                                           mean, fcounts)
        if not spec.dedup and not route.dedups_lossless:
            # oracle routes execute dense/sparse as the legacy
            # passthrough; the decision is still booked (traced zero
            # keeps the callback firing once per compiled execution)
            if route.eager:
                self._record_coalesce(0, 0, decision=decision)
            elif getattr(self, "count_traffic", False):
                zero = jnp.sum(flat >= 0) * 0
                self._record_coalesce(zero, zero, decision=decision)
            slots0, grads0, counts0 = passthrough
            return self._push_window_passthrough(
                state, slots0, grads0, access, mean=mean, counts=counts0)
        # dedup stage (plan taps: keys reservoir BEFORE the coalesce
        # callback opens the window record, obs/trace.py)
        if pre_deduped:
            ded_slots, ded_grads, ded_counts = flat, fgrads, fcounts
            self._trace_keys(ded_slots, **self._trace_shard_args(capacity))
            if getattr(self, "count_traffic", False):
                # the hot-split stage already logged the dedup row
                # deltas on its own ledger, but the wire decision is
                # made HERE — log it with zero row deltas
                zero = jnp.sum(flat >= 0) * 0
                self._record_coalesce(zero, zero, decision=decision)
        else:
            ded_slots, ded_grads, ded_counts = self._prim_window_dedup(
                flat, fgrads, fcounts, capacity)
            self._trace_keys(ded_slots, **self._trace_shard_args(capacity))
            if route.eager:
                self._record_coalesce(int((flat >= 0).sum()),
                                      int((ded_slots >= 0).sum()),
                                      decision=decision)
            elif getattr(self, "count_traffic", False):
                self._record_coalesce(jnp.sum(flat >= 0),
                                      jnp.sum(ded_slots >= 0),
                                      decision=decision)
        if spec.ef:
            state, ded_grads = self._prim_ef_drain(
                state, ded_slots, ded_grads, capacity, quant)
        # mean needs the original contribution multiplicities (dedup
        # collapsed them into ded_counts); plain sums need no counts at
        # all — pre-summing commutes with the owner-side segment sum
        need_counts = ((mean or with_counts) if route.counts_follow_data
                       else True)
        wire = (plan.spec.wire(ded_grads, quant, capacity, need_counts)
                if spec.encoded else None)
        return self._prim_window_exchange(state, ded_slots, ded_grads,
                                          ded_counts, access, mean,
                                          need_counts, wire)

    def _interpret_window_hot_split(self, state, flat, fgrads, fcounts,
                                    access, mean: bool,
                                    counts_present: bool):
        """Execute the ``hot_split`` placement (hybrid): pad, dedup ONCE
        in the unified slot space, reconcile the hot slice with the
        dense psum primitive, re-interpret the tail slice on the tail
        backend (``pre_deduped`` — the dedup pass is not paid twice).
        Uses the hybrid backend's structural primitives (``_pad_batch``,
        ``_split_state``, ``_hot_push``) — only reachable on routes
        declaring ``placement="hot_split"``."""
        from swiftmpi_tpu.parameter.sparse_table import hot_name
        flat, fgrads, fcounts, _ = self._pad_batch(flat, fgrads, fcounts)
        tail_state, hot_state = self._split_state(state)
        n_hot = self._n_hot(state)
        if n_hot == 0:
            return self.tail._interpret_window_flat(
                tail_state, flat, fgrads, access, mean, fcounts)
        cap_tail = next(iter(tail_state.values())).shape[0]
        ded_slots, ded_grads, ded_counts = self.tail._prim_window_dedup(
            flat, fgrads, fcounts, n_hot + cap_tail)
        if self.count_traffic:
            self._record_coalesce(jnp.sum(flat >= 0),
                                  jnp.sum(ded_slots >= 0))
        is_hot = (ded_slots >= 0) & (ded_slots < n_hot)
        tail_slots = jnp.where(ded_slots >= n_hot, ded_slots - n_hot, -1)
        # hot-plane TrafficPlan: the collective decision (psum vs
        # sparse_allreduce, transfer/plan.py compile_hot_plan) is made
        # HERE — backends only execute the primitive the plan names.
        # width_bytes includes the f32 counts column (+4), which is
        # also the sparse wire's per-row index cost, so the same number
        # prices both collectives
        width_bytes = sum(
            np.dtype(jnp.asarray(g).dtype).itemsize * g.shape[1]
            for g in ded_grads.values()) + 4
        hot_plan = self._hot_plan(n_hot, width_bytes)
        sparse_ar = hot_plan.collective == "sparse_allreduce"
        self._count_collective(hot_plan.collective)
        # stage the hot/tail split for the wire tracer under the TAIL's
        # name: the tail backend owns the decision-carrying window
        # record this callback's extras attach to (obs/trace.py)
        tr = obs.get_tracer()
        if tr is not None:
            hot_rows = jnp.sum(is_hot)
            cb = (lambda v, _tr=tr, _n=self.tail.name,
                  _c=hot_plan.collective:
                  _tr.stage(_n, hot_rows=int(v), hot_collective=_c))
            if isinstance(hot_rows, jax.core.Tracer):
                jax.debug.callback(cb, hot_rows)
            else:
                cb(hot_rows)
        # mean normalization now depends on the collapsed
        # multiplicities, so both slices take the counts wire format
        need_counts = mean or counts_present
        new_tail = self.tail._interpret_window_flat(
            tail_state, tail_slots, ded_grads, access, mean,
            ded_counts if need_counts else None, pre_deduped=True)
        if sparse_ar:
            if self.count_traffic:
                # semantic sparse payload: ded_slots hold one
                # representative per slot PER SHARD (the tpu dedup is
                # device-local), so the hot mask sum is exactly the sum
                # of per-shard contributed (index, value) sets — the
                # volume each shard feeds the reduce-scatter — with the
                # delta vs the dense model landing on
                # hot_psum_bytes_saved
                touched = jnp.sum(is_hot)
                self._record_hot_sparse(touched, width_bytes)
                self._record_exchange(touched, width_bytes)
                self._record_saved((n_hot - touched) * width_bytes)
            new_hot = self._hot_push_sparse(
                hot_state, ded_slots, ded_grads, access, mean,
                ded_counts if need_counts else None)
        else:
            if self.count_traffic:
                self._record_hot(jnp.sum(is_hot), n_hot * width_bytes)
                self._record_exchange(jnp.sum(is_hot) * 0 + n_hot,
                                      width_bytes)
            new_hot = self._hot_push(hot_state, ded_slots, ded_grads,
                                     access, mean,
                                     ded_counts if need_counts else None)
        out = dict(new_tail)
        out.update({hot_name(f): v for f, v in new_hot.items()})
        return out


def get_transfer(name: Optional[str] = None,
                 config: Optional[ConfigParser] = None,
                 **kwargs) -> Transfer:
    """Resolve a backend by name or by the ``[cluster] transfer`` config key
    (the BASELINE.json ``transfer=tpu`` flag)."""
    if name is None:
        if config is not None and config.has("cluster", "transfer"):
            name = config.get("cluster", "transfer").to_string()
        else:
            name = "xla"
    if name == "xla":
        from swiftmpi_tpu.transfer.xla import XlaTransfer
        return XlaTransfer(**kwargs)
    if name == "tpu":
        from swiftmpi_tpu.transfer.tpu import TpuTransfer
        return TpuTransfer(**kwargs)
    if name == "hybrid":
        from swiftmpi_tpu.transfer.hybrid import HybridTransfer
        return HybridTransfer(**kwargs)
    if name == "local":
        from swiftmpi_tpu.transfer.local import LocalTransfer
        return LocalTransfer(**kwargs)
    raise ValueError(f"unknown transfer backend {name!r} "
                     "(expected xla|tpu|hybrid|local)")
