"""TrafficPlan compiler: ONE pricing/dispatch table for the transfer stack.

ROADMAP item 4: the transfer matrix grew to four backends x three
renderings x window coalescing x a 5-way wire x EF x numerics/trace
taps, every plane threaded through per-call-site conditionals — PRs 13,
15 and 17 each had to touch all four backends again.  This module is
the fix: every window push now compiles an explicit :class:`TrafficPlan`
(placement, dedup stage, wire format, quantization/EF, observation
taps) from calibration + the live knobs, and ONE interpreter —
``Transfer.push_window`` in :mod:`swiftmpi_tpu.transfer.api` — executes
it over backend *primitives*.  The backends (local/xla/tpu/hybrid) keep
only structural primitives (dedup kernels, dense psum programs, the
hot-psum, routed push/push_span executors); they never ask the
wire-format question, never branch on a format name, and never fire an
obs/trace/numerics tap for the window path.  The PLAN-DISPATCH lint
rule (analysis/rules.py) pins that invariant statically.

Adding a wire format is now a table edit here plus a codec module —
the ``sparse_sketch`` rung (transfer/sketch.py) landed exactly that
way: one :data:`FORMAT_TABLE` row, one pricer term
(parameter/key_index.py), zero backend edits.

The compile step is cached per pricing signature — every input that
can change the decision (rows, capacity, row bytes, quant mode and
row-byte estimate, the sketch knob, the per-family dense ratio, the
expected-unique hint, the quant guard) is part of the key, so a
Controller knob apply (e.g. ``wire_format`` retuning
``window_expected_unique``) re-prices plans on the next window with no
invalidation protocol.  Compiles and cache hits are booked on the
ledger (``transfer/plan_compiles`` / ``transfer/plan_cache_hits``).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Optional, Tuple

from swiftmpi_tpu.transfer.sketch import OFFSET_BYTES, sketch_base_bytes

#: the wire-format ladder, cheapest-machinery first.  Every decision
#: the pricer can return appears here; the interpreter refuses to
#: execute a format this table doesn't know.
WIRE_FORMATS = ("dense", "sparse", "bitmap", "sparse_q", "sparse_sketch")

#: the collective ladder for the dense/hot reconcile planes (ISSUE 19):
#: ``psum`` (hybrid hot head, full replicated buffer), ``psum_scatter``
#: (window dense rung, capacity-shaped tiles) and ``sparse_allreduce``
#: (transfer/sparse_allreduce.py — touched (index, value) rows through
#: Ok-Topk's split-and-exchange).  Every ``TrafficPlan.collective`` the
#: compiler can emit appears here.
COLLECTIVES = ("psum", "psum_scatter", "sparse_allreduce")

#: legal values of the ``collective`` knob (``[cluster] collective:``):
#: ``psum`` pins the dense collectives (bit-identical legacy wire),
#: ``sparse_allreduce`` pins the sparse collective wherever a plan has
#: one, ``auto`` prices the crossover per plan from the live hot-touch
#: density signal.
COLLECTIVE_MODES = ("psum", "auto", "sparse_allreduce")

#: the pull-wire ladder (ISSUE 20): ``full_f32`` ships rows at their
#: stored dtype (the legacy wire, 4-byte key + field bytes per row),
#: ``bf16`` halves the value payload, ``sparse_q`` ships int8 rows
#: with a per-row f32 scale — the PR-10 delta codec's scheme
#: (transfer/delta.py), applied to the server→worker direction.  Every
#: decision :func:`price_pull_formats` can return appears here; the
#: pull interpreter refuses a format this tuple doesn't know.
PULL_FORMATS = ("full_f32", "bf16", "sparse_q")

#: legal values of the ``[cluster] pull_quant`` knob; ``bf16``/``int8``
#: ARM the matching encoded rung, they don't pin it — the pricer still
#: has to clear the quantization-error guard before a pull leaves
#: ``full_f32``.
PULL_QUANT_MODES = ("off", "bf16", "int8")


@dataclass(frozen=True)
class WireFormatSpec:
    """One rung of the wire ladder: what the interpreter must DO for a
    window that chose this format.

    ``dedup``: the window must be globally deduplicated before the
    exchange (the encoded representations index *unique* rows).
    ``ef``: drain/re-bank error-feedback residuals around a lossy value
    encoding.  ``encoded``: the exchange is booked at encoded size via
    :meth:`wire` rather than the executor's default sparse row model.
    """

    name: str
    lossless: bool
    dedup: bool
    ef: bool
    encoded: bool

    def wire(self, grads, quant: str, capacity: int,
             with_counts: bool) -> Optional[Tuple[int, int]]:
        """``(row_bytes, base_bytes)`` the ledger books one exchange of
        this format at, or ``None`` for the executor's default model.
        Must agree with the pricer's byte models in
        ``parameter.key_index.price_window_formats`` — the goldens in
        tests/test_traffic_plan.py diff the two."""
        from swiftmpi_tpu.transfer.api import (grad_row_bytes,
                                               quant_grad_row_bytes)
        if self.name == "sparse_q":
            return (quant_grad_row_bytes(grads, quant,
                                         with_counts=with_counts), 0)
        if self.name == "bitmap":
            return (grad_row_bytes(grads, with_index=False,
                                   with_counts=with_counts),
                    capacity // 8)
        if self.name == "sparse_sketch":
            return (grad_row_bytes(grads, with_index=False,
                                   with_counts=with_counts)
                    + OFFSET_BYTES,
                    sketch_base_bytes(capacity))
        return None


#: name -> spec.  THE table a new wire format is added to.
FORMAT_TABLE: Dict[str, WireFormatSpec] = {
    "dense": WireFormatSpec("dense", lossless=True, dedup=False,
                            ef=False, encoded=False),
    "sparse": WireFormatSpec("sparse", lossless=True, dedup=False,
                             ef=False, encoded=False),
    "bitmap": WireFormatSpec("bitmap", lossless=True, dedup=True,
                             ef=False, encoded=True),
    "sparse_q": WireFormatSpec("sparse_q", lossless=False, dedup=True,
                               ef=True, encoded=True),
    "sparse_sketch": WireFormatSpec("sparse_sketch", lossless=True,
                                    dedup=True, ef=False, encoded=True),
}


@dataclass(frozen=True)
class WindowRoute:
    """Per-backend structural facts the interpreter composes a window
    plan from.  These describe what the backend's primitives ARE, not
    what the wire does — the wire half lives in :data:`FORMAT_TABLE`.

    ``eager``: primitives are host/numpy (the local oracle).
    ``always_decide``: the backend prices every W>1 window even with
    all compression knobs off (tpu/hybrid — their sparse/dense split
    exists regardless); unset, quant-off+sketch-off windows take the
    legacy flatten-and-delegate passthrough untouched (local/xla
    bit-identity).
    ``dedups_lossless``: the ``sparse`` decision still runs the
    backend's dedup primitive before the exchange (tpu/hybrid collapse
    repeats device-locally to cut routed rows; local/xla ship sparse
    windows through the passthrough).
    ``counts_follow_data``: the pricing row-byte model counts the f32
    counts column only when the family actually ships one (tpu/hybrid);
    unset, the oracle paths always price ``with_counts`` rows
    (local/xla legacy behavior, kept bit-identical).
    ``placement``: ``flat`` or ``hot_split`` (hybrid: replicated hot
    head reconciled by one dense psum, deduped tail re-interpreted on
    the tail backend).
    ``collective``: descriptive label of the sparse-path exchange
    primitive, carried into the plan for trace/debug dumps.
    """

    eager: bool = False
    always_decide: bool = False
    dedups_lossless: bool = False
    counts_follow_data: bool = False
    placement: str = "flat"
    collective: str = "gather_scatter"


#: backend name -> route.  THE table a new backend (or collective) is
#: added to.
WINDOW_ROUTES: Dict[str, WindowRoute] = {
    "local": WindowRoute(eager=True, collective="eager"),
    "xla": WindowRoute(collective="gather_scatter"),
    "tpu": WindowRoute(always_decide=True, dedups_lossless=True,
                       counts_follow_data=True, collective="all_to_all"),
    "hybrid": WindowRoute(always_decide=True, dedups_lossless=True,
                          counts_follow_data=True, placement="hot_split",
                          collective="psum+all_to_all"),
}


def window_route(backend: str) -> WindowRoute:
    try:
        return WINDOW_ROUTES[backend]
    except KeyError:
        raise KeyError(f"transfer.plan: backend {backend!r} has no "
                       "window route (add it to WINDOW_ROUTES)") from None


@dataclass(frozen=True)
class TrafficPlan:
    """One compiled window-push plan: every decision the interpreter
    needs, with the pricing evidence attached.  Frozen — a plan is a
    value; re-pricing produces a new plan under a new cache key."""

    family: str
    backend: str
    placement: str
    dedup: str                    # none | backend | pre_deduped
    wire_format: str
    quant: str                    # off | int8 | bf16 (value encoding)
    ef: bool
    collective: str
    taps: Tuple[str, ...]         # interpreter-owned observation taps
    rows: int
    capacity: int
    row_bytes: int
    quant_row_bytes: Optional[int]
    priced: Tuple[Tuple[str, float], ...]

    @property
    def prices(self) -> Dict[str, float]:
        return dict(self.priced)

    @property
    def spec(self) -> WireFormatSpec:
        return FORMAT_TABLE[self.wire_format]


_PLAN_CACHE: Dict[tuple, TrafficPlan] = {}
_PLAN_CACHE_MAX = 4096


def clear_plan_cache() -> None:
    _PLAN_CACHE.clear()


def compile_window_plan(transfer, rows: int, capacity: int,
                        row_bytes: int,
                        quant_row_bytes: Optional[int],
                        with_counts: bool,
                        family: Optional[str] = "window",
                        ) -> Tuple[TrafficPlan, bool]:
    """Compile (or fetch) the :class:`TrafficPlan` for one window shape
    on ``transfer``; returns ``(plan, cache_hit)``.

    The cache key carries EVERY pricing input, including the live
    knobs (``wire_quant``, ``wire_sketch``, the per-family dense ratio,
    ``window_expected_unique``, ``wire_quant_guard``) — a Controller
    apply that moves any of them re-prices on the very next window,
    which is how the ``wire_format`` knob "re-prices plans live"
    without an invalidation protocol."""
    from swiftmpi_tpu.parameter.key_index import price_window_formats
    quant = transfer.wire_quant if quant_row_bytes is not None else "off"
    sketch = bool(transfer.wire_sketch)
    dense_ratio = transfer.wire_dense_ratio(family)
    expected_unique = transfer.window_expected_unique
    guard = transfer.wire_quant_guard
    mode = _collective_mode(transfer)
    key = (transfer.name, family, int(rows), int(capacity),
           int(row_bytes), quant_row_bytes, quant, sketch, dense_ratio,
           expected_unique, guard, bool(with_counts),
           mode, transfer.hot_touched_fraction, transfer.sparse_ar_ratio)
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan, True
    decision, prices = price_window_formats(
        int(rows), int(capacity), int(row_bytes),
        dense_ratio=dense_ratio, expected_unique=expected_unique,
        quant=quant, quant_row_bytes=quant_row_bytes,
        quant_guard=guard, sketch=sketch)
    route = window_route(transfer.name)
    spec = FORMAT_TABLE[decision]
    dedup = ("backend" if spec.dedup
             or (route.dedups_lossless and decision == "sparse")
             else "none")
    taps = ("decision", "coalesce")
    if spec.dedup:
        taps += ("keys",)
    if spec.ef:
        taps += ("ef", "numerics")
    if decision == "dense":
        collective, coll_prices = _dense_rung_collective(
            transfer, mode, prices, int(capacity), int(row_bytes))
        prices = dict(prices, **coll_prices)
    else:
        collective = route.collective
    plan = TrafficPlan(
        family=family or "window", backend=transfer.name,
        placement=route.placement, dedup=dedup, wire_format=decision,
        quant=quant, ef=spec.ef,
        collective=collective,
        taps=taps, rows=int(rows), capacity=int(capacity),
        row_bytes=int(row_bytes), quant_row_bytes=quant_row_bytes,
        priced=tuple(sorted(prices.items())))
    if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
        _PLAN_CACHE.clear()
    _PLAN_CACHE[key] = plan
    return plan, False


def _collective_mode(transfer) -> str:
    """The transfer's ``collective`` knob value, validated against
    :data:`COLLECTIVE_MODES`.  ``psum`` (the class default) keeps every
    plan on its legacy dense collective — bit-identical wire."""
    mode = getattr(transfer, "collective_mode", "psum")
    if mode not in COLLECTIVE_MODES:
        raise ValueError(
            f"transfer.plan: unknown collective mode {mode!r} "
            f"(expected one of {COLLECTIVE_MODES})")
    return mode


def _dense_rung_collective(transfer, mode: str, prices, capacity: int,
                           row_bytes: int):
    """Collective for a window that DENSIFIED: the legacy capacity-
    shaped ``psum_scatter``, or ``sparse_allreduce`` when the knob pins
    it / the touched-fraction crossover prices the sparse exchange
    below the dense tiles.  The density signal for the flat dense rung
    is the pricer's own effective-unique estimate (``prices["sparse"]``
    already IS the sparse (index, value) volume over ``eff`` rows) —
    the collective can rescue a window densified by an aggressively
    tuned per-family dense ratio, at its own ``sparse_ar_ratio``
    guard.  Returns ``(collective, extra_prices)``."""
    if mode == "psum":
        return "psum_scatter", {}
    if mode == "sparse_allreduce":
        return "sparse_allreduce", {}
    from swiftmpi_tpu.parameter.key_index import price_hot_collectives
    eff_fraction = prices["sparse"] / (4.0 + row_bytes) / max(capacity, 1)
    decision, coll_prices = price_hot_collectives(
        capacity, row_bytes, eff_fraction,
        sparse_ar_ratio=transfer.sparse_ar_ratio)
    return ("sparse_allreduce" if decision == "sparse_allreduce"
            else "psum_scatter"), coll_prices


def compile_hot_plan(transfer, n_hot: int, width_bytes: int,
                     ) -> Tuple[TrafficPlan, bool]:
    """Compile (or fetch) the hot-plane reconcile plan for the hybrid
    backend's replicated head: ONE decision — ``collective`` in
    ``{psum, sparse_allreduce}`` — priced by the touched-fraction
    crossover (``parameter.key_index.price_hot_collectives``) from the
    live density signal ``transfer.hot_touched_fraction`` (seeded from
    the vocab histogram, retuned online via the Controller's
    ``collective`` knob — moving it lands a NEW cache key, so the next
    window re-prices with no invalidation protocol, exactly like the
    wire-format knobs).  Returns ``(plan, cache_hit)``."""
    mode = _collective_mode(transfer)
    fraction = transfer.hot_touched_fraction
    ratio = transfer.sparse_ar_ratio
    key = (transfer.name, "hot", int(n_hot), int(width_bytes),
           mode, fraction, ratio)
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan, True
    from swiftmpi_tpu.parameter.key_index import price_hot_collectives
    decision, prices = price_hot_collectives(
        int(n_hot), int(width_bytes), fraction, sparse_ar_ratio=ratio)
    if mode != "auto":
        decision = mode
    plan = TrafficPlan(
        family="hot", backend=transfer.name, placement="hot",
        dedup="pre_deduped", wire_format="dense", quant="off", ef=False,
        collective=decision, taps=("decision",),
        rows=int(round((fraction or 0.0) * n_hot)), capacity=int(n_hot),
        row_bytes=int(width_bytes), quant_row_bytes=None,
        priced=tuple(sorted(prices.items())))
    if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
        _PLAN_CACHE.clear()
    _PLAN_CACHE[key] = plan
    return plan, False


# -- the pull family (ISSUE 20) -------------------------------------------

@dataclass(frozen=True)
class PullRoute:
    """Per-backend structural facts for the PULL interpreter
    (``Transfer.pull`` in transfer/api.py), the mirror of
    :class:`WindowRoute` for the server→worker direction.

    ``eager``: the pull primitive is host/numpy (the local oracle) —
    the interpreter books the ledger and runs the cache shadow inline
    instead of through a traced callback.
    ``placement``: ``flat`` (one gather over the whole slot space) or
    ``hot_split`` (hybrid: replicated-head hits resolved locally at 0
    bytes, tail rows re-based by ``-n_hot`` and re-interpreted on the
    tail backend — so the tail's cache/quant/ledger compose exactly as
    they do standalone).
    """

    eager: bool = False
    placement: str = "flat"


#: backend name -> pull route.  THE table a new backend is added to.
PULL_ROUTES: Dict[str, PullRoute] = {
    "local": PullRoute(eager=True),
    "xla": PullRoute(),
    "tpu": PullRoute(),
    "hybrid": PullRoute(placement="hot_split"),
}


def pull_route(backend: str) -> PullRoute:
    try:
        return PULL_ROUTES[backend]
    except KeyError:
        raise KeyError(f"transfer.plan: backend {backend!r} has no "
                       "pull route (add it to PULL_ROUTES)") from None


@dataclass(frozen=True)
class PullPlan:
    """One compiled pull plan: the wire format the response rows ship
    in, whether the versioned cache is consulted, and the pricing
    evidence.  Frozen — a plan is a value; re-pricing lands a new plan
    under a new cache key, so knob moves need no invalidation
    protocol (same contract as :class:`TrafficPlan`)."""

    backend: str
    placement: str
    wire_format: str
    quant: str                    # off | int8 | bf16 (value encoding)
    cached: bool                  # versioned PullCache consulted
    rows: int
    capacity: int
    row_bytes: int                # full_f32 row bytes (4-byte key incl.)
    wire_row_bytes: int           # chosen format's row bytes
    priced: Tuple[Tuple[str, float], ...]

    @property
    def prices(self) -> Dict[str, float]:
        return dict(self.priced)


def price_pull_formats(rows: int, row_bytes: int,
                       quant: str = "off",
                       quant_row_bytes: Optional[int] = None,
                       quant_guard: float = 1.25):
    """The pull-format decision WITH its evidence: ``(decision,
    prices)`` over :data:`PULL_FORMATS`, the server→worker mirror of
    ``parameter.key_index.price_window_formats``.  The byte models:

      full_f32  ``rows * row_bytes``            (4-byte key + stored rows)
      bf16      ``rows * quant_row_bytes``      (key + 2 bytes/element)
      sparse_q  ``rows * quant_row_bytes``      (key + 1 byte/element
                                                 + 4-byte scale/field)

    With ``quant == "off"`` only ``full_f32`` is priced — the decision
    set itself records that no encoded rung was in play, and off-knob
    pulls stay bit-identical by construction.  An encoded rung wins
    only past the **quantization-error guard**: ``q_vol * quant_guard
    <= full_vol`` (default 1.25 — never perturb the forward read for a
    marginal byte win; a 1-wide int8 field prices at 9 > 8 bytes and
    correctly loses)."""
    full_vol = float(rows) * float(row_bytes)
    prices = {"full_f32": full_vol}
    if quant == "off" or quant_row_bytes is None:
        return "full_f32", prices
    fmt = "bf16" if quant == "bf16" else "sparse_q"
    q_vol = float(rows) * float(quant_row_bytes)
    prices[fmt] = q_vol
    if q_vol * quant_guard <= full_vol:
        return fmt, prices
    return "full_f32", prices


def compile_pull_plan(transfer, rows: int, capacity: int,
                      row_bytes: int,
                      quant_row_bytes: Optional[int],
                      ) -> Tuple[PullPlan, bool]:
    """Compile (or fetch) the :class:`PullPlan` for one pull shape on
    ``transfer``; returns ``(plan, cache_hit)``.  The key carries every
    pricing input — the live ``pull_quant`` / ``pull_quant_guard`` /
    ``pull_cache`` knobs included — so a Controller apply re-prices on
    the very next pull, exactly like the window plans."""
    quant = transfer.pull_quant if quant_row_bytes is not None else "off"
    if quant not in PULL_QUANT_MODES:
        raise ValueError(
            f"transfer.plan: unknown pull_quant mode {quant!r} "
            f"(expected one of {PULL_QUANT_MODES})")
    guard = transfer.pull_quant_guard
    cached = bool(transfer.pull_cache)
    key = (transfer.name, "pull", int(rows), int(capacity),
           int(row_bytes), quant_row_bytes, quant, guard, cached)
    plan = _PLAN_CACHE.get(key)
    if plan is not None:
        return plan, True
    decision, prices = price_pull_formats(
        int(rows), int(row_bytes), quant=quant,
        quant_row_bytes=quant_row_bytes, quant_guard=guard)
    route = pull_route(transfer.name)
    wire_rb = (int(row_bytes) if decision == "full_f32"
               else int(quant_row_bytes))
    plan = PullPlan(
        backend=transfer.name, placement=route.placement,
        wire_format=decision, quant=(quant if decision != "full_f32"
                                     else "off"),
        cached=cached, rows=int(rows), capacity=int(capacity),
        row_bytes=int(row_bytes), wire_row_bytes=wire_rb,
        priced=tuple(sorted(prices.items())))
    if len(_PLAN_CACHE) >= _PLAN_CACHE_MAX:
        _PLAN_CACHE.clear()
    _PLAN_CACHE[key] = plan
    return plan, False
