"""``xla`` transfer backend: gather/scatter, compiler-chosen collectives.

The idiomatic-JAX data plane: ``pull`` is a row gather, ``push`` is an
in-batch segment-sum dedup followed by a one-shot access-method update and a
row scatter.  Under ``jit`` over a mesh with the table row-sharded, XLA
lowers the gather/scatter to the appropriate ICI collectives — the same
traffic the explicit ``tpu`` backend spells out by hand, minus the manual
bucketing.  Everything here is shape-static and traceable.

Dedup-without-unique trick (XLA has no dynamic ``unique``): sort the batch
slots, segment-sum gradients into batch-local segments keyed by
sorted-adjacency, and scatter one combined update per segment.  Cost is
O(B log B + B·d) regardless of table capacity.

``dense_apply=True`` switches push to a full-table dense update (scatter the
summed grads into a (capacity, d) zero array, then apply the access method
to the whole table).  Untouched rows see zero grad and are bit-identical
no-ops for any sane access rule; this trades HBM bandwidth for zero scatter
irregularity and can win for small tables.
"""

from __future__ import annotations

import jax
import jax.numpy as jnp

from swiftmpi_tpu.ops import calibration, pallas_gather, pallas_scatter
from swiftmpi_tpu.transfer.api import (Transfer, bump_row_versions,
                                       grad_row_bytes)

# replica-spread scatter: cap the R-fold temporary at ~256MB so the
# measured-win gate can never OOM a large table's push
_REPLICA_BUDGET_BYTES = 256 << 20


def _replica_R(capacity: int, width: int) -> int:
    """Recorded replica factor for this device kind, bounded by the
    temporary-buffer budget; 0 = no win recorded (gate closed)."""
    v = calibration.lookup("replica_scatter", calibration.device_key()) \
        if calibration.on_tpu() else None
    R = int((v or {}).get("R", 0)) if (v or {}).get("win") else 0
    if R and R * capacity * width * 4 > _REPLICA_BUDGET_BYTES:
        return 0
    return R


def _masked_gather(arr: jax.Array, slots: jax.Array,
                   valid: jax.Array) -> jax.Array:
    # VMEM-resident Pallas gather when the on-chip A/B verdict says it
    # beats XLA's transaction-bound HBM gather (ops/pallas_gather.py;
    # absent a recorded win this branch never taken)
    if pallas_gather.use_vmem_gather(arr):
        return pallas_gather.masked_vmem_gather(arr, slots, valid)
    # clip: an out-of-range slot is a caller bug, but TPU OOB gather yields
    # garbage/NaN rather than trapping — clamp so it stays observable as a
    # wrong row, not as NaN contamination.
    safe = jnp.clip(jnp.where(valid, slots, 0), 0, arr.shape[0] - 1)
    rows = jnp.take(arr, safe, axis=0)
    return jnp.where(valid[:, None], rows, 0)


class XlaTransfer(Transfer):
    name = "xla"

    def __init__(self, dense_apply: bool | None = None):
        """``dense_apply``: True forces the dense full-table push, False
        forces the sort-based sparse push, None (default) picks per call —
        dense when the push batch is at least half the table capacity.
        At that point the sparse path's sort + per-row gather/scatter
        irregularity costs more than sweeping the table once (the
        crossover is measured in docs/ARCHITECTURE.md; word2vec-scale
        batches over demo-conf-scale tables land far on the dense side)."""
        self.dense_apply = dense_apply
        # wire ledger (api.py): XLA chooses the actual collectives, so
        # wire_bytes counts the representation-level payload — sparse:
        # valid rows x (index + grad row); dense: capacity x grad row
        self.count_traffic = False

    def _membership_changed(self) -> None:
        """Elastic membership (api.py): XLA keeps no compiled caches
        here (jit re-specializes on its own), but the expected-unique
        hint was derived from the OLD world's vocab-to-shard spread —
        clear it so the window crossover reverts to raw row counts
        until the model re-derives it for the new shape."""
        self.window_expected_unique = None

    # -- pull (global_pull_access.h:28-43 equivalent) ----------------------
    def _prim_pull(self, state, slots, fields):
        # structural gather only — the ledger/format/cache logic lives
        # in the base-class pull interpreter (api.Transfer.pull)
        slots = jnp.asarray(slots, jnp.int32)
        valid = slots >= 0
        return {f: _masked_gather(state[f], slots, valid)
                for f in fields}

    # -- push (global_push_access.h:26-43 + server.h:159-176) --------------
    def push(self, state, slots, grads, access, mean=False):
        slots = jnp.asarray(slots, jnp.int32)
        capacity = next(iter(state.values())).shape[0]
        dense = self.dense_apply
        if dense is None:
            # per-call compute crossover through the tunable decision
            # hook: dense once the batch reaches capacity/ratio rows.
            # The seed ratio 2.0 reproduces the measured
            # ``>= capacity // 2`` rule exactly (int(cap / 2.0) ==
            # cap // 2), keeping control-off trajectories bit-identical
            dense = slots.shape[0] >= int(
                capacity / self.wire_dense_ratio("push_apply"))
        if dense:
            self._record_exchange(
                capacity, grad_row_bytes(grads, with_index=False))
            return self._push_dense(state, slots, grads, access, mean)
        self._record_exchange(jnp.sum(slots >= 0), grad_row_bytes(grads))
        return self._push_sparse(state, slots, grads, access, mean)

    def _push_dense(self, state, slots, grads, access, mean=False):
        capacity = next(iter(state.values())).shape[0]
        valid = slots >= 0
        # OOB scatter indices are dropped by XLA; route padding there.
        safe = jnp.where(valid, slots, capacity)
        inv = None
        fuse_count = False
        if mean:
            # Single fp32 grad family: fold the contribution counts into
            # the grads scatter as one extra column — one scatter pass
            # over the batch instead of two.  (fp32 only: a bf16 count
            # column goes inexact past 256 occurrences of one key.)
            gs = list(grads.values())
            fuse_count = (len(gs) == 1
                          and jnp.asarray(gs[0]).dtype == jnp.float32)
            if not fuse_count:
                counts = jnp.zeros((capacity,), jnp.float32).at[safe].add(
                    1.0, mode="drop")
                inv = (1.0 / jnp.maximum(counts, 1.0))[:, None]
        def _scatter(g, width):
            # VMEM-resident Pallas scatter when the on-chip A/B verdict
            # says it beats XLA's (ops/pallas_scatter.py; never taken
            # without a recorded win)
            if pallas_scatter.use_vmem_scatter(capacity, width):
                return pallas_scatter.masked_vmem_scatter_add(
                    slots, valid, g, capacity)
            # replica-spread when the on-chip A/B crowned it (round-3:
            # the ~20x-duplicated w2v push serializes RMW chains; R
            # replica tables shorten chains R-fold, one streaming sum
            # folds them back; scripts/scatter_micro.py records the
            # verdict, gate closed without a win or past the budget)
            R = _replica_R(capacity, width)
            if R:
                lane = jax.lax.rem(
                    jnp.arange(g.shape[0], dtype=jnp.int32), R)
                acc = jnp.zeros((R, capacity, width), g.dtype).at[
                    lane, safe].add(g, mode="drop")
                return acc.sum(axis=0)
            acc = jnp.zeros((capacity, width), g.dtype)
            return acc.at[safe].add(g, mode="drop")

        dense_grads = {}
        for f in grads:
            g = jnp.asarray(grads[f])
            width = state[f].shape[1]
            if fuse_count:
                g1 = jnp.concatenate(
                    [g, jnp.ones((g.shape[0], 1), g.dtype)], axis=1)
                acc = _scatter(g1, width + 1)
                dense_grads[f] = acc[:, :width] / jnp.maximum(
                    acc[:, width:], 1.0)
            else:
                acc = _scatter(g, width)
                dense_grads[f] = acc * inv if mean else acc
        new_fields = access.apply_push(state, dense_grads)
        out = dict(state)
        out.update(new_fields)
        return bump_row_versions(out, state, safe)

    # -- span push (stencil rendering; see models/word2vec.py) -------------
    def push_span(self, state, slots, grads, counts, access, mean=False,
                  _wire=None):
        """Sort-free dedup push for POSITION-INDEXED span batches.

        ``_push_sparse`` must sort the batch before it can dedup
        (duplicate slots can sit anywhere in a gather-rendering push),
        and at the 1M-vocab bench shape that argsort of ~151K keys is
        the measured ~13ms push floor.  A stencil span batch has more
        structure: rows are indexed by stream position over a span of
        S = B + 2W tokens, every row already carries the SUM of its
        window-overlap contributions (the model folded those in a dense
        span-local scatter), and ``counts[i]`` says how many.  That
        admits an O(S·d + capacity) dedup with no sort at all:

          rep[k]   = min span position holding slot k — one scatter-min
                     into a (capacity,) int32 plane (~5MB at 1.3M rows)
          owner_i  = rep[slots_i]: every row learns its family head
          combined = scatter-add of grads/counts INTO owner rows — a
                     span-local (S, d) fold, not a capacity scatter
          apply    = gather current rows at owners, one access-method
                     update, scatter-set back (unique by construction)

        ``counts`` carries the per-row contribution multiplicities for
        ``mean=True``: the per-key divisor is the total pair count, the
        same quantity the sorted path derives from its segment sums, so
        normalization semantics match the generic push exactly.
        """
        slots = jnp.asarray(slots, jnp.int32)
        capacity = next(iter(state.values())).shape[0]
        S = slots.shape[0]
        valid = slots >= 0
        if _wire is not None:
            # window path shipping a compressed representation: book the
            # exchange at ENCODED size (see Transfer.push docstring)
            self._record_exchange(jnp.sum(valid), _wire[0],
                                  base_bytes=_wire[1])
        else:
            self._record_exchange(jnp.sum(valid),
                                  grad_row_bytes(grads, with_counts=True))
        safe = jnp.where(valid, slots, 0)
        pos = jnp.arange(S, dtype=jnp.int32)
        rep = jnp.full((capacity,), S, jnp.int32).at[safe].min(
            jnp.where(valid, pos, S))
        owner = jnp.where(valid, rep[safe], S)           # (S,) in [0, S]
        inv = None
        if mean:
            cnt = jnp.zeros((S,), jnp.float32).at[owner].add(
                jnp.asarray(counts, jnp.float32), mode="drop")
            inv = (1.0 / jnp.maximum(cnt, 1.0))[:, None]
        combined = {}
        for f in grads:
            g = jnp.asarray(grads[f])
            acc = jnp.zeros((S, g.shape[1]), g.dtype).at[owner].add(
                g, mode="drop")
            combined[f] = acc * inv if mean else acc
        is_owner = valid & (owner == pos)
        touched = access.touched_fields(grads)
        safe_own = jnp.where(is_owner, slots, 0)
        current = {f: jnp.take(state[f], safe_own, axis=0)
                   for f in touched}
        updated = access.apply_push(current, combined)
        out = dict(state)
        tgt = jnp.where(is_owner, slots, capacity)
        for f in updated:
            # owner rows hold distinct slots by construction (one owner
            # per table row); non-owners route OOB and drop.  The span
            # is position-ordered, not slot-ordered, so no
            # indices_are_sorted hint — uniqueness alone removes the
            # scatter's collision machinery.
            out[f] = state[f].at[tgt].set(
                updated[f], mode="drop", unique_indices=True)
        return bump_row_versions(out, state, tgt)

    # -- window-coalesced push ---------------------------------------------
    # No override: the base-class TrafficPlan interpreter
    # (api.Transfer.push_window) drives this backend's window path, and
    # the base `_prim_window_dedup` (single-device representative
    # trick) + `push_span` ARE this backend's primitives — the traced
    # single-device twin the parity tests diff the tpu/hybrid windows
    # against.  The same holds for `_prim_sparse_allreduce`: the base
    # class's single-program scatter-add merge + full-table apply
    # (transfer/sparse_allreduce.merge_rows) is exactly what Ok-Topk's
    # reduce-scatter/allgather degenerates to on one program, so this
    # backend inherits it unchanged.

    def _push_sparse(self, state, slots, grads, access, mean=False):
        capacity = next(iter(state.values())).shape[0]
        B = slots.shape[0]
        if B == 0:
            return dict(state)
        valid = slots >= 0
        # Sort so duplicates are adjacent; padding (-1 -> capacity) sorts
        # last and is dropped by OOB scatter below.
        sort_keys = jnp.where(valid, slots, capacity)
        order = jnp.argsort(sort_keys)
        sorted_slots = sort_keys[order]
        # Batch-local segment ids: bump at each new slot value.
        new_seg = jnp.concatenate([
            jnp.ones((1,), jnp.int32),
            (sorted_slots[1:] != sorted_slots[:-1]).astype(jnp.int32)])
        seg_ids = jnp.cumsum(new_seg) - 1  # (B,), in [0, B)
        # One representative slot per segment; unused segments -> capacity.
        rep_slots = jnp.full((B,), capacity, jnp.int32).at[seg_ids].set(
            sorted_slots, mode="drop")
        rep_valid = rep_slots < capacity
        safe_rep = jnp.where(rep_valid, rep_slots, 0)

        inv = None
        if mean:
            # seg_ids ascend (cumsum of non-negatives): tell XLA so the
            # scatter lowering can skip the general collision machinery
            seg_counts = jnp.zeros((B,), jnp.float32).at[seg_ids].add(
                valid[order].astype(jnp.float32), mode="drop",
                indices_are_sorted=True)
            inv = (1.0 / jnp.maximum(seg_counts, 1.0))[:, None]
        combined = {}
        for f in grads:
            g = jnp.asarray(grads[f])[order]
            width = g.shape[1]
            acc = jnp.zeros((B, width), g.dtype)
            acc = acc.at[seg_ids].add(g, mode="drop",
                                      indices_are_sorted=True)
            combined[f] = acc * inv if mean else acc

        # only the fields this push's grad families actually update are
        # gathered and re-scattered (a partial push must not round-trip
        # the untouched fields' rows through HBM for nothing)
        touched = access.touched_fields(grads)
        current = {f: jnp.take(state[f], safe_rep, axis=0) for f in touched}
        updated = access.apply_push(current, combined)

        out = dict(state)
        for f in updated:
            # Unused segments' representatives stay == capacity: OOB,
            # dropped.  rep_slots are ascending AND one-per-segment by
            # construction (duplicates exist only among the dropped
            # capacity-fill tail), so the scatter-set needs no collision
            # handling — the hints cut the large-capacity scatter cost
            # (the 1M-vocab step's measured bound).
            out[f] = state[f].at[rep_slots].set(
                updated[f], mode="drop", indices_are_sorted=True,
                unique_indices=True)
        return bump_row_versions(out, state, rep_slots)
