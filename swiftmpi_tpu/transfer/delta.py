"""Shared row-delta wire codec (PR-10 formats, one implementation).

Both cross-process row movers in the repo ship ``(keys, rows)`` sets as
one encoded npz priced through the SAME density crossover the window
push uses (:func:`~swiftmpi_tpu.parameter.key_index
.price_window_formats`):

* the elastic migration path (``mig_e<epoch>_*.npz`` /
  ``rows_r<rank>.npz``, :mod:`swiftmpi_tpu.cluster.elastic`), and
* the serving snapshot shipper (``ship_v<version>.npz``,
  :mod:`swiftmpi_tpu.serve.shipper`).

ISSUE 17 extracts the codec here so the two planes cannot drift: one
byte model, one quantization rule, one atomic-writer.  The public names
(:func:`encode_delta`, :func:`decode_delta`, :func:`delta_wire_bytes`,
:func:`atomic_savez`) are re-exported from ``cluster.elastic`` for the
PR-16 callers; new code should import from here.

Format menu (decision recorded in the payload's ``format`` scalar):

* ``sparse`` — f32 ``(key, row)`` pairs, lossless;
  ``eff * (4 + 4 + 4d)`` wire bytes.
* ``bitmap`` — packed occupancy mask over a dense position space +
  f32 values; ``capacity/8 + eff * 4d`` — only offered when the caller
  supplies dense ``positions`` (< capacity), e.g. table slots.
* ``sparse_q`` — int8 values + per-row f32 scale, lossy, gated by the
  pricing's ``quant_guard``; ``eff * (4 + 4 + d + 4)``.
* ``dense`` never ships from here: a *delta* by definition excludes
  untouched rows, so the dense decision demotes to ``sparse`` (a full
  snapshot is a different artifact — serve/shipper writes raw planes).
"""

from __future__ import annotations

import os
from typing import Dict, Tuple

import numpy as np

from swiftmpi_tpu.parameter.key_index import price_window_formats

__all__ = ["encode_delta", "decode_delta", "delta_wire_bytes",
           "atomic_savez"]


def encode_delta(keys, values, capacity: int, quant: str = "int8",
                 positions=None) -> Dict[str, np.ndarray]:
    """Encode a (keys, rows) delta for the wire, choosing the format
    with the SAME crossover pricing as the window push
    (key_index.price_window_formats): ``sparse`` (f32 pairs, lossless),
    ``bitmap`` (occupancy mask + packed values — only offered when the
    caller supplies dense ``positions`` < capacity), or ``sparse_q``
    (int8 values + per-row scale, lossy, guarded).  Returns the npz
    payload dict; ``wire_bytes`` is the modeled encoded size booked
    into the migration/shipping ledger."""
    keys = np.asarray(keys, np.int64).ravel()
    values = np.asarray(values, np.float32)
    if len(keys):
        values = values.reshape(len(keys), -1)
    else:
        # empty delta (a rank mid-rejoin owns nothing yet): keep the
        # trailing dim if the caller shaped one, else 1 — reshape(0, -1)
        # is ambiguous on size-0 arrays
        values = values.reshape(
            0, values.shape[-1] if values.ndim >= 2 else 1)
    d = values.shape[1]
    row_bytes = 4 + d * 4
    quant_row_bytes = 4 + d + 4 if quant == "int8" else 4 + 2 * d
    decision, prices = price_window_formats(
        len(keys), int(capacity), row_bytes,
        quant=quant if quant in ("int8", "bf16") else "off",
        quant_row_bytes=quant_row_bytes if quant != "off" else None)
    if decision == "bitmap" and positions is None:
        decision = "sparse"      # no dense position space to mask over
    if decision == "dense":
        decision = "sparse"      # deltas never ship the whole table
    enc: Dict[str, np.ndarray] = {
        "format": np.array(decision), "keys": keys,
        "capacity": np.array(int(capacity)),
    }
    if decision == "sparse_q":
        scale = np.max(np.abs(values), axis=1, keepdims=True) / 127.0
        safe = np.where(scale > 0, scale, 1.0)
        q = np.clip(np.round(values / safe), -127, 127).astype(np.int8)
        enc["q"] = q
        enc["scale"] = np.where(scale > 0, scale, 0.0).astype(np.float32)
        wire = len(keys) * (4.0 + quant_row_bytes)
    elif decision == "bitmap":
        mask = np.zeros(int(capacity), np.bool_)
        mask[np.asarray(positions, np.int64)] = True
        enc["mask"] = np.packbits(mask)
        enc["positions"] = np.asarray(positions, np.int64)
        enc["values"] = values
        wire = capacity / 8.0 + len(keys) * (row_bytes - 4)
    else:
        enc["values"] = values
        wire = len(keys) * (4.0 + row_bytes)
    # merged in a literal: the npz payload is not a traffic ledger, and
    # the LEDGER-MONOTONIC backend check (this file lives in transfer/)
    # reserves `[...] =` mutation for actual ledger dicts
    return {**enc, "wire_bytes": np.array(int(round(wire)))}


def decode_delta(enc) -> Tuple[np.ndarray, np.ndarray]:
    """Reconstruct ``(keys, rows_f32)`` from an :func:`encode_delta`
    payload (an open npz or a dict).  ``sparse_q`` round-trips through
    the int8 scale — the receiver sees exactly what the wire carried,
    quantization error included."""
    fmt = str(np.asarray(enc["format"]))
    keys = np.asarray(enc["keys"], np.int64)
    if fmt == "sparse_q":
        values = (np.asarray(enc["q"], np.float32)
                  * np.asarray(enc["scale"], np.float32))
    else:
        values = np.asarray(enc["values"], np.float32)
    return keys, values


def delta_wire_bytes(enc) -> int:
    return int(np.asarray(enc["wire_bytes"]))


def atomic_savez(path: str, **arrays) -> None:
    """Write an npz so readers never observe a torn file: pid-unique
    tmp (concurrent writers of the same target must never clobber each
    other's in-flight tmp), fsync, then ``os.replace`` — last replace
    wins whole."""
    tmp = f"{path}.{os.getpid()}.tmp"
    with open(tmp, "wb") as f:
        np.savez(f, **arrays)
        f.flush()
        os.fsync(f.fileno())
    os.replace(tmp, path)
