"""Text corpus pipeline for word2vec: vocab, subsampling, CBOW batches.

Host-side equivalent of the reference gather/scan machinery:

* vocab + frequency build — the async variant's one global ``gather_keys``
  pass (`/root/reference/src/apps/word2vec/word2vec_global.h:385-444`).
* key derivation — both reference conventions: ``int`` (tokens are already
  integer ids, ``hash_fn2``/atoi, word2vec.h:206) and ``bkdr`` (string
  hash, word2vec_global.h:205-207).
* CBOW window extraction with the per-position random shrink ``b = rand %
  window`` giving effective half-window ``window - b`` (word2vec.h:555,
  567-576), subsampling by the reference keep-rule, and
  ``min_sentence_length`` filtering (word2vec.h:212-224).

Output batches are static-shape: ``centers (B,)``, ``contexts (B, 2W)`` +
mask, all as *vocab indices* (0..V-1); the model maps vocab index → table
slot on device.  Batch assembly is numpy; the C++ native loader is a
drop-in replacement for `iter_cbow_batches` (swiftmpi_tpu.data.native).
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Dict, Iterator, List, Optional, Sequence

import numpy as np

from swiftmpi_tpu.ops.sampling import subsample_keep_prob
from swiftmpi_tpu.utils.hashing import bkdr_hash


@dataclass
class Vocab:
    keys: np.ndarray     # (V,) uint64 external key per vocab index
    counts: np.ndarray   # (V,) int64 corpus frequency
    index: Dict[int, int]  # uint64 key -> vocab index

    def __len__(self) -> int:
        return len(self.keys)

    @property
    def total_words(self) -> int:
        return int(self.counts.sum())

    def index_of(self, key: int):
        """Vocab index for a raw token key (negative ints wrap to uint64,
        matching storage), or None if OOV."""
        return self.index.get(int(key) & ((1 << 64) - 1))


def tokenize(line: str, mode: str = "int") -> List[int]:
    """Words -> integer keys: ``int`` = atoi (sync variant), ``bkdr`` =
    string hash (async variant)."""
    words = line.split()
    if mode == "int":
        out = []
        for w in words:
            try:
                out.append(int(w))
            except ValueError:
                out.append(bkdr_hash(w))
        return out
    if mode == "bkdr":
        return [bkdr_hash(w) for w in words]
    raise ValueError(f"unknown tokenize mode {mode!r}")


def build_vocab(sentences: Sequence[Sequence[int]],
                min_count: int = 1) -> Vocab:
    _M64 = (1 << 64) - 1
    counts: Dict[int, int] = {}
    for sent in sentences:
        for k in sent:
            k &= _M64  # normalize to uint64 (negative int tokens wrap,
            counts[k] = counts.get(k, 0) + 1  # matching the native loader)
    items = [(k, c) for k, c in counts.items() if c >= min_count]
    items.sort(key=lambda kc: (-kc[1], kc[0]))  # frequent-first, stable
    keys = np.array([k for k, _ in items], np.uint64)
    cnts = np.array([c for _, c in items], np.int64)
    return Vocab(keys, cnts, {int(k): i for i, (k, _) in enumerate(items)})


def load_corpus(path: str, mode: str = "int",
                min_sentence_length: int = 1,
                max_sentence_length: int = 1000) -> List[List[int]]:
    """Sentences as key lists; one line = one sentence, except single-line
    corpora (text8) which are chopped into ``max_sentence_length`` chunks
    (the reference reads text8 line-wise too — its LineFileReader returns
    the one giant line; chunking bounds the window scan the same way the
    reference's 1000-word sentence cap does in original word2vec)."""
    sentences = []
    with open(path) as f:
        for line in f:
            toks = tokenize(line, mode)
            for i in range(0, len(toks), max_sentence_length):
                chunk = toks[i:i + max_sentence_length]
                if len(chunk) >= min_sentence_length:
                    sentences.append(chunk)
    return sentences


@dataclass
class CBOWBatch:
    centers: np.ndarray   # (B,) int32 vocab indices
    contexts: np.ndarray  # (B, 2W) int32 vocab indices; 0 at padding
    ctx_mask: np.ndarray  # (B, 2W) bool
    n_words: int          # real (unpadded) center count

    def __len__(self) -> int:
        return len(self.centers)


@dataclass
class StencilBatch:
    """Positional-stencil wire format: the batch is a *stream span* of
    unique tokens plus per-center positions into it, so the device pulls
    at most ``B + 2W`` rows instead of ``B * 2W`` context gathers.

    Expansion semantics (see :func:`stencil_to_cbow`): center row ``i``
    with ``p = center_pos[i]`` and ``h = half[i]`` has center token
    ``tokens[p]`` and contexts ``tokens[j]`` for ``j`` in
    ``[p-h, p+h]``, ``j != p``, ``0 <= j < S`` and
    ``sent_id[j] == sent_id[p]`` (sentence-boundary mask), in increasing
    ``j`` — identical content and order to the per-pair ``CBOWBatch``.
    """

    tokens: np.ndarray      # (S,) int32 span vocab indices; 0 at padding
    sent_id: np.ndarray     # (S,) int32 batch-local sentence id; -1 pad
    center_pos: np.ndarray  # (B,) int32 span index per center; -1 pad
    half: np.ndarray        # (B,) int32 effective half-window; 0 pad
    n_words: int            # real (unpadded) center count

    def __len__(self) -> int:
        return len(self.center_pos)

    @property
    def span(self) -> int:
        return len(self.tokens)


def stencil_to_cbow(batch: StencilBatch, window: int) -> CBOWBatch:
    """Host-side expansion of a stencil batch to per-pair rows — the
    parity anchor: with the same seed, the expanded stream must equal
    the per-pair batcher's stream element for element."""
    W = int(window)
    B = len(batch.center_pos)
    S = batch.span
    centers = np.zeros(B, np.int32)
    ctxs = np.zeros((B, 2 * W), np.int32)
    mask = np.zeros((B, 2 * W), bool)
    for i in range(batch.n_words):
        p = int(batch.center_pos[i])
        h = int(batch.half[i])
        sid = int(batch.sent_id[p])
        js = [j for j in range(p - h, p + h + 1)
              if j != p and 0 <= j < S and batch.sent_id[j] == sid]
        ctx = batch.tokens[js]
        centers[i] = batch.tokens[p]
        ctxs[i, :len(ctx)] = ctx
        mask[i, :len(ctx)] = True
    return CBOWBatch(centers, ctxs, mask, batch.n_words)


class CBOWBatcher:
    """Streams fixed-size CBOW batches over a corpus."""

    def __init__(self, sentences: Sequence[Sequence[int]], vocab: Vocab,
                 window: int, sample: float = -1.0, seed: int = 2008):
        self.vocab = vocab
        self.window = int(window)
        self.sample = float(sample)
        self.rng = np.random.default_rng(seed)
        self.keep_prob = subsample_keep_prob(vocab.counts, sample)
        # pre-map sentences to vocab indices, dropping OOV
        self._sents: List[np.ndarray] = []
        for sent in sentences:
            idx = [i for i in (vocab.index_of(k) for k in sent)
                   if i is not None]
            if idx:
                self._sents.append(np.asarray(idx, np.int32))

    def epoch(self, batch_size: int) -> Iterator[CBOWBatch]:
        """One pass over the corpus in a fresh random sentence order.

        Subsampling follows the reference exactly: ``to_sample`` gates only
        the *center* position (word2vec.h:561-562 ``continue``); dropped
        words still appear in their neighbors' context windows.
        """
        W = self.window
        centers: List[int] = []
        ctxs: List[np.ndarray] = []
        masks: List[np.ndarray] = []

        def flush(n_real):
            c = np.asarray(centers[:batch_size], np.int32)
            x = np.stack(ctxs[:batch_size])
            m = np.stack(masks[:batch_size])
            del centers[:batch_size], ctxs[:batch_size], masks[:batch_size]
            return CBOWBatch(c, x, m, n_real)

        for si in self.rng.permutation(len(self._sents)):
            sent = self._sents[si]
            L = len(sent)
            # per-position random shrink b in [0, W)  (word2vec.h:555)
            bs = self.rng.integers(0, W, size=L)
            if self.sample >= 0:
                center_keep = (self.rng.random(L)
                               < self.keep_prob[sent])
            else:
                center_keep = np.ones(L, bool)
            for pos in range(L):
                if not center_keep[pos]:
                    continue
                half = W - int(bs[pos])
                lo, hi = max(0, pos - half), min(L, pos + half + 1)
                ctx = np.concatenate([sent[lo:pos], sent[pos + 1:hi]])
                if len(ctx) == 0:
                    continue
                row = np.zeros(2 * W, np.int32)
                row[:len(ctx)] = ctx
                m = np.zeros(2 * W, bool)
                m[:len(ctx)] = True
                centers.append(int(sent[pos]))
                ctxs.append(row)
                masks.append(m)
                if len(centers) == batch_size:
                    yield flush(batch_size)
        if centers:
            n_real = len(centers)
            # pad tail to the static batch shape with masked rows
            while len(centers) < batch_size:
                centers.append(0)
                ctxs.append(np.zeros(2 * W, np.int32))
                masks.append(np.zeros(2 * W, bool))
            yield flush(n_real)

    def epoch_stencil(self, batch_size: int) -> Iterator[StencilBatch]:
        """One pass emitting :class:`StencilBatch` stream spans.

        Consumes the rng in *exactly* the order :meth:`epoch` does
        (permutation, then per-sentence shrink array + keep array), so
        the expanded pair stream for a given seed is identical to the
        per-pair epoch — the CPU parity tests pin this.

        Invariants (by construction, not by dedup):
        * span capacity is fixed at ``S = batch_size + 2W`` — the unique
          gather working set per batch;
        * every admitted center's full (sentence-clipped) window is
          resident in the span, so expansion never loses a context;
        * a sentence split across batches replays its last ``W`` tokens
          into the new span so left contexts survive the split.
        """
        W = self.window
        S = batch_size + 2 * W
        tokens = np.zeros(S, np.int32)
        sids = np.full(S, -1, np.int32)
        cpos = np.full(batch_size, -1, np.int32)
        halves = np.zeros(batch_size, np.int32)
        fill = 0   # span rows used
        nc = 0     # centers admitted
        ns = 0     # batch-local sentence counter

        def flush():
            nonlocal tokens, sids, cpos, halves, fill, nc, ns
            out = StencilBatch(tokens, sids, cpos, halves, nc)
            tokens = np.zeros(S, np.int32)
            sids = np.full(S, -1, np.int32)
            cpos = np.full(batch_size, -1, np.int32)
            halves = np.zeros(batch_size, np.int32)
            fill = nc = ns = 0
            return out

        for si in self.rng.permutation(len(self._sents)):
            sent = self._sents[si]
            L = len(sent)
            bs = self.rng.integers(0, W, size=L)
            if self.sample >= 0:
                center_keep = (self.rng.random(L)
                               < self.keep_prob[sent])
            else:
                center_keep = np.ones(L, bool)
            sid = ns
            ns += 1
            p0 = 0       # first sentence position resident in the span
            base = fill  # span index of sentence position p0
            have = 0     # sentence positions [p0, p0+have) are appended
            p = 0
            while p < L:
                half = W - int(bs[p])
                left = min(half, p)
                right = min(half, L - 1 - p)
                if not center_keep[p] or left + right == 0:
                    p += 1
                    continue
                if have == 0:
                    # nothing resident yet: skip any keep-dropped prefix
                    # no future window can reach (all reach >= p - W)
                    p0 = max(p0, p - W)
                end = p + right         # last sentence position needed
                if nc == batch_size or base + (end - p0) >= S:
                    yield flush()
                    # resume mid-sentence: replay the left tail so
                    # upcoming centers keep their left context
                    p0 = max(0, p - W)
                    base = 0
                    n = p - p0
                    tokens[:n] = sent[p0:p]
                    sids[:n] = 0
                    fill = have = n
                    sid, ns = 0, 1
                    continue            # re-admit p in the fresh span
                # append (contiguously) through the window's right edge
                if end - p0 >= have:
                    n_new = end - p0 + 1 - have
                    tokens[fill:fill + n_new] = sent[p0 + have:end + 1]
                    sids[fill:fill + n_new] = sid
                    fill += n_new
                    have += n_new
                cpos[nc] = base + (p - p0)
                halves[nc] = half
                nc += 1
                p += 1
        if nc:
            yield flush()

    def epoch_prefetch(self, batch_size: int, depth: int = 4
                       ) -> Iterator[CBOWBatch]:
        """:meth:`epoch` through a background producer thread
        (io/pipeline.py): rendering runs ``depth`` batches ahead while
        the consumer computes.  Batch order and rng consumption are
        identical to the synchronous epoch — the producer just runs
        the same generator earlier."""
        from swiftmpi_tpu.io.pipeline import PrefetchIterator
        return PrefetchIterator(self.epoch(batch_size), depth=depth,
                                name="cbow-epoch-prefetch")

    def epoch_stencil_prefetch(self, batch_size: int, depth: int = 4
                               ) -> Iterator[StencilBatch]:
        """:meth:`epoch_stencil` through the same background producer
        (identical wire format and order)."""
        from swiftmpi_tpu.io.pipeline import PrefetchIterator
        return PrefetchIterator(self.epoch_stencil(batch_size),
                                depth=depth,
                                name="cbow-stencil-prefetch")


def synthetic_corpus(n_sentences: int, vocab_size: int, length: int = 20,
                     seed: int = 0, zipf: float = 1.2) -> List[List[int]]:
    """Zipf-distributed token streams with local correlation (neighbors
    share a topic), so embeddings have signal to learn."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    p = ranks ** (-zipf)
    p /= p.sum()
    out = []
    for _ in range(n_sentences):
        topic = rng.integers(0, 5)
        base = rng.choice(vocab_size, size=length, p=p)
        # topic words interleaved -> co-occurrence structure
        base[::3] = (topic * 7 + base[::3] // 5) % vocab_size
        out.append([int(x) + 1 for x in base])  # keys are 1-based ints
    return out


def synthetic_corpus_bulk(n_sentences: int, vocab_size: int,
                          length: int = 1000, seed: int = 0,
                          zipf: float = 1.2) -> np.ndarray:
    """Bulk rendering of :func:`synthetic_corpus`'s distribution for
    enwiki-scale corpora (BASELINE config #3: 100M tokens / few-hundred-K
    vocab): one CDF + vectorized ``searchsorted`` draws instead of a
    per-sentence ``rng.choice(p=...)`` (whose per-call CDF rebuild is
    O(V) — hours at 100K x 1000).  Returns an (n_sentences, length)
    int32 array of 1-based keys with the same Zipf marginal and
    per-sentence topic interleave as the list generator."""
    rng = np.random.default_rng(seed)
    ranks = np.arange(1, vocab_size + 1, dtype=np.float64)
    cdf = np.cumsum(ranks ** (-zipf))
    cdf /= cdf[-1]
    out = np.empty((n_sentences, length), np.int32)
    # row chunks bound the float64 draw + int64 searchsorted transients
    # to ~tens of MB (one full 100Kx1000 draw would transiently hold
    # ~2GB — review finding)
    chunk = max(1, 2_000_000 // max(length, 1))
    for i in range(0, n_sentences, chunk):
        n = min(chunk, n_sentences - i)
        base = np.searchsorted(
            cdf, rng.random((n, length)), side="right")
        topics = rng.integers(0, 5, size=(n, 1))
        base[:, ::3] = (topics * 7 + base[:, ::3] // 5) % vocab_size
        out[i:i + n] = base + 1                  # keys are 1-based ints
    return out


def write_tokens_file(arr: np.ndarray, path: str,
                      chunk_rows: int = 4096) -> None:
    """Write an (n_sentences, length) key array as the loader's text
    format (one space-separated sentence per line), chunked so a 100M-
    token corpus streams through a bounded buffer."""
    with open(path, "w") as f:
        for i in range(0, arr.shape[0], chunk_rows):
            chunk = arr[i:i + chunk_rows]
            f.write("\n".join(
                " ".join(map(str, row)) for row in chunk) + "\n")
