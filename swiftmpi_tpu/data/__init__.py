"""Host-side input pipelines: libSVM (LR) and text corpora (word2vec)."""

from swiftmpi_tpu.data.libsvm import (LibSVMBatch, iter_minibatches,
                                      load_file, make_batch, parse_line,
                                      synthetic_dataset)

__all__ = ["LibSVMBatch", "iter_minibatches", "load_file", "make_batch",
           "parse_line", "synthetic_dataset"]
