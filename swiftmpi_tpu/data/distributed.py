"""Distributed data parallelism over the batch stream.

The reference distributes training data by giving each MPI rank its own
file ("Distribute the data set... allocate each node a file",
apps/word2vec/README.md; per-thread byte slices word2vec_global.h:594-600)
— each rank computes on its shard, gradients combine at the servers.

Here the same contract is a wrapper over any per-process batcher: every
process streams batches from its own data shard, and each local batch
becomes one *global* jax.Array sharded over the ``data`` mesh axis
(`jax.make_array_from_process_local_data`) — so the jitted training step
runs one SPMD program over everybody's data and the gradient combine is
whatever the step already does (psum / table scatter).

Lockstep protocol: SPMD requires every process to dispatch the same number
of steps, but shards deplete unevenly (subsampling is stochastic).  Before
each step a tiny allgather exchanges (has_batch, n_words); the epoch ends
the moment ANY shard runs dry — the same "epoch = until the fastest rank
finishes" semantics as the reference's async variant, where threads simply
stop at their slice end (word2vec_global.h:630-651).
"""

from __future__ import annotations

from typing import Iterator, Optional

import jax
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swiftmpi_tpu.cluster.mesh import DATA_AXIS
from swiftmpi_tpu.data.text import CBOWBatch
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)


def shard_sentences(sentences, rank: Optional[int] = None,
                    nprocs: Optional[int] = None):
    """This process's data shard (round-robin, balanced to ±1 sentence) —
    the equivalent of the reference's per-node data file."""
    rank = jax.process_index() if rank is None else rank
    nprocs = jax.process_count() if nprocs is None else nprocs
    return sentences[rank::nprocs]


class DistributedBatcher:
    """Wraps a per-process batcher into a lockstep global batch stream.

    ``batcher`` must yield objects with ``centers/contexts/ctx_mask/
    n_words`` (CBOWBatch shape); under-filled batches are skipped so all
    ranks keep identical static shapes.  The global batch size seen by the
    training step is ``batch_size * process_count``.
    """

    def __init__(self, batcher, mesh: Mesh, axis: str = DATA_AXIS):
        self.batcher = batcher
        self.mesh = mesh
        self.axis = axis
        self.vocab = getattr(batcher, "vocab", None)

    def epoch(self, batch_size: int) -> Iterator[CBOWBatch]:
        from jax.experimental import multihost_utils

        sh1 = NamedSharding(self.mesh, P(self.axis))
        sh2 = NamedSharding(self.mesh, P(self.axis, None))
        it = self.batcher.epoch(batch_size)
        steps = 0
        while True:
            batch = next(it, None)
            while batch is not None and len(batch) != batch_size:
                batch = next(it, None)      # drop ragged tail batches
            flag = np.asarray(
                [0 if batch is None else 1,
                 0 if batch is None else batch.n_words], np.int64)
            flags = multihost_utils.process_allgather(flag)
            if int(flags[:, 0].min()) == 0:
                if batch is not None:
                    log.debug("epoch cut at %d steps: another shard ran "
                              "dry first", steps)
                return
            mk = jax.make_array_from_process_local_data
            yield CBOWBatch(
                mk(sh1, np.ascontiguousarray(batch.centers)),
                mk(sh2, np.ascontiguousarray(batch.contexts)),
                mk(sh2, np.ascontiguousarray(batch.ctx_mask)),
                int(flags[:, 1].sum()))
            steps += 1
