"""libSVM-format data pipeline (a9a-style) for logistic regression.

Host-side equivalent of the reference's ``parse_instance2`` + minibatch
scan (`/root/reference/src/apps/logistic/lr.cpp:103-131,300-355`): lines are
``label feat:val feat:val ...``; ``#`` comments and blank lines skipped.

Reference labels arrive already converted to {0,1} by its
``tools/svm2fm.sh`` awk step; raw a9a uses {-1,+1}, so the parser maps
negative labels to 0 (the conversion the reference does out-of-band).

Batches are padded to static shapes for XLA: ``(B, max_feats)`` feature-id
and value matrices with ``-1`` id padding, matching the transfer layer's
padding convention.
"""

from __future__ import annotations

from dataclasses import dataclass
from typing import Iterator, List, Optional, Tuple

import numpy as np


@dataclass
class LibSVMBatch:
    targets: np.ndarray    # (B,) float32 in {0,1}
    feat_ids: np.ndarray   # (B, F) uint64 feature keys; pad rows repeat 0
    feat_vals: np.ndarray  # (B, F) float32; 0 at padding
    mask: np.ndarray       # (B, F) bool, True where a real feature

    def __len__(self) -> int:
        return len(self.targets)

    def unique_keys(self) -> np.ndarray:
        return np.unique(self.feat_ids[self.mask])


def parse_line(line: str) -> Optional[Tuple[float, List[Tuple[int, float]]]]:
    """One instance, or None for blank/comment (lr.cpp:103-131)."""
    line = line.strip()
    if not line or line.startswith("#"):
        return None
    parts = line.split()
    try:
        label = float(parts[0])
    except ValueError:
        raise ValueError(f"cannot parse label in line {line!r}")
    feats = []
    for tok in parts[1:]:
        if tok.startswith("#"):
            break
        f, _, v = tok.partition(":")
        feats.append((int(f), float(v)))
    return (1.0 if label > 0 else 0.0), feats


def load_file(path: str) -> List[Tuple[float, List[Tuple[int, float]]]]:
    out = []
    with open(path) as f:
        for line in f:
            ins = parse_line(line)
            if ins is not None and ins[1]:
                out.append(ins)
    return out


@dataclass
class CSRData:
    """Whole-dataset CSR arrays (the native parser's output shape); row i's
    features are ``feat_ids[offsets[i]:offsets[i+1]]``."""
    labels: np.ndarray     # (N,) float32 {0,1}
    offsets: np.ndarray    # (N+1,) int64
    feat_ids: np.ndarray   # (nnz,) uint64
    feat_vals: np.ndarray  # (nnz,) float32

    def __len__(self) -> int:
        return len(self.labels)

    @property
    def max_feats(self) -> int:
        if not len(self.labels):
            return 0
        return int(np.max(np.diff(self.offsets)))


def to_csr(instances) -> CSRData:
    """Python instance list -> CSR arrays (fallback for the native parser)."""
    labels = np.asarray([y for y, _ in instances], np.float32)
    offsets = np.zeros(len(instances) + 1, np.int64)
    ids, vals = [], []
    for i, (_, feats) in enumerate(instances):
        for f, v in feats:
            ids.append(f)
            vals.append(v)
        offsets[i + 1] = len(ids)
    return CSRData(labels, offsets, np.asarray(ids, np.uint64),
                   np.asarray(vals, np.float32))


def load_data(path: str) -> CSRData:
    """Load a libSVM file as CSR, via the native C++ parser when available
    (io.cpp smtpu_libsvm_parse) else the python line parser."""
    from swiftmpi_tpu.data import native
    if native.available():
        labels, offsets, ids, vals = native.parse_libsvm_native(path)
        return CSRData(labels, offsets, ids, vals)
    return to_csr(load_file(path))


def make_batch(instances, max_feats: Optional[int] = None) -> LibSVMBatch:
    B = len(instances)
    F = max_feats or max(len(f) for _, f in instances)
    targets = np.zeros(B, np.float32)
    ids = np.zeros((B, F), np.uint64)
    vals = np.zeros((B, F), np.float32)
    mask = np.zeros((B, F), bool)
    for i, (y, feats) in enumerate(instances):
        targets[i] = y
        for j, (f, v) in enumerate(feats[:F]):
            ids[i, j] = f
            vals[i, j] = v
            mask[i, j] = True
    return LibSVMBatch(targets, ids, vals, mask)


def _iter_csr(data: CSRData, batch_size: int, F: int,
              drop_remainder: bool) -> Iterator[LibSVMBatch]:
    """Vectorized minibatch assembly straight from CSR arrays — no
    per-instance python loop."""
    N = len(data)
    nnz = len(data.feat_ids)
    col = np.arange(F)
    for i in range(0, N, batch_size):
        j = min(i + batch_size, N)
        if j - i < batch_size and drop_remainder:
            return
        lens = (data.offsets[i + 1:j + 1] - data.offsets[i:j])
        lens = np.minimum(lens, F)
        mask = col[None, :] < lens[:, None]                  # (b, F)
        if nnz == 0:  # all-feature-less rows: nothing to gather
            ids = np.zeros((j - i, F), np.uint64)
            vals = np.zeros((j - i, F), np.float32)
        else:
            flat = data.offsets[i:j, None] + col[None, :]
            flat = np.clip(flat, 0, nnz - 1)
            ids = np.where(mask, data.feat_ids[flat], np.uint64(0))
            vals = np.where(mask, data.feat_vals[flat], np.float32(0))
        targets = data.labels[i:j]
        if j - i < batch_size:                               # pad tail
            pad = batch_size - (j - i)
            targets = np.concatenate([targets, np.zeros(pad, np.float32)])
            ids = np.concatenate([ids, np.zeros((pad, F), np.uint64)])
            vals = np.concatenate([vals, np.zeros((pad, F), np.float32)])
            mask = np.concatenate([mask, np.zeros((pad, F), bool)])
        yield LibSVMBatch(targets, ids, vals, mask)


def iter_minibatches(instances, batch_size: int,
                     max_feats: Optional[int] = None,
                     drop_remainder: bool = False
                     ) -> Iterator[LibSVMBatch]:
    """Fixed-size minibatches (reference [worker] minibatch config); the
    trailing short batch is padded up to ``batch_size`` with zero-mask rows
    so every step has one static shape (one XLA compilation).  Accepts a
    python instance list or ``CSRData``."""
    if isinstance(instances, CSRData):
        F = max_feats or instances.max_feats
        yield from _iter_csr(instances, batch_size, F, drop_remainder)
        return
    F = max_feats or max(len(f) for _, f in instances)
    for i in range(0, len(instances), batch_size):
        chunk = instances[i:i + batch_size]
        if len(chunk) < batch_size:
            if drop_remainder:
                return
            batch = make_batch(chunk, F)
            pad = batch_size - len(chunk)
            yield LibSVMBatch(
                np.concatenate([batch.targets, np.zeros(pad, np.float32)]),
                np.concatenate([batch.feat_ids,
                                np.zeros((pad, F), np.uint64)]),
                np.concatenate([batch.feat_vals,
                                np.zeros((pad, F), np.float32)]),
                np.concatenate([batch.mask, np.zeros((pad, F), bool)]))
            return
        yield make_batch(chunk, F)


def synthetic_dataset(n: int, dim: int, nnz: int, seed: int = 0,
                      noise: float = 0.0):
    """Linearly separable sparse synthetic data for tests/benchmarks."""
    rng = np.random.default_rng(seed)
    w = rng.normal(size=dim)
    out = []
    for _ in range(n):
        feats_idx = rng.choice(dim, size=nnz, replace=False)
        vals = rng.normal(size=nnz).astype(np.float64)
        score = float(vals @ w[feats_idx]) + rng.normal() * noise
        label = 1.0 if score > 0 else 0.0
        out.append((label, [(int(f) + 1, float(v))
                            for f, v in zip(feats_idx, vals)]))
    return out
