"""ctypes binding to the native C++ data loader (native/loader.cpp).

Drop-in fast path for the host input pipeline: vocab build, corpus
mapping, and CBOW batch assembly run in C++ (the reference's own host-side
machinery is C++ — LineFileReader/split/gather_keys).  Falls back to the
pure-Python pipeline (data/text.py) when the shared library cannot be
built; call ``available()`` to check.

The .so is built on demand with g++ from the repo's ``native/`` directory
and cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from swiftmpi_tpu.data.text import CBOWBatch, StencilBatch, Vocab
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libsmtpu_loader.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load_lib():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        srcs = [os.path.join(_NATIVE_DIR, f)
                for f in ("loader.cpp", "io.cpp")]
        stale = (not os.path.exists(_SO_PATH)
                 or any(os.path.exists(s)
                        and os.path.getmtime(s) > os.path.getmtime(_SO_PATH)
                        for s in srcs))
        if stale:
            srcs = [s for s in srcs if os.path.exists(s)]
            if not srcs:
                _build_failed = True
                return None
            try:
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-Wall", "-shared",
                     "-fPIC", *srcs, "-o", _SO_PATH],
                    check=True, capture_output=True, timeout=120)
            except (subprocess.SubprocessError, FileNotFoundError) as e:
                log.warning("native loader build failed (%s); "
                            "using python pipeline", e)
                _build_failed = True
                return None
        lib = ctypes.CDLL(_SO_PATH)
        c = ctypes
        lib.smtpu_vocab_build.restype = c.c_void_p
        lib.smtpu_vocab_build.argtypes = [c.c_char_p, c.c_int, c.c_int64,
                                          c.c_int64, c.c_int64]
        lib.smtpu_vocab_size.restype = c.c_int64
        lib.smtpu_vocab_size.argtypes = [c.c_void_p]
        lib.smtpu_vocab_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.smtpu_vocab_free.argtypes = [c.c_void_p]
        lib.smtpu_corpus_map.restype = c.c_void_p
        lib.smtpu_corpus_map.argtypes = [c.c_char_p, c.c_int, c.c_void_p,
                                         c.c_int64, c.c_int64]
        lib.smtpu_corpus_n_sentences.restype = c.c_int64
        lib.smtpu_corpus_n_sentences.argtypes = [c.c_void_p]
        lib.smtpu_corpus_n_tokens.restype = c.c_int64
        lib.smtpu_corpus_n_tokens.argtypes = [c.c_void_p]
        lib.smtpu_corpus_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.smtpu_corpus_free.argtypes = [c.c_void_p]
        lib.smtpu_batcher_new.restype = c.c_void_p
        lib.smtpu_batcher_new.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                          c.c_int, c.c_void_p, c.c_uint64]
        lib.smtpu_batcher_reset.argtypes = [c.c_void_p, c.c_uint64]
        lib.smtpu_batcher_next.restype = c.c_int64
        lib.smtpu_batcher_next.argtypes = [c.c_void_p, c.c_int64, c.c_void_p,
                                           c.c_void_p, c.c_void_p]
        lib.smtpu_batcher_next_stencil.restype = c.c_int64
        lib.smtpu_batcher_next_stencil.argtypes = [
            c.c_void_p, c.c_int64, c.c_void_p, c.c_void_p, c.c_void_p,
            c.c_void_p]
        lib.smtpu_batcher_free.argtypes = [c.c_void_p]
        lib.smtpu_prefetcher_new.restype = c.c_void_p
        lib.smtpu_prefetcher_new.argtypes = [c.c_void_p, c.c_int64,
                                             c.c_int64, c.c_uint64]
        lib.smtpu_prefetcher_next.restype = c.c_int64
        lib.smtpu_prefetcher_next.argtypes = [c.c_void_p, c.c_void_p,
                                              c.c_void_p, c.c_void_p]
        lib.smtpu_prefetcher_free.argtypes = [c.c_void_p]
        lib.smtpu_libsvm_parse.restype = c.c_void_p
        lib.smtpu_libsvm_parse.argtypes = [c.c_char_p]
        lib.smtpu_libsvm_n_rows.restype = c.c_int64
        lib.smtpu_libsvm_n_rows.argtypes = [c.c_void_p]
        lib.smtpu_libsvm_nnz.restype = c.c_int64
        lib.smtpu_libsvm_nnz.argtypes = [c.c_void_p]
        lib.smtpu_libsvm_n_bad.restype = c.c_int64
        lib.smtpu_libsvm_n_bad.argtypes = [c.c_void_p]
        lib.smtpu_libsvm_copy.argtypes = [c.c_void_p] + [c.c_void_p] * 4
        lib.smtpu_libsvm_free.argtypes = [c.c_void_p]
        lib.smtpu_dump_rows.restype = c.c_int64
        lib.smtpu_dump_rows.argtypes = [c.c_char_p, c.c_void_p, c.c_int64,
                                        c.c_int64, c.c_void_p, c.c_void_p]
        lib.smtpu_load_rows.restype = c.c_void_p
        lib.smtpu_load_rows.argtypes = [c.c_char_p, c.c_int64, c.c_void_p]
        lib.smtpu_text_n_rows.restype = c.c_int64
        lib.smtpu_text_n_rows.argtypes = [c.c_void_p]
        lib.smtpu_text_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.smtpu_text_free.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load_lib() is not None


_MODE = {"int": 0, "bkdr": 1}


def load_corpus_native(path: str, mode: str = "int", min_count: int = 1,
                       min_sentence_length: int = 1,
                       max_sentence_length: int = 1000):
    """One C++ pass for vocab + one for corpus mapping.

    Returns (vocab, tokens, offsets): ``tokens`` int32 vocab indices
    flattened, ``offsets`` int64 sentence boundaries.
    """
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    vp = lib.smtpu_vocab_build(path.encode(), _MODE[mode], min_count,
                               min_sentence_length, max_sentence_length)
    if not vp:
        raise FileNotFoundError(path)
    try:
        V = lib.smtpu_vocab_size(vp)
        keys = np.empty(V, np.uint64)
        counts = np.empty(V, np.int64)
        lib.smtpu_vocab_copy(vp, keys.ctypes.data, counts.ctypes.data)
        vocab = Vocab(keys, counts,
                      {int(k): i for i, k in enumerate(keys)})
        cp = lib.smtpu_corpus_map(path.encode(), _MODE[mode], vp,
                                  min_sentence_length, max_sentence_length)
        if not cp:
            raise FileNotFoundError(path)
        try:
            n_sent = lib.smtpu_corpus_n_sentences(cp)
            n_tok = lib.smtpu_corpus_n_tokens(cp)
            tokens = np.empty(n_tok, np.int32)
            offsets = np.empty(n_sent + 1, np.int64)
            lib.smtpu_corpus_copy(cp, tokens.ctypes.data,
                                  offsets.ctypes.data)
        finally:
            lib.smtpu_corpus_free(cp)
    finally:
        lib.smtpu_vocab_free(vp)
    return vocab, tokens, offsets


class NativeCBOWBatcher:
    """C++-backed drop-in for ``CBOWBatcher`` (same batch contract)."""

    def __init__(self, tokens: np.ndarray, offsets: np.ndarray, vocab: Vocab,
                 window: int, sample: float = -1.0, seed: int = 2008):
        from swiftmpi_tpu.ops.sampling import subsample_keep_prob
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        self._lib = lib
        self.window = int(window)
        self.vocab = vocab
        # keep buffer refs alive: the batcher borrows these arrays
        self._tokens = np.ascontiguousarray(tokens, np.int32)
        self._offsets = np.ascontiguousarray(offsets, np.int64)
        if sample >= 0:
            self._keep = np.ascontiguousarray(
                subsample_keep_prob(vocab.counts, sample), np.float32)
            keep_ptr = self._keep.ctypes.data
        else:
            self._keep = None
            keep_ptr = None
        self._seed = seed
        self._epoch_i = 0
        self._h = lib.smtpu_batcher_new(
            self._tokens.ctypes.data, self._offsets.ctypes.data,
            len(self._offsets) - 1, self.window, keep_ptr, seed)

    def _drain(self, batch_size: int, next_fn) -> Iterator[CBOWBatch]:
        """Shared batch-yield loop: ``next_fn(centers, contexts, mask)``
        fills one batch and returns n examples (0 = epoch done)."""
        W2 = 2 * self.window
        while True:
            centers = np.zeros(batch_size, np.int32)
            contexts = np.zeros((batch_size, W2), np.int32)
            mask = np.zeros((batch_size, W2), np.uint8)
            n = next_fn(centers.ctypes.data, contexts.ctypes.data,
                        mask.ctypes.data)
            if n == 0:
                return
            yield CBOWBatch(centers, contexts, mask.astype(bool), int(n))
            if n < batch_size:
                return

    def epoch(self, batch_size: int) -> Iterator[CBOWBatch]:
        lib = self._lib
        self._epoch_i += 1
        lib.smtpu_batcher_reset(self._h, self._seed + self._epoch_i)
        yield from self._drain(
            batch_size,
            lambda c, x, m: lib.smtpu_batcher_next(
                self._h, batch_size, c, x, m))

    def epoch_stencil(self, batch_size: int) -> Iterator[StencilBatch]:
        """Stream-span epoch (same wire format as
        ``CBOWBatcher.epoch_stencil``): spans of ``batch_size + 2W``
        unique tokens with per-center positions, assembled in C++."""
        lib = self._lib
        W = self.window
        S = batch_size + 2 * W
        self._epoch_i += 1
        lib.smtpu_batcher_reset(self._h, self._seed + self._epoch_i)
        while True:
            tokens = np.zeros(S, np.int32)
            sids = np.zeros(S, np.int32)
            cpos = np.zeros(batch_size, np.int32)
            half = np.zeros(batch_size, np.int32)
            n = lib.smtpu_batcher_next_stencil(
                self._h, batch_size, tokens.ctypes.data, sids.ctypes.data,
                cpos.ctypes.data, half.ctypes.data)
            if n == 0:
                return
            yield StencilBatch(tokens, sids, cpos, half, int(n))

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.smtpu_batcher_free(self._h)
                self._h = None
        except Exception:
            pass


class PrefetchingCBOWBatcher(NativeCBOWBatcher):
    """NativeCBOWBatcher whose epochs run through the C++ prefetch
    executor: a producer thread assembles batches into a bounded queue
    while the device computes (the reference AsynExec/queue_with_capacity
    machinery recast as input-pipeline overlap — loader.cpp)."""

    def __init__(self, *args, depth: int = 4, **kwargs):
        super().__init__(*args, **kwargs)
        self.depth = int(depth)

    def epoch(self, batch_size: int) -> Iterator[CBOWBatch]:
        lib = self._lib
        self._epoch_i += 1
        p = lib.smtpu_prefetcher_new(self._h, batch_size, self.depth,
                                     self._seed + self._epoch_i)
        try:
            yield from self._drain(
                batch_size,
                lambda c, x, m: lib.smtpu_prefetcher_next(p, c, x, m))
        finally:
            lib.smtpu_prefetcher_free(p)

    def epoch_stencil(self, batch_size: int) -> Iterator[StencilBatch]:
        """The C++ prefetch executor covers only the per-pair wire
        format; the stencil epoch gets the same overlap through the
        Python-thread pipeline (io/pipeline.py) over the synchronous
        native iterator — wire format and batch order unchanged."""
        from swiftmpi_tpu.io.pipeline import PrefetchIterator
        return PrefetchIterator(super().epoch_stencil(batch_size),
                                depth=self.depth,
                                name="native-stencil-prefetch")


# ---- libSVM (io.cpp) ------------------------------------------------------

def parse_libsvm_native(path: str):
    """Whole-file CSR parse: (labels (N,), offsets (N+1,), feat_ids (nnz,),
    feat_vals (nnz,)).  Labels are already mapped to {0,1}."""
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    h = lib.smtpu_libsvm_parse(path.encode())
    if not h:
        raise FileNotFoundError(path)
    try:
        n_bad = lib.smtpu_libsvm_n_bad(h)
        if n_bad:
            raise ValueError(
                f"{path}: {n_bad} malformed libSVM line(s) "
                "(bad label or feature token)")
        n = lib.smtpu_libsvm_n_rows(h)
        nnz = lib.smtpu_libsvm_nnz(h)
        labels = np.empty(n, np.float32)
        offsets = np.empty(n + 1, np.int64)
        ids = np.empty(nnz, np.uint64)
        vals = np.empty(nnz, np.float32)
        lib.smtpu_libsvm_copy(h, labels.ctypes.data, offsets.ctypes.data,
                              ids.ctypes.data, vals.ctypes.data)
    finally:
        lib.smtpu_libsvm_free(h)
    return labels, offsets, ids, vals


# ---- text checkpoints (io.cpp) --------------------------------------------

def dump_rows_native(path: str, keys: np.ndarray, fields) -> int:
    """Write ``key\\tfield0\\tfield1...`` lines; ``fields`` is an ordered
    list of (n, d) float32 arrays.  Returns rows written."""
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    keys = np.ascontiguousarray(keys, np.uint64)
    if len(keys) == 0:  # empty table: empty file, like the python writer
        open(path, "w").close()
        return 0
    arrs = [np.ascontiguousarray(a, np.float32).reshape(len(keys), -1)
            for a in fields]
    dims = np.asarray([a.shape[1] for a in arrs], np.int64)
    ptrs = (ctypes.c_void_p * len(arrs))(
        *[a.ctypes.data for a in arrs])
    n = lib.smtpu_dump_rows(path.encode(), keys.ctypes.data, len(keys),
                            len(arrs), ptrs, dims.ctypes.data)
    if n < 0:
        raise OSError(f"cannot write {path}")
    return int(n)


def load_rows_native(path: str, dims):
    """Read ``key\\tfield...`` lines where field j has ``dims[j]`` floats.
    Returns (keys (N,), [(N, dims[j]) float32 arrays])."""
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    dims = np.asarray(dims, np.int64)
    h = lib.smtpu_load_rows(path.encode(), len(dims), dims.ctypes.data)
    if not h:
        raise FileNotFoundError(path)
    try:
        n = lib.smtpu_text_n_rows(h)
        keys = np.empty(n, np.uint64)
        arrs = [np.empty((n, int(d)), np.float32) for d in dims]
        ptrs = (ctypes.c_void_p * len(arrs))(
            *[a.ctypes.data for a in arrs])
        lib.smtpu_text_copy(h, keys.ctypes.data, ptrs)
    finally:
        lib.smtpu_text_free(h)
    return keys, arrs
