"""ctypes binding to the native C++ data loader (native/loader.cpp).

Drop-in fast path for the host input pipeline: vocab build, corpus
mapping, and CBOW batch assembly run in C++ (the reference's own host-side
machinery is C++ — LineFileReader/split/gather_keys).  Falls back to the
pure-Python pipeline (data/text.py) when the shared library cannot be
built; call ``available()`` to check.

The .so is built on demand with g++ from the repo's ``native/`` directory
and cached next to the source.
"""

from __future__ import annotations

import ctypes
import os
import subprocess
import threading
from typing import Iterator, List, Optional, Sequence

import numpy as np

from swiftmpi_tpu.data.text import CBOWBatch, Vocab
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)

_NATIVE_DIR = os.path.join(os.path.dirname(os.path.dirname(
    os.path.dirname(os.path.abspath(__file__)))), "native")
_SO_PATH = os.path.join(_NATIVE_DIR, "libsmtpu_loader.so")

_lib = None
_lib_lock = threading.Lock()
_build_failed = False


def _load_lib():
    global _lib, _build_failed
    with _lib_lock:
        if _lib is not None or _build_failed:
            return _lib
        if not os.path.exists(_SO_PATH):
            src = os.path.join(_NATIVE_DIR, "loader.cpp")
            if not os.path.exists(src):
                _build_failed = True
                return None
            try:
                subprocess.run(
                    ["g++", "-O3", "-std=c++17", "-Wall", "-shared",
                     "-fPIC", src, "-o", _SO_PATH],
                    check=True, capture_output=True, timeout=120)
            except (subprocess.SubprocessError, FileNotFoundError) as e:
                log.warning("native loader build failed (%s); "
                            "using python pipeline", e)
                _build_failed = True
                return None
        lib = ctypes.CDLL(_SO_PATH)
        c = ctypes
        lib.smtpu_vocab_build.restype = c.c_void_p
        lib.smtpu_vocab_build.argtypes = [c.c_char_p, c.c_int, c.c_int64,
                                          c.c_int64, c.c_int64]
        lib.smtpu_vocab_size.restype = c.c_int64
        lib.smtpu_vocab_size.argtypes = [c.c_void_p]
        lib.smtpu_vocab_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.smtpu_vocab_free.argtypes = [c.c_void_p]
        lib.smtpu_corpus_map.restype = c.c_void_p
        lib.smtpu_corpus_map.argtypes = [c.c_char_p, c.c_int, c.c_void_p,
                                         c.c_int64, c.c_int64]
        lib.smtpu_corpus_n_sentences.restype = c.c_int64
        lib.smtpu_corpus_n_sentences.argtypes = [c.c_void_p]
        lib.smtpu_corpus_n_tokens.restype = c.c_int64
        lib.smtpu_corpus_n_tokens.argtypes = [c.c_void_p]
        lib.smtpu_corpus_copy.argtypes = [c.c_void_p, c.c_void_p, c.c_void_p]
        lib.smtpu_corpus_free.argtypes = [c.c_void_p]
        lib.smtpu_batcher_new.restype = c.c_void_p
        lib.smtpu_batcher_new.argtypes = [c.c_void_p, c.c_void_p, c.c_int64,
                                          c.c_int, c.c_void_p, c.c_uint64]
        lib.smtpu_batcher_reset.argtypes = [c.c_void_p, c.c_uint64]
        lib.smtpu_batcher_next.restype = c.c_int64
        lib.smtpu_batcher_next.argtypes = [c.c_void_p, c.c_int64, c.c_void_p,
                                           c.c_void_p, c.c_void_p]
        lib.smtpu_batcher_free.argtypes = [c.c_void_p]
        _lib = lib
        return _lib


def available() -> bool:
    return _load_lib() is not None


_MODE = {"int": 0, "bkdr": 1}


def load_corpus_native(path: str, mode: str = "int", min_count: int = 1,
                       min_sentence_length: int = 1,
                       max_sentence_length: int = 1000):
    """One C++ pass for vocab + one for corpus mapping.

    Returns (vocab, tokens, offsets): ``tokens`` int32 vocab indices
    flattened, ``offsets`` int64 sentence boundaries.
    """
    lib = _load_lib()
    if lib is None:
        raise RuntimeError("native loader unavailable")
    vp = lib.smtpu_vocab_build(path.encode(), _MODE[mode], min_count,
                               min_sentence_length, max_sentence_length)
    if not vp:
        raise FileNotFoundError(path)
    try:
        V = lib.smtpu_vocab_size(vp)
        keys = np.empty(V, np.uint64)
        counts = np.empty(V, np.int64)
        lib.smtpu_vocab_copy(vp, keys.ctypes.data, counts.ctypes.data)
        vocab = Vocab(keys, counts,
                      {int(k): i for i, k in enumerate(keys)})
        cp = lib.smtpu_corpus_map(path.encode(), _MODE[mode], vp,
                                  min_sentence_length, max_sentence_length)
        if not cp:
            raise FileNotFoundError(path)
        try:
            n_sent = lib.smtpu_corpus_n_sentences(cp)
            n_tok = lib.smtpu_corpus_n_tokens(cp)
            tokens = np.empty(n_tok, np.int32)
            offsets = np.empty(n_sent + 1, np.int64)
            lib.smtpu_corpus_copy(cp, tokens.ctypes.data,
                                  offsets.ctypes.data)
        finally:
            lib.smtpu_corpus_free(cp)
    finally:
        lib.smtpu_vocab_free(vp)
    return vocab, tokens, offsets


class NativeCBOWBatcher:
    """C++-backed drop-in for ``CBOWBatcher`` (same batch contract)."""

    def __init__(self, tokens: np.ndarray, offsets: np.ndarray, vocab: Vocab,
                 window: int, sample: float = -1.0, seed: int = 2008):
        from swiftmpi_tpu.ops.sampling import subsample_keep_prob
        lib = _load_lib()
        if lib is None:
            raise RuntimeError("native loader unavailable")
        self._lib = lib
        self.window = int(window)
        self.vocab = vocab
        # keep buffer refs alive: the batcher borrows these arrays
        self._tokens = np.ascontiguousarray(tokens, np.int32)
        self._offsets = np.ascontiguousarray(offsets, np.int64)
        if sample >= 0:
            self._keep = np.ascontiguousarray(
                subsample_keep_prob(vocab.counts, sample), np.float32)
            keep_ptr = self._keep.ctypes.data
        else:
            self._keep = None
            keep_ptr = None
        self._seed = seed
        self._epoch_i = 0
        self._h = lib.smtpu_batcher_new(
            self._tokens.ctypes.data, self._offsets.ctypes.data,
            len(self._offsets) - 1, self.window, keep_ptr, seed)

    def epoch(self, batch_size: int) -> Iterator[CBOWBatch]:
        lib, W2 = self._lib, 2 * self.window
        self._epoch_i += 1
        lib.smtpu_batcher_reset(self._h, self._seed + self._epoch_i)
        while True:
            centers = np.zeros(batch_size, np.int32)
            contexts = np.zeros((batch_size, W2), np.int32)
            mask = np.zeros((batch_size, W2), np.uint8)
            n = lib.smtpu_batcher_next(
                self._h, batch_size, centers.ctypes.data,
                contexts.ctypes.data, mask.ctypes.data)
            if n == 0:
                return
            yield CBOWBatch(centers, contexts, mask.astype(bool), int(n))
            if n < batch_size:
                return

    def __del__(self):
        try:
            if getattr(self, "_h", None):
                self._lib.smtpu_batcher_free(self._h)
                self._h = None
        except Exception:
            pass
