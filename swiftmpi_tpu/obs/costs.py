"""Compiled-program catalog: XLA cost/memory attribution + retrace
tracking (ISSUE 14).

Every jitted step/kernel the model builders and transfer backends
produce funnels through :func:`track`, which wraps the jit in a
:class:`TrackedFn`.  Disarmed (the default), the wrapper is a single
attribute check around the call — the jit object, its dispatch path and
its traced program are untouched, so a default-off run is bit-identical
to one built before this module existed.  Armed (``[obs] costs: 1`` or
``SMTPU_COSTS=1``), every *compile* event — detected as growth of the
jit's own trace cache — is recorded three ways:

* ``compile/compiles{fn=}`` / ``compile/compile_ms{fn=}`` /
  ``compile/retraces{fn=}`` counters in the telemetry registry, so a
  retrace storm shows up in the JSONL stream and the budget gate, not
  just in ``tests/test_retrace_guard.py``;
* XLA's own ``cost_analysis()`` (flops, bytes accessed — a cheap
  trace + StableHLO emit, no backend compile) and, gated by
  ``[obs] costs_memory``, ``memory_analysis()`` (argument/output/temp
  bytes from one extra backend compile) as ``compile/{flops,bytes,
  peak_bytes}{fn=}`` gauges;
* a crash-safe ``runs/compile_catalog.json`` (schema
  ``smtpu-costs/1``), rewritten atomically on every compile event, so
  bench rooflines and ``telemetry_report.py --compile`` can diff the
  measured numbers against the hand byte/FLOP models
  (:func:`CostCatalog.note_hand_model`).

Retrace semantics are **per handle**, matching the retrace-guard test:
one name may cover many jit objects (the w2v fused cache holds one per
group length, the tpu backend one per push signature) and each handle's
FIRST compile is expected; only a handle compiling *again* — genuine
shape/dtype churn on one program — books a retrace.  A control-plane
safe-point recompile builds fresh handles, so it books compiles, never
retraces.
"""

from __future__ import annotations

import json
import os
import threading
import time
from typing import Any, Dict, Optional

#: catalog artifact schema tag (``runs/compile_catalog.json``).
COSTS_SCHEMA = "smtpu-costs/1"
COSTS_SCHEMA_PREFIX = "smtpu-costs/"

#: env override that arms the catalog without a config edit — the bench
#: harness sets it in child processes so rooflines get measured numbers.
ENV_COSTS = "SMTPU_COSTS"


class CostCatalog:
    """Per-process compile-event ledger.  Created disarmed; armed by
    :func:`configure_costs` (or programmatically by the bench child).
    Writers go through :func:`get_catalog` each call — the instance is
    swapped by :func:`reset_for_tests`, like the metrics registry."""

    def __init__(self, enabled: bool = False,
                 path: Optional[str] = None,
                 memory: bool = True, analyze_max: int = 1,
                 run: str = "run"):
        self.enabled = enabled
        self.path = path
        #: run memory_analysis (one extra backend compile per analyzed
        #: handle) — [obs] costs_memory
        self.memory = memory
        #: handles analyzed per fn name (lower+cost_analysis per handle
        #: is cheap but not free; the first handle is representative)
        self.analyze_max = analyze_max
        self.run = run
        self._lock = threading.Lock()
        self._fns: Dict[str, dict] = {}     # guarded-by: _lock
        self._analyzed: Dict[str, int] = {}  # guarded-by: _lock

    # -- the compile event -------------------------------------------------
    def on_compile(self, name: str, fn, args, kwargs, dt_ms: float,
                   handle_compiles: int, steps_per_call: int = 1) -> None:
        """Book one compile of ``fn`` (the unwrapped jit) under ``name``.
        ``handle_compiles`` is the wrapping handle's own compile count —
        > 1 means this very program re-traced, which is the retrace
        signal.  ``dt_ms`` is the wall time of the compiling call (it
        includes the first execution — the operator-facing number is
        "how long did the step stall for this compile")."""
        retrace = handle_compiles > 1
        with self._lock:
            e = self._fns.get(name)
            if e is None:
                e = self._fns[name] = {
                    "fn": name, "compiles": 0, "retraces": 0,
                    "compile_ms_total": 0.0, "last_compile_ms": 0.0,
                    "steps_per_call": steps_per_call,
                }
            e["compiles"] += 1
            e["compile_ms_total"] += dt_ms
            e["last_compile_ms"] = dt_ms
            e["steps_per_call"] = steps_per_call
            if retrace:
                e["retraces"] += 1
            n_analyzed = self._analyzed.get(name, 0)
            analyze = n_analyzed < self.analyze_max
            if analyze:
                self._analyzed[name] = n_analyzed + 1
        from swiftmpi_tpu import obs
        reg = obs.get_registry()
        reg.counter("compile/compiles", fn=name).inc()
        reg.counter("compile/compile_ms", fn=name).inc(dt_ms)
        if retrace:
            reg.counter("compile/retraces", fn=name).inc()
        if analyze:
            a = _analyze(fn, args, kwargs, memory=self.memory)
            if a:
                with self._lock:
                    self._fns[name].update(a)
                if a.get("flops"):
                    reg.gauge("compile/flops", fn=name).set(a["flops"])
                if a.get("bytes_accessed"):
                    reg.gauge("compile/bytes",
                              fn=name).set(a["bytes_accessed"])
                if a.get("peak_bytes"):
                    reg.gauge("compile/peak_bytes",
                              fn=name).set(a["peak_bytes"])
        self._persist()

    # -- hand-model drift --------------------------------------------------
    def note_hand_model(self, name: str, flops: Optional[float] = None,
                        bytes_accessed: Optional[float] = None) -> None:
        """Record the hand byte/FLOP model's *per-call* prediction for
        ``name`` so reports can print measured-vs-model drift.  Callers
        with per-step models multiply by the fn's steps_per_call."""
        with self._lock:
            e = self._fns.setdefault(name, {
                "fn": name, "compiles": 0, "retraces": 0,
                "compile_ms_total": 0.0, "last_compile_ms": 0.0,
                "steps_per_call": 1,
            })
            if flops is not None:
                e["hand_flops"] = float(flops)
            if bytes_accessed is not None:
                e["hand_bytes"] = float(bytes_accessed)
        self._persist()

    # -- reads -------------------------------------------------------------
    def entry(self, name: str) -> Optional[dict]:
        with self._lock:
            e = self._fns.get(name)
            return dict(e) if e is not None else None

    def entries(self) -> Dict[str, dict]:
        with self._lock:
            return {k: dict(v) for k, v in self._fns.items()}

    def snapshot(self) -> dict:
        """The ``smtpu-costs/1`` document: per-fn compile/retrace
        counts, measured flops/bytes, and drift percentages wherever a
        hand model was noted next to a measurement."""
        fns = self.entries()
        for e in fns.values():
            _add_drift(e)
        return {"schema": COSTS_SCHEMA, "run": self.run,
                "ts": time.time(), "fns": fns}

    # -- persistence ---------------------------------------------------
    def _persist(self) -> None:
        path = self.path
        if not path:
            return
        doc = self.snapshot()
        try:
            parent = os.path.dirname(path)
            if parent:
                os.makedirs(parent, exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                json.dump(doc, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        except OSError:
            pass    # artifact write must never take down training


def _add_drift(e: dict) -> None:
    """measured-vs-hand drift: positive = the hand model OVERestimates."""
    f, hf = e.get("flops"), e.get("hand_flops")
    if f and hf is not None:
        e["flops_drift_pct"] = round(100.0 * (hf - f) / f, 1)
    b, hb = e.get("bytes_accessed"), e.get("hand_bytes")
    if b and hb is not None:
        e["bytes_drift_pct"] = round(100.0 * (hb - b) / b, 1)


def _analyze(fn, args, kwargs, memory: bool = True) -> dict:
    """Best-effort XLA analysis of one compiled handle.  ``lower()`` is
    shape-only, so it is safe even after the triggering call donated
    its buffers; ``cost_analysis()`` on the Lowered needs no backend
    compile.  ``memory_analysis()`` does one — gated by ``memory``."""
    out: Dict[str, Any] = {}
    lower = getattr(fn, "lower", None)
    if lower is None:
        return out
    try:
        lowered = lower(*args, **kwargs)
    except Exception:
        return out
    try:
        ca = lowered.cost_analysis()
        if isinstance(ca, (list, tuple)):    # Compiled-level shape
            ca = ca[0] if ca else {}
        if isinstance(ca, dict):
            f = ca.get("flops")
            b = ca.get("bytes accessed")
            if f is not None and float(f) > 0:
                out["flops"] = float(f)
            if b is not None and float(b) > 0:
                out["bytes_accessed"] = float(b)
    except Exception:
        pass
    if memory:
        try:
            ms = lowered.compile().memory_analysis()
            arg = int(getattr(ms, "argument_size_in_bytes", 0))
            outb = int(getattr(ms, "output_size_in_bytes", 0))
            tmp = int(getattr(ms, "temp_size_in_bytes", 0))
            alias = int(getattr(ms, "alias_size_in_bytes", 0))
            out["argument_bytes"] = arg
            out["output_bytes"] = outb
            out["temp_bytes"] = tmp
            out["alias_bytes"] = alias
            # live-at-once upper bound: donated (aliased) buffers are
            # not double-counted
            out["peak_bytes"] = max(arg + outb + tmp - alias, 0)
        except Exception:
            pass
    return out


class TrackedFn:
    """The funnel wrapper around one jit handle.

    Call path invariant: the wrapped jit is ALWAYS the callee — armed
    or not, cached or first call — so arming cannot change dispatch
    behavior, only observe it.  Compile detection is the jit's own
    ``_cache_size()`` growing across a call (the same signal
    tests/test_retrace_guard.py pins); handles without a cache probe
    (plain callables) simply never book events.

    Unknown attributes forward to the wrapped fn, so ``lower()`` /
    ``_cache_size()`` callers don't need to know about the wrapper.
    """

    __slots__ = ("_fn", "name", "steps_per_call", "_compiles",
                 "__weakref__")

    def __init__(self, name: str, fn, steps_per_call: int = 1):
        self._fn = fn
        self.name = name
        self.steps_per_call = max(int(steps_per_call), 1)
        self._compiles = 0

    def __call__(self, *args, **kwargs):
        cat = _CATALOG
        if not cat.enabled:
            return self._fn(*args, **kwargs)
        before = self._cache_size()
        t0 = time.perf_counter()
        out = self._fn(*args, **kwargs)
        if before >= 0 and self._cache_size() > before:
            dt_ms = (time.perf_counter() - t0) * 1e3
            self._compiles += 1
            cat.on_compile(self.name, self._fn, args, kwargs, dt_ms,
                           self._compiles, self.steps_per_call)
        return out

    def _cache_size(self) -> int:
        cs = getattr(self._fn, "_cache_size", None)
        if cs is None:
            return -1
        try:
            return int(cs())
        except Exception:
            return -1

    def __getattr__(self, attr):
        return getattr(self._fn, attr)

    def __repr__(self) -> str:
        return f"TrackedFn({self.name!r}, {self._fn!r})"


def track(name: str, fn, steps_per_call: int = 1) -> TrackedFn:
    """Register ``fn`` (a jit handle) in the catalog under ``name``.
    Idempotent on an already-tracked fn (keeps the original name)."""
    if isinstance(fn, TrackedFn):
        return fn
    return TrackedFn(name, fn, steps_per_call)


# -- module globals (the registry pattern: swap via reset_for_tests) --------

_CATALOG = CostCatalog()


def get_catalog() -> CostCatalog:
    """The process-global catalog (disarmed unless configured)."""
    return _CATALOG


def reset_for_tests() -> CostCatalog:
    global _CATALOG
    _CATALOG = CostCatalog()
    return _CATALOG


def configure_costs(config, run: str = "run") -> Optional[CostCatalog]:
    """Arm the catalog from ``[obs]`` config (or ``SMTPU_COSTS=1``).

    Knobs: ``costs`` (master switch, default 0), ``costs_path`` (JSON
    artifact, default ``runs/compile_catalog.json``; empty = in-memory
    only) and ``costs_memory`` (memory_analysis compile, default 1).
    Returns the armed catalog, or None when the plane stays off."""
    g = config.get_or
    on = g("obs", "costs", 0).to_bool() or \
        os.environ.get(ENV_COSTS, "") not in ("", "0")
    if not on:
        return None
    cat = get_catalog()
    cat.enabled = True
    cat.run = run
    cat.path = g("obs", "costs_path",
                 os.path.join("runs", "compile_catalog.json")).to_string()
    cat.memory = g("obs", "costs_memory", 1).to_bool()
    return cat
