"""Triggered profiler windows: bounded ``jax.profiler`` captures on a
live run (ISSUE 14).

A :class:`ProfileSession` sits on the consumed-step funnel
(``obs.record_step``) and captures an N-step device trace when any of
three triggers fires:

* the ``[obs] profile_at: <step>`` knob (one capture, at that step);
* a trigger file in the fleet directory — ``request_profile()`` / the
  ``python -m swiftmpi_tpu.obs.profiler <fleet_dir>`` CLI writes
  ``profile_trigger.json`` and every rank's session picks it up on its
  next (throttled) poll, so one command profiles the whole fleet
  (``launch.py -profile-at`` pre-arms the same thing via env);
* :meth:`request` — wired to the numerics plane so a critical anomaly
  captures the very steps that misbehaved
  (``[obs] profile_on_anomaly``).

Artifacts land under ``runs/profiles/profile_step<N>_r<rank>/``: the
raw TensorBoard/perfetto trace plus a ``profile_summary.json`` from
:func:`parse_trace_dir` — a best-effort chrome-trace parse that splits
device- from host-side events (the ``process_name`` metadata) and
attributes duration to the existing ``named_scope``/``span`` phase
names, reporting per-phase device-vs-host skew.  The same attribution
lands in the registry as ``profile/{device_ms,host_ms,skew_ms}{phase=}``
gauges and ``profile/{sessions,steps}`` counters, so the capture is
visible in the telemetry stream it explains.

No session installed (the default) means ``record_step`` never touches
this module — trajectories stay bit-identical.
"""

from __future__ import annotations

import glob
import gzip
import json
import os
import time
from typing import Dict, List, Optional

from swiftmpi_tpu.obs.identity import process_rank

#: fleet-dir trigger file: ``{"id": n, "steps": k}``; ids increase so a
#: session replays each request exactly once.
TRIGGER_FILENAME = "profile_trigger.json"

#: per-capture summary schema (``profile_summary.json``).
PROFILE_SCHEMA = "smtpu-profile/1"

#: env pre-arm (set by ``launch.py -profile-at`` for every rank).
ENV_PROFILE_AT = "SMTPU_PROFILE_AT"
ENV_PROFILE_STEPS = "SMTPU_PROFILE_STEPS"

#: phase names the trace parser attributes duration to — the union of
#: the host ``obs.span`` names and the in-jit ``obs.named_scope`` names
#: already emitted across the codebase.  Substring match: XLA embeds
#: scope names inside fused-kernel labels.
KNOWN_PHASES = (
    "window_dedup", "wire_exchange", "apply", "pallas_gather_stencil",
    "serve/topk", "render", "h2d", "input_wait", "dispatch",
    "checkpoint_save",
)


def request_profile(fleet_dir: str, steps: int = 5) -> dict:
    """Drop a capture request in ``fleet_dir`` for every rank's session
    to pick up.  Monotonic id = previous id + 1 (a stale file from a
    finished run is superseded, not replayed)."""
    path = os.path.join(fleet_dir, TRIGGER_FILENAME)
    prev = 0
    try:
        with open(path) as f:
            prev = int(json.load(f).get("id", 0))
    except (OSError, ValueError):
        pass
    req = {"id": prev + 1, "steps": int(steps), "ts": time.time()}
    os.makedirs(fleet_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(req, f)
    os.replace(tmp, path)
    return req


# -- trace parsing ----------------------------------------------------------

def parse_trace_dir(root: str,
                    phases: Optional[tuple] = None) -> dict:
    """Best-effort phase attribution over every chrome-format trace
    (``*.trace.json.gz`` and the perfetto twin) under ``root``.

    Complete events (``ph == "X"``) are split device/host by their
    process's ``process_name`` metadata (``/device:...`` vs host) and
    their duration is credited to the first KNOWN phase whose name is a
    substring of the event name — nested events under a scope repeat
    the scope in their names, so this over-counts nesting rather than
    attributing to the wrong phase; the numbers are for *ranking*
    phases, not summing to wall clock.  Events matching no phase
    aggregate under ``"other"``."""
    phases = phases or KNOWN_PHASES
    device_ms: Dict[str, float] = {}
    host_ms: Dict[str, float] = {}
    files = sorted(
        set(glob.glob(os.path.join(root, "**", "*.trace.json.gz"),
                      recursive=True))
        | set(glob.glob(os.path.join(root, "**",
                                     "perfetto_trace.json.gz"),
                        recursive=True)))
    # the per-host trace and the perfetto export carry the same events;
    # parse only one of each basename flavor to avoid double counting
    if any(p.endswith(".trace.json.gz")
           and not p.endswith("perfetto_trace.json.gz") for p in files):
        files = [p for p in files
                 if not p.endswith("perfetto_trace.json.gz")]
    n_events = 0
    for path in files:
        try:
            with gzip.open(path, "rt") as f:
                doc = json.load(f)
        except (OSError, ValueError):
            continue
        events = doc.get("traceEvents") or []
        procs: Dict[int, str] = {}
        for ev in events:
            if ev.get("ph") == "M" and ev.get("name") == "process_name":
                procs[ev.get("pid")] = str(
                    (ev.get("args") or {}).get("name", ""))
        for ev in events:
            if ev.get("ph") != "X":
                continue
            dur_ms = float(ev.get("dur", 0.0)) / 1e3   # trace dur is µs
            if dur_ms <= 0:
                continue
            name = str(ev.get("name", ""))
            if name.startswith("$"):        # python frame-trace noise
                continue
            n_events += 1
            side = device_ms if "/device:" in procs.get(
                ev.get("pid"), "") else host_ms
            for ph in phases:
                if ph in name:
                    side[ph] = side.get(ph, 0.0) + dur_ms
                    break
            else:
                side["other"] = side.get("other", 0.0) + dur_ms
    skew_ms = {ph: host_ms.get(ph, 0.0) - device_ms.get(ph, 0.0)
               for ph in set(device_ms) | set(host_ms)}
    return {"schema": PROFILE_SCHEMA, "files": len(files),
            "events": n_events, "device_ms": device_ms,
            "host_ms": host_ms, "skew_ms": skew_ms}


# -- the session ------------------------------------------------------------

class ProfileSession:
    """One rank's triggered-capture state machine.  Single-threaded by
    construction: every transition happens on the trainer thread inside
    ``obs.record_step`` (anomaly requests only park a flag)."""

    def __init__(self, profile_dir: str = os.path.join("runs",
                                                       "profiles"),
                 steps: int = 5, profile_at: int = -1,
                 fleet_dir: Optional[str] = None,
                 poll_s: float = 1.0,
                 capture_on_anomaly: bool = False):
        self.profile_dir = profile_dir
        self.steps = max(int(steps), 1)
        self.profile_at = int(profile_at)
        self.fleet_dir = fleet_dir or None
        self.poll_s = poll_s
        self.capture_on_anomaly = capture_on_anomaly
        self.captures: List[dict] = []
        self._consumed = 0
        self._active: Optional[dict] = None
        self._pending: Optional[dict] = None
        self._done_trigger_id = 0
        self._last_poll = 0.0

    # -- triggers ----------------------------------------------------------
    def request(self, steps: Optional[int] = None,
                reason: str = "manual") -> None:
        """Ask for a capture at the next consumed step.  Safe from any
        thread (it only parks a dict); ignored while one is already
        pending or active."""
        if self._active is None and self._pending is None:
            self._pending = {"steps": int(steps or self.steps),
                             "reason": reason}

    def _poll_trigger(self) -> None:
        now = time.monotonic()
        if now - self._last_poll < self.poll_s:
            return
        self._last_poll = now
        try:
            with open(os.path.join(self.fleet_dir,
                                   TRIGGER_FILENAME)) as f:
                req = json.load(f)
        except (OSError, ValueError):
            return
        tid = int(req.get("id", 0))
        if tid <= self._done_trigger_id:
            return
        self._done_trigger_id = tid
        self.request(steps=int(req.get("steps", self.steps)),
                     reason=f"trigger:{tid}")

    # -- the step funnel ---------------------------------------------------
    def on_step(self, n: int = 1) -> None:
        """Account ``n`` consumed steps; start/stop captures at step
        granularity (a fused group of L steps counts as L — a capture
        window never splits a dispatch)."""
        self._consumed += n
        if self._active is not None:
            self._active["remaining"] -= n
            if self._active["remaining"] <= 0:
                self._stop()
            return
        if 0 <= self.profile_at <= self._consumed:
            self.profile_at = -1      # the knob fires once
            self._start(self.steps, "profile_at")
            return
        if self._pending is None and self.fleet_dir:
            self._poll_trigger()
        if self._pending is not None:
            p, self._pending = self._pending, None
            self._start(p["steps"], p["reason"])

    def close(self) -> None:
        """Finish an in-flight capture (end of training mid-window)."""
        if self._active is not None:
            self._stop()

    # -- capture lifecycle -------------------------------------------------
    def _start(self, steps: int, reason: str) -> None:
        import jax
        out = os.path.join(
            self.profile_dir,
            f"profile_step{self._consumed}_r{process_rank() or 0}")
        try:
            os.makedirs(out, exist_ok=True)
            jax.profiler.start_trace(out, create_perfetto_trace=True)
        except Exception:
            return   # a second profiler on the host must not kill train
        self._active = {"dir": out, "start_step": self._consumed,
                        "steps": steps, "remaining": steps,
                        "reason": reason, "t0": time.perf_counter()}
        from swiftmpi_tpu import obs
        obs.get_registry().counter("profile/sessions").inc()

    def _stop(self) -> None:
        import jax
        act, self._active = self._active, None
        try:
            jax.profiler.stop_trace()
        except Exception:
            pass
        captured = act["steps"] - max(act["remaining"], 0)
        summary = parse_trace_dir(act["dir"])
        summary.update(
            run_dir=act["dir"], reason=act["reason"],
            start_step=act["start_step"],
            steps=captured, rank=process_rank() or 0,
            wall_ms=(time.perf_counter() - act["t0"]) * 1e3)
        try:
            with open(os.path.join(act["dir"],
                                   "profile_summary.json"), "w") as f:
                json.dump(summary, f, indent=1, sort_keys=True)
        except OSError:
            pass
        from swiftmpi_tpu import obs
        reg = obs.get_registry()
        reg.counter("profile/steps").inc(captured)
        for ph, v in summary["device_ms"].items():
            reg.gauge("profile/device_ms", phase=ph).set(v)
        for ph, v in summary["host_ms"].items():
            reg.gauge("profile/host_ms", phase=ph).set(v)
        for ph, v in summary["skew_ms"].items():
            reg.gauge("profile/skew_ms", phase=ph).set(v)
        rec = obs.get_recorder()
        if rec is not None:
            rec.event("profile/capture",
                      {k: summary[k] for k in
                       ("run_dir", "reason", "start_step", "steps",
                        "files", "events")})
        self.captures.append(summary)


def on_critical_anomaly(anomaly: dict) -> None:
    """Numerics-plane hook: a critical anomaly captures the very steps
    that misbehaved.  No-op unless a session with
    ``capture_on_anomaly`` is installed."""
    from swiftmpi_tpu import obs
    sess = obs.get_profiler()
    if sess is not None and sess.capture_on_anomaly:
        sess.request(reason=f"anomaly:{anomaly.get('anomaly', '?')}")


def main(argv: Optional[list] = None) -> int:
    """``python -m swiftmpi_tpu.obs.profiler <fleet_dir> [--steps N]``:
    request an N-step capture from every rank of a live fleet run."""
    import argparse
    ap = argparse.ArgumentParser(
        description="drop a profile trigger in a fleet dir")
    ap.add_argument("fleet_dir", help="launch.py -fleet-dir target")
    ap.add_argument("--steps", type=int, default=5,
                    help="capture window length in consumed steps")
    args = ap.parse_args(argv)
    req = request_profile(args.fleet_dir, steps=args.steps)
    print(f"profile trigger id={req['id']} steps={req['steps']} "
          f"written to {os.path.join(args.fleet_dir, TRIGGER_FILENAME)}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
