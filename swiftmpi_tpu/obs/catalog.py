"""Declared telemetry series catalog — the ONE list of metric names.

Every instrument registration in the codebase (``reg.counter(...)``,
``reg.gauge(...)``, ``reg.histogram(...)``, the transfer backends'
``_obs_inc`` mirror, the fault bus's ``_obs_count``) must use a name
declared here.  The TELEMETRY-CATALOG lint rule
(:mod:`swiftmpi_tpu.analysis.rules`) enforces the match statically, so
a typo'd series name — or a new series added to one of the four
transfer-backend mirrors but not the others — fails the lint gate
instead of silently forking the dashboard namespace.

Two declaration forms:

* :data:`SERIES` — exact names.  Labels are NOT part of the identity
  here (the registry's ``name{label=v}`` series keys stay free-form);
  the catalog pins the *name* half of the contract that
  docs/ARCHITECTURE.md "Telemetry plane" documents in prose.
* :data:`PREFIXES` — dynamic families built with f-strings whose
  stem is static (``control/<knob-name>`` gauges, the microbench
  ``micro_<gauge>`` context scalars).  The lint rule checks an
  f-string's leading literal chunk against these.

``transfer/`` series are declared via :data:`TRANSFER_KEYS` (the bare
ledger key, as passed to ``Transfer._obs_inc``) and expanded into
``SERIES`` below, so the ledger key list lives in exactly one place.
"""

from __future__ import annotations

#: Ledger keys mirrored by ``Transfer._obs_inc`` as ``transfer/<key>``.
#: All four backends (local/xla/tpu/hybrid) — including the tpu
#: backend's eager-drain paths, which bypass ``_accum_*`` — must book
#: through keys declared here.
TRANSFER_KEYS = frozenset({
    "wire_bytes", "dispatches",
    "window_sparse", "window_dense",            # legacy 2-way decisions
    "window_fmt",                               # 5-way, fmt= label
    "collective",                               # psum|sparse_ar, kind=
    "hot_psum_bytes_saved",                     # sparse_ar wire delta
    "plan_compiles", "plan_cache_hits",         # TrafficPlan compiler
    "coalesced_rows_in", "coalesced_rows_out",
    "pull_bytes", "pull_rows", "pull_hot_rows",
    "pull_cache_hits", "pull_delta_rows",        # delta-pull cache plane
    "pull_bytes_saved",
    "pull_fmt",                                  # pull decisions, fmt=
    "routed_rows", "overflow_dropped",          # tpu routing ledger
    "hot_rows", "psum_bytes",                   # hybrid hot plane
    "membership_changes",                       # elastic epoch adoptions
})

SERIES = frozenset({
    # host phase spans (obs.span) + bench latency publish default
    "phase_ms", "step_ms",
    # input pipeline (io/pipeline.py)
    "pipeline/produced", "pipeline/consumed", "pipeline/queue_depth",
    # training loops (word2vec/glove via Throughput sampler bridge)
    "train/host_stall_ms_total", "train/device_ms_total",
    "train/words_per_sec",
    # checkpoints (io/checkpoint.py)
    "checkpoint/saves", "checkpoint/restores",
    # health probes (utils/health.py)
    "health/probe_ok", "health/probe_fail", "health/probe_ms",
    # fault-injection bus (testing/faults.py)
    "faults/injected", "faults/step_events", "faults/checkpoint_events",
    # serving plane (serve/)
    "serve/queries", "serve/rows_read", "serve/hits", "serve/misses",
    "serve/topk_queries", "serve/latency_ms", "serve/snapshots",
    "serve/snapshot_version", "serve/staleness_steps",
    # snapshot shipping (serve/shipper.py, ISSUE 17): trainer-side
    # publish kind/byte counters (delta_fmt carries a fmt= label) and
    # the replica-side replay gauges ({replica=r<rank>} labeled)
    "serve/delta_publishes", "serve/delta_bytes", "serve/delta_fmt",
    "serve/full_publishes", "serve/full_bytes", "serve/ship_version",
    "serve/replica_version", "serve/replica_lag", "serve/staleness_s",
    # control plane (control/controller.py)
    "control/evaluations", "control/decisions",
    "control/decisions_applied", "control/sketch_observed",
    # fleet observability (obs/collector.py, obs/recorder.py heartbeats)
    "telemetry/heartbeats",
    "fleet/step_ms_skew", "fleet/wire_bytes_imbalance",
    "fleet/members_live", "fleet/members_stalled", "fleet/members_dead",
    "fleet/straggler_rank",
    # numerics health plane (obs/numerics.py, ISSUE 13): in-jit bundle
    # gauges mirrored by NumericsCollector.sampler plus the detector's
    # anomaly severity counter; ef_mass carries a field= label
    "numerics/grad_norm", "numerics/grad_norm_hot",
    "numerics/grad_norm_tail", "numerics/update_ratio", "numerics/loss",
    "numerics/ef_mass", "numerics/nonfinite", "numerics/quant_err",
    "numerics/anomalies",
    # fleet-level numerics mirror (obs/collector.py)
    "fleet/grad_norm_divergence", "fleet/anomalies",
    # compiler & device-cost plane (obs/costs.py, ISSUE 14): per-fn
    # compile/retrace counters and XLA cost/memory-analysis gauges,
    # all labeled fn=<catalog name>
    "compile/compiles", "compile/retraces", "compile/compile_ms",
    "compile/flops", "compile/bytes", "compile/peak_bytes",
    # triggered profiler windows (obs/profiler.py): capture counters
    # and per-phase device/host attribution from the trace parse
    "profile/sessions", "profile/steps",
    "profile/device_ms", "profile/host_ms", "profile/skew_ms",
    # wire-path tracing plane (obs/trace.py, ISSUE 15): flight-recorder
    # volume counters, the last-traced-window gauge smtpu_top's WIN
    # column reads, and the hot-key attribution gauges (key= label)
    "trace/windows", "trace/records", "trace/dumps",
    "trace/last_window_id",
    "trace/hot_key_touches", "trace/hot_key_bytes",
    # elastic membership plane (cluster/membership.py + elastic.py,
    # ISSUE 16): per-rank adopted epoch / workload gauges, the modeled
    # migration-delta traffic, and the fleet-level mirrors
    "elastic/epoch", "elastic/loss", "elastic/rows_owned",
    "elastic/migration_bytes",
    "fleet/epoch", "fleet/reconverge_steps", "fleet/migration_bytes",
    # serve-fleet mirrors (obs/collector.py serve_view, ISSUE 17)
    "fleet/serve_replicas", "fleet/serve_qps", "fleet/serve_lag_max",
    "fleet/serve_version",
}) | frozenset("transfer/" + k for k in TRANSFER_KEYS)

#: Dynamic-name families: an f-string series name passes the catalog
#: check when its leading literal chunk starts with one of these.
PREFIXES = (
    "control/",     # per-knob gauges: control/<knob.name>
    "micro_",       # microbench context gauges: micro_<key>{cell=}
)


def declared(name: str) -> bool:
    """True when ``name`` is a declared series (exact or prefix)."""
    return name in SERIES or any(name.startswith(p) for p in PREFIXES)


def declared_prefix(stem: str) -> bool:
    """True when an f-string whose literal stem is ``stem`` builds
    names inside a declared dynamic family."""
    return any(stem.startswith(p) for p in PREFIXES)
