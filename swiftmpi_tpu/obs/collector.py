"""Fleet telemetry: merge per-rank streams into one cross-rank timeline.

Every telemetry capability before this module observes exactly ONE
process — a :class:`~swiftmpi_tpu.obs.recorder.StepRecorder` per rank,
one JSONL per rank.  The questions an N-process deployment actually
raises are *cross-rank*: which rank is the straggler, how skewed is the
wire load across shards, is any member stalled or dead.
:class:`FleetCollector` answers them by tailing the per-rank
``smtpu-telemetry/1`` streams (plus the supervisor's event log) in a
shared **fleet directory** and merging them into a schema-versioned
``smtpu-fleet/1`` timeline.

Fleet directory layout (the ``SMTPU_FLEET_DIR`` contract,
cluster/bootstrap.py):

* ``telemetry_r<rank>_p<pid>.jsonl`` — one stream per rank *life*: a
  supervisor restart keeps the rank and changes the pid, so pre- and
  post-restart streams coexist and the collector merges them into one
  member history (restart count = streams − 1).
* ``supervisor.jsonl`` — ``smtpu-fleet-sup/1`` events appended by
  ``launch.py``: spawn / exit (with normalized rc and whether the
  supervisor itself delivered the kill) / restart / world_start /
  world_exit.  These correlate a member's silence with *why* it went
  silent — a heartbeat gap WITH a supervisor exit event is a recorded
  death; a gap without one is an **unnoticed death**, which the budget
  gate treats as an observability failure.
* ``fleet.jsonl`` — the merged timeline :meth:`FleetCollector.
  write_timeline` emits (consumed by ``telemetry_report.py --fleet``
  and ``check_traffic_budget.py``).

Merge key: **consumed step**.  Per-rank wall clocks are reconstructed as
``meta.ts + record.t`` (the meta line carries ``time.time()`` at
recorder start; records carry monotonic seconds since start), so
cross-rank step alignment tolerates ragged process start times without
any clock-sync machinery — good to the NTP skew of one host, which is
exactly the supervised-local deployment this collector targets.

Health state machine (per member, evaluated at ``now`` = the newest
timestamp seen anywhere in the fleet, so post-hoc analysis of a
finished run does not read everything as dead):

``live`` --(no proof of life for stall_after_s)--> ``stalled``
--(proof resumes)--> ``live``; any state --(supervisor exit rc!=0 or
signal)--> ``dead``; any state --(exit rc==0)--> ``exited``; ``live``/
``stalled`` --(silence > dead_after_s, NO supervisor event)--> ``dead``
(flagged *unnoticed*).  Proof of life = any step record or heartbeat.

Skew metrics (see :meth:`FleetCollector.summary`):

* ``fleet/step_ms_skew`` — p50 over aligned steps of
  ``max(step_ms) − min(step_ms)`` across ranks; ``_pct`` normalizes by
  the fleet-median step time so gates survive hardware changes.
* ``fleet/wire_bytes_imbalance`` — ``max/mean − 1`` over per-rank
  cumulative wire bytes (0 = perfectly balanced), the per-parameter
  load-skew signal Parallax-style placement feeds on.
* straggler attribution — per aligned interval, the rank with the
  largest ``step_ms``; the fleet-level straggler is the rank with the
  largest *total* step time over the common range, flagged when it
  exceeds ``straggler_factor`` × the median rank's total.
"""

from __future__ import annotations

import glob
import json
import os
import re
import time
from typing import Dict, List, Optional, Tuple

from swiftmpi_tpu.cluster.bootstrap import ENV_FLEET_DIR  # noqa: F401
from swiftmpi_tpu.obs.registry import parse_series_key

FLEET_SCHEMA = "smtpu-fleet/1"
FLEET_SCHEMA_V = 1
SUP_SCHEMA = "smtpu-fleet-sup/1"
SUPERVISOR_LOG = "supervisor.jsonl"
MERGED_TIMELINE = "fleet.jsonl"

_STREAM_GLOB = "telemetry_*.jsonl"
_STREAM_RE = re.compile(r"telemetry_(?:r(?P<rank>\d+)_)?p(?P<pid>\d+)\.jsonl$")


def stream_filename(rank: Optional[int], pid: int) -> str:
    """Per-life stream name: rank + pid together, so a restarted rank
    (same rank, new pid) opens a NEW file instead of interleaving with
    its previous life's tail."""
    if rank is None:
        return f"telemetry_p{pid}.jsonl"
    return f"telemetry_r{rank}_p{pid}.jsonl"


def repair_json_line(line: str) -> Optional[dict]:
    """Best-effort parse of a truncated JSON object line (a rank killed
    mid-``write``).  Balances any unterminated string and unclosed
    brackets, retrying progressively shorter prefixes until one parses;
    returns the dict (caller marks it ``repaired``) or None.  A twin of
    this function lives in scripts/telemetry_report.py, which must stay
    repo-import-free — keep the two in sync."""
    s = line.strip()
    if not s.startswith("{"):
        return None
    for cut in range(len(s), max(len(s) - 4096, 0), -1):
        prefix = s[:cut]
        stack: List[str] = []
        in_str = esc = False
        for ch in prefix:
            if esc:
                esc = False
            elif ch == "\\":
                esc = True
            elif ch == '"':
                in_str = not in_str
            elif not in_str and ch in "{[":
                stack.append(ch)
            elif not in_str and ch in "}]":
                if not stack:
                    break
                stack.pop()
        else:
            if esc:
                continue
            closed = prefix + ('"' if in_str else "")
            for b in reversed(stack):
                closed += "}" if b == "{" else "]"
            try:
                obj = json.loads(closed)
            except ValueError:
                continue
            if isinstance(obj, dict):
                return obj
    return None


class SupervisorLog:
    """Append-only ``smtpu-fleet-sup/1`` event sink for ``launch.py``.

    One instance per *supervision* (it survives restart-the-world
    attempts); every event is flushed immediately — a supervisor that
    crashes must not take the crash evidence with it."""

    def __init__(self, fleet_dir: str):
        os.makedirs(fleet_dir, exist_ok=True)
        self.path = os.path.join(fleet_dir, SUPERVISOR_LOG)
        self._file = open(self.path, "a")

    def event(self, kind: str, **payload) -> dict:
        rec = {"v": FLEET_SCHEMA_V, "schema": SUP_SCHEMA,
               "kind": str(kind), "ts": time.time(), **payload}
        self._file.write(json.dumps(rec, sort_keys=True) + "\n")
        self._file.flush()
        return rec

    def close(self) -> None:
        if self._file is not None:
            self._file.close()
            self._file = None


class _Stream:
    """Incremental tail state for one per-life telemetry JSONL."""

    def __init__(self, path: str):
        self.path = path
        self.pos = 0
        self.carry = b""
        self.meta: Optional[dict] = None
        self.rank: Optional[int] = None
        self.pid: Optional[int] = None
        self.ident: Optional[str] = None
        self.t0 = 0.0                       # wall clock at recorder start
        self.records: List[dict] = []       # step records, t_abs added
        self.events: List[dict] = []        # control/... out-of-band lines
        self.heartbeats: List[float] = []   # wall-clock ts
        self.summary: Optional[dict] = None
        self.first_seen: Optional[float] = None
        self.last_seen: Optional[float] = None
        self.dropped = 0
        self.recovered = 0
        m = _STREAM_RE.search(os.path.basename(path))
        if m:
            self.pid = int(m.group("pid"))
            if m.group("rank") is not None:
                self.rank = int(m.group("rank"))

    # -- tailing -----------------------------------------------------------
    def poll(self, final: bool = False) -> int:
        """Consume newly appended complete lines; with ``final`` also
        repair-parse a truncated trailing line.  Returns records read."""
        try:
            with open(self.path, "rb") as f:
                f.seek(self.pos)
                chunk = f.read()
        except OSError:
            return 0
        self.pos += len(chunk)
        data = self.carry + chunk
        lines = data.split(b"\n")
        self.carry = lines.pop()            # incomplete tail (or b"")
        n = 0
        for raw in lines:
            if not raw.strip():
                continue
            try:
                rec = json.loads(raw)
            except ValueError:
                self.dropped += 1
                continue
            if isinstance(rec, dict):
                self._ingest(rec)
                n += 1
        if final and self.carry.strip():
            rec = repair_json_line(self.carry.decode("utf-8", "replace"))
            self.carry = b""
            if rec is not None:
                rec["repaired"] = True
                self._ingest(rec)
                self.recovered += 1
                n += 1
            else:
                self.dropped += 1
        return n

    def _mark_seen(self, t: float) -> None:
        if self.first_seen is None or t < self.first_seen:
            self.first_seen = t
        if self.last_seen is None or t > self.last_seen:
            self.last_seen = t

    def _ingest(self, rec: dict) -> None:
        kind = rec.get("kind")
        if kind == "meta":
            self.meta = rec
            self.t0 = float(rec.get("ts", 0.0))
            if rec.get("rank") is not None:
                self.rank = int(rec["rank"])
            if rec.get("pid") is not None:
                self.pid = int(rec["pid"])
            self.ident = rec.get("ident")
            self._mark_seen(self.t0)
        elif kind == "step":
            rec["t_abs"] = self.t0 + float(rec.get("t", 0.0))
            self.records.append(rec)
            self._mark_seen(rec["t_abs"])
        elif kind == "heartbeat":
            ts = float(rec.get("ts", self.t0 + float(rec.get("t", 0.0))))
            self.heartbeats.append(ts)
            self._mark_seen(ts)
        elif kind == "summary":
            self.summary = rec
            self._mark_seen(self.t0 + float(rec.get("elapsed_s", 0.0)))
        else:
            rec["t_abs"] = self.t0 + float(rec.get("t", 0.0))
            self.events.append(rec)
            self._mark_seen(rec["t_abs"])

    @property
    def member_key(self) -> str:
        """Merge key: the RANK (stable across restarts), falling back to
        the ident/pid for bare unlaunched processes."""
        if self.rank is not None:
            return str(self.rank)
        return self.ident or f"p{self.pid or 0}"


class FleetCollector:
    """Tail every stream in ``fleet_dir``; merge into one timeline.

    ``poll()`` is incremental and cheap — the live inspector calls it in
    a refresh loop; post-hoc consumers call ``poll(final=True)`` once to
    also repair-parse truncated tails.  All analysis methods
    (:meth:`members`, :meth:`health`, :meth:`aligned`, :meth:`summary`,
    :meth:`timeline`) are pure reads over the ingested state.
    """

    def __init__(self, fleet_dir: str, stall_after_s: float = 5.0,
                 dead_after_s: float = 15.0,
                 straggler_factor: float = 1.3):
        if dead_after_s < stall_after_s:
            raise ValueError("dead_after_s must be >= stall_after_s")
        self.dir = fleet_dir
        self.stall_after_s = float(stall_after_s)
        self.dead_after_s = float(dead_after_s)
        self.straggler_factor = float(straggler_factor)
        self._streams: Dict[str, _Stream] = {}
        self._sup = _Stream(os.path.join(fleet_dir, SUPERVISOR_LOG))
        self._sup_events: List[dict] = []
        self._polls = 0

    # -- ingest ------------------------------------------------------------
    def poll(self, final: bool = False) -> int:
        """Discover new streams, tail everything; returns records read."""
        self._polls += 1
        n = 0
        for path in sorted(glob.glob(os.path.join(self.dir,
                                                  _STREAM_GLOB))):
            if path not in self._streams:
                self._streams[path] = _Stream(path)
            n += self._streams[path].poll(final=final)
        n += self._poll_supervisor(final=final)
        return n

    def _poll_supervisor(self, final: bool = False) -> int:
        s = self._sup
        before = len(s.events)
        s.poll(final=final)
        # supervisor lines carry their own wall clock; _ingest routed
        # them into .events (no meta line in the supervisor log)
        new = s.events[before:]
        for rec in new:
            rec.pop("t_abs", None)
            self._sup_events.append(rec)
        return len(new)

    @property
    def supervisor_events(self) -> List[dict]:
        return list(self._sup_events)

    # -- membership --------------------------------------------------------
    def members(self) -> Dict[str, dict]:
        """Per-member merged history: streams ordered by start time, so
        a restarted rank's lives concatenate into one record.  Restart
        count is derived (streams − 1) and cross-checked against the
        supervisor's spawn events when present."""
        by_key: Dict[str, List[_Stream]] = {}
        for s in self._streams.values():
            if s.meta is None and not s.records and not s.heartbeats:
                continue                    # empty/unborn stream
            by_key.setdefault(s.member_key, []).append(s)
        out: Dict[str, dict] = {}
        for key, streams in by_key.items():
            streams.sort(key=lambda s: (s.first_seen or 0.0, s.path))
            steps = [r for s in streams for r in s.records]
            hb = sorted(t for s in streams for t in s.heartbeats)
            exits = self._exits_for(key, streams)
            out[key] = {
                "rank": streams[-1].rank,
                "ident": streams[-1].ident or key,
                "pids": [s.pid for s in streams],
                "streams": [s.path for s in streams],
                "restarts": len(streams) - 1,
                "records": len(steps),
                "heartbeats": len(hb),
                "first_step": min((int(r["step"]) for r in steps),
                                  default=None),
                "last_step": max((int(r["step"]) for r in steps),
                                 default=None),
                "first_seen": streams[0].first_seen,
                "last_seen": max((s.last_seen or 0.0) for s in streams),
                "clean_summary": streams[-1].summary is not None,
                "recovered": sum(s.recovered for s in streams),
                "dropped": sum(s.dropped for s in streams),
                "exits": exits,
                "_streams": streams,
            }
            out[key]["last_window"] = self._last_window(out[key])
        return out

    def _exits_for(self, key: str, streams: List[_Stream]) -> List[dict]:
        pids = {s.pid for s in streams if s.pid is not None}
        exits = []
        for ev in self._sup_events:
            if ev.get("kind") != "exit":
                continue
            if str(ev.get("rank")) == key or ev.get("pid") in pids:
                exits.append({"ts": ev.get("ts"), "pid": ev.get("pid"),
                              "rc": ev.get("rc"),
                              "by_supervisor":
                                  bool(ev.get("by_supervisor"))})
        exits.sort(key=lambda e: e["ts"] or 0.0)
        return exits

    # -- health ------------------------------------------------------------
    def now(self) -> float:
        """Evaluation instant: the newest timestamp seen anywhere — so
        analyzing a finished run judges members against the run's own
        end, not against the analyst's wall clock."""
        ts = [s.last_seen for s in self._streams.values()
              if s.last_seen is not None]
        ts += [ev.get("ts", 0.0) for ev in self._sup_events]
        return max(ts) if ts else time.time()

    @staticmethod
    def _proof_times(member: dict) -> List[float]:
        times: List[float] = []
        for s in member["_streams"]:
            times.extend(r["t_abs"] for r in s.records)
            times.extend(s.heartbeats)
            if s.first_seen is not None:
                times.append(s.first_seen)
        return sorted(times)

    def stall_episodes(self, member: dict) -> List[dict]:
        """INNER proof-of-life gaps longer than ``stall_after_s`` — the
        member went quiet and came back.  The trailing gap is death
        territory and handled by :meth:`health` instead."""
        times = self._proof_times(member)
        out = []
        for a, b in zip(times, times[1:]):
            if b - a > self.stall_after_s:
                out.append({"t0": a, "t1": b, "gap_s": b - a})
        return out

    def health(self, at: Optional[float] = None) -> Dict[str, str]:
        """``live`` / ``stalled`` / ``dead`` / ``exited`` per member (see
        module docstring for the state machine)."""
        at = self.now() if at is None else at
        out = {}
        for key, m in self.members().items():
            last_pid = next((p for p in reversed(m["pids"])
                             if p is not None), None)
            exit_ev = next((e for e in reversed(m["exits"])
                            if last_pid is None or e["pid"] == last_pid),
                           None)
            if exit_ev is not None:
                out[key] = "exited" if exit_ev["rc"] == 0 else "dead"
                continue
            age = at - m["last_seen"]
            if age > self.dead_after_s:
                out[key] = "dead"
            elif age > self.stall_after_s:
                out[key] = "stalled"
            else:
                out[key] = "live"
        return out

    def unnoticed_deaths(self, at: Optional[float] = None) -> List[str]:
        """Members whose heartbeat gap says dead but for which the
        supervisor recorded NO exit event — the fleet lost a rank and
        nothing noticed.  The budget gate fails the run on these."""
        at = self.now() if at is None else at
        health = self.health(at)
        return [key for key, m in self.members().items()
                if health[key] == "dead" and not m["exits"]]

    # -- cross-rank step alignment ----------------------------------------
    @staticmethod
    def _per_step(member: dict) -> Dict[int, Tuple[float, float, float]]:
        """step -> (t_abs, step_ms, cumulative wire bytes).  Later lives
        overwrite overlapping steps (a resumed rank re-runs them)."""
        out: Dict[int, Tuple[float, float, float]] = {}
        for s in member["_streams"]:
            prev_t: Optional[float] = None
            wire = 0.0
            for r in s.records:
                for ckey, delta in (r.get("counters") or {}).items():
                    name, _ = parse_series_key(ckey)
                    if name == "transfer/wire_bytes":
                        wire += float(delta)
                steps = max(int(r.get("steps", 1)), 1)
                t = r["t_abs"]
                ms = ((t - prev_t) / steps * 1e3
                      if prev_t is not None else 0.0)
                out[int(r["step"])] = (t, ms, wire)
                prev_t = t
        return out

    def aligned(self) -> List[dict]:
        """One row per consumed step present in >= 2 members: per-rank
        arrival time / step_ms / cumulative wire, plus the row's skew
        and slowest-rank attribution."""
        per = {key: self._per_step(m)
               for key, m in self.members().items()}
        counts: Dict[int, int] = {}
        for table in per.values():
            for step in table:
                counts[step] = counts.get(step, 0) + 1
        rows = []
        for step in sorted(s for s, c in counts.items() if c >= 2):
            t = {k: v[step][0] for k, v in per.items() if step in v}
            ms = {k: v[step][1] for k, v in per.items() if step in v}
            wire = {k: v[step][2] for k, v in per.items() if step in v}
            timed = {k: v for k, v in ms.items() if v > 0.0}
            row = {"step": step, "t": t, "step_ms": ms, "wire": wire}
            if timed:
                slowest = max(timed, key=timed.get)
                row["skew_ms"] = max(timed.values()) - min(timed.values())
                row["slowest"] = slowest
            rows.append(row)
        return rows

    # -- numerics health (obs/numerics.py, ISSUE 13) -----------------------
    @staticmethod
    def _member_anomalies(member: dict) -> Dict[str, int]:
        """``numerics/anomaly`` event counts by severity for one member
        (the stream ingest routes event lines into ``s.events``)."""
        out: Dict[str, int] = {}
        for s in member["_streams"]:
            for ev in s.events:
                if ev.get("kind") != "numerics/anomaly":
                    continue
                sev = str(ev.get("severity", "warning"))
                out[sev] = out.get(sev, 0) + 1
        return out

    @staticmethod
    def _grad_norms(member: dict) -> Dict[int, float]:
        """step -> latest ``numerics/grad_norm`` gauge; later lives
        overwrite overlapping steps, like :meth:`_per_step`."""
        out: Dict[int, float] = {}
        for s in member["_streams"]:
            for r in s.records:
                for gkey, v in (r.get("gauges") or {}).items():
                    name, _ = parse_series_key(gkey)
                    if name == "numerics/grad_norm":
                        out[int(r["step"])] = float(v)
        return out

    def numerics_divergence(self) -> List[dict]:
        """Cross-rank grad-norm divergence anomalies over the aligned
        steps — the fleet half of the numerics health plane."""
        from swiftmpi_tpu.obs import numerics as obs_numerics
        per_step: Dict[int, Dict[str, float]] = {}
        for key, m in self.members().items():
            for step, v in self._grad_norms(m).items():
                per_step.setdefault(step, {})[key] = v
        return obs_numerics.cross_rank_divergence(per_step)

    # -- wire-trace correlation (obs/trace.py, ISSUE 15) -------------------
    @staticmethod
    def _member_trace_windows(member: dict) -> Dict[int, dict]:
        """win id -> latest ``trace/window`` event for one member (the
        tracer mirrors each finalized record onto the recorder's event
        stream when a fleet dir is armed); later lives overwrite."""
        out: Dict[int, dict] = {}
        for s in member["_streams"]:
            for ev in s.events:
                if ev.get("kind") != "trace/window":
                    continue
                try:
                    out[int(ev["win"])] = ev
                except (KeyError, TypeError, ValueError):
                    continue
        return out

    def window_correlation(self) -> List[dict]:
        """One row per window id traced by >= 2 members: per-rank
        arrival time / encoded bytes / surviving rows, the cross-rank
        arrival spread, and last-to-arrive attribution.  Window ids are
        per-rank monotonic over the same consumed-step sequence, so
        equal ids across ranks are the same logical exchange — the
        causal join key the per-rank ledgers cannot provide."""
        per = {key: self._member_trace_windows(m)
               for key, m in self.members().items()}
        counts: Dict[int, int] = {}
        for table in per.values():
            for win in table:
                counts[win] = counts.get(win, 0) + 1
        rows = []
        for win in sorted(w for w, c in counts.items() if c >= 2):
            evs = {k: v[win] for k, v in per.items() if win in v}
            t = {k: float(e.get("t_abs", 0.0)) for k, e in evs.items()}
            row = {
                "win": win,
                "step": max((int(e.get("step", 0))
                             for e in evs.values()), default=0),
                "backend": next(iter({str(e.get("backend"))
                                      for e in evs.values()}), None),
                "decision": sorted({str(e.get("decision"))
                                    for e in evs.values()}),
                "t": t,
                "enc_bytes": {k: int(e.get("enc_bytes", 0))
                              for k, e in evs.items()},
                "rows_out": {k: int(e.get("rows_out", 0))
                             for k, e in evs.items()},
            }
            if t:
                row["spread_ms"] = (max(t.values()) - min(t.values())) \
                    * 1e3
                row["last_rank"] = max(t, key=t.get)
            rows.append(row)
        return rows

    @staticmethod
    def _last_window(member: dict) -> Optional[dict]:
        """Most recent traced window for one member — smtpu_top's WIN
        column ({win, t_abs}), None when the member never traced."""
        table = FleetCollector._member_trace_windows(member)
        if not table:
            return None
        win = max(table)
        return {"win": win,
                "t_abs": float(table[win].get("t_abs", 0.0))}

    # -- elastic membership (cluster/membership.py, ISSUE 16) --------------
    @staticmethod
    def _member_epochs(member: dict) -> Dict[int, int]:
        """step -> adopted ``elastic/epoch`` gauge for one member; later
        lives overwrite overlapping steps, like :meth:`_per_step`."""
        out: Dict[int, int] = {}
        for s in member["_streams"]:
            for r in s.records:
                for gkey, v in (r.get("gauges") or {}).items():
                    name, _ = parse_series_key(gkey)
                    if name == "elastic/epoch":
                        out[int(r["step"])] = int(v)
        return out

    def elastic_view(self, at: Optional[float] = None) -> Optional[dict]:
        """Fleet digest of the elastic membership plane, or None when no
        member ever published ``elastic/epoch`` (a static world).

        * ``fleet_epoch`` — the highest epoch any member adopted.
        * ``fleet_reconverge_steps`` — over the members that reached
          ``fleet_epoch``, the spread between the first and the last
          member's first step at it: how long the world took to agree
          on the final membership.  None while a LIVE member still
          hasn't caught up (reconvergence not yet provable).
        * ``migration_bytes`` — total modeled delta traffic
          (``elastic/migration_bytes`` counter) across all members: the
          cost of every adoption and rejoin, priced by the same PR-10
          byte model as training traffic.
        """
        at = self.now() if at is None else at
        members = self.members()
        epochs = {k: self._member_epochs(m) for k, m in members.items()}
        if not any(epochs.values()):
            return None
        fleet_epoch = max(max(t.values()) for t in epochs.values() if t)
        first_at = {k: min(s for s, e in t.items() if e == fleet_epoch)
                    for k, t in epochs.items()
                    if t and fleet_epoch in t.values()}
        health = self.health(at)
        laggards = [k for k, t in epochs.items()
                    if t and k not in first_at
                    and health.get(k) in ("live", "stalled")]
        reconverge = (max(first_at.values()) - min(first_at.values())
                      if first_at and not laggards else None)
        mig = 0.0
        for m in members.values():
            for s in m["_streams"]:
                for r in s.records:
                    for ckey, delta in (r.get("counters") or {}).items():
                        name, _ = parse_series_key(ckey)
                        if name == "elastic/migration_bytes":
                            mig += float(delta)
        return {"fleet_epoch": fleet_epoch,
                "fleet_reconverge_steps": reconverge,
                "migration_bytes": int(mig),
                "epoch_first_step": first_at,
                "laggards": laggards}

    # -- serve fleet plane (serve/shipper.py, ISSUE 17) --------------------
    @staticmethod
    def _member_serve(member: dict) -> Optional[dict]:
        """One member's serving digest, or None when it never published
        a ``serve/*`` series.  Role comes from which side of the ship
        stream the member booked: ``serve/ship_version`` → trainer,
        ``serve/replica_version`` → replica."""
        gauges: Dict[str, float] = {}
        counters: Dict[str, float] = {}
        bounds = None
        hist_counts: Optional[List[int]] = None
        for s in member["_streams"]:
            for r in s.records:
                for gkey, v in (r.get("gauges") or {}).items():
                    name, _ = parse_series_key(gkey)
                    if name.startswith("serve/"):
                        gauges[name] = float(v)     # last write wins
                for ckey, delta in (r.get("counters") or {}).items():
                    name, _ = parse_series_key(ckey)
                    if name.startswith("serve/"):
                        counters[name] = (counters.get(name, 0.0)
                                          + float(delta))
                for hkey, h in (r.get("hists") or {}).items():
                    name, _ = parse_series_key(hkey)
                    if name != "serve/latency_ms":
                        continue
                    if h.get("bounds") is not None:
                        bounds = list(h["bounds"])
                    cs = h.get("counts") or []
                    if hist_counts is None:
                        hist_counts = list(cs)
                    else:
                        for i, c in enumerate(cs):
                            hist_counts[i] += c
        if not gauges and not counters:
            return None
        role = ("trainer" if "serve/ship_version" in gauges
                else "replica" if "serve/replica_version" in gauges
                or "serve/queries" in counters else None)
        span_s = max((member["last_seen"] or 0.0)
                     - (member["first_seen"] or 0.0), 1e-9)
        queries = counters.get("serve/queries", 0.0)
        hits = counters.get("serve/hits", 0.0)
        rows = counters.get("serve/rows_read", 0.0)
        p50 = p99 = None
        if bounds is not None and hist_counts:
            from swiftmpi_tpu.obs.registry import quantile_from_buckets
            p50 = quantile_from_buckets(bounds, hist_counts, 0.50)
            p99 = quantile_from_buckets(bounds, hist_counts, 0.99)
        return {
            "role": role,
            "version": gauges.get("serve/replica_version",
                                  gauges.get("serve/ship_version")),
            "lag": gauges.get("serve/replica_lag"),
            "staleness_s": gauges.get("serve/staleness_s"),
            "queries": int(queries),
            "qps": queries / span_s,
            "p50_ms": p50, "p99_ms": p99,
            "hit_ratio": (hits / rows) if rows else None,
            "delta_publishes": int(
                counters.get("serve/delta_publishes", 0)),
            "full_publishes": int(
                counters.get("serve/full_publishes", 0)),
            "delta_bytes": int(counters.get("serve/delta_bytes", 0)),
            "full_bytes": int(counters.get("serve/full_bytes", 0)),
        }

    def serve_view(self, at: Optional[float] = None) -> Optional[dict]:
        """Fleet digest of the serve-fleet plane, or None when no member
        published ``serve/*`` (a training-only world).  Aggregate qps
        sums the replica readers; version/lag expose the delta-chain
        replay state the staleness bound rides on."""
        members = self.members()
        per = {k: v for k, m in members.items()
               if (v := self._member_serve(m)) is not None}
        if not per:
            return None
        replicas = [k for k, v in per.items() if v["role"] == "replica"]
        versions = [v["version"] for v in per.values()
                    if v["version"] is not None]
        lags = [v["lag"] for v in per.values() if v["lag"] is not None]
        stale = [v["staleness_s"] for v in per.values()
                 if v["staleness_s"] is not None]
        return {
            "members": per,
            "serve_replicas": len(replicas),
            "serve_qps_total": sum(
                per[k]["qps"] for k in replicas),
            "serve_version": max(versions) if versions else None,
            "serve_lag_max": max(lags) if lags else 0.0,
            "serve_staleness_max_s": max(stale) if stale else 0.0,
            "delta_publishes": sum(
                v["delta_publishes"] for v in per.values()),
            "full_publishes": sum(
                v["full_publishes"] for v in per.values()),
            "delta_bytes": sum(v["delta_bytes"] for v in per.values()),
            "full_bytes": sum(v["full_bytes"] for v in per.values()),
        }

    # -- fleet summary -----------------------------------------------------
    @staticmethod
    def _p50(vals: List[float]) -> float:
        if not vals:
            return 0.0
        vs = sorted(vals)
        return vs[len(vs) // 2]

    def summary(self, at: Optional[float] = None) -> dict:
        at = self.now() if at is None else at
        members = self.members()
        health = self.health(at)
        rows = self.aligned()
        skews = [r["skew_ms"] for r in rows if "skew_ms" in r]
        all_ms = [v for r in rows for v in r["step_ms"].values() if v > 0]
        skew_ms = self._p50(skews)
        med_ms = self._p50(all_ms)
        # Straggler attribution sums step time over the COMMON aligned
        # range only — rows where every reporting member is present.  A
        # killed rank has fewer rows than the survivors; comparing raw
        # totals over unequal ranges would crown whoever ran longest,
        # not whoever ran slowest.  (Falls back to all rows when the
        # members never fully overlap.)
        per_tables = {k: self._per_step(m) for k, m in members.items()}
        reporting = {k for k, t in per_tables.items() if t}
        common = [r for r in rows if set(r["t"]) >= reporting] or rows
        totals = {}                        # total step time per member
        for r in common:
            for k, v in r["step_ms"].items():
                totals[k] = totals.get(k, 0.0) + v
        straggler = None
        straggler_score = 0.0
        if len(totals) >= 2:
            worst = max(totals, key=totals.get)
            med_total = self._p50(list(totals.values()))
            if med_total > 0:
                straggler_score = totals[worst] / med_total
                if straggler_score >= self.straggler_factor:
                    straggler = worst
        wire_totals = {}
        for key, table in per_tables.items():
            wire_totals[key] = (max(v[2] for v in table.values())
                                if table else 0.0)
        imbalance = 0.0
        positive = [v for v in wire_totals.values()]
        if positive and max(positive) > 0:
            mean = sum(positive) / len(positive)
            if mean > 0:
                imbalance = max(positive) / mean - 1.0
        unnoticed = self.unnoticed_deaths(at)
        anomalies = {k: self._member_anomalies(m)
                     for k, m in members.items()}
        divergence = self.numerics_divergence()
        return {
            "v": FLEET_SCHEMA_V, "kind": "summary",
            "schema": FLEET_SCHEMA,
            "run": os.path.basename(os.path.normpath(self.dir)) or "fleet",
            "ranks": sorted(members),
            "at": at,
            "aligned_steps": len(rows),
            "last_step": {k: m["last_step"] for k, m in members.items()},
            "step_ms_p50": {k: self._p50(
                [v[1] for v in table.values() if v[1] > 0])
                for k, table in per_tables.items()},
            "fleet_step_ms_skew_ms": skew_ms,
            "fleet_step_ms_skew_pct": (100.0 * skew_ms / med_ms
                                       if med_ms > 0 else 0.0),
            "fleet_wire_bytes_imbalance": imbalance,
            "wire_bytes": wire_totals,
            "straggler_rank": straggler,
            "straggler_score": straggler_score,
            "health": health,
            "restarts": {k: m["restarts"] for k, m in members.items()},
            "heartbeats": {k: m["heartbeats"]
                           for k, m in members.items()},
            "recovered": sum(m["recovered"] for m in members.values()),
            "dropped": sum(m["dropped"] for m in members.values()),
            "unnoticed_deaths": unnoticed,
            # numerics health plane (obs/numerics.py)
            "numerics_anomalies": {k: v for k, v in anomalies.items()
                                   if v},
            "numerics_anomaly_total": sum(
                sum(v.values()) for v in anomalies.values()),
            "numerics_critical_total": sum(
                v.get("critical", 0) for v in anomalies.values()),
            "fleet_grad_norm_divergence": max(
                (d["ratio"] for d in divergence), default=0.0),
            "cross_rank_anomalies": len(divergence),
            # wire-trace plane (obs/trace.py): window records joined on
            # the per-rank-monotonic window id
            "trace_windows_correlated": len(self.window_correlation()),
            "last_window": {k: m["last_window"]
                            for k, m in members.items()},
        } | ({
            # elastic membership plane (ISSUE 16) — keys only appear
            # when some member published elastic/epoch, so static-world
            # summaries (and their goldens) are unchanged
            "fleet_epoch": ev["fleet_epoch"],
            "fleet_reconverge_steps": ev["fleet_reconverge_steps"],
            "migration_bytes": ev["migration_bytes"],
        } if (ev := self.elastic_view(at)) is not None else {}) | ({
            # serve-fleet plane (ISSUE 17) — same conditional-merge
            # contract: training-only summaries are byte-identical
            "serve_replicas": sv["serve_replicas"],
            "serve_qps_total": sv["serve_qps_total"],
            "serve_version": sv["serve_version"],
            "serve_lag_max": sv["serve_lag_max"],
            "serve_staleness_max_s": sv["serve_staleness_max_s"],
            "serve_delta_publishes": sv["delta_publishes"],
            "serve_full_publishes": sv["full_publishes"],
            "serve_delta_bytes": sv["delta_bytes"],
            "serve_full_bytes": sv["full_bytes"],
        } if (sv := self.serve_view(at)) is not None else {})

    # -- merged timeline ---------------------------------------------------
    def _health_transitions(self, at: float) -> List[dict]:
        """Reconstructed per-member health-transition events, correlated
        with the supervisor evidence: the ``live -> dead`` line for a
        killed rank carries its exit's rc/by_supervisor payload."""
        out = []
        health = self.health(at)
        for key, m in self.members().items():
            out.append({"v": FLEET_SCHEMA_V, "kind": "health",
                        "rank": key, "to": "live",
                        "t": m["first_seen"]})
            for ep in self.stall_episodes(m):
                out.append({"v": FLEET_SCHEMA_V, "kind": "health",
                            "rank": key, "to": "stalled", "t": ep["t0"],
                            "gap_s": ep["gap_s"]})
                out.append({"v": FLEET_SCHEMA_V, "kind": "health",
                            "rank": key, "to": "live", "t": ep["t1"]})
            state = health[key]
            if state in ("dead", "exited"):
                ev = m["exits"][-1] if m["exits"] else None
                out.append({
                    "v": FLEET_SCHEMA_V, "kind": "health", "rank": key,
                    "to": state,
                    "t": (ev["ts"] if ev else m["last_seen"]),
                    "exit": ev,
                    "unnoticed": ev is None and state == "dead"})
        out.sort(key=lambda e: e.get("t") or 0.0)
        return out

    def timeline(self, max_rows: Optional[int] = None) -> List[dict]:
        """The full merged ``smtpu-fleet/1`` record list: meta, member
        summaries, supervisor events, health transitions, per-step
        aligned rows (optionally capped to the LAST ``max_rows``), and
        the fleet summary."""
        at = self.now()
        members = self.members()
        meta = {"v": FLEET_SCHEMA_V, "kind": "meta",
                "schema": FLEET_SCHEMA,
                "run": os.path.basename(os.path.normpath(self.dir))
                or "fleet",
                "dir": self.dir, "generated_ts": time.time(),
                "ranks": sorted(members),
                "streams": sum(len(m["streams"])
                               for m in members.values())}
        recs: List[dict] = [meta]
        health = self.health(at)
        for key in sorted(members):
            m = members[key]
            recs.append({
                "v": FLEET_SCHEMA_V, "kind": "member", "rank": key,
                "ident": m["ident"], "pids": m["pids"],
                "restarts": m["restarts"], "records": m["records"],
                "heartbeats": m["heartbeats"],
                "first_step": m["first_step"],
                "last_step": m["last_step"],
                "health": health[key], "exits": m["exits"],
                "stall_episodes": self.stall_episodes(m),
                "anomalies": self._member_anomalies(m),
                "recovered": m["recovered"], "dropped": m["dropped"]})
        for ev in self._sup_events:
            recs.append({**ev, "kind": "sup/" + str(ev.get("kind"))})
        recs.extend(self._health_transitions(at))
        for d in self.numerics_divergence():
            recs.append({"v": FLEET_SCHEMA_V,
                         "kind": "numerics/cross_rank", **d})
        wrows = self.window_correlation()
        if max_rows is not None and len(wrows) > max_rows:
            wrows = wrows[-max_rows:]
        for row in wrows:
            recs.append({"v": FLEET_SCHEMA_V, "kind": "trace/window_corr",
                         **row})
        rows = self.aligned()
        if max_rows is not None and len(rows) > max_rows:
            rows = rows[-max_rows:]
        for row in rows:
            recs.append({"v": FLEET_SCHEMA_V, "kind": "fleet_step",
                         **row})
        recs.append(self.summary(at))
        return recs

    def write_timeline(self, path: Optional[str] = None,
                       max_rows: Optional[int] = None) -> str:
        path = path or os.path.join(self.dir, MERGED_TIMELINE)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            for rec in self.timeline(max_rows=max_rows):
                f.write(json.dumps(rec, sort_keys=True) + "\n")
        os.replace(tmp, path)
        return path

    # -- registry mirror ---------------------------------------------------
    def mirror_to_registry(self) -> None:
        """Publish the fleet gauges into this process's obs registry (one
        branch when telemetry is off) — the inspector process's own
        telemetry then carries the fleet view like any other series."""
        from swiftmpi_tpu import obs
        reg = obs.get_registry()
        if not reg.enabled:
            return
        s = self.summary()
        reg.gauge("fleet/step_ms_skew").set(s["fleet_step_ms_skew_ms"])
        reg.gauge("fleet/wire_bytes_imbalance").set(
            s["fleet_wire_bytes_imbalance"])
        health = s["health"]
        reg.gauge("fleet/members_live").set(
            sum(1 for v in health.values() if v == "live"))
        reg.gauge("fleet/members_stalled").set(
            sum(1 for v in health.values() if v == "stalled"))
        reg.gauge("fleet/members_dead").set(
            sum(1 for v in health.values() if v == "dead"))
        reg.gauge("fleet/straggler_rank").set(
            float(s["straggler_rank"])
            if s["straggler_rank"] is not None and
            str(s["straggler_rank"]).isdigit() else -1.0)
        reg.gauge("fleet/grad_norm_divergence").set(
            s["fleet_grad_norm_divergence"])
        reg.gauge("fleet/anomalies").set(
            float(s["numerics_anomaly_total"]))
        if "fleet_epoch" in s:
            reg.gauge("fleet/epoch").set(float(s["fleet_epoch"]))
            reg.gauge("fleet/migration_bytes").set(
                float(s["migration_bytes"]))
            if s["fleet_reconverge_steps"] is not None:
                reg.gauge("fleet/reconverge_steps").set(
                    float(s["fleet_reconverge_steps"]))
        if "serve_replicas" in s:
            reg.gauge("fleet/serve_replicas").set(
                float(s["serve_replicas"]))
            reg.gauge("fleet/serve_qps").set(float(s["serve_qps_total"]))
            reg.gauge("fleet/serve_lag_max").set(
                float(s["serve_lag_max"]))
            if s["serve_version"] is not None:
                reg.gauge("fleet/serve_version").set(
                    float(s["serve_version"]))
