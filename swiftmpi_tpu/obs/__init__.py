"""Unified telemetry plane (ISSUE 6).

One :class:`~swiftmpi_tpu.obs.registry.MetricsRegistry` for the whole
process — the transfer wire ledgers, the ``Throughput`` meter, pipeline
stats, fault events, checkpoint durations, and health probes all report
here instead of keeping private counters.  A
:class:`~swiftmpi_tpu.obs.recorder.StepRecorder` turns the registry into
a per-step JSONL time-series; :func:`span` wraps host-side hot-path
phases in ``profiler.annotate`` trace annotations AND a ``phase_ms``
histogram under the same name, so the TensorBoard trace and the JSONL
agree; :func:`named_scope` carries the same phase names into compiled
code (host timing is meaningless inside jit — the named scope shows up
in the device trace instead).

Everything is gated by ``[worker] telemetry:`` (see :func:`configure`).
The registry is process-global and created **disabled**: with telemetry
off, every instrument write and every ``span()`` is a single branch —
the measured-overhead test in tests/test_telemetry.py pins this down.

Module-level state exists because instruments are written from layers
with no config object in scope (transfer backends, the fault bus, the
health probes).  Tests get a clean slate via :func:`reset_for_tests`
(wired into tests/conftest.py); long-lived writers must therefore fetch
the registry through :func:`get_registry` (or re-check identity against
a cached reference) rather than caching it forever.
"""

from __future__ import annotations

import os
import time
from typing import Optional

import jax

from swiftmpi_tpu.obs.identity import process_ident, process_rank
from swiftmpi_tpu.obs.recorder import SCHEMA, SCHEMA_V, StepRecorder
from swiftmpi_tpu.obs.registry import (DEFAULT_BUCKETS_MS, MetricsRegistry,
                                       parse_series_key,
                                       quantile_from_buckets, series_key)
from swiftmpi_tpu.obs.collector import (FLEET_SCHEMA, FleetCollector,
                                        SupervisorLog, stream_filename)
from swiftmpi_tpu.obs import costs
from swiftmpi_tpu.obs import profiler as profiler_mod
from swiftmpi_tpu.obs.costs import CostCatalog, TrackedFn, get_catalog
from swiftmpi_tpu.cluster.bootstrap import ENV_FLEET_DIR
# aliased import: a bare ``from ...utils import profiler`` would shadow
# the ``obs.profiler`` SUBMODULE attribute on this package, silently
# rerouting ``from swiftmpi_tpu.obs import profiler`` to the host-side
# trace-annotation helpers (numerics.py and launch.py import the
# submodule that way)
from swiftmpi_tpu.utils import profiler as _host_profiler

__all__ = [
    "DEFAULT_BUCKETS_MS", "MetricsRegistry", "StepRecorder", "SCHEMA",
    "SCHEMA_V", "FLEET_SCHEMA", "FleetCollector", "SupervisorLog",
    "stream_filename", "series_key", "parse_series_key",
    "quantile_from_buckets", "process_ident", "process_rank",
    "get_registry", "set_enabled", "reset_for_tests", "span",
    "named_scope", "configure", "install_recorder", "uninstall_recorder",
    "get_recorder", "record_step", "CostCatalog", "TrackedFn",
    "get_catalog", "get_profiler", "install_profiler",
    "uninstall_profiler", "get_tracer", "install_tracer",
    "uninstall_tracer",
]

#: named scope for *compiled* code — same phase names as :func:`span`,
#: rendered into the device trace by XLA instead of timed on the host.
named_scope = jax.named_scope

_REGISTRY = MetricsRegistry(enabled=False)
_RECORDER: Optional[StepRecorder] = None
_PROFILER = None    # Optional[obs.profiler.ProfileSession]
_TRACER = None      # Optional[obs.trace.WindowTracer]


def get_registry() -> MetricsRegistry:
    """The process-global registry (disabled unless telemetry is on)."""
    return _REGISTRY


def set_enabled(on: bool) -> MetricsRegistry:
    _REGISTRY.enabled = bool(on)
    return _REGISTRY


def reset_for_tests() -> MetricsRegistry:
    """Swap in a fresh disabled registry and drop any installed recorder.

    Cached instrument handles bound to the old registry keep working but
    write into the discarded object — hence writers re-check
    ``get_registry()`` identity (see ``Transfer._obs_state``)."""
    global _REGISTRY, _RECORDER, _PROFILER, _TRACER
    _REGISTRY = MetricsRegistry(enabled=False)
    _RECORDER = None
    _PROFILER = None
    uninstall_tracer()
    costs.reset_for_tests()
    return _REGISTRY


# -- named spans ------------------------------------------------------------

class _NullSpan:
    """Returned when telemetry is off: a shared, stateless no-op."""

    __slots__ = ()

    def __enter__(self):
        return self

    def __exit__(self, *exc):
        return False


_NULL_SPAN = _NullSpan()


class _Span:
    """Host span = TraceAnnotation + ``phase_ms{phase=<name>}`` sample."""

    __slots__ = ("_hist", "_ann", "_t0")

    def __init__(self, hist, name: str):
        self._hist = hist
        self._ann = _host_profiler.annotate(name)

    def __enter__(self):
        self._ann.__enter__()
        self._t0 = time.perf_counter()
        return self

    def __exit__(self, *exc):
        dt_ms = (time.perf_counter() - self._t0) * 1e3
        self._ann.__exit__(*exc)
        self._hist.observe(dt_ms)
        return False


def span(name: str):
    """Named host-phase span: ``with obs.span("render"): ...``.

    Telemetry off -> a shared no-op context (one branch, no allocation).
    On -> a ``jax.profiler.TraceAnnotation`` plus a sample in the
    ``phase_ms{phase=<name>}`` histogram, so the trace viewer and
    ``telemetry_report.py`` see the same phase under the same name.
    Only meaningful OUTSIDE jit — use :func:`named_scope` inside.
    """
    reg = _REGISTRY
    if not reg.enabled:
        return _NULL_SPAN
    return _Span(reg.histogram("phase_ms", phase=name), name)


# -- recorder install point -------------------------------------------------

def install_recorder(rec: StepRecorder) -> StepRecorder:
    """Make ``rec`` the recorder :func:`record_step` feeds.  Layers with
    no config in scope (Trainer.step) report steps through the global."""
    global _RECORDER
    _RECORDER = rec
    return rec


def uninstall_recorder() -> Optional[StepRecorder]:
    global _RECORDER
    rec, _RECORDER = _RECORDER, None
    return rec


def get_recorder() -> Optional[StepRecorder]:
    return _RECORDER


def record_step(n: int = 1) -> None:
    """Account ``n`` consumed train steps on the installed recorder (a
    fused scan group counts its whole length) and the installed profiler
    session (ISSUE 14 triggered windows).  No-op when neither exists."""
    rec = _RECORDER
    if rec is not None:
        rec.on_steps(n)
    prof = _PROFILER
    if prof is not None:
        prof.on_step(n)
    tr = _TRACER
    if tr is not None:
        tr.on_step(n)


# -- profiler-session install point (obs/profiler.py) -----------------------

def install_profiler(sess):
    """Make ``sess`` the ProfileSession :func:`record_step` feeds."""
    global _PROFILER
    _PROFILER = sess
    return sess


def uninstall_profiler():
    global _PROFILER
    sess, _PROFILER = _PROFILER, None
    return sess


def get_profiler():
    return _PROFILER


# -- wire-tracer install point (obs/trace.py) -------------------------------

def install_tracer(tr, crash_flush: bool = True):
    """Make ``tr`` the WindowTracer the transfer ledgers and
    :func:`record_step` feed.  ``crash_flush`` enrolls it in the
    recorder module's atexit + fatal-signal hooks so a killed rank
    still leaves a flight-recorder dump behind."""
    global _TRACER
    _TRACER = tr
    if crash_flush:
        from swiftmpi_tpu.obs import recorder as recorder_mod
        recorder_mod._CRASH_RECORDERS.add(tr)
        recorder_mod._install_crash_hooks()
    return tr


def uninstall_tracer():
    """Clean teardown: detach the tracer WITHOUT dumping (a crash dump
    from a normal exit would be noise) and drop its crash enrollment."""
    global _TRACER
    tr, _TRACER = _TRACER, None
    if tr is not None:
        from swiftmpi_tpu.obs import recorder as recorder_mod
        recorder_mod._CRASH_RECORDERS.discard(tr)
    return tr


def get_tracer():
    return _TRACER


# -- config gate ------------------------------------------------------------

def configure(config, run: str = "run",
              meta: Optional[dict] = None) -> Optional[StepRecorder]:
    """Arm the telemetry plane from ``[worker]`` / ``[obs]`` config.

    Knobs under ``[worker]``:

    * ``telemetry: 1``        — master switch (default 0 = everything off)
    * ``telemetry_path:``     — JSONL sink (default ``telemetry.jsonl``;
      empty string = ring buffer only, no file)
    * ``telemetry_every: K``  — record every K consumed steps (default 1)
    * ``telemetry_ring: N``   — ring-buffer retention (default 1024)
    * ``telemetry_flush: N``  — JSONL write-buffer size (default 64)

    Fleet knobs under ``[obs]`` (ISSUE 12):

    * ``fleet_dir:`` — shared fleet-telemetry directory; the
      ``SMTPU_FLEET_DIR`` environment variable (set by
      ``launch.py -fleet-dir``) overrides it.  A fleet dir ARMS
      telemetry even when ``[worker] telemetry`` is off — a launcher
      asking for fleet observability must not be silently ignored by a
      worker config that never mentions telemetry — and redirects the
      JSONL sink to ``<fleet_dir>/telemetry_r<rank>_p<pid>.jsonl`` so
      every process life gets its own stream for the
      :class:`FleetCollector` to merge.
    * ``heartbeat_s: S`` — proof-of-life cadence (default 2.0 in fleet
      mode, 0 = off otherwise).
    * ``crash_flush: 1`` — atexit + fatal-signal telemetry flush
      (default on; see recorder.py).

    Compiler/device-cost knobs under ``[obs]`` (ISSUE 14) are armed
    here too, INDEPENDENTLY of the recorder — the compile catalog
    persists ``runs/compile_catalog.json`` and the profiler session
    captures traces even when the JSONL sink is off:

    * ``costs: 1`` / ``costs_path`` / ``costs_memory`` — the compiled-
      program catalog (obs/costs.py; ``SMTPU_COSTS=1`` overrides).
    * ``profile_at`` / ``profile_steps`` / ``profile_dir`` /
      ``profile_trigger`` / ``profile_on_anomaly`` — triggered profiler
      windows (obs/profiler.py; ``SMTPU_PROFILE_AT`` overrides, set by
      ``launch.py -profile-at`` for every rank).

    Returns the installed :class:`StepRecorder`, or ``None`` when
    telemetry is off.  The caller owns ``close()`` (or use it as a
    context manager); close appends the summary line and uninstalls
    nothing — :func:`uninstall_recorder` is explicit.
    """
    g = config.get_or
    fleet_dir = os.environ.get(ENV_FLEET_DIR) or \
        g("obs", "fleet_dir", "").to_string()
    cat = costs.configure_costs(config, run=run)
    prof = _configure_profiler(config, fleet_dir)
    tr = _configure_tracer(config, fleet_dir)
    if cat is not None or prof is not None or tr is not None:
        # instruments must record even without a JSONL sink — the
        # catalog artifact, the capture summaries and the trace ring
        # still read them
        set_enabled(True)
    if not g("worker", "telemetry", 0).to_bool() and not fleet_dir:
        return None
    set_enabled(True)
    path = g("worker", "telemetry_path", "telemetry.jsonl").to_string()
    if fleet_dir:
        os.makedirs(fleet_dir, exist_ok=True)
        path = os.path.join(
            fleet_dir, stream_filename(process_rank(), os.getpid()))
    rec = StepRecorder(
        _REGISTRY,
        path=path or None,
        run=run,
        ring=g("worker", "telemetry_ring", 1024).to_int32(),
        flush_every=g("worker", "telemetry_flush", 64).to_int32(),
        every=g("worker", "telemetry_every", 1).to_int32(),
        meta=meta,
        heartbeat_s=g("obs", "heartbeat_s",
                      2.0 if fleet_dir else 0.0).to_float(),
        crash_flush=g("obs", "crash_flush", 1).to_bool(),
    )
    if tr is not None:
        # hot-key attribution + last-window gauges ride the step series
        rec.add_sampler(tr.sampler)
    return install_recorder(rec)


def _configure_profiler(config, fleet_dir: str):
    """Install a ProfileSession when any trigger path is armed: the
    ``profile_at`` knob (or its launcher env override), the fleet-dir
    trigger file (on by default in fleet mode — polling is one stat per
    second), or the numerics-anomaly hook.  None of them armed (the
    default) installs nothing — ``record_step`` stays recorder-only."""
    g = config.get_or
    at = g("obs", "profile_at", -1).to_int32()
    env_at = os.environ.get(profiler_mod.ENV_PROFILE_AT, "")
    if env_at:
        at = int(env_at)
    steps = g("obs", "profile_steps", 5).to_int32()
    env_steps = os.environ.get(profiler_mod.ENV_PROFILE_STEPS, "")
    if env_steps:
        steps = int(env_steps)
    trigger = bool(fleet_dir) and g("obs", "profile_trigger",
                                    1).to_bool()
    on_anomaly = g("obs", "profile_on_anomaly", 0).to_bool()
    if at < 0 and not trigger and not on_anomaly:
        return None
    sess = profiler_mod.ProfileSession(
        profile_dir=g("obs", "profile_dir",
                      os.path.join("runs", "profiles")).to_string(),
        steps=steps, profile_at=at,
        fleet_dir=fleet_dir if trigger else None,
        capture_on_anomaly=on_anomaly)
    return install_profiler(sess)


def _configure_tracer(config, fleet_dir: str):
    """Install a WindowTracer when ``[obs] trace`` is armed (default off
    — the transfer ledgers' host callbacks then never touch the trace
    plane and the key-reservoir tap stays out of the traced programs,
    which is the bit-identity contract the ON-vs-OFF tests pin).  Like
    every format-affecting knob, arming or clearing mid-run requires a
    step rebuild for the reservoir/EF taps to appear or vanish; the
    record/ledger plumbing itself follows the tracer live."""
    g = config.get_or
    if not g("obs", "trace", 0).to_bool():
        return None
    cur = get_tracer()
    if cur is not None:
        # repeated train() calls must not stack tracers: the old one
        # would stay enrolled in _CRASH_RECORDERS and dump a stale
        # "crash" ring at exit.  The installed instance follows the
        # run live; re-arming with different knobs needs an explicit
        # uninstall_tracer() first.
        return cur
    from swiftmpi_tpu.obs import trace as trace_mod
    tr = trace_mod.WindowTracer(
        trace_dir=g("obs", "trace_dir", "runs").to_string(),
        ring=g("obs", "trace_ring", 256).to_int32(),
        sample=g("obs", "trace_sample", 1).to_int32(),
        keys=g("obs", "trace_keys", 64).to_int32(),
        topk=g("obs", "trace_topk", 8).to_int32(),
        fleet_dir=fleet_dir or None,
        dump_on_anomaly=g("obs", "trace_on_anomaly", 1).to_bool())
    return install_tracer(
        tr, crash_flush=g("obs", "crash_flush", 1).to_bool())
