"""Per-step telemetry time-series: registry deltas -> ring buffer -> JSONL.

End-of-run scalars (``train_metrics``, ``Transfer.traffic()``) say *what*
a run cost; auto-placement and wire-format audits need *when* — a live,
queryable per-step series.  :class:`StepRecorder` snapshots the
:class:`~swiftmpi_tpu.obs.registry.MetricsRegistry` once per recorded
step, keeps the per-step **deltas** in a bounded ring buffer (long runs
hold O(ring) memory, never O(steps)), and flushes every record to a
schema-versioned JSONL file (``telemetry.jsonl``) alongside the run's
other output.

Record schema (one JSON object per line, ``"v": 1`` on every line):

* ``kind: "meta"``   — first line: schema id, run name, rank/pid identity,
  caller-supplied metadata.
* ``kind: "step"``   — ``step`` (cumulative consumed steps), ``steps``
  (steps covered by this record — a fused scan group records once for L
  steps), ``t`` (seconds since recorder start), ``counters`` (deltas for
  the series that moved), ``gauges`` (current values), ``hists``
  (per-record bucket-count deltas; ``bounds`` ride along the first time a
  series appears).
* ``kind: "summary"`` — last line: cumulative counter totals, final
  gauges, and p50/p95/p99 per histogram — so one-shot consumers (the
  traffic-budget gate) never have to re-sum the deltas.
* ``kind: "heartbeat"`` — proof-of-life line with a wall-clock ``ts``,
  emitted inline from :meth:`on_steps` at the ``heartbeat_s`` cadence
  and flushed IMMEDIATELY (no buffering): a rank that stalls stops
  heartbeating, and the silence itself is the fleet-health signal a
  :class:`~swiftmpi_tpu.obs.collector.FleetCollector` reads.  No
  background thread — a heartbeat that a hung consumer loop cannot emit
  would defeat the point.
* other kinds — out-of-band :meth:`StepRecorder.event` lines (the
  control plane's ``control/decision`` records): arbitrary payload
  stamped with the recorder's step/clock, same ``"v"`` versioning.

Writes happen only on the recording thread (the training loop's consumer
side); the registry itself is what the producer threads hit, and its
snapshot is lock-consistent.  ``telemetry_every: K`` thins recording to
every K-th step when per-step snapshots are too hot for a small step.

Flush-on-crash (ISSUE 12 satellite): a killed rank used to lose exactly
the buffered tail that explains the kill.  ``crash_flush=True`` enrolls
the recorder in a process-wide atexit + fatal-signal (SIGTERM/SIGINT/
SIGHUP) hook that closes every live recorder — summary line included —
then restores the previous handler and re-delivers the signal so the
launcher still sees the normalized 128+signum exit code.  SIGKILL is
uncatchable by design; the immediate heartbeat flush bounds that loss
to one flush interval.
"""

from __future__ import annotations

import atexit
import json
import os
import signal
import threading
import time
import weakref
from collections import deque
from typing import Callable, Dict, List, Optional

from swiftmpi_tpu.obs.identity import process_ident, process_rank
from swiftmpi_tpu.obs.registry import (MetricsRegistry,
                                       quantile_from_buckets)

SCHEMA = "smtpu-telemetry/1"
SCHEMA_V = 1

# -- crash-flush machinery ---------------------------------------------------
# Recorders enrolled for flush-on-crash.  A WeakSet so an abandoned
# recorder never outlives its owner just because it asked for crash
# safety; close() is idempotent so double-delivery (atexit after a
# handled signal) is harmless.
_CRASH_RECORDERS: "weakref.WeakSet[StepRecorder]" = weakref.WeakSet()
_CRASH_SIGNALS = (signal.SIGTERM, signal.SIGINT, signal.SIGHUP)
_crash_hooks_installed = False
_prev_handlers: Dict[int, object] = {}


def _flush_all_recorders() -> None:
    for rec in list(_CRASH_RECORDERS):
        try:
            rec.close()
        except Exception:       # a broken sink must not mask the signal
            pass


def _crash_signal_handler(signum, frame) -> None:
    _flush_all_recorders()
    # Restore whatever was installed before us and re-deliver, so the
    # process still dies with the correct 128+signum status the
    # launcher's _normalize_rc expects (default disposition) — or the
    # application's own handler (e.g. KeyboardInterrupt) still runs.
    prev = _prev_handlers.get(signum, signal.SIG_DFL)
    if callable(prev):
        prev(signum, frame)
        return
    signal.signal(signum, signal.SIG_DFL if prev is None else prev)
    os.kill(os.getpid(), signum)


def _install_crash_hooks() -> None:
    """Idempotent; atexit covers normal interpreter teardown, the signal
    handlers cover supervisor SIGTERM teardown.  signal.signal only
    works on the main thread — off-thread enrollment keeps the atexit
    half and skips signals (ValueError guard)."""
    global _crash_hooks_installed
    if _crash_hooks_installed:
        return
    atexit.register(_flush_all_recorders)
    if threading.current_thread() is threading.main_thread():
        for sig in _CRASH_SIGNALS:
            try:
                prev = signal.getsignal(sig)
                signal.signal(sig, _crash_signal_handler)
                _prev_handlers[sig] = prev
            except (ValueError, OSError):
                pass
    _crash_hooks_installed = True


class StepRecorder:
    """Snapshot registry deltas per train step; flush JSONL.

    ``ring`` bounds in-memory retention (a deque of the last N records);
    ``flush_every`` bounds the write buffer; ``every`` thins recording.
    ``samplers`` are callables ``fn(registry)`` invoked right before each
    snapshot — the bridge for instruments that keep their own cumulative
    state (the ``Throughput`` meter, ``PrefetchIterator.stats()``): they
    ``set_total``/``gauge.set`` the registry from their internal counters
    so the delta machinery sees them like any native series.
    """

    def __init__(self, registry: MetricsRegistry, path: Optional[str] = None,
                 run: str = "run", ring: int = 1024, flush_every: int = 64,
                 every: int = 1, meta: Optional[dict] = None,
                 heartbeat_s: float = 0.0, crash_flush: bool = False):
        if ring < 1:
            raise ValueError(f"telemetry ring must be >= 1, got {ring}")
        if every < 1:
            raise ValueError(f"telemetry_every must be >= 1, got {every}")
        self.registry = registry
        self.path = path
        self.run = run
        self.every = int(every)
        self._ring: deque = deque(maxlen=int(ring))
        self._flush_every = max(1, int(flush_every))
        self._samplers: List[Callable[[MetricsRegistry], None]] = []
        self._buf: List[str] = []
        self._file = None
        self._closed = False
        self._step_total = 0
        self._steps_unrecorded = 0
        self._records_written = 0
        self._t0 = time.monotonic()
        self._prev = registry.snapshot()
        self._bounds_emitted = set()
        self._meta = {"v": SCHEMA_V, "kind": "meta", "schema": SCHEMA,
                      "run": run, "rank": process_rank(),
                      "pid": os.getpid(), "ident": process_ident(),
                      "ts": time.time(), **(meta or {})}
        self._buf.append(json.dumps(self._meta, sort_keys=True))
        self.heartbeat_s = float(heartbeat_s)
        self._last_hb = 0.0
        if crash_flush:
            _CRASH_RECORDERS.add(self)
            _install_crash_hooks()
        if self.heartbeat_s > 0:
            self.heartbeat()            # first proof of life ASAP

    # -- samplers ----------------------------------------------------------
    def add_sampler(self, fn: Callable[[MetricsRegistry], None]) -> None:
        """Register ``fn(registry)`` to run before every snapshot."""
        self._samplers.append(fn)

    # -- recording ---------------------------------------------------------
    def on_steps(self, n: int = 1) -> None:
        """Account ``n`` consumed train steps; records when the
        ``every`` cadence is due.  Call from the consumer thread."""
        if self._closed:
            return
        self._step_total += n
        self._steps_unrecorded += n
        if self._steps_unrecorded >= self.every:
            self._record()
        if self.heartbeat_s > 0 and \
                time.monotonic() - self._last_hb >= self.heartbeat_s:
            self.heartbeat()

    def heartbeat(self) -> Optional[dict]:
        """Write a proof-of-life line NOW and flush it — unlike every
        other record this must hit the disk immediately, because its
        absence is what a FleetCollector reads as a stall.  Carries the
        wall clock (``ts``) so cross-rank heartbeat ages are comparable
        without reconstructing from the meta line."""
        if self._closed:
            return None
        self._last_hb = time.monotonic()
        if self.registry.enabled:
            self.registry.counter("telemetry/heartbeats").inc()
        rec = {"v": SCHEMA_V, "kind": "heartbeat",
               "step": self._step_total,
               "t": self._last_hb - self._t0,
               "ts": time.time(),
               "rank": self._meta["rank"], "ident": self._meta["ident"]}
        if self.path:
            self._buf.append(json.dumps(rec, sort_keys=True))
            self.flush()
        return rec

    def _record(self) -> None:
        for fn in self._samplers:
            fn(self.registry)
        cur = self.registry.snapshot()
        d = MetricsRegistry.delta(self._prev, cur)
        self._prev = cur
        hists = {}
        for k, h in d["hists"].items():
            entry = {"n": h["n"], "sum": h["sum"], "counts": h["counts"]}
            if k not in self._bounds_emitted:
                entry["bounds"] = list(h["bounds"])
                self._bounds_emitted.add(k)
            hists[k] = entry
        rec = {"v": SCHEMA_V, "kind": "step",
               "step": self._step_total,
               "steps": self._steps_unrecorded,
               "t": time.monotonic() - self._t0,
               "rank": self._meta["rank"], "ident": self._meta["ident"],
               "counters": d["counters"], "gauges": d["gauges"],
               "hists": hists}
        self._steps_unrecorded = 0
        self._ring.append(rec)
        self._records_written += 1
        if self.path:
            self._buf.append(json.dumps(rec, sort_keys=True))
            if len(self._buf) >= self._flush_every:
                self.flush()

    def event(self, kind: str, payload: Optional[dict] = None) -> dict:
        """Append a schema-versioned out-of-band event line (e.g. the
        control plane's ``control/decision`` records).  Events carry the
        recorder's current step count and clock so they interleave with
        the step series on a shared axis; they ride the same ring/flush
        machinery as step records but never perturb the delta snapshots.
        Returns the record written."""
        if self._closed:
            return {}
        rec = {"v": SCHEMA_V, "kind": str(kind),
               "step": self._step_total,
               "t": time.monotonic() - self._t0,
               "rank": self._meta["rank"], "ident": self._meta["ident"],
               **(payload or {})}
        self._ring.append(rec)
        if self.path:
            self._buf.append(json.dumps(rec, sort_keys=True))
            if len(self._buf) >= self._flush_every:
                self.flush()
        return rec

    # -- read side ---------------------------------------------------------
    def records(self) -> List[dict]:
        """The ring buffer's current contents (most recent ``ring``
        step records, oldest first)."""
        return list(self._ring)

    @property
    def steps_recorded(self) -> int:
        return self._step_total

    # -- sinks -------------------------------------------------------------
    def flush(self) -> None:
        if not self.path or not self._buf:
            self._buf = self._buf if self.path else []
            return
        if self._file is None:
            self._file = open(self.path, "a")
        self._file.write("\n".join(self._buf) + "\n")
        self._file.flush()
        self._buf = []

    def close(self) -> None:
        """Record any unrecorded tail steps, append the summary line, and
        flush.  Idempotent."""
        if self._closed:
            return
        if self._steps_unrecorded:
            self._record()
        for fn in self._samplers:
            fn(self.registry)
        snap = self.registry.snapshot()
        summary = {"v": SCHEMA_V, "kind": "summary", "run": self.run,
                   "rank": self._meta["rank"], "ident": self._meta["ident"],
                   "steps": self._step_total,
                   "elapsed_s": time.monotonic() - self._t0,
                   "counters": snap["counters"], "gauges": snap["gauges"],
                   "quantiles": {
                       k: {"p50": quantile_from_buckets(
                               h["bounds"], h["counts"], 0.50),
                           "p95": quantile_from_buckets(
                               h["bounds"], h["counts"], 0.95),
                           "p99": quantile_from_buckets(
                               h["bounds"], h["counts"], 0.99),
                           "n": h["count"],
                           "mean_ms": h["sum"] / h["count"]
                           if h["count"] else 0.0}
                       for k, h in snap["hists"].items()}}
        self._closed = True
        _CRASH_RECORDERS.discard(self)
        if self.path:
            self._buf.append(json.dumps(summary, sort_keys=True))
            self.flush()
            if self._file is not None:
                self._file.close()
                self._file = None
        self.summary = summary

    def __enter__(self) -> "StepRecorder":
        return self

    def __exit__(self, *exc) -> None:
        self.close()
