"""Process/rank identity for log lines and telemetry records.

``launch.py`` gives every supervised child a rank via
``SMTPU_PROCESS_ID`` (cluster/bootstrap.py).  Everything that emits an
attributable line — the logger, the StepRecorder, fault events — tags it
with ``r<rank>`` when launched, or ``p<pid>`` for a bare single process,
so interleaved output from an 8-process cell stays attributable.

Read the environment *per call*, never cached at import: tests
monkeypatch ``SMTPU_PROCESS_ID`` and the supervisor re-execs children
with fresh ranks after a restart.
"""

from __future__ import annotations

import os
from typing import Optional

from swiftmpi_tpu.cluster.bootstrap import ENV_PROCESS_ID


def process_rank() -> Optional[int]:
    """The launcher-assigned process rank, or None for a bare process."""
    raw = os.environ.get(ENV_PROCESS_ID)
    if raw is None:
        return None
    try:
        return int(raw)
    except ValueError:
        return None


def process_ident() -> str:
    """``r<rank>`` under the launcher, ``p<pid>`` otherwise."""
    rank = process_rank()
    if rank is not None:
        return f"r{rank}"
    return f"p{os.getpid()}"
