"""Wire-path tracing plane: per-window trace records + flight recorder
(ISSUE 15).

The telemetry stack answers *how much* (cumulative ledgers, per-step
deltas) but not *why this window*: once a push window's 4-way wire
decision, dedup ratio, EF drain, and encoded volume fold into counters,
the individual window is gone.  :class:`WindowTracer` keeps it — a
sampled, schema-versioned (``smtpu-trace/1``) record per coalesced push
window, assembled host-side from the SAME ``jax.debug.callback``
landing points the wire ledger already uses, so arming the tracer never
changes the traced program for the counter path (trajectories stay
bit-identical ON vs OFF; the optional key-reservoir tap adds pure reads
only).

One record per window carries:

* ``win`` — monotonic per-rank window id, assigned at callback time (a
  compiled window program executes many times; ids count executions).
  SPMD ranks run the same window sequence, so the id doubles as the
  cross-rank correlation key the
  :class:`~swiftmpi_tpu.obs.collector.FleetCollector` merges on.
* ``step`` / ``steps`` — consumed-step position and the range since the
  previous record (fed from ``obs.record_step``; callbacks retire
  asynchronously, so attribution is one dispatch coarse).
* ``decision`` + ``prices`` — the wire-format decision WITH every
  losing candidate's modeled byte cost
  (``parameter.key_index.price_window_formats``): the "why".
* ``rows_in`` / ``rows_out`` — the window dedup's input/surviving rows,
  exactly the values the ``coalesced_rows_*`` ledger booked.
* ``enc_bytes`` — encoded exchange bytes, exactly the value the
  ``wire_bytes`` ledger booked for the window's exchange(s).
* ``ef_drained`` / ``ef_rebanked`` — |residual| mass drained into and
  re-banked out of the ``@ef`` planes by ``ef_quantize_window``
  (sparse_q windows; armed-only traced sums).
* ``keys`` + ``shard_rows`` / ``shard_bytes`` — a bounded strided
  reservoir of surviving slot ids and, where the backend knows its
  routing, surviving rows (hence encoded bytes) per destination shard.
* ``phase_ms`` — best-effort per-phase latency lift: the host
  ``phase_ms`` histogram sums plus the profiler's per-phase device
  attribution gauges (``window_dedup``/``wire_exchange``/``apply``)
  when a capture has run.

A bounded ring holds the last N records — the **flight recorder** — and
dumps them to ``<trace_dir>/trace_r<rank>_p<pid>.jsonl`` on crash-flush
(enrolled in the recorder module's atexit + fatal-signal hooks), on a
critical numerics anomaly (:func:`on_critical_anomaly`, called by
``AnomalyDetector``), or on an explicit fleet-dir trigger file
(``trace_trigger.json`` — the same monotonic-id replay-once pattern as
the profiler's ``profile_trigger.json``; :func:`request_trace` / the
``python -m swiftmpi_tpu.obs.trace <fleet_dir>`` CLI writes it).

Hot-key attribution: every sampled window's key reservoir feeds bounded
touch/byte estimators (each sampled key stands for ``rows_out /
sample_n`` rows and ``enc_bytes / sample_n`` bytes); the control
plane's :class:`~swiftmpi_tpu.control.sketch.DecayedSketch`, when
attached, replaces the touch *ranking* with its exact decayed counts.
Top-K keys publish as ``trace/hot_key_touches{key=}`` /
``trace/hot_key_bytes{key=}`` gauges via :meth:`WindowTracer.sampler`.

The record layout is deliberately the per-window tuple a TrafficPlan
interpreter would execute — (families, dedup, format, encoded volume,
destination split) — so the ROADMAP's compiler refactor can validate
its plans against this plane as ground truth.
"""

from __future__ import annotations

import json
import os
import time
from collections import deque
from typing import Dict, List, Optional

import numpy as np

from swiftmpi_tpu.obs.identity import process_ident, process_rank

TRACE_SCHEMA = "smtpu-trace/1"
TRACE_SCHEMA_V = 1

#: fleet-dir trigger file: ``{"id": n}``; ids increase so every rank's
#: tracer replays each dump request exactly once (profiler pattern).
TRIGGER_FILENAME = "trace_trigger.json"

#: the named scopes whose latency the record lifts (see module doc).
TRACE_PHASES = ("window_dedup", "wire_exchange", "apply")

#: bound on the hot-key estimator tables; pruned to half when exceeded.
_HOT_TABLE_MAX = 4096


def request_trace(fleet_dir: str) -> dict:
    """Drop a flight-recorder dump request in ``fleet_dir`` for every
    rank's tracer.  Monotonic id = previous id + 1, atomic replace."""
    path = os.path.join(fleet_dir, TRIGGER_FILENAME)
    prev = 0
    try:
        with open(path) as f:
            prev = int(json.load(f).get("id", 0))
    except (OSError, ValueError):
        pass
    req = {"id": prev + 1, "ts": time.time()}
    os.makedirs(fleet_dir, exist_ok=True)
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as f:
        json.dump(req, f)
    os.replace(tmp, path)
    return req


class WindowTracer:
    """One rank's per-window trace state machine.

    All mutation happens on host callback threads funneled through the
    ledger's ``jax.debug.callback`` landing points plus the trainer
    thread's ``obs.record_step`` — the same single-consumer discipline
    as the wire ledger itself, so no lock is taken on the hot path.
    """

    def __init__(self, trace_dir: str = "runs", ring: int = 256,
                 sample: int = 1, keys: int = 64, topk: int = 8,
                 fleet_dir: Optional[str] = None, poll_s: float = 1.0,
                 dump_on_anomaly: bool = True,
                 anomaly_min_gap_s: float = 5.0):
        if ring < 1:
            raise ValueError(f"trace ring must be >= 1, got {ring}")
        self.trace_dir = trace_dir
        self.sample = max(int(sample), 1)
        self.keys = max(int(keys), 0)
        self.topk = max(int(topk), 0)
        self.fleet_dir = fleet_dir or None
        self.poll_s = float(poll_s)
        self.dump_on_anomaly = bool(dump_on_anomaly)
        self.anomaly_min_gap_s = float(anomaly_min_gap_s)
        self._ring: deque = deque(maxlen=int(ring))
        self._win = 0                   # monotonic window id (1-based)
        self._records = 0               # sampled records assembled
        self._consumed = 0              # steps via on_step
        self._prev_step = 0             # step of the previous record
        self._open: Dict[str, dict] = {}      # backend -> open record
        self._staged: Dict[str, dict] = {}    # backend -> pending extras
        self._prices: Dict[tuple, dict] = {}  # (backend, decision) -> why
        self._touch: Dict[int, float] = {}    # slot -> est. touches
        self._bytes: Dict[int, float] = {}    # slot -> est. wire bytes
        self._sketch = None
        self.dumps: List[str] = []
        self._done_trigger_id = 0
        self._last_poll = 0.0
        self._last_anomaly_dump = 0.0
        self._closed = False
        self._t0 = time.monotonic()

    # -- feeds from the transfer layer (host callback side) ----------------
    def on_decision(self, backend: str, decision: str, prices: dict,
                    rows: int, capacity: int, row_bytes: int,
                    quant: str = "off") -> None:
        """Cache one wire-format pricing (host-side, once per build).
        The decision is baked into the compiled window program, so
        attaching the latest pricing for ``(backend, decision)`` to every
        runtime record with that decision is exact as long as the program
        in use is the one most recently priced — which the step-rebuild
        contract for format-affecting knobs guarantees."""
        self._prices[(backend, decision)] = {
            "prices": {k: float(v) for k, v in prices.items()},
            "rows": int(rows), "capacity": int(capacity),
            "row_bytes": int(row_bytes), "quant": quant}

    def stage(self, backend: str, **extras) -> None:
        """Park window extras (EF mass, key reservoir, shard rows) for
        the backend's next finalized record."""
        self._staged.setdefault(backend, {}).update(extras)

    def stage_ef(self, backend: str, drained, rebanked) -> None:
        self.stage(backend, ef_drained=float(drained),
                   ef_rebanked=float(rebanked))

    def stage_keys(self, backend: str, sample, shard_rows=None) -> None:
        sample = np.asarray(sample).ravel()
        extras = {"keys": sample[sample >= 0].astype(np.int64)}
        if shard_rows is not None:
            extras["shard_rows"] = np.asarray(shard_rows).ravel()
        self.stage(backend, **extras)

    def on_window(self, backend: str, decision: str, rows_in: int,
                  rows_out: int, family: str = "window") -> None:
        """A window dedup landed: assign the next window id and open a
        record (finalizing any predecessor still waiting for its
        exchange).  Called from the ledger's ``_accum_coalesce`` landing
        point, so it fires exactly once per compiled window execution."""
        if self._closed:
            return
        prev = self._open.pop(backend, None)
        if prev is not None:
            self._finish(prev)
        self._win += 1
        staged = self._staged.pop(backend, {})
        if self._win % self.sample != 0:
            self._count("trace/windows", 1)
            return
        rec = {"v": TRACE_SCHEMA_V, "schema": TRACE_SCHEMA,
               "kind": "trace/window", "win": self._win,
               "backend": backend, "decision": decision,
               "step": self._consumed,
               "steps": [self._prev_step, self._consumed],
               "t": time.monotonic() - self._t0,
               "families": {family: int(rows_in)},
               "rows_in": int(rows_in), "rows_out": int(rows_out),
               "enc_bytes": 0, "exchanges": 0}
        why = self._prices.get((backend, decision))
        if why is not None:
            rec.update(prices=why["prices"], capacity=why["capacity"],
                       row_bytes=why["row_bytes"], quant=why["quant"])
        self._attach(rec, staged)
        self._count("trace/windows", 1)
        self._open[backend] = rec

    def on_exchange(self, backend: str, rows: int, row_bytes: int,
                    base_bytes: int = 0,
                    decision: Optional[str] = None) -> None:
        """An exchange landed on the ledger.  Three cases: (a) a
        decision-less exchange while this backend's window record is
        open is the window's wire hop — book its encoded bytes and
        finalize; (b) an exchange CARRYING a decision is a dense window
        (the dense path never books a dedup) — it is a whole record by
        itself; (c) anything else (per-step pushes) is not a window and
        is ignored."""
        if self._closed:
            return
        nbytes = int(rows) * int(row_bytes) + int(base_bytes)
        rec = self._open.get(backend)
        if decision is not None:
            if rec is not None:
                self._finish(self._open.pop(backend))
            self._win += 1
            staged = self._staged.pop(backend, {})
            if self._win % self.sample != 0:
                self._count("trace/windows", 1)
                return
            rec = {"v": TRACE_SCHEMA_V, "schema": TRACE_SCHEMA,
                   "kind": "trace/window", "win": self._win,
                   "backend": backend, "decision": decision,
                   "step": self._consumed,
                   "steps": [self._prev_step, self._consumed],
                   "t": time.monotonic() - self._t0,
                   "families": {}, "rows_in": int(rows),
                   "rows_out": int(rows),
                   "enc_bytes": nbytes, "exchanges": 1,
                   "wire_row_bytes": int(row_bytes),
                   "base_bytes": int(base_bytes)}
            why = self._prices.get((backend, decision))
            if why is not None:
                rec.update(prices=why["prices"],
                           capacity=why["capacity"],
                           row_bytes=why["row_bytes"], quant=why["quant"])
            self._attach(rec, staged)
            self._count("trace/windows", 1)
            self._finish(rec)
            return
        if rec is None:
            return
        rec["enc_bytes"] += nbytes
        rec["exchanges"] += 1
        rec["wire_row_bytes"] = int(row_bytes)
        rec["base_bytes"] = int(base_bytes)
        self._finish(self._open.pop(backend))

    # -- record assembly ---------------------------------------------------
    @staticmethod
    def _attach(rec: dict, staged: dict) -> None:
        for k in ("ef_drained", "ef_rebanked"):
            if k in staged:
                rec[k] = float(staged[k])
        if "hot_rows" in staged:        # hybrid's replicated-head slice
            rec["hot_rows"] = int(staged["hot_rows"])
        if "keys" in staged:
            rec["keys"] = [int(v) for v in staged["keys"]]
        if "shard_rows" in staged:
            rec["shard_rows"] = [int(v) for v in staged["shard_rows"]]

    def _finish(self, rec: dict) -> None:
        """Seal one record: per-shard encoded bytes, phase lift, hot-key
        accounting, ring append, registry mirror, fleet event."""
        if rec.get("shard_rows") and rec.get("wire_row_bytes"):
            rb = rec["wire_row_bytes"]
            rec["shard_bytes"] = [int(r) * rb for r in rec["shard_rows"]]
        rec["phase_ms"] = self._lift_phases()
        self._hot_account(rec)
        self._ring.append(rec)
        self._records += 1
        self._prev_step = rec["step"]
        self._count("trace/records", 1)
        from swiftmpi_tpu import obs
        r = obs.get_recorder()
        if r is not None and self.fleet_dir:
            r.event("trace/window",
                    {k: rec[k] for k in ("win", "backend", "decision",
                                         "rows_in", "rows_out",
                                         "enc_bytes")})

    @staticmethod
    def _lift_phases() -> dict:
        """Best-effort latency attribution for the window phases: the
        cumulative host ``phase_ms`` histogram sums plus, when a
        profiler capture has run, its per-phase device-ms gauges.
        Cumulative-by-design — consecutive records' deltas attribute a
        window interval, matching the ledger's no-reset contract."""
        from swiftmpi_tpu import obs
        from swiftmpi_tpu.obs.registry import series_key
        reg = obs.get_registry()
        if not reg.enabled:
            return {}
        snap = reg.snapshot()
        out = {}
        for ph in TRACE_PHASES:
            h = snap["hists"].get(series_key("phase_ms", {"phase": ph}))
            if h is not None and h["count"]:
                out[ph] = h["sum"]
            dev = snap["gauges"].get(
                series_key("profile/device_ms", {"phase": ph}))
            if dev:
                out[ph + "_device"] = dev
        return out

    def _count(self, name: str, n: int) -> None:
        from swiftmpi_tpu import obs
        reg = obs.get_registry()
        if reg.enabled:
            reg.counter(name).inc(n)

    # -- hot-key attribution -----------------------------------------------
    def attach_sketch(self, sketch) -> None:
        """Use the control plane's DecayedSketch for touch ranking; the
        reservoir keeps supplying the byte attribution."""
        self._sketch = sketch

    def _hot_account(self, rec: dict) -> None:
        keys = rec.get("keys")
        if not keys:
            return
        n = len(keys)
        touch_share = float(rec.get("rows_out", 0)) / n
        byte_share = float(rec.get("enc_bytes", 0)) / n
        for k in keys:
            self._touch[k] = self._touch.get(k, 0.0) + touch_share
            self._bytes[k] = self._bytes.get(k, 0.0) + byte_share
        if len(self._touch) > _HOT_TABLE_MAX:
            keep = sorted(self._touch, key=self._touch.get,
                          reverse=True)[:_HOT_TABLE_MAX // 2]
            self._touch = {k: self._touch[k] for k in keep}
            self._bytes = {k: v for k, v in self._bytes.items()
                           if k in self._touch}

    def hot_keys(self, k: Optional[int] = None) -> List[dict]:
        """Top-K keys by touches (sketch-exact when attached, reservoir
        estimate otherwise), each with its attributed wire bytes."""
        k = self.topk if k is None else int(k)
        if k <= 0 or not self._touch:
            return []
        touch = dict(self._touch)
        if self._sketch is not None:
            try:
                counts = np.asarray(self._sketch.counts)
                for key in touch:
                    if 0 <= key < counts.size:
                        touch[key] = float(counts[key])
            except Exception:
                pass        # a mis-sized sketch must not kill tracing
        top = sorted(touch, key=touch.get, reverse=True)[:k]
        return [{"key": int(key), "touches": float(touch[key]),
                 "bytes": float(self._bytes.get(key, 0.0))}
                for key in top]

    def sampler(self, reg) -> None:
        """StepRecorder sampler: publish the hot-key attribution and the
        last traced window id as gauges before every snapshot."""
        if not reg.enabled:
            return
        reg.gauge("trace/last_window_id").set(float(self._win))
        for h in self.hot_keys():
            key = str(h["key"])
            reg.gauge("trace/hot_key_touches", key=key).set(h["touches"])
            reg.gauge("trace/hot_key_bytes", key=key).set(h["bytes"])

    # -- the step funnel + trigger poll ------------------------------------
    def on_step(self, n: int = 1) -> None:
        self._consumed += n
        if self.fleet_dir:
            self._poll_trigger()

    def _poll_trigger(self) -> None:
        now = time.monotonic()
        if now - self._last_poll < self.poll_s:
            return
        self._last_poll = now
        try:
            with open(os.path.join(self.fleet_dir,
                                   TRIGGER_FILENAME)) as f:
                req = json.load(f)
        except (OSError, ValueError):
            return
        tid = int(req.get("id", 0))
        if tid <= self._done_trigger_id:
            return
        self._done_trigger_id = tid
        self.dump(reason=f"trigger:{tid}")

    # -- flight recorder ---------------------------------------------------
    def records(self) -> List[dict]:
        """The ring's current contents (oldest first)."""
        return list(self._ring)

    @property
    def window_id(self) -> int:
        return self._win

    def dump(self, reason: str = "manual",
             path: Optional[str] = None) -> Optional[str]:
        """Write the flight-recorder ring (meta line + last-N records)
        to ``trace_r<rank>_p<pid>.jsonl``; atomic replace so a reader
        never sees a half-written dump from a LIVE dump (a crash dump is
        best-effort by nature — the repair parser owns that case)."""
        rank = process_rank() or 0
        path = path or os.path.join(
            self.trace_dir, f"trace_r{rank}_p{os.getpid()}.jsonl")
        meta = {"v": TRACE_SCHEMA_V, "kind": "meta",
                "schema": TRACE_SCHEMA, "reason": reason,
                "ts": time.time(), "rank": rank, "pid": os.getpid(),
                "ident": process_ident(), "win": self._win,
                "step": self._consumed, "records": len(self._ring),
                "hot_keys": self.hot_keys()}
        try:
            os.makedirs(self.trace_dir or ".", exist_ok=True)
            tmp = f"{path}.tmp.{os.getpid()}"
            with open(tmp, "w") as f:
                f.write(json.dumps(meta, sort_keys=True) + "\n")
                for rec in self._ring:
                    f.write(json.dumps(rec, sort_keys=True) + "\n")
            os.replace(tmp, path)
        except OSError:
            return None
        self.dumps.append(path)
        self._count("trace/dumps", 1)
        return path

    def close(self) -> None:
        """Crash-flush hook (recorder module's atexit/signal machinery
        calls ``close()`` on every enrolled object): seal any open
        record and dump the ring.  Idempotent; a clean teardown
        uninstalls the tracer instead of closing it, so normal exits
        leave no dump behind."""
        if self._closed:
            return
        for backend in list(self._open):
            self._finish(self._open.pop(backend))
        self._closed = True
        if self._ring:
            self.dump(reason="crash")


def on_critical_anomaly(anomaly: dict) -> None:
    """Numerics-plane hook: a critical anomaly freezes the evidence by
    dumping the flight recorder (throttled — a repeating anomaly must
    not turn the tracer into a disk flood).  No-op unless a tracer with
    ``dump_on_anomaly`` is installed."""
    from swiftmpi_tpu import obs
    tr = obs.get_tracer()
    if tr is None or not tr.dump_on_anomaly or tr._closed:
        return
    now = time.monotonic()
    if now - tr._last_anomaly_dump < tr.anomaly_min_gap_s:
        return
    tr._last_anomaly_dump = now
    tr.dump(reason=f"anomaly:{anomaly.get('anomaly', '?')}")


def main(argv: Optional[list] = None) -> int:
    """``python -m swiftmpi_tpu.obs.trace <fleet_dir>``: request a
    flight-recorder dump from every rank of a live fleet run."""
    import argparse
    ap = argparse.ArgumentParser(
        description="drop a trace-dump trigger in a fleet dir")
    ap.add_argument("fleet_dir", help="launch.py -fleet-dir target")
    args = ap.parse_args(argv)
    req = request_trace(args.fleet_dir)
    print(f"trace trigger id={req['id']} written to "
          f"{os.path.join(args.fleet_dir, TRIGGER_FILENAME)}")
    return 0


if __name__ == "__main__":
    import sys
    sys.exit(main())
