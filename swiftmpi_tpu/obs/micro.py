"""Telemetry emission for the kernel microbench scripts.

``scripts/gather_micro.py`` / ``scripts/scatter_micro.py`` print their
cells as free text — fine for a human in a tunnel window, invisible to
the diff tooling.  :class:`MicroTelemetry` gives those scripts the same
schema-versioned JSONL (``smtpu-telemetry/1``) every other producer
emits, so ``scripts/telemetry_report.py`` renders a microbench run's
phase table and ``scripts/check_traffic_budget.py`` can gate one run
against another exactly like bench cells:

    mt = MicroTelemetry(path, run="gather_micro")
    mt.cell("gather/cap17314_d100_fp32", ms)
    ...
    mt.close()

Each cell lands as one step record whose wall-ms is a
``phase_ms{phase=micro/<name>}`` histogram sample — the same series
shape ``obs.span`` gives the training phases, so ``phase_table`` picks
the cells up with zero new parsing.  The budget script additionally
folds every ``micro/...`` phase into its own pseudo-cell carrying a
``kernel_ms`` metric (see ``load_telemetry_cells``).
"""

from __future__ import annotations

from typing import Optional

from swiftmpi_tpu.obs.recorder import StepRecorder
from swiftmpi_tpu.obs.registry import MetricsRegistry


class MicroTelemetry:
    """Own-registry StepRecorder wrapper for microbench scripts (never
    touches the process-global registry — a microbench must not bleed
    series into a training run's telemetry)."""

    def __init__(self, path: str, run: str = "micro",
                 meta: Optional[dict] = None):
        self.registry = MetricsRegistry(enabled=True)
        self.recorder = StepRecorder(
            self.registry, path=path, run=run,
            meta={"micro": True, **(meta or {})})

    def cell(self, name: str, ms: float, **gauges) -> None:
        """Record one measured cell: ``ms`` wall-clock milliseconds as
        a ``phase_ms{phase=micro/<name>}`` sample, plus optional scalar
        context (shape sizes, GB/s) as ``micro_<k>{cell=<name>}``
        gauges."""
        self.registry.histogram("phase_ms",
                                phase=f"micro/{name}").observe(float(ms))
        for k, v in gauges.items():
            self.registry.gauge(f"micro_{k}", cell=name).set(float(v))
        self.recorder.on_steps(1)

    def close(self) -> None:
        self.recorder.close()
