"""One metrics registry for the whole framework.

PRs 3-5 grew four separate ad-hoc instrument sets — the transfer wire
ledger, the ``Throughput`` stall/device split, ``PrefetchIterator.stats()``
and the fault event bus — with no shared names, reset semantics, or sink.
:class:`MetricsRegistry` is the one place they all report now:

* **Counter** — monotonically non-decreasing total.  Never reset; readers
  take deltas (the Prometheus convention, and the convention
  ``Transfer.traffic()`` documents).  ``set_total`` adapts an external
  cumulative total (a wire ledger) into the same monotonic contract.
* **Gauge** — last-write-wins scalar (queue depth, words/s).
* **Histogram** — fixed upper-bound buckets with count/sum, built for
  latency distributions; quantiles are interpolated from the buckets so
  a histogram never stores per-observation data.

Identity is ``name`` plus sorted ``labels`` (``phase_ms{phase=dispatch}``),
so per-backend / per-phase series coexist under one name.  All writes are
thread-safe — the input pipeline's producer thread and the consumer loop
both write concurrently (tests/test_telemetry.py exercises exactly that).

Cost model: the registry is created **disabled** and every instrument
write starts with one attribute check — telemetry off costs a branch, not
a lock (the measured-overhead test asserts this stays near zero).  When
enabled, writes take one small lock; instrument handles are cached by the
call sites so the hot path never rebuilds label keys.
"""

from __future__ import annotations

import threading
from bisect import bisect_left
from typing import Dict, List, Optional, Tuple

#: default histogram upper bounds, in ms: 50µs .. ~26s, x2 per bucket —
#: wide enough for a CPU-emulated dispatch and a chip-side phase alike
DEFAULT_BUCKETS_MS = tuple(0.05 * (2.0 ** i) for i in range(20))


def series_key(name: str, labels: Optional[Dict[str, str]] = None) -> str:
    """Canonical series id: ``name{k=v,...}`` with sorted labels."""
    if not labels:
        return name
    inner = ",".join(f"{k}={labels[k]}" for k in sorted(labels))
    return f"{name}{{{inner}}}"


def parse_series_key(key: str) -> Tuple[str, Dict[str, str]]:
    """Inverse of :func:`series_key` (used by the run analyzer)."""
    if "{" not in key:
        return key, {}
    name, _, rest = key.partition("{")
    labels = {}
    for pair in rest.rstrip("}").split(","):
        if "=" in pair:
            k, _, v = pair.partition("=")
            labels[k] = v
    return name, labels


class Counter:
    """Monotonic total.  ``inc`` adds; ``set_total`` merges an external
    cumulative total without ever going backwards."""

    __slots__ = ("_reg", "key", "value")

    def __init__(self, reg: "MetricsRegistry", key: str):
        self._reg = reg
        self.key = key
        self.value = 0.0

    def inc(self, n: float = 1.0) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self.value += n

    def set_total(self, total: float) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            if total > self.value:
                self.value = total


class Gauge:
    __slots__ = ("_reg", "key", "value")

    def __init__(self, reg: "MetricsRegistry", key: str):
        self._reg = reg
        self.key = key
        self.value = 0.0

    def set(self, v: float) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        with reg._lock:
            self.value = float(v)


class Histogram:
    """Fixed-bucket histogram: ``bounds[i]`` is the inclusive upper edge
    of bucket i; the last bucket is the +inf overflow."""

    __slots__ = ("_reg", "key", "bounds", "counts", "count", "sum")

    def __init__(self, reg: "MetricsRegistry", key: str,
                 bounds: Tuple[float, ...]):
        self._reg = reg
        self.key = key
        self.bounds = bounds
        self.counts = [0] * (len(bounds) + 1)
        self.count = 0
        self.sum = 0.0

    def observe(self, v: float) -> None:
        reg = self._reg
        if not reg.enabled:
            return
        i = bisect_left(self.bounds, v)
        with reg._lock:
            self.counts[i] += 1
            self.count += 1
            self.sum += v


def quantile_from_buckets(bounds, counts, q: float) -> float:
    """Linear-interpolated quantile from cumulative bucket counts; the
    overflow bucket clamps to the top finite edge (same convention as
    Prometheus ``histogram_quantile``)."""
    total = sum(counts)
    if total == 0:
        return 0.0
    rank = q * total
    seen = 0.0
    for i, c in enumerate(counts):
        if c == 0:
            continue
        if seen + c >= rank:
            if i >= len(bounds):          # overflow bucket
                return float(bounds[-1]) if bounds else 0.0
            lo = float(bounds[i - 1]) if i > 0 else 0.0
            hi = float(bounds[i])
            frac = (rank - seen) / c
            return lo + (hi - lo) * frac
        seen += c
    return float(bounds[-1]) if bounds else 0.0


class MetricsRegistry:
    """Thread-safe labeled instrument registry (see module docstring)."""

    def __init__(self, enabled: bool = False):
        self.enabled = bool(enabled)
        self._lock = threading.Lock()
        self._counters: Dict[str, Counter] = {}
        self._gauges: Dict[str, Gauge] = {}
        self._hists: Dict[str, Histogram] = {}

    # -- instrument handles (cached; cheap to hold, cheap when disabled) ---
    def counter(self, name: str, **labels) -> Counter:
        key = series_key(name, labels)
        c = self._counters.get(key)
        if c is None:
            with self._lock:
                c = self._counters.setdefault(key, Counter(self, key))
        return c

    def gauge(self, name: str, **labels) -> Gauge:
        key = series_key(name, labels)
        g = self._gauges.get(key)
        if g is None:
            with self._lock:
                g = self._gauges.setdefault(key, Gauge(self, key))
        return g

    def histogram(self, name: str, buckets: Optional[Tuple[float, ...]]
                  = None, **labels) -> Histogram:
        key = series_key(name, labels)
        h = self._hists.get(key)
        if h is None:
            with self._lock:
                h = self._hists.setdefault(
                    key, Histogram(self, key,
                                   tuple(buckets or DEFAULT_BUCKETS_MS)))
        return h

    # -- read side ---------------------------------------------------------
    def snapshot(self) -> Dict[str, Dict]:
        """Point-in-time copy of every series: ``{"counters": {key: v},
        "gauges": {key: v}, "hists": {key: {"count", "sum", "counts",
        "bounds"}}}``.  Taken under the write lock, so a snapshot is
        internally consistent even against concurrent producers."""
        with self._lock:
            return {
                "counters": {k: c.value for k, c in self._counters.items()},
                "gauges": {k: g.value for k, g in self._gauges.items()},
                "hists": {k: {"count": h.count, "sum": h.sum,
                              "counts": list(h.counts),
                              "bounds": h.bounds}
                          for k, h in self._hists.items()},
            }

    @staticmethod
    def delta(prev: Dict[str, Dict], cur: Dict[str, Dict]) -> Dict[str, Dict]:
        """Per-step view between two snapshots: counter deltas (only the
        series that moved), gauge current values, histogram bucket-count
        deltas.  The StepRecorder calls this once per recorded step."""
        counters = {}
        for k, v in cur["counters"].items():
            d = v - prev["counters"].get(k, 0.0)
            if d:
                counters[k] = d
        hists = {}
        for k, h in cur["hists"].items():
            p = prev["hists"].get(k)
            pc = p["counts"] if p else [0] * len(h["counts"])
            dc = [a - b for a, b in zip(h["counts"], pc)]
            n = h["count"] - (p["count"] if p else 0)
            if n:
                hists[k] = {"n": n,
                            "sum": h["sum"] - (p["sum"] if p else 0.0),
                            "counts": dc,
                            "bounds": h["bounds"]}
        return {"counters": counters, "gauges": dict(cur["gauges"]),
                "hists": hists}

    def quantile(self, name_or_key: str, q: float, **labels) -> float:
        key = series_key(name_or_key, labels) if labels else name_or_key
        h = self._hists.get(key)
        if h is None:
            return 0.0
        with self._lock:
            counts, bounds = list(h.counts), h.bounds
        return quantile_from_buckets(bounds, counts, q)

    def series_keys(self) -> List[str]:
        with self._lock:
            return (list(self._counters) + list(self._gauges)
                    + list(self._hists))
