"""Numerics health plane (ISSUE 13): in-jit gradient/EF/quantization
telemetry with a host-side anomaly detector.

The wire stack is deliberately lossy — int8/bf16 ``wire_quant`` with
error-feedback residuals, window staleness, hogwild races — and this
module is the runtime evidence that the gradients riding it are still
healthy.  Three pieces:

* **Traced bundle helpers** (:func:`push_stats`, :func:`state_stats`) —
  pure ``jnp`` reductions the jitted step builders fold into the
  existing fused scan when ``[obs] numerics`` is armed: gradient
  sum-of-squares split by hot/tail plane, nonfinite element counts,
  update-vs-param mass, and per-field EF residual mass.  With the plane
  off the builders never call them, so the traced program — and the
  trajectory — is bit-identical to a build without this module.

* :class:`NumericsCollector` — the host-side staging target.  Traced
  code ships the bundle out through ``jax.debug.callback`` (the traffic
  ledger discipline: no host sync on the dispatch path); the collector
  folds it into cumulative state and mirrors it as declared
  ``numerics/*`` registry series from a StepRecorder sampler.  The
  quantization-error tap (:meth:`NumericsCollector.quant_tap`) is
  handed to ``transfer.api.set_numerics_tap`` so all four backends'
  EF/quantize paths book their pre-vs-post error through one funnel.

* :class:`AnomalyDetector` — rolling EWMA+MAD baselines per series,
  emitting schema-versioned (:data:`SCHEMA`) ``numerics/anomaly``
  telemetry events with severity and evidence.  Observe-only by
  default; the Controller can register a demote hook that fires on
  SUSTAINED EF-residual runaway (``[obs] numerics_patience``
  consecutive anomalous windows) to drop ``wire_quant`` to lossless.
  Baselines serialize (:meth:`AnomalyDetector.state`) so checkpoints
  carry them across ``train_with_resume`` restarts instead of
  re-learning — and false-alarming — on the first post-restore window.

Cross-rank divergence is the fleet half: :func:`cross_rank_divergence`
scores the per-rank ``numerics/grad_norm`` gauges the FleetCollector
extracts from aligned steps.
"""

from __future__ import annotations

import json
import math
import threading
from typing import Callable, Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.parameter.sparse_table import hot_name, is_ef_field

#: anomaly event payload schema rides every ``numerics/anomaly`` event
SCHEMA = "smtpu-numerics/1"

#: anomaly kinds the detector emits (docs/ARCHITECTURE.md "Numerics
#: health" documents the triage story per kind)
ANOMALY_KINDS = ("loss_spike", "grad_norm_explosion",
                 "ef_residual_runaway", "nonfinite",
                 "cross_rank_divergence")

#: gauge-series -> anomaly kind scored by the rolling baseline (all
#: upward-only: a shrinking norm is convergence, not an anomaly)
_SERIES_KIND = {
    "numerics/loss": "loss_spike",
    "numerics/grad_norm": "grad_norm_explosion",
    "numerics/grad_norm_hot": "grad_norm_explosion",
    "numerics/grad_norm_tail": "grad_norm_explosion",
    "numerics/update_ratio": "grad_norm_explosion",
}


def enabled(config) -> bool:
    """The ``[obs] numerics`` master switch (default 0 = off)."""
    return config.get_or("obs", "numerics", 0).to_bool()


def detector_from_config(config) -> "AnomalyDetector":
    """Build a detector from the ``[obs] numerics_*`` knob family."""
    g = config.get_or
    return AnomalyDetector(
        alpha=g("obs", "numerics_alpha", 0.1).to_float(),
        k=g("obs", "numerics_mad_k", 6.0).to_float(),
        warmup=g("obs", "numerics_warmup", 8).to_int32(),
        patience=g("obs", "numerics_patience", 3).to_int32(),
    )


# -- traced bundle helpers --------------------------------------------------

def push_stats(slots, grads: dict, n_hot: int):
    """One step's push-gradient statistics, as traced reductions.

    ``slots`` is the per-row slot array (any shape whose elements match
    the leading dims of each grad; ``None`` for dense pushes, which are
    all-tail by definition); ``grads`` the per-field row gradients;
    ``n_hot`` the static hot-plane row count (0 = no hot plane).

    Returns ``(sq_total, sq_hot, nonfinite)``: finite-masked gradient
    sum-of-squares (total and hot-plane share) and the nonfinite element
    count.  Nonfinite elements are EXCLUDED from the norms — a single
    NaN must show up in ``numerics/nonfinite``, not poison the
    grad-norm baseline into permanent NaN.
    """
    sq_total = jnp.zeros((), jnp.float32)
    sq_hot = jnp.zeros((), jnp.float32)
    nonfin = jnp.zeros((), jnp.int32)
    for g in grads.values():
        g32 = jnp.asarray(g, jnp.float32)
        finite = jnp.isfinite(g32)
        nonfin = nonfin + jnp.sum(
            (~finite).astype(jnp.int32), dtype=jnp.int32)
        row_sq = jnp.sum(jnp.where(finite, g32, 0.0) ** 2, axis=-1)
        sq_total = sq_total + jnp.sum(row_sq)
        if n_hot > 0 and slots is not None:
            hot = ((slots >= 0) & (slots < n_hot)).astype(jnp.float32)
            sq_hot = sq_hot + jnp.sum(row_sq * hot)
    return sq_total, sq_hot, nonfin


def state_stats(before: dict, after: dict, grad_fields):
    """Once-per-dispatch table statistics, as traced reductions.

    ``before``/``after`` are the table state at dispatch entry and
    exit; ``grad_fields`` the parameter fields the step updates.
    Returns ``(upd_sq, par_sq, ef_mass, nonfin)``: finite-masked
    update and parameter sum-of-squares (their ratio is the classic
    update/param health number), per-EF-plane residual L1 mass keyed by
    the base field name, and the nonfinite element count across the
    updated params and residual planes.
    """
    upd_sq = jnp.zeros((), jnp.float32)
    par_sq = jnp.zeros((), jnp.float32)
    nonfin = jnp.zeros((), jnp.int32)
    keys = []
    for f in grad_fields:
        keys.append(f)
        if hot_name(f) in after:        # hybrid replicated hot overlay
            keys.append(hot_name(f))
    for f in keys:
        b = jnp.asarray(before[f], jnp.float32)
        a = jnp.asarray(after[f], jnp.float32)
        fin = jnp.isfinite(a)
        nonfin = nonfin + jnp.sum((~fin).astype(jnp.int32),
                                  dtype=jnp.int32)
        a0 = jnp.where(fin, a, 0.0)
        b0 = jnp.where(jnp.isfinite(b), b, 0.0)
        upd_sq = upd_sq + jnp.sum((a0 - b0) ** 2)
        par_sq = par_sq + jnp.sum(b0 ** 2)
    ef_mass = {}
    for name in after:
        if not is_ef_field(name):
            continue
        r = jnp.asarray(after[name], jnp.float32)
        fin = jnp.isfinite(r)
        nonfin = nonfin + jnp.sum((~fin).astype(jnp.int32),
                                  dtype=jnp.int32)
        base = name[:name.rindex("@")]
        ef_mass[base] = jnp.sum(jnp.abs(jnp.where(fin, r, 0.0)))
    return upd_sq, par_sq, ef_mass, nonfin


def spec_stats(pushes, n_hot: int):
    """Fold :func:`push_stats` over one step's PushSpec list (also
    accepts scan-stacked specs — the reductions are shape-agnostic).
    Dense capacity-shaped specs have no slot identity; they count as
    all-tail."""
    sq = jnp.zeros((), jnp.float32)
    hot = jnp.zeros((), jnp.float32)
    nf = jnp.zeros((), jnp.int32)
    for spec in pushes:
        slots = None if getattr(spec, "dense", False) else spec.slots
        s, h, n = push_stats(slots, spec.grads, n_hot)
        sq, hot, nf = sq + s, hot + h, nf + n
    return sq, hot, nf


def tree_stats(tree):
    """Finite-masked sum-of-squares + nonfinite count over a pytree
    (the dense trainer's grads/updates/params — no slot identity, no
    hot plane)."""
    sq = jnp.zeros((), jnp.float32)
    nonfin = jnp.zeros((), jnp.int32)
    for g in jax.tree_util.tree_leaves(tree):
        g32 = jnp.asarray(g, jnp.float32)
        fin = jnp.isfinite(g32)
        nonfin = nonfin + jnp.sum((~fin).astype(jnp.int32),
                                  dtype=jnp.int32)
        sq = sq + jnp.sum(jnp.where(fin, g32, 0.0) ** 2)
    return sq, nonfin


def stage_dense(collector: "NumericsCollector", params, grads,
                updates, loss) -> None:
    """Dense-trainer bundle (models/trainer.py): grad mass,
    update/param ratio and nonfinite counts over the param pytree —
    no hot plane, no EF residuals.  ``params`` is the PRE-update
    pytree; ``loss`` the step's scalar loss."""
    gsq, g_nf = tree_stats(grads)
    upd_sq, u_nf = tree_stats(updates)
    par_sq, _ = tree_stats(params)
    bundle = {
        "gsq": gsq, "gsq_hot": jnp.zeros((), jnp.float32),
        "upd_sq": upd_sq, "par_sq": par_sq,
        "nonfinite": g_nf + u_nf,
        "loss_sum": jnp.asarray(loss, jnp.float32),
        "loss_n": jnp.ones((), jnp.float32),
    }
    collector.stage_traced(bundle, {})


def stage_step(collector: "NumericsCollector", state0, state1,
               grad_acc, es, ec, grad_fields) -> None:
    """Assemble one dispatch's bundle inside the traced step and ship
    it to ``collector``: ``grad_acc`` is the (sq, sq_hot, nonfinite)
    accumulation over the dispatch's pushes, ``state0``/``state1`` the
    table at dispatch entry/exit, ``es``/``ec`` the loss sum and
    example count the step already computes."""
    gsq, gsq_hot, g_nf = grad_acc
    upd_sq, par_sq, ef_mass, s_nf = state_stats(state0, state1,
                                                grad_fields)
    bundle = {
        "gsq": gsq, "gsq_hot": gsq_hot,
        "upd_sq": upd_sq, "par_sq": par_sq,
        "nonfinite": (jnp.asarray(g_nf, jnp.int32)
                      + jnp.asarray(s_nf, jnp.int32)),
        "loss_sum": jnp.asarray(es, jnp.float32),
        "loss_n": jnp.asarray(ec, jnp.float32),
    }
    collector.stage_traced(bundle, ef_mass)


# -- host-side collector ----------------------------------------------------

class NumericsCollector:
    """Staging target for the traced bundle + registry mirror.

    ``stage_traced`` is called from inside the jitted step with a flat
    dict of scalar reductions; the values arrive on the host through
    ``jax.debug.callback`` whenever the runtime retires the dispatch —
    asynchronously, so the dispatch path never blocks on telemetry.
    ``sampler`` runs on the StepRecorder's record path and publishes
    the latest bundle (plus cumulative nonfinite / quant-error totals)
    as ``numerics/*`` series, then lets the detector score them.
    """

    def __init__(self, detector: Optional["AnomalyDetector"] = None):
        self.detector = detector
        self._lock = threading.Lock()
        self._latest: Dict[str, float] = {}      # guarded-by: _lock
        self._ef_mass: Dict[str, float] = {}     # guarded-by: _lock
        self._nonfinite = 0.0                    # guarded-by: _lock
        self._quant_err = 0.0                    # guarded-by: _lock
        self._bundles = 0                        # guarded-by: _lock

    # .. staging (called from traced OR eager code) ........................

    def stage_traced(self, bundle: dict, ef_mass: dict) -> None:
        """Ship one dispatch's bundle out of traced code.  ``bundle``
        holds scalar tracers (gsq/gsq_hot/upd_sq/par_sq/nonfinite/
        loss_sum/loss_n), ``ef_mass`` per-field scalar tracers."""
        jax.debug.callback(self._on_bundle, bundle, ef_mass)

    def _on_bundle(self, bundle, ef_mass) -> None:
        with self._lock:
            self._latest = {k: float(v) for k, v in bundle.items()}
            self._ef_mass = {k: float(v) for k, v in ef_mass.items()}
            self._nonfinite += float(bundle.get("nonfinite", 0.0))
            self._bundles += 1

    def quant_tap(self, err_sq) -> None:
        """Accumulate one quantized window's pre-vs-post error norm.
        Works traced (xla/tpu call it inside ``ef_quantize_window``)
        and eager (the local oracle's numpy path)."""
        if isinstance(err_sq, jax.core.Tracer):
            jax.debug.callback(self._on_quant, err_sq)
        else:
            self._on_quant(err_sq)

    def _on_quant(self, err_sq) -> None:
        v = float(np.asarray(err_sq))
        if not math.isfinite(v):
            with self._lock:
                self._nonfinite += 1.0
            return
        with self._lock:
            self._quant_err += math.sqrt(max(v, 0.0))

    def sync(self) -> None:
        """Drain in-flight debug callbacks (call at safe points — end
        of train, before a final record — never per step)."""
        jax.effects_barrier()

    @property
    def bundles(self) -> int:
        """Dispatch bundles received so far (train_metrics surface)."""
        with self._lock:
            return self._bundles

    # .. publishing ........................................................

    def sampler(self, reg) -> None:
        """StepRecorder sampler: mirror the latest bundle as declared
        series, then let the detector score the sample."""
        with self._lock:
            latest = dict(self._latest)
            ef_mass = dict(self._ef_mass)
            nonfinite = self._nonfinite
            quant_err = self._quant_err
        if not latest and not ef_mass and not nonfinite and not quant_err:
            return
        values: Dict[str, float] = {}
        gsq = latest.get("gsq", 0.0)
        gsq_hot = latest.get("gsq_hot", 0.0)
        values["numerics/grad_norm"] = math.sqrt(max(gsq, 0.0))
        values["numerics/grad_norm_hot"] = math.sqrt(max(gsq_hot, 0.0))
        values["numerics/grad_norm_tail"] = math.sqrt(
            max(gsq - gsq_hot, 0.0))
        par_sq = latest.get("par_sq", 0.0)
        if par_sq > 0.0:
            values["numerics/update_ratio"] = math.sqrt(
                max(latest.get("upd_sq", 0.0), 0.0) / par_sq)
        loss_n = latest.get("loss_n", 0.0)
        if loss_n > 0.0:
            values["numerics/loss"] = latest.get("loss_sum", 0.0) / loss_n
        for name, v in values.items():
            reg.gauge(name).set(v)
        for f, m in sorted(ef_mass.items()):
            reg.gauge("numerics/ef_mass", field=f).set(m)
        reg.counter("numerics/nonfinite").set_total(nonfinite)
        reg.counter("numerics/quant_err").set_total(quant_err)
        if self.detector is not None:
            for f, m in sorted(ef_mass.items()):
                values[f"numerics/ef_mass{{field={f}}}"] = m
            self.detector.on_sample(reg, values, nonfinite)


# -- rolling-baseline anomaly detector --------------------------------------

class AnomalyDetector:
    """EWMA+MAD baselines per series, anomaly events, demote hook.

    Per series the detector keeps ``(mean, dev, n)`` where ``dev`` is
    an EWMA of absolute deviation (a MAD proxy that needs no window
    buffer).  A sample scores anomalous when it exceeds the baseline by
    ``k`` deviations UPWARD after ``warmup`` samples; ``2k`` promotes
    the severity to ``critical``.  Anomalous samples update the
    baseline with their clamped value (``mean + k*dev``) so a genuine
    regime shift is absorbed over a few windows instead of either
    poisoning the baseline instantly or alarming forever.
    """

    def __init__(self, alpha: float = 0.1, k: float = 6.0,
                 warmup: int = 8, patience: int = 3):
        self.alpha = float(alpha)
        self.k = float(k)
        self.warmup = max(int(warmup), 1)
        self.patience = max(int(patience), 1)
        self._base: Dict[str, List[float]] = {}   # series -> [m, dev, n]
        self._streaks: Dict[str, int] = {}
        self._nonfinite_seen = 0.0
        self._hooks: List[Callable[[dict], None]] = []
        self._hook_fired = False
        self.anomalies_emitted = 0

    # .. hook contract (docs/ARCHITECTURE.md "Numerics health") ...........

    def add_demote_hook(self, fn: Callable[[dict], None]) -> None:
        """Register ``fn(anomaly)`` to fire ONCE on sustained EF-residual
        runaway (``patience`` consecutive anomalous windows on any
        ``numerics/ef_mass`` series).  Observe-only until someone calls
        this — the Controller's ``attach_numerics`` is the one caller."""
        self._hooks.append(fn)

    # .. scoring ..........................................................

    def observe(self, series: str, value: float) -> Optional[dict]:
        """Score one sample against the series' rolling baseline and
        update it.  Returns the anomaly dict (kind/severity/evidence)
        or None.  Also drives the sustained-runaway streaks and fires
        the demote hook when an ef_mass streak reaches ``patience``."""
        kind = _SERIES_KIND.get(series)
        if kind is None and series.startswith("numerics/ef_mass"):
            kind = "ef_residual_runaway"
        if kind is None:
            return None
        if not math.isfinite(value):
            return self._mk("nonfinite", series, value, None, None,
                            "critical")
        m, dev, n = self._base.get(series, (value, 0.0, 0.0))
        anomaly = None
        if n >= self.warmup:
            scale = max(dev, 1e-3 * max(abs(m), 1.0), 1e-12)
            z = (value - m) / scale
            if z > 2.0 * self.k:
                anomaly = self._mk(kind, series, value, m, dev,
                                   "critical", z=z)
            elif z > self.k:
                anomaly = self._mk(kind, series, value, m, dev,
                                   "warning", z=z)
        absorbed = value if anomaly is None else m + self.k * max(dev, 0.0)
        a = self.alpha
        m = m + a * (absorbed - m) if n else absorbed
        dev = dev + a * (abs(absorbed - m) - dev)
        self._base[series] = [m, dev, n + 1]
        if kind == "ef_residual_runaway":
            streak = self._streaks.get(series, 0) + 1 if anomaly else 0
            self._streaks[series] = streak
            if anomaly is not None and streak >= self.patience \
                    and not self._hook_fired:
                self._hook_fired = True
                anomaly["sustained"] = streak
                for h in list(self._hooks):
                    h(dict(anomaly))
        return anomaly

    def on_sample(self, reg, values: Dict[str, float],
                  nonfinite_total: float) -> List[dict]:
        """One recorded step's worth of scoring: every gauge in
        ``values`` plus the cumulative nonfinite counter (any forward
        motion is a critical anomaly — NaNs never self-heal)."""
        out = []
        if nonfinite_total > self._nonfinite_seen:
            out.append(self._mk(
                "nonfinite", "numerics/nonfinite",
                nonfinite_total - self._nonfinite_seen, None, None,
                "critical"))
            self._nonfinite_seen = nonfinite_total
        for series, v in values.items():
            a = self.observe(series, v)
            if a is not None:
                out.append(a)
        for a in out:
            self._emit(reg, a)
        return out

    def _mk(self, kind, series, value, mean, dev, severity, z=None):
        a = {"schema": SCHEMA, "anomaly": kind, "series": series,
             "severity": severity, "value": float(value)}
        if mean is not None:
            a["baseline"] = float(mean)
            a["mad"] = float(dev)
        if z is not None:
            a["z"] = float(z)
        return a

    def _emit(self, reg, anomaly: dict) -> None:
        from swiftmpi_tpu import obs
        self.anomalies_emitted += 1
        reg.counter("numerics/anomalies",
                    severity=anomaly["severity"]).inc()
        rec = obs.get_recorder()
        if rec is not None:
            rec.event("numerics/anomaly", anomaly)
        if anomaly["severity"] == "critical":
            # triggered profiler window (ISSUE 14): a critical anomaly
            # captures the very steps that misbehaved — no-op unless
            # [obs] profile_on_anomaly armed a session
            from swiftmpi_tpu.obs import profiler as obs_profiler
            from swiftmpi_tpu.obs import trace as obs_trace
            obs_profiler.on_critical_anomaly(anomaly)
            # flight-recorder dump (ISSUE 15): preserve the last-N
            # window wire records surrounding the anomaly — no-op
            # unless a tracer is installed with [obs] trace_on_anomaly
            obs_trace.on_critical_anomaly(anomaly)

    # .. checkpoint carry ..................................................

    def state(self) -> dict:
        """JSON-able rolling state for ``save_checkpoint(extra=...)``."""
        return {"schema": SCHEMA, "alpha": self.alpha, "k": self.k,
                "warmup": self.warmup, "patience": self.patience,
                "series": {s: list(v) for s, v in self._base.items()},
                "streaks": dict(self._streaks),
                "nonfinite_seen": self._nonfinite_seen,
                "hook_fired": self._hook_fired}

    def load_state(self, state: dict) -> bool:
        """Restore baselines saved by :meth:`state`.  Unknown or
        foreign-schema payloads are ignored (False) — a detector must
        never crash a resume over its own bookkeeping."""
        if not isinstance(state, dict) or \
                state.get("schema") != SCHEMA:
            return False
        self._base = {str(s): [float(v[0]), float(v[1]), float(v[2])]
                      for s, v in (state.get("series") or {}).items()}
        self._streaks = {str(s): int(v)
                         for s, v in (state.get("streaks") or {}).items()}
        self._nonfinite_seen = float(state.get("nonfinite_seen", 0.0))
        self._hook_fired = bool(state.get("hook_fired", False))
        return True

    def state_bytes(self) -> np.ndarray:
        """:meth:`state` as a uint8 array (the checkpoint ``extra``
        vehicle — npz carries arrays, not dicts)."""
        raw = json.dumps(self.state()).encode("utf-8")
        return np.frombuffer(raw, dtype=np.uint8)

    def load_state_bytes(self, arr) -> bool:
        try:
            state = json.loads(np.asarray(arr, np.uint8)
                               .tobytes().decode("utf-8"))
        except (ValueError, UnicodeDecodeError):
            return False
        return self.load_state(state)


# -- fleet half -------------------------------------------------------------

def cross_rank_divergence(per_step: Dict[int, Dict[str, float]],
                          factor: float = 4.0,
                          min_ranks: int = 2) -> List[dict]:
    """Score aligned per-rank grad norms for cross-rank divergence.

    ``per_step`` maps step -> {rank: grad_norm}.  A step where the
    max/min ratio across >= ``min_ranks`` live ranks exceeds ``factor``
    is a ``warning``; ``factor**2`` promotes to ``critical``.  Returns
    anomaly dicts (same shape the detector emits) sorted by step —
    the FleetCollector folds them into the merged timeline.
    """
    out = []
    for step in sorted(per_step):
        norms = {r: v for r, v in per_step[step].items()
                 if v is not None and math.isfinite(v)}
        if len(norms) < min_ranks:
            continue
        lo_rank = min(norms, key=lambda r: norms[r])
        hi_rank = max(norms, key=lambda r: norms[r])
        lo, hi = norms[lo_rank], norms[hi_rank]
        ratio = hi / max(lo, 1e-12)
        if ratio <= factor:
            continue
        severity = "critical" if ratio > factor * factor else "warning"
        out.append({"schema": SCHEMA, "anomaly": "cross_rank_divergence",
                    "series": "numerics/grad_norm", "severity": severity,
                    "step": int(step), "ratio": float(ratio),
                    "max_rank": str(hi_rank), "min_rank": str(lo_rank),
                    "value": float(hi), "baseline": float(lo)})
    return out
