"""Parallelism primitives: collectives and context-parallel attention."""

from swiftmpi_tpu.parallel.collectives import (all_gather, all_to_all,
                                               axis_index, axis_size, pmean,
                                               psum, reduce_scatter,
                                               ring_permute)
from swiftmpi_tpu.parallel.ring_attention import (SEQ_AXIS, full_attention,
                                                  ring_attention,
                                                  ulysses_attention)

__all__ = ["all_gather", "all_to_all", "axis_index", "axis_size", "pmean",
           "psum", "reduce_scatter", "ring_permute", "SEQ_AXIS",
           "full_attention", "ring_attention", "ulysses_attention"]
