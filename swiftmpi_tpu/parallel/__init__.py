"""Parallelism primitives: collectives, context/pipeline/expert parallel."""

from swiftmpi_tpu.parallel.collectives import (all_gather, all_to_all,
                                               axis_index, axis_size, pmean,
                                               psum, reduce_scatter,
                                               ring_permute)
from swiftmpi_tpu.parallel.moe import (EXPERT_AXIS, MoEParams,
                                       init_moe_params, moe_ffn,
                                       moe_ffn_reference)
from swiftmpi_tpu.parallel.pipeline import (STAGE_AXIS, pipeline_apply,
                                            pipeline_loss,
                                            stack_stage_params)
from swiftmpi_tpu.parallel.ring_attention import (SEQ_AXIS, full_attention,
                                                  ring_attention,
                                                  ulysses_attention)

__all__ = ["all_gather", "all_to_all", "axis_index", "axis_size", "pmean",
           "psum", "reduce_scatter", "ring_permute", "SEQ_AXIS",
           "full_attention", "ring_attention", "ulysses_attention",
           "STAGE_AXIS", "pipeline_apply", "pipeline_loss",
           "stack_stage_params", "EXPERT_AXIS", "MoEParams",
           "init_moe_params", "moe_ffn", "moe_ffn_reference"]
