"""Expert parallelism: top-k routed mixture-of-experts over a mesh axis.

Nothing to port from the reference (SURVEY.md §2.7 "Not present: EP as
MoE") — but its sharded-parameter-table design has a direct modern
descendant: experts are rows of a parameter table sharded over an
``expert`` mesh axis, and token→expert routing is the same
"key → owning shard → all_to_all → apply → all_to_all back" pattern the
``transfer=tpu`` pull/push backend uses for sparse rows.  This module is
that pattern for dense FFN experts (GShard/Switch style):

1. Router: per-token logits over E experts; top-k gating with normalized
   softmax weights + the standard load-balance auxiliary loss.
2. Capacity: each expert processes at most C tokens per device shard
   (static shape, XLA-friendly); overflow tokens are dropped (their
   combine weight is zero — they pass through the residual).
3. Dispatch: one-hot ``(T, E, C)`` dispatch tensor → einsum into per-
   expert buffers → ``all_to_all`` over the ``expert`` axis so each device
   holds *all* shards' tokens for *its* experts → local FFN → reverse
   ``all_to_all`` → weighted combine.

Everything is einsum + two all_to_alls: MXU-shaped, static, fusable.
"""

from __future__ import annotations

import math
from functools import partial
from typing import NamedTuple, Tuple

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu.parallel.collectives import all_to_all

EXPERT_AXIS = "expert"


class MoEParams(NamedTuple):
    """Router + stacked expert FFN weights.

    ``w_in``/``w_out`` leading dim is E (global expert count) — shard it
    ``P('expert')`` the same way the sparse table rows shard over
    ``model``.
    """
    router: jax.Array   # (d_model, E)
    w_in: jax.Array     # (E, d_model, d_ff)
    w_out: jax.Array    # (E, d_ff, d_model)


def init_moe_params(key, d_model: int, d_ff: int, n_experts: int,
                    dtype=jnp.float32) -> MoEParams:
    kr, ki, ko = jax.random.split(key, 3)
    s_in = 1.0 / math.sqrt(d_model)
    s_out = 1.0 / math.sqrt(d_ff)
    return MoEParams(
        router=jax.random.normal(kr, (d_model, n_experts), dtype) * s_in,
        w_in=jax.random.normal(ki, (n_experts, d_model, d_ff), dtype) * s_in,
        w_out=jax.random.normal(ko, (n_experts, d_ff, d_model), dtype)
        * s_out,
    )


def _top_k_gating(logits: jax.Array, k: int):
    """(T, E) logits -> gates (T, E) with k nonzeros/row (renormalized),
    plus the two per-expert densities whose product is the GShard
    load-balance aux loss: E * sum_e density_e * density_proxy_e.
    The densities are token means, so shards pmean them *before* the
    product — making the distributed aux exactly the global one."""
    T, E = logits.shape
    probs = jax.nn.softmax(logits, axis=-1)
    top_vals, top_idx = lax.top_k(probs, k)                    # (T, k)
    gates = jnp.zeros_like(probs).at[
        jnp.arange(T)[:, None], top_idx].set(top_vals)
    gates = gates / jnp.maximum(gates.sum(-1, keepdims=True), 1e-9)
    density = (gates > 0).astype(probs.dtype).mean(axis=0)     # (E,)
    density_proxy = probs.mean(axis=0)                         # (E,)
    return gates, density, density_proxy


def _dispatch_mask(gates: jax.Array, capacity: int):
    """Turn (T, E) gates into a one-hot (T, E, C) dispatch tensor with
    positions assigned first-come-first-served per expert; tokens beyond
    capacity get an all-zero row (dropped)."""
    assigned = gates > 0                                       # (T, E)
    pos = jnp.cumsum(assigned.astype(jnp.int32), axis=0) - 1   # (T, E)
    keep = assigned & (pos < capacity)
    onehot = jax.nn.one_hot(jnp.where(keep, pos, capacity), capacity,
                            dtype=gates.dtype)                 # (T, E, C)
    dispatch = onehot * keep[..., None].astype(gates.dtype)
    combine = dispatch * gates[..., None]
    return dispatch, combine


def moe_ffn(params: MoEParams, x: jax.Array, mesh: Mesh, *,
            axis: str = EXPERT_AXIS, k: int = 2,
            capacity_factor: float = 2.0
            ) -> Tuple[jax.Array, jax.Array]:
    """Expert-parallel MoE FFN.

    ``x``: global ``(T, d_model)`` tokens, sharded ``P(axis)`` on T (dp and
    ep share the axis, the standard layout).  Experts shard ``P(axis)`` on
    E.  Returns ``(y, aux_loss)`` with ``y`` sharded like ``x``.
    """
    n = int(mesh.shape[axis])
    E = params.router.shape[1]
    if E % n:
        raise ValueError(f"experts={E} must divide over axis size {n}")
    T = x.shape[0]
    if T % n:
        raise ValueError(f"tokens={T} must divide over axis size {n}")
    t_local = T // n
    capacity = max(1, int(math.ceil(t_local * k / E * capacity_factor)))

    x_spec = P(axis)
    p_spec = MoEParams(router=P(), w_in=P(axis), w_out=P(axis))

    @partial(jax.shard_map, mesh=mesh, in_specs=(p_spec, x_spec),
             out_specs=(x_spec, P()), check_vma=False)
    def _moe(p, xl):
        gates, dens, proxy = _top_k_gating(xl @ p.router, k)    # (t, E)
        aux = (lax.pmean(dens, axis) * lax.pmean(proxy, axis)).sum() * E
        dispatch, combine = _dispatch_mask(gates, capacity)     # (t,E,C)
        # per-expert buffers, then route shards->owners over the axis
        buf = jnp.einsum("tec,td->ecd", dispatch, xl)           # (E,C,d)
        buf = all_to_all(buf, axis, split_axis=0, concat_axis=1)
        # now (E/n, n*C, d): all devices' tokens for my experts
        h = jax.nn.relu(jnp.einsum("ecd,edf->ecf", buf, p.w_in))
        out = jnp.einsum("ecf,efd->ecd", h, p.w_out)
        out = all_to_all(out, axis, split_axis=1, concat_axis=0)
        y = jnp.einsum("tec,ecd->td", combine, out)             # (t, d)
        return y, aux

    return _moe(params, x)


def moe_ffn_reference(params: MoEParams, x: jax.Array, *, k: int = 2):
    """Dense single-device golden: every token through its top-k experts,
    no capacity drops.  For tests (capacity_factor high => must match)."""
    gates, dens, proxy = _top_k_gating(x @ params.router, k)
    aux = (dens * proxy).sum() * params.router.shape[1]
    h = jax.nn.relu(jnp.einsum("td,edf->tef", x, params.w_in))
    per_e = jnp.einsum("tef,efd->ted", h, params.w_out)
    return jnp.einsum("te,ted->td", gates, per_e), aux
