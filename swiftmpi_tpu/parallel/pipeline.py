"""Pipeline parallelism: GPipe-style microbatched stage pipeline.

The reference has no pipeline parallelism to port (SURVEY.md §2.7 "Not
present: PP") — this is the TPU-native design for it, built the mesh way:

* The model is a chain of ``n_stages`` identical-signature stage functions
  whose parameters are stacked on a leading axis and **row-sharded over a
  ``stage`` mesh axis** — each device (group) holds exactly its stage's
  weights, like the sparse table holds its rows.
* The batch is split into M microbatches.  A ``lax.scan`` runs
  ``M + n_stages - 1`` ticks; at every tick each stage applies its function
  to the activation it currently holds and hands the result to its ``+1``
  neighbour with a single ``ppermute`` hop (ICI neighbour traffic only —
  the same primitive ring attention uses).
* The schedule is expressed with ``lax.scan`` (not ``fori_loop``) so the
  whole pipeline is **differentiable**: ``jax.grad`` through
  ``pipeline_apply`` transposes the scan + ppermute into the reverse
  pipeline schedule automatically — no hand-written backward pass.

Bubble fraction is the classic (n-1)/(M+n-1); pick M >= 4*n for <20%
overhead.  All shapes are static: microbatch count and stage count are
Python ints at trace time, as XLA requires.
"""

from __future__ import annotations

from functools import partial
from typing import Any, Callable

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu.parallel.collectives import ring_permute

STAGE_AXIS = "stage"


def stack_stage_params(params_list) -> Any:
    """Stack per-stage parameter pytrees on a new leading ``stage`` axis.

    The result is what ``pipeline_apply`` expects: one pytree whose leaves
    have shape ``(n_stages, ...)``, shardable with ``P('stage', ...)``.
    """
    return jax.tree.map(lambda *xs: jnp.stack(xs), *params_list)


def pipeline_apply(stage_fn: Callable, stage_params: Any, x: jax.Array,
                   mesh: Mesh, *, axis: str = STAGE_AXIS,
                   num_microbatches: int) -> jax.Array:
    """Run ``x`` through the stage pipeline; returns the final activation.

    ``stage_fn(params_i, act) -> act`` must keep the activation shape
    (classic homogeneous-pipeline restriction; wrap embed/head layers
    outside the pipelined trunk).  ``stage_params`` leaves have leading dim
    ``n_stages`` and are sharded ``P(axis)``; ``x`` is the global batch
    ``(B, ...)`` with ``B % num_microbatches == 0``.

    The returned array is replicated over ``axis`` (it is psum'd off the
    last stage), so callers can compute the loss without caring where the
    pipeline ended.
    """
    n = int(mesh.shape[axis])
    n_stacked = {leaf.shape[0] for leaf in jax.tree.leaves(stage_params)}
    if n_stacked != {n}:
        raise ValueError(
            f"stage_params leading dims {sorted(n_stacked)} must all equal "
            f"the '{axis}' axis size {n} (one stage per device group)")
    B = x.shape[0]
    if B % num_microbatches:
        raise ValueError(f"batch {B} % microbatches {num_microbatches} != 0")
    mb = B // num_microbatches
    M = num_microbatches

    p_spec = jax.tree.map(lambda _: P(axis), stage_params)

    @partial(jax.shard_map, mesh=mesh, in_specs=(p_spec, P()),
             out_specs=P(), check_vma=False)
    def _pipe(params_l, x_full):
        # params_l leaves: (1, ...) — this device's stage; drop the dim.
        params = jax.tree.map(lambda p: p[0], params_l)
        my = lax.axis_index(axis)
        x_mb = x_full.reshape((M, mb) + x_full.shape[1:])

        state0 = jnp.zeros((mb,) + x_full.shape[1:], x_full.dtype)
        out0 = jnp.zeros_like(x_mb)

        def tick(carry, t):
            state, out = carry
            # stage 0 ingests microbatch t (clamped; masked past M)
            feed = lax.dynamic_index_in_dim(
                x_mb, jnp.minimum(t, M - 1), 0, keepdims=False)
            state = jnp.where((my == 0) & (t < M), feed, state)
            y = stage_fn(params, state)
            # last stage emits microbatch t-(n-1) once warmed up
            slot = jnp.clip(t - (n - 1), 0, M - 1)
            emit = (my == n - 1) & (t >= n - 1)
            cur = lax.dynamic_index_in_dim(out, slot, 0, keepdims=False)
            out = lax.dynamic_update_index_in_dim(
                out, jnp.where(emit, y, cur), slot, 0)
            # hand activations to the +1 neighbour (ring; wraparound into
            # stage 0 is overwritten by the feed next tick)
            state = ring_permute(y, axis)
            return (state, out), None

        (_, out), _ = lax.scan(tick, (state0, out0),
                               jnp.arange(M + n - 1))
        # replicate the result off the last stage
        out = lax.psum(jnp.where(my == n - 1, out, jnp.zeros_like(out)),
                       axis)
        return out.reshape(x_full.shape)

    return _pipe(stage_params, x)


def pipeline_loss(stage_fn: Callable, loss_fn: Callable, stage_params: Any,
                  x: jax.Array, target: Any, mesh: Mesh, *,
                  axis: str = STAGE_AXIS, num_microbatches: int):
    """Convenience: scalar ``loss_fn(final_act, target)`` over the pipeline
    output — the thing to ``jax.grad`` for pipelined training."""
    y = pipeline_apply(stage_fn, stage_params, x, mesh, axis=axis,
                       num_microbatches=num_microbatches)
    return loss_fn(y, target)
