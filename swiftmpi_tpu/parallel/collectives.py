"""Named-axis collective helpers.

The TPU data plane the reference implements with sockets (SURVEY.md §2.8):
thin, uniformly-named wrappers over ``jax.lax`` collectives for use inside
``shard_map`` bodies, plus mesh-level helpers.  Exists mostly so higher
layers (transfer backends, context parallelism) read as communication
patterns — psum / all_gather / reduce_scatter / ppermute / all_to_all —
rather than lax incantations.
"""

from __future__ import annotations

from typing import Sequence, Tuple

import jax
import jax.numpy as jnp
from jax import lax

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (lax.axis_size alias)


def psum(x, axis: str):
    """Dense gradient combine (the reference's server-side add across
    worker pushes, expressed as an ICI all-reduce)."""
    return lax.psum(x, axis)


def pmean(x, axis: str):
    return lax.pmean(x, axis)


def all_gather(x, axis: str, *, tiled: bool = True):
    return lax.all_gather(x, axis, tiled=tiled)


def reduce_scatter(x, axis: str, *, scatter_dimension: int = 0):
    return lax.psum_scatter(x, axis, scatter_dimension=scatter_dimension,
                            tiled=True)


def all_to_all(x, axis: str, split_axis: int, concat_axis: int):
    return lax.all_to_all(x, axis, split_axis, concat_axis, tiled=True)


def ring_permute(x, axis: str, shift: int = 1):
    """Send my block to my +shift neighbor along the ring (the ppermute
    backbone of ring attention)."""
    n = lax.axis_size(axis)
    perm = [(j, (j + shift) % n) for j in range(n)]
    return lax.ppermute(x, axis, perm)


def axis_index(axis: str):
    return lax.axis_index(axis)


def axis_size(axis: str):
    return lax.axis_size(axis)
