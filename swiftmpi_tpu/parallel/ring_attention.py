"""Context parallelism for long sequences: ring attention + Ulysses.

The reference predates transformers — nothing to port (SURVEY.md §2.7
"Not present: SP/CP, ring attention, Ulysses") — but long-context is
first-class in this framework, so both standard strategies are provided as
mesh-native primitives:

* ``ring_attention`` — sequence sharded over a mesh axis; K/V blocks rotate
  around the ring via ``ppermute`` while each device folds one block per
  step into an online-softmax accumulator (flash-attention style).  ICI
  traffic per step is one K/V block; memory is O(S/n) per device.  Supports
  causal masking with block-level skipping of the always-masked products.
* ``ulysses_attention`` — all_to_all reshard: sequence-sharded activations
  become head-sharded, full-sequence attention runs locally per head group,
  then all_to_all back.  Two collectives total; requires heads % n == 0.

Both are numerically checked against ``full_attention`` in the test suite
on an 8-device mesh.  Layout convention: ``(batch, seq, heads, head_dim)``.
"""

from __future__ import annotations

import math
from functools import partial
from typing import Optional

import jax
import jax.numpy as jnp
from jax import lax
from jax.sharding import Mesh, PartitionSpec as P

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu.parallel.collectives import all_to_all, ring_permute

SEQ_AXIS = "seq"
_NEG = -1e30


def full_attention(q, k, v, causal: bool = False):
    """Single-device softmax attention golden (B, S, H, D).

    Scores and softmax are f32 regardless of input dtype — the MXU
    accumulates in f32 anyway, so asking for f32 out of the score
    einsum is free, and a bf16 softmax over S terms loses real bits.
    The probs are cast back to the value dtype so the PV einsum stays
    on the bf16 MXU path."""
    scale = 1.0 / math.sqrt(q.shape[-1])
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if causal:
        Sq, Sk = s.shape[-2], s.shape[-1]
        mask = jnp.tril(jnp.ones((Sq, Sk), bool))
        s = jnp.where(mask, s, _NEG)
    p = jax.nn.softmax(s, axis=-1)
    return jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                      preferred_element_type=jnp.float32
                      ).astype(v.dtype)


def _fold_block(q, k, v, m, l, o, scale, mask):
    """One online-softmax accumulation step (flash-attention recurrence).

    q: (B, Sq, H, D); k, v: (B, Sk, H, D); m, l: (B, H, Sq) f32;
    o: (B, Sq, H, D) f32; mask: (Sq, Sk) bool or None.

    The running max/sum/output stats stay f32 across ring steps (bf16
    online-softmax statistics drift as blocks fold in); the two einsums
    keep their bf16 MXU inputs, with f32 requested out of the MXU's
    native f32 accumulation.
    """
    s = jnp.einsum("bqhd,bkhd->bhqk", q, k,
                   preferred_element_type=jnp.float32) * scale
    if mask is not None:
        s = jnp.where(mask[None, None], s, _NEG)
    m_new = jnp.maximum(m, s.max(axis=-1))
    p = jnp.exp(s - m_new[..., None])            # (B, H, Sq, Sk) f32
    corr = jnp.exp(m - m_new)                    # (B, H, Sq) f32
    l_new = l * corr + p.sum(axis=-1)
    pv = jnp.einsum("bhqk,bkhd->bqhd", p.astype(v.dtype), v,
                    preferred_element_type=jnp.float32)
    o_new = o * corr.transpose(0, 2, 1)[..., None] + pv
    return m_new, l_new, o_new


def ring_attention(q, k, v, mesh: Mesh, axis: str = SEQ_AXIS,
                   causal: bool = False):
    """Attention with Q, K, V sequence-sharded over ``axis``.

    Inputs/outputs are global ``(B, S, H, D)`` arrays; internally each
    device processes its S/n query block against all K/V blocks as they
    rotate around the ring.
    """
    n = int(mesh.shape[axis])
    scale = 1.0 / math.sqrt(q.shape[-1])
    spec = P(None, axis, None, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _ring(q_l, k_l, v_l):
        B, Sq, H, D = q_l.shape
        my = lax.axis_index(axis)

        # step 0: my own (diagonal) block — within-block causal mask
        m0 = jnp.full((B, H, Sq), _NEG, jnp.float32)
        l0 = jnp.zeros((B, H, Sq), jnp.float32)
        o0 = jnp.zeros(q_l.shape, jnp.float32)
        diag_mask = (jnp.tril(jnp.ones((Sq, Sq), bool)) if causal
                     else None)
        m1, l1, o1 = _fold_block(q_l, k_l, v_l, m0, l0, o0, scale,
                                 diag_mask)

        def body(step, carry):
            # permute first, then fold: the last rotation is never wasted
            k_cur, v_cur, m, l, o = carry
            k_cur = ring_permute(k_cur, axis)
            v_cur = ring_permute(v_cur, axis)
            src = (my - step) % n          # whose block we now hold

            def fold(c):
                m, l, o = c
                return _fold_block(q_l, k_cur, v_cur, m, l, o, scale,
                                   None)

            if causal:
                # src > my blocks are entirely in the future: skip the
                # matmuls, not just mask them (uniform predicate: every
                # device is at the same step).
                m, l, o = lax.cond(src > my, lambda c: c, fold, (m, l, o))
            else:
                m, l, o = fold((m, l, o))
            return (k_cur, v_cur, m, l, o)

        _, _, m, l, o = lax.fori_loop(
            1, n, body, (k_l, v_l, m1, l1, o1))
        l = jnp.maximum(l, 1e-20)
        return (o / l.transpose(0, 2, 1)[..., None]).astype(q_l.dtype)

    return _ring(q, k, v)


def ulysses_attention(q, k, v, mesh: Mesh, axis: str = SEQ_AXIS,
                      causal: bool = False):
    """All-to-all sequence parallelism (DeepSpeed-Ulysses pattern):
    reshard seq-sharded -> head-sharded, attend over the full sequence
    locally, reshard back.  Needs H % n == 0."""
    n = int(mesh.shape[axis])
    H = q.shape[2]
    if H % n:
        raise ValueError(f"heads={H} must be divisible by axis size {n}")
    spec = P(None, axis, None, None)

    @partial(jax.shard_map, mesh=mesh, in_specs=(spec, spec, spec),
             out_specs=spec, check_vma=False)
    def _ulysses(q_l, k_l, v_l):
        # (B, S/n, H, D) -> all_to_all over heads -> (B, S, H/n, D)
        def fwd(x):
            return all_to_all(x, axis, split_axis=2, concat_axis=1)

        def bwd(x):
            return all_to_all(x, axis, split_axis=1, concat_axis=2)

        o = full_attention(fwd(q_l), fwd(k_l), fwd(v_l), causal=causal)
        return bwd(o)

    return _ulysses(q, k, v)
