"""Lint engine: file loading, suppression directives, baseline, runner.

Deliberately dependency-free (stdlib ``ast`` only) so the gate runs in
any environment the repo imports in — including a box with no jax.
Rules live in :mod:`swiftmpi_tpu.analysis.rules`; this module owns the
mechanics every rule shares:

* :class:`LintFile` — parsed source + per-line suppression directives.
  A directive on a block header (``def``/``class``/``with``/``for``)
  expands to the whole block's line span, so one justified comment can
  cover e.g. a trainer-thread-only device function in a serve module.
* fingerprints — ``sha1(rule | relpath | normalized line text | k)``
  where ``k`` disambiguates identical lines.  Line-content-based, so a
  baseline survives unrelated edits that shift line numbers.
* baseline — checked-in JSON of grandfathered fingerprints with a
  required ``justification`` string per entry (the "benign legacy
  pattern" contract; an empty baseline is the healthy state).
"""

from __future__ import annotations

import ast
import hashlib
import json
import os
import re
from dataclasses import dataclass, field
from typing import Dict, Iterable, List, Optional, Sequence, Set, Tuple

BASELINE_NAME = "lint_baseline.json"
JSON_SCHEMA = "smtpu-lint/1"

_DIRECTIVE_RE = re.compile(
    r"#\s*smtpu-lint:\s*(disable|disable-file)\s*=\s*"
    r"([A-Z0-9\-]+(?:\s*,\s*[A-Z0-9\-]+)*)")

#: statements whose header-line directive covers the whole block
_BLOCK_STMTS = (ast.FunctionDef, ast.AsyncFunctionDef, ast.ClassDef,
                ast.With, ast.For, ast.While, ast.If, ast.Try)


@dataclass
class Finding:
    rule: str
    path: str            # repo-relative, forward slashes
    line: int
    col: int
    message: str
    fingerprint: str = ""

    def to_dict(self) -> dict:
        return {"rule": self.rule, "path": self.path, "line": self.line,
                "col": self.col, "message": self.message,
                "fingerprint": self.fingerprint}

    def render(self) -> str:
        return (f"{self.path}:{self.line}:{self.col}: "
                f"{self.rule}: {self.message}")


class LintFile:
    """One parsed source file plus its suppression machinery."""

    def __init__(self, path: str, rel: str, source: str):
        self.path = path
        self.rel = rel.replace(os.sep, "/")
        self.source = source
        self.lines = source.splitlines()
        self.tree: Optional[ast.Module] = None
        self.parse_error: Optional[SyntaxError] = None
        #: line -> set of rule ids disabled on that line
        self._line_disables: Dict[int, Set[str]] = {}
        self._file_disables: Set[str] = set()
        try:
            self.tree = ast.parse(source, filename=rel)
        except SyntaxError as e:
            self.parse_error = e
            return
        self._collect_directives()

    # -- directives -------------------------------------------------------
    def _collect_directives(self) -> None:
        raw: Dict[int, Set[str]] = {}
        for i, text in enumerate(self.lines, start=1):
            m = _DIRECTIVE_RE.search(text)
            if not m:
                continue
            rules = {r.strip() for r in m.group(2).split(",") if r.strip()}
            if m.group(1) == "disable-file":
                self._file_disables |= rules
            else:
                raw.setdefault(i, set()).update(rules)
        self._line_disables = dict(raw)
        if not raw or self.tree is None:
            return
        # block-header directives cover the statement's full line span
        for node in ast.walk(self.tree):
            if not isinstance(node, _BLOCK_STMTS):
                continue
            header = raw.get(node.lineno)
            # a decorated def's directive may sit on the first decorator
            if header is None and getattr(node, "decorator_list", None):
                header = raw.get(node.decorator_list[0].lineno)
            if header is None:
                continue
            for ln in range(node.lineno, (node.end_lineno or node.lineno) + 1):
                self._line_disables.setdefault(ln, set()).update(header)

    def suppressed(self, rule: str, line: int) -> bool:
        if rule in self._file_disables:
            return True
        return rule in self._line_disables.get(line, set())

    # -- fingerprints -----------------------------------------------------
    def line_text(self, line: int) -> str:
        if 1 <= line <= len(self.lines):
            return self.lines[line - 1].strip()
        return ""


def fingerprint(rule: str, rel: str, line_text: str, occurrence: int) -> str:
    norm = re.sub(r"\s+", " ", line_text.strip())
    h = hashlib.sha1(
        f"{rule}|{rel}|{norm}|{occurrence}".encode()).hexdigest()
    return h[:16]


@dataclass
class LintContext:
    """Shared lookups rules may need (resolved once per run)."""

    root: str
    #: docs/OPERATIONS.md text for KNOB-DOC ("" when absent)
    operations_md: str = ""
    #: extra knob-doc text sources (ARCHITECTURE.md is NOT consulted —
    #: OPERATIONS.md is the operator-facing contract)
    extras: dict = field(default_factory=dict)

    @classmethod
    def for_root(cls, root: str) -> "LintContext":
        ops = os.path.join(root, "docs", "OPERATIONS.md")
        text = ""
        if os.path.exists(ops):
            with open(ops, encoding="utf-8") as f:
                text = f.read()
        return cls(root=root, operations_md=text)


# -- file collection --------------------------------------------------------

_DEFAULT_SCOPES = ("swiftmpi_tpu", "scripts", "bench.py")
_EXCLUDE_DIRS = {"__pycache__", ".git", "runs"}


def default_paths(root: str) -> List[str]:
    """The repo lint scope: the package, scripts/, and bench.py.
    tests/ is deliberately out — fixtures there reproduce violations
    on purpose."""
    out: List[str] = []
    for scope in _DEFAULT_SCOPES:
        p = os.path.join(root, scope)
        if os.path.isfile(p):
            out.append(p)
            continue
        for dirpath, dirnames, filenames in os.walk(p):
            dirnames[:] = [d for d in dirnames if d not in _EXCLUDE_DIRS]
            for fn in sorted(filenames):
                if fn.endswith(".py"):
                    out.append(os.path.join(dirpath, fn))
    return out


def load_files(paths: Sequence[str], root: str) -> List[LintFile]:
    files = []
    for p in paths:
        rel = os.path.relpath(p, root)
        with open(p, encoding="utf-8") as f:
            src = f.read()
        files.append(LintFile(p, rel, src))
    return files


# -- baseline ---------------------------------------------------------------

def load_baseline(path: str) -> Dict[str, dict]:
    """fingerprint -> entry.  Missing file = empty baseline."""
    if not os.path.exists(path):
        return {}
    with open(path, encoding="utf-8") as f:
        data = json.load(f)
    return {e["fingerprint"]: e for e in data.get("findings", [])}


def write_baseline(path: str, findings: Iterable[Finding],
                   justification: str = "TODO: justify or fix") -> int:
    """Write a baseline of grandfathered findings.  The default
    ``justification`` is a deliberate placeholder: an entry still
    carrying it (or any empty/TODO text) is NOT a justified suppression,
    and :func:`run_lint` surfaces it as a ``BASELINE-JUSTIFY`` finding
    until a real reason is written in."""
    entries = [{"rule": f.rule, "path": f.path, "line_hint": f.line,
                "fingerprint": f.fingerprint,
                "justification": justification}
               for f in findings]
    with open(path, "w", encoding="utf-8") as f:
        json.dump({"schema": JSON_SCHEMA, "findings": entries}, f,
                  indent=2, sort_keys=True)
        f.write("\n")
    return len(entries)


def _unjustified(entry: dict) -> bool:
    """True when a baseline entry's justification is missing, blank, or
    still the ``write_baseline`` placeholder (any text starting with
    ``TODO``, case-insensitive)."""
    j = str(entry.get("justification") or "").strip()
    return not j or j.upper().startswith("TODO")


# -- runner -----------------------------------------------------------------

def run_lint(paths: Optional[Sequence[str]] = None,
             root: Optional[str] = None,
             rules: Optional[Sequence] = None,
             baseline: Optional[Dict[str, dict]] = None,
             ) -> Tuple[List[Finding], List[Finding]]:
    """Run ``rules`` over ``paths``; returns ``(new, baselined)``.

    Findings suppressed by inline directives are dropped entirely;
    findings whose fingerprint appears in ``baseline`` land in the
    second list.  Fingerprint occurrence counters are assigned per
    (rule, file, normalized line text) in file order, so two identical
    offending lines get distinct stable fingerprints.
    """
    from swiftmpi_tpu.analysis.rules import RULES
    if root is None:
        root = repo_root()
    if paths is None:
        paths = default_paths(root)
    if rules is None:
        rules = RULES
    ctx = LintContext.for_root(root)
    baseline = baseline or {}
    new: List[Finding] = []
    old: List[Finding] = []
    for lf in load_files(paths, root):
        if lf.parse_error is not None:
            e = lf.parse_error
            new.append(Finding("PARSE", lf.rel, e.lineno or 0, 0,
                               f"syntax error: {e.msg}",
                               fingerprint("PARSE", lf.rel, e.msg or "", 0)))
            continue
        per_file: List[Finding] = []
        for rule in rules:
            for f in rule.check(lf, ctx):
                if lf.suppressed(f.rule, f.line):
                    continue
                per_file.append(f)
        # stable fingerprints: occurrence index per identical key
        seen: Dict[Tuple[str, str], int] = {}
        for f in sorted(per_file, key=lambda f: (f.line, f.col, f.rule)):
            text = lf.line_text(f.line)
            key = (f.rule, re.sub(r"\s+", " ", text))
            k = seen.get(key, 0)
            seen[key] = k + 1
            f.fingerprint = fingerprint(f.rule, lf.rel, text, k)
            entry = baseline.get(f.fingerprint)
            if entry is None:
                new.append(f)
                continue
            old.append(f)
            if _unjustified(entry):
                # a suppression without a reason is not a suppression —
                # the placeholder write_baseline stamps in must be
                # replaced by a human-written justification, or the
                # finding keeps gating
                bj = Finding(
                    "BASELINE-JUSTIFY", f.path, f.line, f.col,
                    f"baseline entry for {f.rule} ({f.fingerprint}) has "
                    "an empty or placeholder justification — write the "
                    f"reason into {BASELINE_NAME} or fix the finding",
                    fingerprint("BASELINE-JUSTIFY", lf.rel, text, k))
                if not lf.suppressed("BASELINE-JUSTIFY", f.line) and \
                        bj.fingerprint not in baseline:
                    new.append(bj)
    return new, old


def repo_root() -> str:
    """The repo checkout containing this package (…/swiftmpi_tpu/..)."""
    return os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
