"""The invariant rules.  Each encodes one hard-won repo contract; the
origin incident and enforcement rationale per rule live in
docs/ARCHITECTURE.md "Invariant catalog".

Rules are deliberately *syntactic with narrow scopes* rather than
whole-program dataflow: each invariant names the files that carry it
(the serve read path, the pipeline producer, the transfer ledger), so
a per-file AST pass with light intra-function tracking catches the
regression classes that actually happened without drowning the gate in
false positives.  Where a rule cannot decide statically (a series name
held in a bare variable), it stays silent rather than guessing — the
fixtures in tests/test_lint.py pin exactly what each rule sees.
"""

from __future__ import annotations

import ast
import re
from typing import Dict, Iterator, List, Optional, Set, Tuple

from swiftmpi_tpu.analysis.core import Finding, LintContext, LintFile

# ---------------------------------------------------------------------------
# shared AST helpers


def attr_chain(node: ast.AST) -> Optional[str]:
    """Dotted chain for Name/Attribute trees: ``jax.random.split`` —
    None when the root is not a plain Name (calls, subscripts...)."""
    parts: List[str] = []
    while isinstance(node, ast.Attribute):
        parts.append(node.attr)
        node = node.value
    if isinstance(node, ast.Name):
        parts.append(node.id)
        return ".".join(reversed(parts))
    return None


def parent_map(tree: ast.AST) -> Dict[ast.AST, ast.AST]:
    p: Dict[ast.AST, ast.AST] = {}
    for node in ast.walk(tree):
        for child in ast.iter_child_nodes(node):
            p[child] = node
    return p


def _const_str(node: ast.AST) -> Optional[str]:
    if isinstance(node, ast.Constant) and isinstance(node.value, str):
        return node.value
    return None


def _target_names(target: ast.AST) -> Set[str]:
    """Plain names (re)bound by an assignment target (tuples unpacked)."""
    out: Set[str] = set()
    for n in ast.walk(target):
        if isinstance(n, ast.Name):
            out.add(n.id)
    return out


def _target_chains(target: ast.AST) -> Set[str]:
    """Dotted chains (self.x ...) rebound by an assignment target."""
    out: Set[str] = set()
    if isinstance(target, (ast.Tuple, ast.List)):
        for e in target.elts:
            out |= _target_chains(e)
    else:
        c = attr_chain(target)
        if c:
            out.add(c)
    return out


class Rule:
    id: str = ""
    description: str = ""

    def check(self, f: LintFile, ctx: LintContext) -> Iterator[Finding]:
        raise NotImplementedError

    def finding(self, f: LintFile, node: ast.AST, msg: str) -> Finding:
        return Finding(self.id, f.rel, getattr(node, "lineno", 0),
                       getattr(node, "col_offset", 0), msg)


# ---------------------------------------------------------------------------
# DONATE-ESCAPE


def _donate_positions(call: ast.Call) -> Optional[Tuple[int, ...]]:
    """Donated argnums from a ``jax.jit(...)`` / ``partial(jax.jit,...)``
    call node, or None when it doesn't donate."""
    chain = attr_chain(call.func)
    inner = None
    if chain in ("jax.jit", "jit"):
        inner = call
    elif chain in ("partial", "functools.partial") and call.args:
        if attr_chain(call.args[0]) in ("jax.jit", "jit"):
            inner = call
    if inner is None:
        return None
    for kw in inner.keywords:
        if kw.arg == "donate_argnums":
            v = kw.value
            if isinstance(v, ast.Constant) and isinstance(v.value, int):
                return (v.value,)
            if isinstance(v, (ast.Tuple, ast.List)):
                out = []
                for e in v.elts:
                    if isinstance(e, ast.Constant) and \
                            isinstance(e.value, int):
                        out.append(e.value)
                return tuple(out)
            return ()          # dynamic donate spec: treat as unknown
    return None


class DonateEscape(Rule):
    """A buffer passed at a donated position of a jitted function must
    not be read afterwards, nor captured by a closure/thread: the NEXT
    dispatch deletes the donated device buffer outright (the PR-8
    serve-plane bug class: a snapshot holding the live table state went
    ``Array has been deleted`` under readers)."""

    id = "DONATE-ESCAPE"
    description = ("donated-buffer argument read or captured after a "
                   "donating jit call")

    def check(self, f, ctx):
        tree = f.tree
        parents = parent_map(tree)
        donating: Dict[str, Tuple[int, ...]] = {}     # module-level names
        factories: Dict[str, Dict[str, Tuple[int, ...]]] = {}  # per class
        donating_attrs: Dict[str, Tuple[int, ...]] = {}        # self.X

        # pass 1: module-level donating defs/assignments + class factories
        for node in ast.walk(tree):
            if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
                for dec in node.decorator_list:
                    if isinstance(dec, ast.Call):
                        pos = _donate_positions(dec)
                        if pos is not None:
                            donating[node.name] = pos
            elif isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                pos = _donate_positions(node.value)
                if pos is not None:
                    for t in node.targets:
                        for n in _target_names(t):
                            donating[n] = pos
            elif isinstance(node, ast.ClassDef):
                factories[node.name] = self._class_factories(node)
                for meth_pos in [factories[node.name]]:
                    pass
                # self.X = self.<factory>() anywhere in the class
                for sub in ast.walk(node):
                    if isinstance(sub, ast.Assign) and \
                            isinstance(sub.value, ast.Call):
                        fchain = attr_chain(sub.value.func)
                        if fchain and fchain.startswith("self."):
                            meth = fchain[len("self."):]
                            pos = factories[node.name].get(meth)
                            if pos is not None:
                                for t in sub.targets:
                                    for c in _target_chains(t):
                                        if c.startswith("self."):
                                            donating_attrs[c] = pos
                        else:
                            pos = _donate_positions(sub.value)
                            if pos is not None:
                                for t in sub.targets:
                                    for c in _target_chains(t):
                                        if c.startswith("self."):
                                            donating_attrs[c] = pos

        # pass 2: per-scope read-after-donation analysis
        scopes = [tree] + [n for n in ast.walk(tree)
                           if isinstance(n, (ast.FunctionDef,
                                             ast.AsyncFunctionDef))]
        all_factories: Dict[str, Tuple[int, ...]] = {}
        for per_class in factories.values():
            all_factories.update(per_class)
        for scope in scopes:
            yield from self._scan_scope(f, scope, parents, donating,
                                        donating_attrs, all_factories)

    @staticmethod
    def _class_factories(cls: ast.ClassDef
                         ) -> Dict[str, Tuple[int, ...]]:
        """Methods that RETURN a donating jitted function (directly, via
        a local name, or via another factory of the same class) — one
        fixpoint pass so ``_fused_for -> _build_multi_step`` chains
        resolve."""
        out: Dict[str, Tuple[int, ...]] = {}
        changed = True
        while changed:
            changed = False
            for meth in cls.body:
                if not isinstance(meth, ast.FunctionDef) or meth.name in out:
                    continue
                local: Dict[str, Tuple[int, ...]] = {}
                for node in ast.walk(meth):
                    if isinstance(node, ast.FunctionDef) and node is not meth:
                        for dec in node.decorator_list:
                            if isinstance(dec, ast.Call):
                                pos = _donate_positions(dec)
                                if pos is not None:
                                    local[node.name] = pos
                    elif isinstance(node, ast.Assign) and \
                            isinstance(node.value, ast.Call):
                        pos = _donate_positions(node.value)
                        fchain = attr_chain(node.value.func)
                        if pos is None and fchain and \
                                fchain.startswith("self."):
                            pos = out.get(fchain[len("self."):])
                        if pos is not None:
                            for t in node.targets:
                                for n in _target_names(t):
                                    local[n] = pos
                for node in ast.walk(meth):
                    if isinstance(node, ast.Return) and node.value is not None:
                        pos = None
                        if isinstance(node.value, ast.Name):
                            pos = local.get(node.value.id)
                        elif isinstance(node.value, ast.Call):
                            pos = _donate_positions(node.value)
                            fchain = attr_chain(node.value.func)
                            if pos is None and fchain and \
                                    fchain.startswith("self."):
                                pos = out.get(fchain[len("self."):])
                        if pos is not None:
                            out[meth.name] = pos
                            changed = True
                            break
        return out

    def _scan_scope(self, f, scope, parents, donating, donating_attrs,
                    factories):
        body_nodes: List[ast.AST] = []     # nodes outside nested defs
        nested_defs: List[ast.AST] = []
        for node in ast.iter_child_nodes(scope):
            self._split(node, body_nodes, nested_defs, top=scope)
        # local donating names: n = self._factory(...) / n = jax.jit(...)
        local = dict(donating)
        for node in body_nodes:
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Call):
                pos = _donate_positions(node.value)
                fchain = attr_chain(node.value.func)
                if pos is None and fchain and fchain.startswith("self."):
                    pos = factories.get(fchain[len("self."):])
                if pos is not None:
                    for t in node.targets:
                        for n in _target_names(t):
                            local[n] = pos
        # rebind lines per chain
        rebinds: Dict[str, List[int]] = {}
        for node in body_nodes:
            if isinstance(node, (ast.Assign, ast.AugAssign, ast.For)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for c in _target_names(t) | _target_chains(t):
                        rebinds.setdefault(c, []).append(node.lineno)
        # loads per chain (outermost attribute/name only)
        loads: Dict[str, List[ast.AST]] = {}
        for node in body_nodes:
            if isinstance(node, (ast.Name, ast.Attribute)) and \
                    isinstance(getattr(node, "ctx", None), ast.Load):
                if isinstance(parents.get(node), ast.Attribute):
                    continue                   # inner part of a chain
                c = attr_chain(node)
                if c:
                    loads.setdefault(c, []).append(node)

        for node in body_nodes:
            if not isinstance(node, ast.Call):
                continue
            pos = None
            fchain = attr_chain(node.func)
            if isinstance(node.func, ast.Name):
                pos = local.get(node.func.id)
            elif fchain and fchain in donating_attrs:
                pos = donating_attrs[fchain]
            if not pos:
                continue
            stmt = node
            while stmt in parents and not isinstance(
                    stmt, (ast.Assign, ast.AugAssign, ast.Expr,
                           ast.Return)):
                stmt = parents[stmt]
            bound = set()
            if isinstance(stmt, ast.Assign):
                for t in stmt.targets:
                    bound = bound | _target_names(t) | _target_chains(t)
            for p in pos:
                if p >= len(node.args):
                    continue
                arg = node.args[p]
                chain = attr_chain(arg)
                if chain is None:
                    continue
                if chain in bound:
                    continue           # canonical x = step(x, ...) rebind
                call_line = node.lineno
                next_rebind = min(
                    [ln for ln in rebinds.get(chain, [])
                     if ln > call_line] or [10 ** 9])
                for ld in loads.get(chain, []):
                    if ld is arg:
                        continue
                    if call_line < ld.lineno < next_rebind:
                        yield self.finding(
                            f, ld,
                            f"`{chain}` was donated to "
                            f"`{fchain or '<fn>'}` on line {call_line} "
                            "(donate_argnums) and is read afterwards — "
                            "the next dispatch deletes the buffer; copy "
                            "before donating or rebind the name")
                for nd in nested_defs:
                    names = {n.id for n in ast.walk(nd)
                             if isinstance(n, ast.Name)
                             and isinstance(n.ctx, ast.Load)}
                    argnames = set()
                    a = getattr(nd, "args", None)
                    if a is not None:
                        argnames = {x.arg for x in
                                    a.args + a.kwonlyargs +
                                    ([a.vararg] if a.vararg else []) +
                                    ([a.kwarg] if a.kwarg else [])}
                    root = chain.split(".")[0]
                    if root in names - argnames and \
                            chain not in bound and \
                            not rebinds.get(chain):
                        yield self.finding(
                            f, nd,
                            f"closure captures `{root}` which is donated "
                            f"to `{fchain or '<fn>'}` on line "
                            f"{call_line} — a thread/callback reading it "
                            "races buffer deletion; capture a host copy "
                            "instead")

    def _split(self, node, body_nodes, nested_defs, top):
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef,
                             ast.Lambda)) and node is not top:
            nested_defs.append(node)
            return
        body_nodes.append(node)
        for child in ast.iter_child_nodes(node):
            self._split(child, body_nodes, nested_defs, top)


# ---------------------------------------------------------------------------
# READER-PURE-HOST

_SERVE_ALLOW = {
    "serve/snapshot.py": ("jax.device_get", "jax.tree_util"),
    "serve/reader.py": (),
    "serve/query.py": (),
    "serve/shipper.py": (),
}


class ReaderPureHost(Rule):
    """Serve read-path modules are pure host: no ``jax.``/``jnp.``
    device ops.  Reader threads launching device programs against the
    trainer's dispatches rendezvous-deadlock XLA:CPU (PR-8); snapshots
    may use exactly ``jax.device_get``/``jax.tree_util`` — the
    trainer-thread D2H copy."""

    id = "READER-PURE-HOST"
    description = "device op reachable from the serve read path"

    def check(self, f, ctx):
        allow = None
        for suffix, al in _SERVE_ALLOW.items():
            if f.rel.endswith(suffix):
                allow = al
        if allow is None:
            return
        parents = parent_map(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = ([a.name for a in node.names]
                        if isinstance(node, ast.Import)
                        else [node.module or ""])
                for m in mods:
                    if m == "jax" and "jax.device_get" in allow:
                        continue
                    if m.split(".")[0] == "jax" or m == "jnp":
                        yield self.finding(
                            f, node,
                            f"import of `{m}` in a pure-host serve "
                            "module — readers must never touch the "
                            "device runtime")
            elif isinstance(node, (ast.Name, ast.Attribute)):
                if isinstance(parents.get(node), ast.Attribute):
                    continue
                chain = attr_chain(node)
                if not chain:
                    continue
                root = chain.split(".")[0]
                if root not in ("jax", "jnp"):
                    continue
                if any(chain == a or chain.startswith(a + ".")
                       for a in allow):
                    continue
                yield self.finding(
                    f, node,
                    f"`{chain}` in a pure-host serve module — reader "
                    "threads must not launch device programs "
                    "(XLA:CPU rendezvous deadlock class); gather from "
                    "the snapshot's host replica instead")


# ---------------------------------------------------------------------------
# PRODUCER-NO-RNG / PRODUCER-NO-DEVICE

_PIPELINE_SUFFIX = "io/pipeline.py"


class ProducerNoRng(Rule):
    """The pipeline producer owns no RNG: all key splitting happens on
    the consumer in consumption order (PR-5 bit-identity contract), so
    nothing under io/pipeline.py may touch an RNG."""

    id = "PRODUCER-NO-RNG"
    description = "RNG use inside the input-pipeline producer module"

    def check(self, f, ctx):
        if not f.rel.endswith(_PIPELINE_SUFFIX):
            return
        parents = parent_map(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.Name, ast.Attribute)):
                if isinstance(parents.get(node), ast.Attribute):
                    continue
                chain = attr_chain(node) or ""
                if chain.startswith(("jax.random", "np.random",
                                     "numpy.random", "random.")):
                    yield self.finding(
                        f, node,
                        f"`{chain}` in the pipeline module — the "
                        "producer owns no RNG; split keys on the "
                        "consumer in consumption order")
            elif isinstance(node, (ast.Import, ast.ImportFrom)):
                mods = ([a.name for a in node.names]
                        if isinstance(node, ast.Import)
                        else [node.module or ""])
                for m in mods:
                    if m == "random" or m.startswith("jax.random"):
                        yield self.finding(
                            f, node,
                            f"import of `{m}` in the pipeline module — "
                            "the producer owns no RNG")


class ProducerNoDevice(Rule):
    """The producer thread must not consult thread-local device context
    (``jax.default_device`` is consumer-thread state) or place arrays
    implicitly: ``device_put`` needs the explicit sharding captured by
    the consumer at build time."""

    id = "PRODUCER-NO-DEVICE"
    description = ("implicit device placement / default_device consult "
                   "in the pipeline module")

    def check(self, f, ctx):
        if not f.rel.endswith(_PIPELINE_SUFFIX):
            return
        parents = parent_map(f.tree)
        for node in ast.walk(f.tree):
            if isinstance(node, (ast.Name, ast.Attribute)):
                if isinstance(parents.get(node), ast.Attribute):
                    continue
                chain = attr_chain(node) or ""
                if chain.startswith(("jax.default_device", "jax.devices",
                                     "jnp.", "jax.numpy")):
                    yield self.finding(
                        f, node,
                        f"`{chain}` in the pipeline module — "
                        "jax.default_device is thread-local consumer "
                        "state and implicit placement races it; use "
                        "the sharding captured at pipeline build time")
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func) or ""
                if chain.endswith("device_put") and \
                        len(node.args) + len(node.keywords) < 2:
                    yield self.finding(
                        f, node,
                        "`device_put` without an explicit "
                        "sharding/device in the pipeline module — "
                        "implicit placement reads the consumer's "
                        "thread-local default_device from the producer "
                        "thread")


# ---------------------------------------------------------------------------
# LEDGER-MONOTONIC

_LEDGER_KEYS = None  # resolved lazily from obs.catalog


def _ledger_keys() -> Set[str]:
    global _LEDGER_KEYS
    if _LEDGER_KEYS is None:
        from swiftmpi_tpu.obs.catalog import TRANSFER_KEYS
        _LEDGER_KEYS = set(TRANSFER_KEYS) | {
            "window_fmt_dense", "window_fmt_sparse", "window_fmt_q",
            "window_fmt_bitmap", "window_fmt_sketch"}
    return _LEDGER_KEYS


class LedgerMonotonic(Rule):
    """Traffic ledgers are monotonic totals: backends never reset or
    assign counters (PR-6 contract — interval numbers are
    snapshot-and-subtract), and call sites outside the transfer layer
    use ``traffic_delta`` instead of hand-rolled subtraction (PR-9
    migrated every one; hand-rolling races the eager-count drain)."""

    id = "LEDGER-MONOTONIC"
    description = ("ledger counter reset, or hand-rolled traffic delta "
                   "outside transfer/")

    def check(self, f, ctx):
        in_transfer = "/transfer/" in "/" + f.rel
        if in_transfer:
            yield from self._check_backend(f)
        yield from self._check_hand_rolled(f)

    def _check_backend(self, f):
        keys = _ledger_keys()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign):
                for t in node.targets:
                    if isinstance(t, ast.Subscript):
                        k = _const_str(t.slice)
                        if k in keys:
                            yield self.finding(
                                f, node,
                                f"assignment to ledger counter "
                                f"[{k!r}] — ledgers are monotonic "
                                "totals with no reset; use += and let "
                                "readers snapshot-and-subtract")
            elif isinstance(node, ast.AugAssign):
                if isinstance(node.target, ast.Subscript) and \
                        isinstance(node.op, ast.Sub):
                    k = _const_str(node.target.slice)
                    if k in keys:
                        yield self.finding(
                            f, node,
                            f"`-=` on ledger counter [{k!r}] — "
                            "monotonic totals never decrease")
            elif isinstance(node, ast.FunctionDef):
                if re.match(r"(reset|clear)_.*(traffic|ledger|wire)",
                            node.name):
                    yield self.finding(
                        f, node,
                        f"method `{node.name}` — there is no reset in "
                        "the ledger contract (monotonic totals; "
                        "readers use traffic_delta)")

    def _check_hand_rolled(self, f):
        scopes = [f.tree] + [n for n in ast.walk(f.tree)
                             if isinstance(n, (ast.FunctionDef,
                                               ast.AsyncFunctionDef))]
        for scope in scopes:
            tracked: Set[str] = set()
            for node in scope.body if isinstance(scope, ast.Module) \
                    else ast.walk(scope):
                if isinstance(node, ast.Assign) and \
                        isinstance(node.value, ast.Call) and \
                        isinstance(node.value.func, ast.Attribute) and \
                        node.value.func.attr in ("traffic",
                                                 "wire_traffic"):
                    for t in node.targets:
                        tracked |= _target_names(t)
            if len(tracked) < 2:
                continue
            for node in ast.walk(scope):
                if isinstance(node, ast.BinOp) and \
                        isinstance(node.op, ast.Sub):
                    lr = (self._root(node.left), self._root(node.right))
                    if lr[0] in tracked and lr[1] in tracked and \
                            lr[0] != lr[1]:
                        yield self.finding(
                            f, node,
                            f"hand-rolled traffic delta "
                            f"`{lr[0]} - {lr[1]}` — use "
                            "Transfer.traffic_delta(since), which "
                            "reconstructs the interval without racing "
                            "the eager-count drain")

    @staticmethod
    def _root(node) -> Optional[str]:
        while isinstance(node, (ast.Subscript, ast.Call, ast.Attribute)):
            node = node.func if isinstance(node, ast.Call) else node.value
        if isinstance(node, ast.Name):
            return node.id
        return None


# ---------------------------------------------------------------------------
# TELEMETRY-CATALOG

_INSTRUMENT_ATTRS = ("counter", "gauge", "histogram")
_CATALOG_EXEMPT = ("obs/registry.py", "obs/catalog.py", "obs/recorder.py",
                   "analysis/")


class TelemetryCatalog(Rule):
    """Every telemetry series registered with a literal name must be
    declared in :mod:`swiftmpi_tpu.obs.catalog` — catching label drift
    across the four transfer-backend mirrors (incl. the tpu backend's
    eager-drain paths) and dashboard-silent typos.  Dynamic f-string
    names must fall inside a declared prefix family; bare-variable
    names are invisible to the checker and pass."""

    id = "TELEMETRY-CATALOG"
    description = "telemetry series name not in the declared catalog"

    def check(self, f, ctx):
        if any(x in f.rel for x in _CATALOG_EXEMPT):
            return
        from swiftmpi_tpu.obs import catalog
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or not node.args:
                continue
            fn = node.func
            wrapper = None
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _INSTRUMENT_ATTRS:
                wrapper = ""
            elif isinstance(fn, (ast.Attribute, ast.Name)):
                name = fn.attr if isinstance(fn, ast.Attribute) else fn.id
                if name == "_obs_inc":
                    wrapper = "transfer/"
                elif name == "_obs_count":
                    wrapper = ""
            if wrapper is None:
                continue
            for cand in self._name_candidates(node.args[0]):
                kind, value = cand
                if kind == "exact":
                    if not catalog.declared(wrapper + value):
                        yield self.finding(
                            f, node,
                            f"series `{wrapper + value}` is not "
                            "declared in swiftmpi_tpu/obs/catalog.py — "
                            "declare it (or fix the typo) so the "
                            "four backend mirrors stay in sync")
                elif kind == "prefix":
                    if not catalog.declared_prefix(wrapper + value):
                        yield self.finding(
                            f, node,
                            f"dynamic series name with stem "
                            f"`{wrapper + value}` matches no declared "
                            "prefix family in obs/catalog.py")

    @staticmethod
    def _name_candidates(arg):
        s = _const_str(arg)
        if s is not None:
            yield ("exact", s)
            return
        if isinstance(arg, ast.IfExp):
            for side in (arg.body, arg.orelse):
                s = _const_str(side)
                if s is not None:
                    yield ("exact", s)
            return
        if isinstance(arg, ast.JoinedStr):
            stem = ""
            for v in arg.values:
                s = _const_str(v)
                if s is None:
                    break
                stem += s
            yield ("prefix", stem)
            return
        if isinstance(arg, ast.BinOp) and isinstance(arg.op, ast.Add):
            s = _const_str(arg.left)
            if s is not None:
                yield ("prefix", s)
        # bare variables: statically invisible, skip


# ---------------------------------------------------------------------------
# LOCK-GUARD

_GUARD_RE = re.compile(r"#\s*guarded-by:\s*([A-Za-z_]\w*)")
_MUTATORS = {"append", "appendleft", "add", "clear", "pop", "popitem",
             "remove", "update", "extend", "insert", "discard",
             "setdefault"}


class LockGuard(Rule):
    """Fields annotated ``# guarded-by: <lock>`` on their ``__init__``
    assignment may only be mutated inside ``with self.<lock>:`` (any
    method but ``__init__``, which runs happens-before publication).
    Encodes the SnapshotPublisher swap contract: readers race
    ``_latest``/``_history``, so every write goes through the
    Condition."""

    id = "LOCK-GUARD"
    description = "guarded field mutated outside its lock"

    def check(self, f, ctx):
        for node in ast.walk(f.tree):
            if isinstance(node, ast.ClassDef):
                yield from self._check_class(f, node)

    def _check_class(self, f, cls):
        guards: Dict[str, str] = {}
        init = None
        for meth in cls.body:
            if isinstance(meth, ast.FunctionDef) and \
                    meth.name == "__init__":
                init = meth
        if init is None:
            return
        for node in ast.walk(init):
            if isinstance(node, ast.Assign):
                m = _GUARD_RE.search(f.lines[node.lineno - 1]
                                     if node.lineno <= len(f.lines) else "")
                if not m:
                    continue
                for t in node.targets:
                    for c in _target_chains(t):
                        if c.startswith("self."):
                            guards[c[len("self."):]] = m.group(1)
        if not guards:
            return
        parents = parent_map(cls)
        for meth in cls.body:
            if not isinstance(meth, ast.FunctionDef) or \
                    meth.name == "__init__":
                continue
            for node in ast.walk(meth):
                field = self._mutated_field(node, guards)
                if field is None:
                    continue
                lock = guards[field]
                if not self._under_lock(node, parents, lock):
                    yield self.finding(
                        f, node,
                        f"`self.{field}` is guarded-by `{lock}` but "
                        f"mutated outside `with self.{lock}:` — "
                        "readers race this field")

    @staticmethod
    def _mutated_field(node, guards) -> Optional[str]:
        if isinstance(node, (ast.Assign, ast.AugAssign)):
            targets = (node.targets if isinstance(node, ast.Assign)
                       else [node.target])
            for t in targets:
                for c in _target_chains(t):
                    if c.startswith("self.") and \
                            c[len("self."):] in guards:
                        return c[len("self."):]
        elif isinstance(node, ast.Call) and \
                isinstance(node.func, ast.Attribute) and \
                node.func.attr in _MUTATORS:
            c = attr_chain(node.func.value)
            if c and c.startswith("self.") and \
                    c[len("self."):] in guards:
                return c[len("self."):]
        return None

    @staticmethod
    def _under_lock(node, parents, lock: str) -> bool:
        cur = parents.get(node)
        while cur is not None:
            if isinstance(cur, ast.With):
                for item in cur.items:
                    c = attr_chain(item.context_expr)
                    if c == f"self.{lock}":
                        return True
            cur = parents.get(cur)
        return False


# ---------------------------------------------------------------------------
# EPOCH-GUARD

_EPOCH_GUARD_RE = re.compile(r"#\s*epoch-guard:")
_EPOCH_MUTATORS = ("write_membership", "adopt_owner_map")
_EPOCH_FIELDS = {"owner_of_shard", "shard_owner", "member_table",
                 "membership_epoch", "_membership_epoch",
                 "live_ranks", "_live_ranks"}


class EpochGuard(Rule):
    """Elastic-membership state (cluster/membership.py) moves only
    forward: epochs never regress, and every adoption of a new owner
    map must validate the advance (raise ``StaleEpochError`` on
    regression) before publishing.  Any function that rebinds
    membership state — calls :func:`write_membership` /
    ``adopt_owner_map``, or assigns an epoch/owner/live-set field —
    must carry a ``# epoch-guard: <how the advance is validated>``
    annotation on the validation line, so the invariant is stated at
    every mutation site and un-guarded writes stand out in review."""

    id = "EPOCH-GUARD"
    description = "membership state mutated without an epoch-guard note"

    def check(self, f, ctx):
        for fn in ast.walk(f.tree):
            if not isinstance(fn, (ast.FunctionDef,
                                   ast.AsyncFunctionDef)):
                continue
            if fn.name == "write_membership":
                continue        # the guarded choke point itself
            if fn.name == "__init__":
                continue        # pre-publication init (no epoch yet),
                # same happens-before reasoning as LOCK-GUARD
            trigger = self._trigger(fn)
            if trigger is None or self._annotated(f, fn):
                continue
            yield self.finding(
                f, trigger,
                f"function `{fn.name}` mutates elastic-membership "
                "state without a `# epoch-guard:` annotation — state "
                "how the epoch advance is validated (StaleEpochError "
                "on regression) at the mutation site")

    @staticmethod
    def _trigger(fn) -> Optional[ast.AST]:
        """First membership mutation inside ``fn``, or None."""
        for node in ast.walk(fn):
            if isinstance(node, ast.Call):
                chain = attr_chain(node.func)
                if chain and chain.split(".")[-1] in _EPOCH_MUTATORS:
                    return node
            elif isinstance(node, (ast.Assign, ast.AugAssign,
                                   ast.AnnAssign)):
                targets = (node.targets if isinstance(node, ast.Assign)
                           else [node.target])
                for t in targets:
                    for c in _target_chains(t):
                        if c.split(".")[-1] in _EPOCH_FIELDS:
                            return node
        return None

    @staticmethod
    def _annotated(f, fn) -> bool:
        end = getattr(fn, "end_lineno", None) or len(f.lines)
        for line in f.lines[fn.lineno - 1:end]:
            if _EPOCH_GUARD_RE.search(line):
                return True
        return False


# ---------------------------------------------------------------------------
# KNOB-DOC

_CONFIG_RECEIVERS = ("config", "conf", "cfg", "_config")
_CONFIG_METHODS = ("get", "get_or", "has")


class KnobDoc(Rule):
    """Every ``[section] key`` config read must be documented in
    docs/OPERATIONS.md (the knob reference carries the default and the
    operational meaning).  A knob that exists only in code is a knob
    operators discover during an incident."""

    id = "KNOB-DOC"
    description = "config knob read without an OPERATIONS.md entry"

    def check(self, f, ctx):
        ops = ctx.operations_md
        aliases: Set[str] = set()
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Assign) and \
                    isinstance(node.value, ast.Attribute) and \
                    node.value.attr in _CONFIG_METHODS and \
                    self._config_receiver(node.value.value):
                for t in node.targets:
                    aliases |= _target_names(t)
        for node in ast.walk(f.tree):
            if not isinstance(node, ast.Call) or len(node.args) < 2:
                continue
            fn = node.func
            is_knob = False
            if isinstance(fn, ast.Attribute) and \
                    fn.attr in _CONFIG_METHODS and \
                    self._config_receiver(fn.value):
                is_knob = True
            elif isinstance(fn, ast.Name) and fn.id in aliases:
                is_knob = True
            if not is_knob:
                continue
            section = _const_str(node.args[0])
            key = _const_str(node.args[1])
            if section is None or key is None:
                continue
            if f"[{section}] {key}" not in ops:
                yield self.finding(
                    f, node,
                    f"config knob `[{section}] {key}` has no "
                    "`[section] key` entry in docs/OPERATIONS.md — "
                    "add it to the knob reference (with its default)")

    @staticmethod
    def _config_receiver(node) -> bool:
        c = attr_chain(node)
        if not c:
            return False
        last = c.split(".")[-1]
        return last in _CONFIG_RECEIVERS


# ---------------------------------------------------------------------------
# PLAN-DISPATCH

#: the wire-format ladder (mirrors transfer.plan.WIRE_FORMATS plus the
#: pull family's transfer.plan.PULL_FORMATS; literal so the linter
#: never imports jax).  The pull rung "bf16" is deliberately ABSENT:
#: bare "bf16" is also a dtype string and a quant-knob value, and
#: comparing a knob against it (word2vec config parsing, quant codecs)
#: is not format dispatch — "full_f32"/"sparse_q" are the distinctive
#: members that mark a pull-format branch, same reasoning as the bare
#: "psum" exclusion below.
_WIRE_FORMAT_NAMES = frozenset(
    ("dense", "sparse", "bitmap", "sparse_q", "sparse_sketch",
     "full_f32"))

#: the collective ladder (mirrors transfer.plan.COLLECTIVES minus the
#: bare "psum", which is also a jax.lax primitive name and would false-
#: positive on legitimate axis-name plumbing; comparing against either
#: distinctive member of the ladder is what marks a dispatch)
_COLLECTIVE_NAMES = frozenset(("sparse_allreduce", "psum_scatter"))

#: attribute/function names whose CALL is the wire-format question
#: (push-window, hot-collective and pull families alike)
_PLAN_QUESTIONS = frozenset(
    ("decide_wire_format", "price_window_formats", "window_wire_format",
     "compile_window_plan", "price_hot_collectives", "compile_hot_plan",
     "compile_pull_plan", "price_pull_formats", "pull_route"))

#: transfer-layer modules allowed to interpret plans: the interpreter
#: itself, the plan compiler, and the codec modules its tables point at
#: (a codec IMPLEMENTS formats — encode/decode/byte-model — which is
#: the opposite of a backend dispatching on them; delta.py is the
#: PR-17 row-delta codec, sketch.py the sparse_sketch codec)
#: (pull_cache.py is the delta-pull shadow — a cache keyed on row
#: versions, not a backend; it implements the hit/miss byte model the
#: pull plan prices, so it sits with the codecs)
_PLAN_INTERPRETER_FILES = frozenset(
    ("api.py", "plan.py", "sketch.py", "delta.py",
     "sparse_allreduce.py", "pull_cache.py"))


class PlanDispatch(Rule):
    """The TrafficPlan interpreter (transfer/api.py ``push_window``) is
    the ONE dispatch point of the transfer stack: backend modules are
    primitive providers and must neither ask the wire-format question
    (``decide_wire_format``/``price_window_formats``/
    ``compile_window_plan``) nor branch on a wire-format name.  A new
    format is a plan-table edit plus a codec module — the moment a
    backend compares against ``"bitmap"`` the table stops being the
    single source of truth and every future rung pays four backends
    again (the pre-PR-18 tax this rule pins out).  Collective selection
    (``"sparse_allreduce"`` vs the dense collectives) is the same
    dispatch in a different column of the plan table, so comparing
    against a collective name trips identically."""

    id = "PLAN-DISPATCH"
    description = ("wire-format branch or pricing call in a transfer "
                   "backend (belongs in the plan interpreter)")

    def check(self, f, ctx):
        rel = "/" + f.rel.replace("\\", "/")
        if "/transfer/" not in rel:
            return
        if rel.rsplit("/", 1)[-1] in _PLAN_INTERPRETER_FILES:
            return
        for node in ast.walk(f.tree):
            if isinstance(node, ast.Compare):
                name = self._format_operand(node)
                if name is not None:
                    kind = ("collective" if name in _COLLECTIVE_NAMES
                            else "wire format")
                    yield self.finding(
                        f, node,
                        f"comparison against {kind} {name!r} in a "
                        "transfer backend — format dispatch belongs in "
                        "the TrafficPlan interpreter "
                        "(transfer/api.py); add formats via "
                        "transfer/plan.py tables")
            elif isinstance(node, ast.Call):
                chain = attr_chain(node.func) or ""
                leaf = chain.split(".")[-1]
                if leaf in _PLAN_QUESTIONS:
                    yield self.finding(
                        f, node,
                        f"`{leaf}` called from a transfer backend — "
                        "only the TrafficPlan interpreter "
                        "(transfer/api.py) asks the wire-format "
                        "question; backends receive a compiled plan")

    @staticmethod
    def _format_operand(node: ast.Compare):
        """The wire-format or collective name a comparison tests
        against, if any: catches ``x == "bitmap"``, ``x ==
        "sparse_allreduce"`` and ``x in ("dense", "sparse")``."""
        names = _WIRE_FORMAT_NAMES | _COLLECTIVE_NAMES
        for side in (node.left, *node.comparators):
            if isinstance(side, ast.Constant) and side.value in names:
                return side.value
            if isinstance(side, (ast.Tuple, ast.List, ast.Set)):
                for e in side.elts:
                    if isinstance(e, ast.Constant) and \
                            e.value in names:
                        return e.value
        return None


RULES = (DonateEscape(), ReaderPureHost(), ProducerNoRng(),
         ProducerNoDevice(), LedgerMonotonic(), TelemetryCatalog(),
         LockGuard(), EpochGuard(), KnobDoc(), PlanDispatch())
