"""smtpu-lint: repo-native static invariant checker (ISSUE 11).

The repo's host-side concurrency invariants — donated buffers never
escape their dispatch, serve readers never launch device programs, the
pipeline producer owns no RNG, traffic ledgers never reset, telemetry
series match the declared catalog, lock-guarded fields mutate under
their lock, config knobs are documented — were each discovered as a
real bug (see docs/ARCHITECTURE.md "Invariant catalog").  This package
encodes them as AST lint rules so refactors that churn the carrying
files (multi-host scale-out, the TrafficPlan compiler) get machine
checking instead of archaeology.

Entry points:

* ``python -m swiftmpi_tpu.analysis.lint`` — the gate run by
  scripts/run_tier1.sh (text or ``--format json``, rc 1 on new
  findings).
* ``scripts/smtpu_lint.py`` — the same CLI as a script.
* :func:`run_lint` — programmatic API (tests, tooling).

Suppression: ``# smtpu-lint: disable=RULE[,RULE...]`` on the offending
line (on a ``def``/``class``/``with`` header it covers the whole
block); ``# smtpu-lint: disable-file=RULE`` anywhere covers the file.
Grandfathered findings live in the checked-in baseline
(``lint_baseline.json`` at the repo root) — benign legacy patterns
only, never actual bugs.
"""

from swiftmpi_tpu.analysis.core import (Finding, LintContext, LintFile,
                                        load_baseline, run_lint,
                                        write_baseline)
from swiftmpi_tpu.analysis.rules import RULES

__all__ = ["Finding", "LintContext", "LintFile", "RULES", "run_lint",
           "load_baseline", "write_baseline"]
