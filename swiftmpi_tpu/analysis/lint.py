"""smtpu-lint CLI: ``python -m swiftmpi_tpu.analysis.lint [paths...]``.

Exit codes: 0 clean (baselined-only counts as clean), 1 new findings,
2 usage error.  ``--write-baseline`` grandfathers the current NEW
findings into the baseline file (each entry still needs a human
``justification`` edit before review).
"""

from __future__ import annotations

import argparse
import json
import os
import sys
from typing import List, Optional

from swiftmpi_tpu.analysis import core


def build_parser() -> argparse.ArgumentParser:
    p = argparse.ArgumentParser(
        prog="smtpu_lint",
        description="repo-native static invariant checker (see "
                    "docs/ARCHITECTURE.md 'Invariant catalog')")
    p.add_argument("paths", nargs="*",
                   help="files to lint (default: repo lint scope — the "
                        "package, scripts/, bench.py)")
    p.add_argument("--root", default=None,
                   help="repo root (default: auto-detected from the "
                        "package location)")
    p.add_argument("--format", choices=("text", "json"), default="text")
    p.add_argument("--baseline", default=None,
                   help="baseline JSON path (default: "
                        f"<root>/{core.BASELINE_NAME}; 'none' disables)")
    p.add_argument("--write-baseline", action="store_true",
                   help="write current NEW findings into the baseline "
                        "file and exit 0")
    p.add_argument("--out", default=None,
                   help="also write the JSON report to this path "
                        "(for runs/ archiving)")
    return p


def report_json(new, old) -> dict:
    return {
        "schema": core.JSON_SCHEMA,
        "new": [f.to_dict() for f in new],
        "baselined": [f.to_dict() for f in old],
        "counts": {"new": len(new), "baselined": len(old)},
    }


def main(argv: Optional[List[str]] = None) -> int:
    args = build_parser().parse_args(argv)
    root = os.path.abspath(args.root) if args.root else core.repo_root()
    paths = [os.path.abspath(p) for p in args.paths] or None

    baseline = {}
    baseline_path = args.baseline
    if baseline_path != "none":
        if baseline_path is None:
            baseline_path = os.path.join(root, core.BASELINE_NAME)
        baseline = core.load_baseline(baseline_path)

    new, old = core.run_lint(paths=paths, root=root, baseline=baseline)

    if args.write_baseline:
        if baseline_path in (None, "none"):
            print("--write-baseline needs a baseline path",
                  file=sys.stderr)
            return 2
        n = core.write_baseline(baseline_path, old + new)
        print(f"wrote {n} finding(s) to {baseline_path} "
              "(edit each 'justification' before committing)")
        return 0

    payload = report_json(new, old)
    if args.out:
        os.makedirs(os.path.dirname(args.out) or ".", exist_ok=True)
        with open(args.out, "w", encoding="utf-8") as f:
            json.dump(payload, f, indent=2)
            f.write("\n")

    if args.format == "json":
        json.dump(payload, sys.stdout, indent=2)
        print()
    else:
        for f in new:
            print(f.render())
        if old:
            print(f"# {len(old)} baselined finding(s) suppressed "
                  f"(see {baseline_path})")
        if new:
            print(f"# {len(new)} new finding(s)")
        else:
            print("# lint clean")
    return 1 if new else 0


if __name__ == "__main__":
    sys.exit(main())
