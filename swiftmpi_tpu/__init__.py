"""swiftmpi_tpu — a TPU-native distributed parameter-server framework.

A from-scratch re-design of the capabilities of logicxin/SwiftMPI (a C++
MPI+ZeroMQ asynchronous parameter server; see SURVEY.md) for TPU hardware:

* the *cluster* is a ``jax.sharding.Mesh`` instead of MPI ranks + sockets
  (``swiftmpi_tpu.cluster``);
* the *parameter server* is a row-sharded dense table in HBM instead of a
  ``dense_hash_map`` server process (``swiftmpi_tpu.parameter``);
* the *transfer layer*'s pull/push RPCs are XLA collectives over ICI —
  ``all_to_all`` + ``segment_sum`` for sparse rows, ``psum`` for dense
  gradients — selected via ``transfer=tpu`` (``swiftmpi_tpu.transfer``);
* the *apps* (logistic regression, word2vec, sent2vec) keep the reference's
  gather → pull → compute → push loop structure, but each step is a single
  jitted SPMD program (``swiftmpi_tpu.models``, ``swiftmpi_tpu.apps``).

Layer map mirrors SURVEY.md §1: utils → cluster (mesh) → transfer →
parameter → models/apps, plus ops (device kernels), parallel (collectives /
context parallelism), data (input pipeline), io (checkpointing).
"""

__version__ = "0.1.0"

from swiftmpi_tpu import utils  # noqa: F401
