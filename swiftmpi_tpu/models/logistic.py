"""Sparse logistic regression on the TPU parameter server.

Re-design of the reference LR app (`/root/reference/src/apps/logistic/
lr.cpp`), same capability and math, TPU-shaped execution:

* reference: per minibatch, multithreaded per-line ``learn_instance``
  (sigmoid dot + per-key grad accumulation, lr.cpp:355-375) around a
  pull/push RPC pair (lr.cpp:213-236).
* here: the whole minibatch is one jitted SPMD step — padded ``(B, F)``
  feature matrices, masked sigmoid-dot, per-key mean-normalized gradient
  (the reference's ``grad/count`` at serialization, lr.cpp:32-38) computed
  in-step, then a transfer push applying server-side AdaGrad
  (lr.cpp:68-75).

Math parity: predict = σ(Σ w_f·x_f); err = target − predict (gradient
*ascent* on log-likelihood); per-iteration training error = mean err²
(lr.cpp:358-375); AdaGrad with fudge 1e-6; weights initialized U(0,1) by
``gen_float`` (lr.cpp:48-50) — here the same distribution via jax.random.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.cluster.cluster import Cluster
from swiftmpi_tpu.data.libsvm import (CSRData, LibSVMBatch, iter_minibatches,
                                      load_data, load_file)  # noqa: F401
from swiftmpi_tpu.io.checkpoint import (dump_table_text, load_table_text)
from swiftmpi_tpu.parameter import lr_access
from swiftmpi_tpu.parameter.key_index import CapacityError
from swiftmpi_tpu.utils.config import ConfigParser, global_config
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.pipeline import DispatchWindow

log = get_logger(__name__)


def _max_feats(data) -> int:
    if isinstance(data, CSRData):
        return data.max_feats
    return max(len(f) for _, f in data)


def lr_formatter(row: Dict[str, np.ndarray]) -> str:
    """Reference LRParam operator<<: just the weight (lr.cpp:24-27)."""
    return repr(float(row["val"][0]))


def lr_parser(text: str) -> Dict[str, np.ndarray]:
    return {"val": np.array([float(text.split()[0])], np.float32)}


class LogisticRegression:
    def __init__(self, config: Optional[ConfigParser] = None,
                 cluster: Optional[Cluster] = None,
                 capacity_per_shard: int = 1 << 16, seed: int = 0):
        self.config = config if config is not None else global_config()
        self.minibatch = (self.config.get("worker", "minibatch").to_int32()
                          if self.config.has("worker", "minibatch") else 200)
        lr = (self.config.get("server", "initial_learning_rate").to_float()
              if self.config.has("server", "initial_learning_rate") else 0.05)
        self.cluster = cluster or Cluster(self.config).initialize()
        self.access = lr_access(lr)
        self.table = self.cluster.create_table(
            "lr", self.access, capacity_per_shard, seed=seed)
        self.transfer = self.cluster.transfer
        # [worker] inner_steps: fuse N minibatches per dispatch via
        # lax.scan, as in word2vec — through the axon tunnel one dispatch
        # costs ~5ms, which dwarfs an a9a-scale step
        self.inner_steps = (
            self.config.get("worker", "inner_steps").to_int32()
            if self.config.has("worker", "inner_steps") else 1)
        # [worker] dense_features: auto|0|1 — capacity-dense rendering
        # for small feature spaces (see _dense_core)
        self.dense_features = (
            self.config.get("worker", "dense_features").to_string()
            if self.config.has("worker", "dense_features") else "auto")
        # [worker] scan_unroll: lax.scan unroll factor for the fused
        # multi-batch step — at a9a scale each iteration is microseconds
        # of MXU work, so per-iteration loop overhead can dominate;
        # unrolling lets XLA pipeline iterations (A/B'd on chip)
        self.scan_unroll = (
            self.config.get("worker", "scan_unroll").to_int32()
            if self.config.has("worker", "scan_unroll") else 1)
        self._step = None
        self._multi = None
        self._dense_step = None
        self._dense_multi = None

    # -- fused minibatch step ---------------------------------------------
    def _step_core(self, state, slots, vals, mask, targets):
        access = self.access
        transfer = self.transfer
        B, F = slots.shape
        flat = jnp.where(mask, slots, -1).reshape(-1)
        rows = transfer.pull(state, flat, access)["val"]
        w = rows.reshape(B, F)
        logits = jnp.sum(w * vals * mask, axis=1)
        predict = jax.nn.sigmoid(logits)
        row_valid = mask.any(axis=1)
        err = jnp.where(row_valid, targets - predict, 0.0)
        # mean=True: the reference's grad.val/grad.count normalization at
        # push serialization (lr.cpp:32-38), folded into the transfer's
        # dedup pass
        contrib = (err[:, None] * vals * mask).reshape(-1)
        new_state = transfer.push(
            state, flat, {"val": contrib[:, None]}, access, mean=True)
        loss = jnp.sum(err * err) / jnp.maximum(row_valid.sum(), 1)
        return new_state, loss, row_valid.sum()

    def _build_step(self):
        from swiftmpi_tpu import obs
        return obs.costs.track("lr_step", jax.jit(self._step_core))

    def _build_scan(self, core):
        """Scan a fused step over a stack of minibatches in ONE dispatch.

        The reference amortizes per-batch overhead with 13 worker threads
        per rank (lr.cpp:225); on TPU the equivalent lever is fusing the
        per-batch host->device round-trip away — through a tunnel each
        dispatch costs ~5ms, which dwarfs the a9a-scale step compute.
        Inputs carry a leading ``n_batches`` axis; returns per-batch
        losses/counts so the training-error log stays per-minibatch."""

        unroll = max(1, self.scan_unroll)

        @jax.jit
        def multi(state, *cols):
            def body(state, xs):
                state, loss, n = core(state, *xs)
                return state, (loss, n)
            state, (losses, ns) = jax.lax.scan(body, state, cols,
                                               unroll=unroll)
            return state, losses, ns

        return multi

    def _build_multi_step(self):
        from swiftmpi_tpu import obs
        return obs.costs.track("lr_multi",
                               self._build_scan(self._step_core))

    # -- dense-features rendering -----------------------------------------
    # At a9a scale (123 features, capacity ~160) the padded-sparse step
    # is transaction-bound: B*F scalar weight gathers + a scatter push,
    # each ~10ns on chip regardless of width, cap the step far below
    # both the MXU and the CPU baseline (round-2 live window: 0.06x
    # CPU).  When the whole weight table is small, the TPU-first shape
    # is capacity-DENSE: densify each minibatch host-side once and the
    # step becomes two skinny MXU matmuls (X @ w, X^T @ err) plus a
    # dense AdaGrad apply — identical math (same per-key contribution
    # and count multiset, so the mean normalization and update rule
    # match the sparse push bit-for-bit modulo float summation order),
    # zero per-row transactions.  The sparse rendering remains the
    # general path for url/kdd-scale feature spaces.

    DENSE_CAP_LIMIT = 2048

    def dense_enabled(self) -> bool:
        mode = self.dense_features.lower()
        if mode in ("0", "off", "false"):
            return False
        if mode in ("1", "on", "true"):
            return True
        # auto: an MXU play — on CPU the densified batches move ~5x the
        # bytes of the padded-sparse layout and measure ~7x slower than
        # the sparse step, so auto only flips when THIS model's devices
        # are TPUs (not jax.devices()[0]: a process can expose both, and
        # a CPU-pinned run must not inherit the TPU verdict)
        dev = self.cluster.mesh.devices.flat[0]
        return (dev.platform == "tpu"
                and self.table.capacity <= self.DENSE_CAP_LIMIT)

    def _densify(self, slots, vals, mask, targets):
        """(B, F) padded-sparse batch -> capacity-dense ``(X, cnt, t, v)``:
        ``X[b, slot] += val`` and ``cnt[slot] += 1`` per valid
        (row, feature) occurrence — the same contribution and count
        multiset the sparse push sees (duplicate features in one row
        accumulate in both, as in the reference's per-key grad/count)."""
        cap = self.table.capacity
        B, F = slots.shape
        X = np.zeros((B, cap), np.float32)
        cnt = np.zeros((cap,), np.float32)
        m = np.asarray(mask, bool)
        rows = np.broadcast_to(np.arange(B)[:, None], (B, F))
        np.add.at(X, (rows[m], np.asarray(slots)[m]),
                  np.asarray(vals, np.float32)[m])
        # only the per-slot total ever feeds the mean normalization, so
        # ship the (cap,) reduction, not a (B, cap) presence matrix
        np.add.at(cnt, np.asarray(slots)[m], 1.0)
        return (X, cnt, np.asarray(targets, np.float32), m.any(axis=1))

    def _dense_core(self, state, X, cnt, targets, valid):
        access = self.access
        w = state["val"][:, 0].astype(jnp.float32)        # (cap,)
        predict = jax.nn.sigmoid(X @ w)
        err = jnp.where(valid, targets - predict, 0.0)
        # err @ X, not X.T @ err: the same contraction, but the spelled
        # transpose materializes a (cap, B) shuffle that measured ~3x
        # the whole remaining step on both backends
        grad = err @ X                                    # (cap,) MXU
        mean_grad = grad / jnp.maximum(cnt, 1.0)
        new_fields = access.apply_push(state,
                                       {"val": mean_grad[:, None]})
        state = {**state, **new_fields}
        n = valid.sum()
        loss = jnp.sum(err * err) / jnp.maximum(n, 1)
        return state, loss, n

    def _build_dense_step(self):
        from swiftmpi_tpu import obs
        return obs.costs.track("lr_dense_step",
                               jax.jit(self._dense_core))

    def _build_dense_multi(self):
        from swiftmpi_tpu import obs
        return obs.costs.track("lr_dense_multi",
                               self._build_scan(self._dense_core))

    # -- training (lr.cpp:157-240) ----------------------------------------
    def train(self, data, niters: int = 1,
              max_feats: Optional[int] = None) -> List[float]:
        """``data``: path to a libSVM file, a pre-parsed instance list, or
        ``CSRData`` (native parser output).  Returns per-iteration mean
        training error (reference logs ``error: total/nrecords`` per iter,
        lr.cpp:231)."""
        if isinstance(data, str):
            data = load_data(data)
        if self._step is None:
            self._step = self._build_step()
        inner = max(1, self.inner_steps)
        if inner > 1 and self._multi is None:
            self._multi = self._build_multi_step()
        F = max_feats or _max_feats(data)
        losses = []
        state = self.table.state
        # deferred per-batch loss scalars: fetched once per epoch (a
        # float() per batch is a blocking device round trip); the
        # DispatchWindow keeps the async pipeline bounded on the
        # emulated multi-device CPU mesh (see utils/pipeline.py for the
        # rendezvous-starvation failure mode it prevents)
        window = DispatchWindow()
        pending = []
        group = []

        def queue(loss, n):
            pending.append((loss, n))
            window.push(loss)

        def flush_group():
            nonlocal state
            if not group:
                return
            entries = group
            if self.dense_enabled():
                entries = [self._densify(*e) for e in entries]
                if self._dense_step is None:
                    self._dense_step = self._build_dense_step()
                    self._dense_multi = self._build_dense_multi()
                one, many = self._dense_step, self._dense_multi
            else:
                one, many = self._step, self._multi
            if len(entries) == inner and inner > 1:
                stacked = tuple(
                    jnp.asarray(np.stack(col)) for col in zip(*entries))
                state, ls, ns = many(state, *stacked)
                queue(ls, ns)
            else:
                # tail (or pre-grow flush) smaller than a full group:
                # per-batch dispatch avoids a recompile per distinct size
                for cols in entries:
                    state, loss, n = one(
                        state, *(jnp.asarray(c) for c in cols))
                    queue(loss, n)
            group.clear()

        for it in range(niters):
            total, count = 0.0, 0
            for batch in iter_minibatches(data, self.minibatch, F):
                keys = np.where(batch.mask, batch.feat_ids, 0)
                while True:
                    try:
                        slots = self.table.key_index.lookup(keys)
                        break
                    except CapacityError:
                        # unlike the reference's self-growing
                        # dense_hash_map, dense HBM arrays grow by explicit
                        # re-layout; the jitted step bakes in capacity, so
                        # rebuild it (loop: one batch may need >1 doubling).
                        # Queued batches hold OLD-layout slots — flush them
                        # through the old step first.
                        flush_group()
                        self.table.state = state   # sync the live buffers
                        self.table.grow()
                        log.info("table grown to %d rows",
                                 self.table.capacity)
                        self._step = self._build_step()
                        self._multi = (self._build_multi_step()
                                       if inner > 1 else None)
                        # dense programs bake in the old capacity too;
                        # rebuilt lazily at next flush (growth may also
                        # have pushed capacity past the dense limit)
                        self._dense_step = None
                        self._dense_multi = None
                        state = self.table.state
                group.append((slots, batch.feat_vals, batch.mask,
                              batch.targets))
                if len(group) == inner:
                    flush_group()
            flush_group()
            for loss, n in pending:
                loss, n = np.asarray(loss), np.asarray(n)
                # scanned groups return per-batch vectors
                total += float((loss * n).sum())
                count += int(n.sum())
            pending.clear()
            window.clear()
            mean_err = total / max(count, 1)
            losses.append(mean_err)
            log.info("iter %d: %d records  error: %.6f", it, count, mean_err)
        self.table.state = state
        return losses

    # -- prediction (lr.cpp:240-295) --------------------------------------
    def predict(self, data, max_feats: Optional[int] = None) -> np.ndarray:
        if isinstance(data, str):
            data = load_data(data)
        F = max_feats or _max_feats(data)
        scores = []
        for batch in iter_minibatches(data, self.minibatch, F):
            slots = self.table.key_index.lookup(
                np.where(batch.mask, batch.feat_ids, 0), create=False)
            slots = np.where(batch.mask, slots, -1)
            rows = self.transfer.pull(
                self.table.state, jnp.asarray(slots.reshape(-1)),
                self.access)["val"]
            w = np.asarray(rows).reshape(len(batch), F)
            logits = (w * batch.feat_vals * batch.mask).sum(axis=1)
            scores.append(1.0 / (1.0 + np.exp(-logits)))
        return np.concatenate(scores)[:len(data)]

    def error_rate(self, data) -> float:
        """Offline eval, the reference's tools/evaluate.py (26-line
        threshold-at-0.5 error rate)."""
        if isinstance(data, str):
            data = load_data(data)
        scores = self.predict(data)
        targets = (data.labels if isinstance(data, CSRData)
                   else np.array([y for y, _ in data]))
        return float(((scores > 0.5) != (targets > 0.5)).mean())

    # -- checkpoint (lr.cpp:297-300; server.h:49-77) -----------------------
    def save(self, path: str) -> int:
        return dump_table_text(self.table, path, fields=("val",))

    def load(self, path: str) -> int:
        n = load_table_text(self.table, path, fields=("val",))
        # loading may have grown the table; the jitted steps bake in the
        # old capacity (push scatter bounds), so force a rebuild on next
        # train()
        self._step = None
        self._multi = None
        self._dense_step = None
        self._dense_multi = None
        return n
