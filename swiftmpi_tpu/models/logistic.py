"""Sparse logistic regression on the TPU parameter server.

Re-design of the reference LR app (`/root/reference/src/apps/logistic/
lr.cpp`), same capability and math, TPU-shaped execution:

* reference: per minibatch, multithreaded per-line ``learn_instance``
  (sigmoid dot + per-key grad accumulation, lr.cpp:355-375) around a
  pull/push RPC pair (lr.cpp:213-236).
* here: the whole minibatch is one jitted SPMD step — padded ``(B, F)``
  feature matrices, masked sigmoid-dot, per-key mean-normalized gradient
  (the reference's ``grad/count`` at serialization, lr.cpp:32-38) computed
  in-step, then a transfer push applying server-side AdaGrad
  (lr.cpp:68-75).

Math parity: predict = σ(Σ w_f·x_f); err = target − predict (gradient
*ascent* on log-likelihood); per-iteration training error = mean err²
(lr.cpp:358-375); AdaGrad with fudge 1e-6; weights initialized U(0,1) by
``gen_float`` (lr.cpp:48-50) — here the same distribution via jax.random.
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.cluster.cluster import Cluster
from swiftmpi_tpu.data.libsvm import (CSRData, LibSVMBatch, iter_minibatches,
                                      load_data, load_file)  # noqa: F401
from swiftmpi_tpu.io.checkpoint import (dump_table_text, load_table_text)
from swiftmpi_tpu.parameter import lr_access
from swiftmpi_tpu.parameter.key_index import CapacityError
from swiftmpi_tpu.utils.config import ConfigParser, global_config
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.pipeline import DispatchWindow

log = get_logger(__name__)


def _max_feats(data) -> int:
    if isinstance(data, CSRData):
        return data.max_feats
    return max(len(f) for _, f in data)


def lr_formatter(row: Dict[str, np.ndarray]) -> str:
    """Reference LRParam operator<<: just the weight (lr.cpp:24-27)."""
    return repr(float(row["val"][0]))


def lr_parser(text: str) -> Dict[str, np.ndarray]:
    return {"val": np.array([float(text.split()[0])], np.float32)}


class LogisticRegression:
    def __init__(self, config: Optional[ConfigParser] = None,
                 cluster: Optional[Cluster] = None,
                 capacity_per_shard: int = 1 << 16, seed: int = 0):
        self.config = config if config is not None else global_config()
        self.minibatch = (self.config.get("worker", "minibatch").to_int32()
                          if self.config.has("worker", "minibatch") else 200)
        lr = (self.config.get("server", "initial_learning_rate").to_float()
              if self.config.has("server", "initial_learning_rate") else 0.05)
        self.cluster = cluster or Cluster(self.config).initialize()
        self.access = lr_access(lr)
        self.table = self.cluster.create_table(
            "lr", self.access, capacity_per_shard, seed=seed)
        self.transfer = self.cluster.transfer
        # [worker] inner_steps: fuse N minibatches per dispatch via
        # lax.scan, as in word2vec — through the axon tunnel one dispatch
        # costs ~5ms, which dwarfs an a9a-scale step
        self.inner_steps = (
            self.config.get("worker", "inner_steps").to_int32()
            if self.config.has("worker", "inner_steps") else 1)
        self._step = None
        self._multi = None

    # -- fused minibatch step ---------------------------------------------
    def _step_core(self, state, slots, vals, mask, targets):
        access = self.access
        transfer = self.transfer
        B, F = slots.shape
        flat = jnp.where(mask, slots, -1).reshape(-1)
        rows = transfer.pull(state, flat, access)["val"]
        w = rows.reshape(B, F)
        logits = jnp.sum(w * vals * mask, axis=1)
        predict = jax.nn.sigmoid(logits)
        row_valid = mask.any(axis=1)
        err = jnp.where(row_valid, targets - predict, 0.0)
        # mean=True: the reference's grad.val/grad.count normalization at
        # push serialization (lr.cpp:32-38), folded into the transfer's
        # dedup pass
        contrib = (err[:, None] * vals * mask).reshape(-1)
        new_state = transfer.push(
            state, flat, {"val": contrib[:, None]}, access, mean=True)
        loss = jnp.sum(err * err) / jnp.maximum(row_valid.sum(), 1)
        return new_state, loss, row_valid.sum()

    def _build_step(self):
        return jax.jit(self._step_core)

    def _build_multi_step(self):
        """Scan the fused step over a stack of minibatches in ONE dispatch.

        The reference amortizes per-batch overhead with 13 worker threads
        per rank (lr.cpp:225); on TPU the equivalent lever is fusing the
        per-batch host->device round-trip away — through a tunnel each
        dispatch costs ~5ms, which dwarfs the a9a-scale step compute.
        Inputs carry a leading ``n_batches`` axis; returns per-batch
        losses/counts so the training-error log stays per-minibatch."""

        @jax.jit
        def multi(state, slots, vals, mask, targets):
            def body(state, xs):
                state, loss, n = self._step_core(state, *xs)
                return state, (loss, n)
            state, (losses, ns) = jax.lax.scan(
                body, state, (slots, vals, mask, targets))
            return state, losses, ns

        return multi

    # -- training (lr.cpp:157-240) ----------------------------------------
    def train(self, data, niters: int = 1,
              max_feats: Optional[int] = None) -> List[float]:
        """``data``: path to a libSVM file, a pre-parsed instance list, or
        ``CSRData`` (native parser output).  Returns per-iteration mean
        training error (reference logs ``error: total/nrecords`` per iter,
        lr.cpp:231)."""
        if isinstance(data, str):
            data = load_data(data)
        if self._step is None:
            self._step = self._build_step()
        inner = max(1, self.inner_steps)
        if inner > 1 and self._multi is None:
            self._multi = self._build_multi_step()
        F = max_feats or _max_feats(data)
        losses = []
        state = self.table.state
        # deferred per-batch loss scalars: fetched once per epoch (a
        # float() per batch is a blocking device round trip); the
        # DispatchWindow keeps the async pipeline bounded on the
        # emulated multi-device CPU mesh (see utils/pipeline.py for the
        # rendezvous-starvation failure mode it prevents)
        window = DispatchWindow()
        pending = []
        group = []

        def queue(loss, n):
            pending.append((loss, n))
            window.push(loss)

        def flush_group():
            nonlocal state
            if not group:
                return
            if len(group) == inner and inner > 1:
                stacked = tuple(
                    jnp.asarray(np.stack(col)) for col in zip(*group))
                state, ls, ns = self._multi(state, *stacked)
                queue(ls, ns)
            else:
                # tail (or pre-grow flush) smaller than a full group:
                # per-batch dispatch avoids a recompile per distinct size
                for slots, vals, mask, targets in group:
                    state, loss, n = self._step(
                        state, jnp.asarray(slots), jnp.asarray(vals),
                        jnp.asarray(mask), jnp.asarray(targets))
                    queue(loss, n)
            group.clear()

        for it in range(niters):
            total, count = 0.0, 0
            for batch in iter_minibatches(data, self.minibatch, F):
                keys = np.where(batch.mask, batch.feat_ids, 0)
                while True:
                    try:
                        slots = self.table.key_index.lookup(keys)
                        break
                    except CapacityError:
                        # unlike the reference's self-growing
                        # dense_hash_map, dense HBM arrays grow by explicit
                        # re-layout; the jitted step bakes in capacity, so
                        # rebuild it (loop: one batch may need >1 doubling).
                        # Queued batches hold OLD-layout slots — flush them
                        # through the old step first.
                        flush_group()
                        self.table.state = state   # sync the live buffers
                        self.table.grow()
                        log.info("table grown to %d rows",
                                 self.table.capacity)
                        self._step = self._build_step()
                        self._multi = (self._build_multi_step()
                                       if inner > 1 else None)
                        state = self.table.state
                group.append((slots, batch.feat_vals, batch.mask,
                              batch.targets))
                if len(group) == inner:
                    flush_group()
            flush_group()
            for loss, n in pending:
                loss, n = np.asarray(loss), np.asarray(n)
                # scanned groups return per-batch vectors
                total += float((loss * n).sum())
                count += int(n.sum())
            pending.clear()
            window.clear()
            mean_err = total / max(count, 1)
            losses.append(mean_err)
            log.info("iter %d: %d records  error: %.6f", it, count, mean_err)
        self.table.state = state
        return losses

    # -- prediction (lr.cpp:240-295) --------------------------------------
    def predict(self, data, max_feats: Optional[int] = None) -> np.ndarray:
        if isinstance(data, str):
            data = load_data(data)
        F = max_feats or _max_feats(data)
        scores = []
        for batch in iter_minibatches(data, self.minibatch, F):
            slots = self.table.key_index.lookup(
                np.where(batch.mask, batch.feat_ids, 0), create=False)
            slots = np.where(batch.mask, slots, -1)
            rows = self.transfer.pull(
                self.table.state, jnp.asarray(slots.reshape(-1)),
                self.access)["val"]
            w = np.asarray(rows).reshape(len(batch), F)
            logits = (w * batch.feat_vals * batch.mask).sum(axis=1)
            scores.append(1.0 / (1.0 + np.exp(-logits)))
        return np.concatenate(scores)[:len(data)]

    def error_rate(self, data) -> float:
        """Offline eval, the reference's tools/evaluate.py (26-line
        threshold-at-0.5 error rate)."""
        if isinstance(data, str):
            data = load_data(data)
        scores = self.predict(data)
        targets = (data.labels if isinstance(data, CSRData)
                   else np.array([y for y, _ in data]))
        return float(((scores > 0.5) != (targets > 0.5)).mean())

    # -- checkpoint (lr.cpp:297-300; server.h:49-77) -----------------------
    def save(self, path: str) -> int:
        return dump_table_text(self.table, path, fields=("val",))

    def load(self, path: str) -> int:
        n = load_table_text(self.table, path, fields=("val",))
        # loading may have grown the table; the jitted steps bake in the
        # old capacity (push scatter bounds), so force a rebuild on next
        # train()
        self._step = None
        self._multi = None
        return n
