"""Model families: the reference's three apps, TPU-native.

logistic (lr.cpp), word2vec sync+async (word2vec.h / word2vec_global.h),
sent2vec (sent2vec.cpp).
"""

from swiftmpi_tpu.models.logistic import LogisticRegression
from swiftmpi_tpu.models.word2vec import Word2Vec
from swiftmpi_tpu.models.sent2vec import Sent2Vec, build_word_model_from_dump

__all__ = ["LogisticRegression", "Word2Vec", "Sent2Vec",
           "build_word_model_from_dump"]
