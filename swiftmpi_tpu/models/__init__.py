"""Model families: the reference's three apps, TPU-native, plus the
transformer LM that exercises the long-context / multi-axis parallelism.

logistic (lr.cpp), word2vec sync+async (word2vec.h / word2vec_global.h),
sent2vec (sent2vec.cpp); transformer, GloVe, and the embedding query
index are new surface (no reference counterpart — SURVEY.md §2.7).
"""

from swiftmpi_tpu.models.embedding import EmbeddingIndex
from swiftmpi_tpu.models.glove import GloVe
from swiftmpi_tpu.models.logistic import LogisticRegression
from swiftmpi_tpu.models.word2vec import Word2Vec
from swiftmpi_tpu.models.sent2vec import Sent2Vec, build_word_model_from_dump
from swiftmpi_tpu.models.transformer import (TransformerConfig, forward,
                                             forward_pipelined, init_params,
                                             lm_loss, param_shardings,
                                             sgd_step)

__all__ = ["EmbeddingIndex", "GloVe", "LogisticRegression",
           "Word2Vec", "Sent2Vec",
           "build_word_model_from_dump", "TransformerConfig", "forward",
           "forward_pipelined", "init_params", "lm_loss",
           "param_shardings", "sgd_step", "TrainState", "Trainer",
           "make_optimizer"]

_TRAINER_EXPORTS = ("TrainState", "Trainer", "make_optimizer")


def __getattr__(name):
    # lazy: keeps optax out of the import graph of users who never touch
    # the transformer trainer (word2vec/logistic need only jax)
    if name in _TRAINER_EXPORTS:
        from swiftmpi_tpu.models import trainer

        return getattr(trainer, name)
    raise AttributeError(name)
