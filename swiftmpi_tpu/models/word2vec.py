"""word2vec (CBOW + negative sampling) on the TPU parameter server.

Re-design of the reference word2vec apps — sync variant
(`/root/reference/src/apps/word2vec/word2vec.h`, used by w2v_local.cpp) and
async/global variant (`word2vec_global.h`, used by w2v.cpp) — as a single
model with a fused SPMD training step.

Reference hot loop (word2vec.h:550-615), per center word:
    b = rand % window;  context = +-(window-b) neighbors
    neu1 = sum of context input vectors v              (CBOW, raw sum)
    for target in {center (label 1), K negatives (label 0)}:
        skip negative if target == center
        f = neu1 . h_target
        g = (label - sigmoid_clipped(f)) * alpha       (ExpTable clip)
        error += 10000 * g^2                           (word2vec.h:593)
        h_grad[target] += g * neu1 ; neu1e += g * h_target
    v_grad[context_j] += neu1e  for each context word

Here the whole minibatch of that loop is one jitted step: padded
``(B, 2W)`` context matrices, ``(B, K)`` negatives drawn on device from the
alias-method unigram^0.75 sampler, gradients mean-normalized per key (the
reference's ``grad /= count`` at push serialization, word2vec.h:120-132),
pushed once through the transfer layer onto the row-sharded table with
server-side AdaGrad (word2vec.h:177-185).

Variant mapping (SURVEY.md §2.7): the reference's sync variant is this step
verbatim; its async/global variant (per-thread unsynchronized pull/push,
stale gradients, word2vec_global.h:577-651) maps to ``local_steps > 1`` —
gradients are computed against a table snapshot refreshed only every
``local_steps`` batches while pushes land immediately, reproducing
bounded-staleness async SGD without abandoning SPMD.

Skip-gram mode (``[word2vec] sg: 1`` — the BASELINE.md config-#2 text8
benchmark): each (context, center) pair is an independent example — input
vector v[context word], targets h[center] (label 1) + K fresh negatives per
pair (label 0), exactly the word2vec.c skip-gram loop the reference's CBOW
hot loop was derived from.  Same batch layout; the pair axis is (B, 2W).
"""

from __future__ import annotations

from functools import partial
from typing import Dict, List, Optional

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (jax.shard_map alias)
from swiftmpi_tpu import obs
from swiftmpi_tpu.cluster.cluster import Cluster
from swiftmpi_tpu.data.text import (CBOWBatcher, Vocab, build_vocab,
                                    load_corpus)  # noqa: F401 (Vocab: API)
from swiftmpi_tpu.io.checkpoint import dump_table_text, load_table_text
from swiftmpi_tpu.ops import pallas_stencil
from swiftmpi_tpu.ops.sampling import (build_unigram_alias, sample_alias,
                                       sample_alias_slots)
from swiftmpi_tpu.ops.sigmoid import sigmoid_clipped
from swiftmpi_tpu.parameter import w2v_access
from swiftmpi_tpu.testing import faults
from swiftmpi_tpu.transfer import PushSpec
from swiftmpi_tpu.utils.config import ConfigParser, global_config
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.pipeline import (DispatchWindow,
                                         resolve_dispatch_bound)
from swiftmpi_tpu.utils.timers import Throughput

log = get_logger(__name__)


def _dev(x):
    """Batch arg -> device: distributed batches arrive as global
    jax.Arrays whose sharding must be left alone (jnp.asarray would
    re-place them); host arrays go through jnp.asarray."""
    return x if isinstance(x, jax.Array) else jnp.asarray(x)


class _LossAccum:
    """Non-blocking loss accumulator: queues per-dispatch device scalars
    and folds every 256 into ONE on-device scalar (a single stacked-sum
    dispatch, no host sync), so an epoch holds O(1) buffers and the
    epoch-end fetch is one round trip — not two per batch.  Folds in
    float32: exact up to 2^24 per fold, and beyond that the loss
    denominator's relative error is <1e-7, immaterial.

    ``bound`` feeds a utils.pipeline.DispatchWindow (default "auto":
    bound the async pipeline only on the emulated cpu mesh, where
    unbounded in-flight sharded programs CHECK-abort at collective
    rendezvous — see that module's docstring for the failure mode).

    ``fold`` is the retention bound: the queue never holds more than
    ``fold`` device scalars (an epoch of 10k tiny batches retains at
    most ``fold``, not 10k — ``peak_queued`` makes that checkable).
    The drain itself is non-blocking: the stacked-sum is just another
    async dispatch."""

    _FOLD = 256

    def __init__(self, bound="auto", fold: int = _FOLD):
        if fold < 2:
            raise ValueError(f"_LossAccum fold must be >= 2, got {fold}")
        self._q = []
        self._fold = fold
        self.peak_queued = 0
        self._window = DispatchWindow(bound)

    def add(self, x) -> None:
        x = jnp.asarray(x, jnp.float32)
        self._q.append(x)
        self._window.push(x)
        self.peak_queued = max(self.peak_queued, len(self._q))
        if len(self._q) >= self._fold:
            self._q = [jnp.stack(self._q).sum()]

    def total(self) -> float:
        if not self._q:
            return 0.0
        # drain the dispatch pipeline before issuing the stack program:
        # the newest scalar's completion implies every queued step ran
        jax.block_until_ready(self._q[-1])
        self._window.clear()
        return float(jnp.stack(self._q).sum())


def _stack_group_host(batches):
    """Stack a group of same-shape batches host-side (one contiguous H2D
    transfer per field, not one per batch).  Pure numpy — this is the
    rendering work the input pipeline's producer thread runs off the
    critical path."""
    return (np.stack([np.asarray(b.centers) for b in batches]),
            np.stack([np.asarray(b.contexts) for b in batches]),
            np.stack([np.asarray(b.ctx_mask) for b in batches]))


def _stack_group_host_stencil(batches):
    """StencilBatch variant of ``_stack_group_host``.  Every stencil
    batch is fixed-shape (span and center arrays are padded, only
    ``n_words`` varies), so even epoch tails stack and fuse."""
    return (np.stack([np.asarray(b.tokens) for b in batches]),
            np.stack([np.asarray(b.sent_id) for b in batches]),
            np.stack([np.asarray(b.center_pos) for b in batches]),
            np.stack([np.asarray(b.half) for b in batches]))


def _stack_group(batches):
    return tuple(jnp.asarray(f) for f in _stack_group_host(batches))


def _stack_group_stencil(batches):
    return tuple(jnp.asarray(f)
                 for f in _stack_group_host_stencil(batches))


def _cbow_targets(slot_of_vocab, alias_prob, alias_idx, centers,
                  contexts, ctx_mask, key, K):
    """Shared CBOW batch layout: draw the negatives and build the
    target/context slot matrices + validity masks.  ONE copy used by
    both the gather and dense renderings — their identical sampling
    stream (the basis of the dense mode's parity guarantee) is
    identical by construction, not by parallel maintenance."""
    B = centers.shape[0]
    # fused draw: negatives and their table slots from ONE packed row
    # gather (sampling was ~6.5ms of the 17.7ms chip step as separate
    # scalar gathers — see ops/sampling.sample_alias_slots)
    negs, neg_slots = sample_alias_slots(
        key, alias_prob, alias_idx, slot_of_vocab, (B, K))
    t_slots = jnp.concatenate(
        [slot_of_vocab[centers][:, None], neg_slots], axis=1)  # (B, K+1)
    ctx_slots = jnp.where(ctx_mask, slot_of_vocab[contexts], -1)
    row_valid = ctx_mask.any(axis=1)
    # negative == center is skipped (word2vec.h:584-586)
    t_valid = jnp.concatenate(
        [jnp.ones((B, 1), bool), negs != centers[:, None]], axis=1)
    t_valid = t_valid & row_valid[:, None]
    return t_slots, ctx_slots, t_valid


def _assemble_push(tf, cf, h_flat, v_flat):
    """Lay out one push per gradient family: h-grads keyed by target
    slots, v-grads keyed by context slots, both with ``mean=True`` — the
    reference's per-key grad/count normalization (word2vec.h:120-132)
    now happens inside the transfer's own dedup pass, where the counts
    come free with the segment/scatter sums.  (Round 1 concatenated both
    families into a single zero-padded batch — which doubled every
    downstream push array; round 2's worker-side pre-scaling cost a
    capacity scatter + batch gather + (B, d) multiply per family, ~25%
    of the measured step — both folded away here.)  Per-family pushes
    carry only real contributions; apply_push handles partial grad
    dicts."""
    return (PushSpec(tf, {"h": h_flat}, mean=True),
            PushSpec(cf, {"v": v_flat}, mean=True))


def w2v_formatter(row: Dict[str, np.ndarray]) -> str:
    """Reference WParam operator<< layout: v-vector TAB h-vector
    (word2vec.h:100-110)."""
    v = " ".join(repr(float(x)) for x in row["v"])
    h = " ".join(repr(float(x)) for x in row["h"])
    return f"{v}\t{h}"


def w2v_parser(text: str) -> Dict[str, np.ndarray]:
    v_s, _, h_s = text.partition("\t")
    return {"v": np.array([float(x) for x in v_s.split()], np.float32),
            "h": np.array([float(x) for x in h_s.split()], np.float32)}


class Word2Vec:
    def __init__(self, config: Optional[ConfigParser] = None,
                 cluster: Optional[Cluster] = None,
                 capacity_per_shard: Optional[int] = None, seed: int = 0):
        self.config = config if config is not None else global_config()
        g = self.config.get_or
        self.len_vec = g("word2vec", "len_vec", 100).to_int32()
        self.window = g("word2vec", "window", 4).to_int32()
        self.negative = g("word2vec", "negative", 20).to_int32()
        self.sample = g("word2vec", "sample", -1.0).to_float()
        self.sg = g("word2vec", "sg", 0).to_int32()
        # TPU-first opt-in: one pool of negatives shared by the whole
        # batch (see _build_grads_shared) instead of the reference's
        # per-center draws.  Pool size defaults to 1024: sharing K-per-
        # center-sized pools starves the negative phase (each vocab word
        # is drawn ~B-times less often per epoch).
        self.shared_negatives = g(
            "word2vec", "shared_negatives", 0).to_int32()
        self.shared_pool = g("word2vec", "shared_pool", 1024).to_int32()
        # TPU-first opt-in: positional-stencil rendering — the batcher
        # emits stream POSITIONS over a span of B + 2W tokens and the
        # step gathers only the span's unique rows (≤ B + 2W instead of
        # B·2W context rows), computing context sums as a fixed-offset
        # sliding window with sentence-boundary masks.  Composes with
        # shared_negatives for the pool-negative h side.  See
        # _build_grads_stencil.
        self.stencil = g("word2vec", "stencil", 0).to_int32()
        # TPU-first opt-in with PARITY semantics: compute the NS phase
        # through full (B, capacity) logits on the MXU instead of
        # random row gathers (see _build_grads_dense) — same sampling
        # stream, same math, different memory shape.  Default "auto":
        # on a single TPU device with a recorded on-chip win for this
        # rendering (ops/calibration, written by the chip session's
        # step-level A/B) and a small table, use it; 0/1 force.
        _dense_raw = g("word2vec", "dense_logits", "auto").to_string()
        self.dense_logits = None if _dense_raw == "auto" \
            else int(_dense_raw)
        self.alpha = g("word2vec", "learning_rate", 0.05).to_float()
        self.min_sentence_length = g(
            "word2vec", "min_sentence_length", 1).to_int32()
        self.minibatch = g("worker", "minibatch", 5000).to_int32()
        # [worker] inner_steps: fuse N sync steps per dispatch via
        # lax.scan (amortizes per-dispatch latency, ~5ms through the
        # tunnel).  Default 1 = exactly one dispatch per batch.
        self.inner_steps = g("worker", "inner_steps", 1).to_int32()
        # [cluster] push_window: coalesce W consecutive steps' pushes
        # into ONE exchange per push family (transfer.push_window).
        # Gradients inside a window are computed against window-start
        # state, so staleness is bounded by W-1 steps; W=1 (default)
        # keeps the per-step path bit-identically.  Only meaningful on
        # the fused (inner_steps > 1) sync path.
        self.push_window_size = g("cluster", "push_window", 1).to_int32()
        if self.push_window_size < 1:
            raise ValueError("[cluster] push_window must be >= 1")
        # [cluster] wire_quant: off|int8|bf16 — value quantization for
        # the window push's sparse wire formats.  Arms the 4-way
        # dense/sparse/bitmap/sparse_q crossover on the transfer and the
        # per-field @ef error-feedback residual planes on the table
        # (quantization error banks worker-side and drains into the next
        # quantized window, so the trajectory tracks the f32 wire within
        # the documented envelope).  "off" (default) keeps the 2-way
        # decision and the wire bit-identical to the pre-quantization
        # path.  Only meaningful with push_window > 1.
        self.wire_quant = g("cluster", "wire_quant", "off").to_string()
        if self.wire_quant not in ("off", "int8", "bf16"):
            raise ValueError("[cluster] wire_quant must be off, int8 or "
                             f"bf16, got {self.wire_quant!r}")
        # [cluster] pull_quant: off|int8|bf16 — wire quantization for
        # the PULL family (transfer/plan.py price_pull_formats).  The
        # dequantized read perturbs the forward pass only — server
        # state is never written through a quantizer, so no EF plane is
        # involved and the PR-10 trajectory envelope applies.  "off"
        # (default) keeps pulls bit-identical to the f32 wire.
        self.pull_quant = g("cluster", "pull_quant", "off").to_string()
        if self.pull_quant not in ("off", "int8", "bf16"):
            raise ValueError("[cluster] pull_quant must be off, int8 or "
                             f"bf16, got {self.pull_quant!r}")
        # [cluster] pull_cache: N > 0 arms the worker-side versioned
        # pull cache with N direct-mapped lines (transfer/pull_cache.py)
        # and the table's @rowver stamp plane.  Version-exact hits ship
        # zero value bytes (watermark + hit bitmap only); the ledger's
        # pull_bytes drops accordingly.  0 (default) keeps the table
        # state pytree and the pull ledger bit-identical.
        self.pull_cache = g("cluster", "pull_cache", 0).to_int32()
        if self.pull_cache < 0:
            raise ValueError("[cluster] pull_cache must be >= 0, got "
                             f"{self.pull_cache!r}")
        # [cluster] wire_sketch: 0|1 — admit the counting-sketch index
        # rung (sparse_sketch: bucketed uint16 counts + uint8 in-bucket
        # offsets instead of i32 indices) to the window wire-format
        # crossover.  Lossless and EF-compatible; the TrafficPlan pricer
        # (parameter/key_index.py) still picks per family, so arming the
        # knob only changes the wire where the sketch byte model wins.
        # Only meaningful with push_window > 1.
        self.wire_sketch = g("cluster", "wire_sketch", 0).to_int32()
        if self.wire_sketch not in (0, 1):
            raise ValueError("[cluster] wire_sketch must be 0 or 1, got "
                             f"{self.wire_sketch!r}")
        # [cluster] collective: psum|auto|sparse_allreduce — collective
        # selection for the dense/hot reconcile planes (transfer/
        # sparse_allreduce.py).  "psum" (default) keeps the legacy dense
        # collectives bit-identically; "auto" prices the Ok-Topk sparse
        # split-and-exchange against the dense psum per plan from the
        # live hot-touch density (seeded from the vocab histogram,
        # retuned by the Controller); "sparse_allreduce" pins it.
        # Only meaningful on the hybrid/tpu window paths.
        self.collective_mode = g("cluster", "collective",
                                 "psum").to_string()
        from swiftmpi_tpu.transfer.plan import COLLECTIVE_MODES
        if self.collective_mode not in COLLECTIVE_MODES:
            raise ValueError("[cluster] collective must be one of "
                             f"{COLLECTIVE_MODES}, got "
                             f"{self.collective_mode!r}")
        # [worker] pipeline: K > 0 turns on the asynchronous input
        # pipeline (io/pipeline.py) — a producer thread renders batches
        # K ahead and eagerly device_puts them so H2D overlaps compute.
        # 0 (default) keeps the synchronous loop bit-identically: the
        # producer owns no RNG and preserves batch order, so K only
        # changes WHEN work happens, never what is computed.
        self.pipeline_depth = g("worker", "pipeline", 0).to_int32()
        if self.pipeline_depth < 0:
            raise ValueError("[worker] pipeline must be >= 0")
        # [worker] dispatch_depth: in-flight dispatch watermark
        # (utils.pipeline.resolve_dispatch_bound).  "auto" = backend
        # policy, tightened to a finite bound whenever the pipeline is
        # on; an integer forces it; 0 = unbounded.
        self.dispatch_depth = g("worker", "dispatch_depth",
                                "auto").to_string()
        self.local_steps = g("word2vec", "local_steps", 1).to_int32()
        # "" /"snapshot" (bounded-staleness via local_steps) / "hogwild"
        # (genuinely unsynchronized per-device replicas, see
        # _build_hogwild_step)
        self.async_mode = g("word2vec", "async_mode", "").to_string()
        server_lr = g("server", "initial_learning_rate", 0.7).to_float()
        # [server] dtype: bfloat16 halves the embedding fields' HBM
        # gather/scatter bytes (the measured TPU bottleneck); math stays
        # fp32 (upcast on pull, round once on store), accumulators fp32
        dtype_s = g("server", "dtype", "float32").to_string()
        if dtype_s not in ("float32", "bfloat16"):
            raise ValueError(f"[server] dtype must be float32 or "
                             f"bfloat16, got {dtype_s!r}")
        self.param_dtype = jnp.bfloat16 if dtype_s == "bfloat16" \
            else jnp.float32

        # [serve] every: publish a bounded-staleness serving snapshot of
        # the table every N consumed train steps (serve/snapshot.py);
        # 0 (default) = serving plane off.  [serve] depth bounds how many
        # published generations the publisher itself keeps referenced.
        self.serve_every = g("serve", "every", 0).to_int32()
        self.serve_depth = g("serve", "depth", 2).to_int32()
        if self.serve_every < 0:
            raise ValueError("[serve] every must be >= 0")
        self.serve_publisher = None

        # [control] (control/): the adaptive control plane — re-derive
        # hot_k / push_window / wire-format knobs online from the live
        # traffic ledger and the decayed id-frequency sketch.  Off (the
        # default) constructs NOTHING: no sketch, no controller, no
        # observation — trajectories are bit-identical to a build
        # without the plane (the tests pin this down).
        from swiftmpi_tpu.control import ControlSettings
        self.control_settings = ControlSettings.from_config(self.config)
        self.controller = None
        self._control_sketch = None
        self._control_recompiles = 0
        self._control_dirty = False

        # [obs] numerics: the training-numerics health plane (ISSUE 13,
        # obs/numerics.py).  Off (the default) constructs NOTHING and
        # traces NOTHING extra into the step — trajectories are
        # bit-identical to a build without the plane; on, the fused
        # step ships a fixed-cost bundle (grad norms, update/param
        # ratio, EF residual mass, quant error, nonfinite counts) to a
        # host collector + anomaly detector armed in train().
        from swiftmpi_tpu.obs import numerics as obs_numerics
        self.numerics_on = obs_numerics.enabled(self.config)
        self._numerics: Optional[obs_numerics.NumericsCollector] = None
        self._numerics_restore = None   # checkpointed baseline bytes
        self._numerics_rec_id: Optional[int] = None

        self.cluster = cluster or Cluster(self.config).initialize()
        # [cluster] data_plane (read by Cluster.initialize): steers the
        # stencil step's neu1 between the XLA gather->mask->sum chain
        # and the fused Pallas stencil kernel (ops/pallas_stencil.py)
        self.data_plane = getattr(self.cluster, "data_plane", "auto")
        self.access = w2v_access(server_lr, self.len_vec,
                                 param_dtype=self.param_dtype)
        self._capacity_per_shard = capacity_per_shard
        self.table = None
        self.transfer = self.cluster.transfer
        self.vocab: Optional[Vocab] = None
        self._step = None
        self._fused_cache = {}
        self._tail_fuse_frozen = False
        self._key = jax.random.key(seed ^ 0x5EED)
        # per-train() observability: hogwild tail-skip count, hybrid
        # transfer traffic counters — refreshed by every train() call
        self.train_metrics: dict = {}

    # -- vocab / table bring-up (word2vec_global.h:385-444) ----------------
    def build(self, sentences) -> "Word2Vec":
        return self.build_from_vocab(build_vocab(sentences))

    def build_from_vocab(self, vocab: Vocab) -> "Word2Vec":
        """Bring up table + sampler from a prebuilt vocab (e.g. the native
        C++ loader's) without a python counting pass."""
        self.vocab = vocab
        V = len(self.vocab)
        if V == 0:
            raise ValueError(
                "empty vocabulary — no sentence survived loading; check the "
                "corpus and [word2vec] min_sentence_length")
        if self.table is None:
            cap = self._capacity_per_shard or max(
                64, int(V * 1.3 / self.cluster.n_servers) + 1)
            partition = None
            if getattr(self.transfer, "name", "") == "hybrid":
                # Zipf-aware hot/cold split: replicate the measured
                # frequency head, shard the tail (transfer/hybrid.py).
                # batch_rows drives the dense-vs-sparse crossover in the
                # calibration: the head pays off while its dense psum
                # stays comparable to the head hits a batch routes.
                from swiftmpi_tpu.parameter.key_index import \
                    HotColdPartition
                partition = HotColdPartition.from_counts(
                    self.vocab.keys, self.vocab.counts,
                    batch_rows=self.minibatch)
                log.info(
                    "hybrid placement: %d hot keys (%.1f%% of token "
                    "mass) replicated; %d tail keys sharded",
                    partition.n_hot, 100 * (partition.head_mass or 0.0),
                    V - partition.n_hot)
            self.table = self.cluster.create_table(
                "w2v", self.access, cap, partition=partition)
        slots = self.table.key_index.lookup(self.vocab.keys)
        self._slot_of_vocab = jnp.asarray(slots, jnp.int32)
        if self.push_window_size > 1 and hasattr(
                self.transfer, "window_expected_unique"):
            # sharpen the per-window sparse/dense wire-format crossover
            # with the Zipf-aware expected unique-row count of a window's
            # worth of token draws (cluster.hashfrag.expected_unique_rows)
            from swiftmpi_tpu.cluster.hashfrag import expected_unique_rows
            self.transfer.window_expected_unique = expected_unique_rows(
                self.vocab.counts,
                self.push_window_size * self.minibatch)
        if self.wire_quant != "off":
            if self.push_window_size > 1:
                self.transfer.wire_quant = self.wire_quant
                # EF residual planes for every window-pushed grad family
                # — created BEFORE any step compiles so the state pytree
                # shape is stable for the fused scan and checkpoints
                self.table.ensure_ef(tuple(self.access.grad_fields))
            else:
                log.warning(
                    "[cluster] wire_quant: %s has no effect at "
                    "push_window: 1 (per-step pushes ship f32); "
                    "ignoring", self.wire_quant)
        if self.wire_sketch:
            if self.push_window_size > 1 and hasattr(
                    self.transfer, "wire_sketch"):
                self.transfer.wire_sketch = True
            else:
                log.warning(
                    "[cluster] wire_sketch has no effect at "
                    "push_window: 1 (per-step pushes ship indexed "
                    "rows); ignoring")
        if self.collective_mode != "psum":
            if self.push_window_size > 1 and hasattr(
                    self.transfer, "collective_mode"):
                self.transfer.collective_mode = self.collective_mode
                self.transfer.hot_touched_fraction = \
                    self._seed_hot_touched_fraction()
                log.info(
                    "[cluster] collective: %s armed (seed hot-touch "
                    "density %.4f)", self.collective_mode,
                    self.transfer.hot_touched_fraction or 0.0)
            else:
                log.warning(
                    "[cluster] collective: %s has no effect at "
                    "push_window: 1 (the per-step hot psum is not "
                    "plan-compiled); ignoring", self.collective_mode)
        if self.pull_quant != "off":
            # unlike the push-side knobs, pulls happen every step at
            # any window size — no push_window gate
            self.transfer.pull_quant = self.pull_quant
            log.info("[cluster] pull_quant: %s armed", self.pull_quant)
        if self.pull_cache:
            self.transfer.pull_cache = int(self.pull_cache)
            # the @rowver plane the watermark protocol reads — created
            # BEFORE any step compiles so the state pytree shape is
            # stable for the fused scan and checkpoints
            self.table.ensure_row_versions()
            log.info("[cluster] pull_cache: %d lines armed",
                     self.pull_cache)
        prob, alias = build_unigram_alias(self.vocab.counts)
        self._alias_prob = jnp.asarray(prob)
        self._alias_idx = jnp.asarray(alias)
        if self.control_settings.enabled:
            self._arm_control()
        log.info("vocab: %d words, %d tokens; table capacity %d",
                 V, self.vocab.total_words, self.table.capacity)
        return self

    def _seed_hot_touched_fraction(self):
        """Expected fraction of the replicated hot head touched by ONE
        coalesced window — the density signal the collective crossover
        prices (key_index.price_hot_collectives): E[unique hot rows] =
        sum over the head of 1-(1-p_i)^draws with p_i the key's FULL-
        vocab probability (the window's draws land on the whole vocab,
        only the head subset is priced), over n_hot.  Same saturation
        model as the window_expected_unique seed
        (hashfrag.expected_unique_rows), restricted to the head.
        ``None`` when there is no hot head — auto then keeps psum."""
        part = getattr(self.table.key_index, "partition", None)
        n_hot = int(getattr(part, "n_hot", 0) or 0)
        if n_hot <= 0:
            return None
        c = np.asarray(self.vocab.counts, np.float64).ravel()
        total = c.sum()
        if total <= 0:
            return None
        head = np.sort(c)[::-1][:n_hot] / total
        draws = self.push_window_size * self.minibatch
        touched = float(np.sum(-np.expm1(
            draws * np.log1p(-np.minimum(head, 1.0)))))
        return min(touched / n_hot, 1.0)

    # -- the fused step ----------------------------------------------------
    def _build_step(self):
        """Sync step: grads against current state + immediate push.  The
        table state is donated — the update is in-place in HBM."""
        grads_fn = self._build_grads()
        apply_fn = self._build_apply()
        # numerics plane: `num is None` (the default) leaves the traced
        # program untouched — the branches below are Python-time
        from swiftmpi_tpu.obs import numerics as obs_numerics
        num = self._numerics
        n_hot = self.table.n_hot
        gfields = tuple(self.access.grad_fields)

        if self.stencil:
            @partial(jax.jit, donate_argnums=0)
            def step_st(state, slot_of_vocab, alias_prob, alias_idx,
                        tokens, sent_id, center_pos, half, key):
                pushes, es, ec = grads_fn(
                    state, slot_of_vocab, alias_prob, alias_idx,
                    tokens, sent_id, center_pos, half, key)
                out = apply_fn(state, pushes)
                if num is not None:
                    obs_numerics.stage_step(
                        num, state, out,
                        obs_numerics.spec_stats(pushes, n_hot),
                        es, ec, gfields)
                return out, es, ec

            return obs.costs.track("w2v_step", step_st)

        @partial(jax.jit, donate_argnums=0)
        def step(state, slot_of_vocab, alias_prob, alias_idx,
                 centers, contexts, ctx_mask, key):
            pushes, es, ec = grads_fn(
                state, slot_of_vocab, alias_prob, alias_idx,
                centers, contexts, ctx_mask, key)
            out = apply_fn(state, pushes)
            if num is not None:
                obs_numerics.stage_step(
                    num, state, out,
                    obs_numerics.spec_stats(pushes, n_hot),
                    es, ec, gfields)
            return out, es, ec

        return obs.costs.track("w2v_step", step)

    def _fused_for(self, n_inner: int):
        """Compiled fused scan of ``n_inner`` steps, cached per length.
        The epoch loop fuses FULL groups of ``inner_steps`` and (since
        round 4) the tail group too — a small corpus whose epoch is a
        handful of batches otherwise degrades to per-batch dispatches,
        each ~5ms of pure tunnel latency (round-3 verdict Weak #4: the
        300K-token epoch sat at 3.2x CPU while text8 hit 14.4x).

        Distinct tail lengths are bounded by [2, inner_steps), but NOT
        fixed per corpus: per-epoch subsampling re-randomization (e.g.
        native.py's seed+epoch_i) shifts the full-batch count between
        epochs, so a multi-epoch run may compile a few tail lengths as
        it encounters them — amortized across the run and persisted by
        the JAX compilation cache.  Timing harnesses that must NEVER
        compile inside a timed region set ``_tail_fuse_frozen`` after
        their warm epoch: frozen, an uncached length reports None and
        the caller falls back to the already-compiled single step."""
        fn = self._fused_cache.get(n_inner)
        if fn is None:
            if self._tail_fuse_frozen and n_inner != self.inner_steps:
                return None
            # cost-catalog funnel (ISSUE 14): one name covers every
            # fused length — each length is its own handle, so a new
            # tail length books a compile, never a retrace
            fn = self._fused_cache[n_inner] = obs.costs.track(
                "w2v_multi", self._build_multi_step(n_inner),
                steps_per_call=n_inner)
        return fn

    def _build_multi_step(self, n_inner: int):
        """``n_inner`` training steps in one dispatch via lax.scan —
        amortizes per-call dispatch latency (the single-chip bottleneck:
        one fused step executes in ~0.1ms, comparable to dispatch).
        Batches arrive stacked on a leading (n_inner, ...) axis."""
        grads_fn = self._build_grads()
        if self.push_window_size > 1:
            return self._build_multi_step_windowed(n_inner, grads_fn)
        apply_fn = self._build_apply()
        # numerics plane: armed, each scan step folds its push stats
        # into extra scan outputs and ONE bundle ships per dispatch;
        # off (num None), the traced program is exactly the legacy one
        from swiftmpi_tpu.obs import numerics as obs_numerics
        num = self._numerics
        n_hot = self.table.n_hot
        gfields = tuple(self.access.grad_fields)

        if self.stencil:
            @partial(jax.jit, donate_argnums=0)
            def multi_st(state, slot_of_vocab, alias_prob, alias_idx,
                         tokens_s, sids_s, cpos_s, half_s, key):
                keys = jax.random.split(key, n_inner)
                state0 = state

                def body(state, xs):
                    t, s, c, h, k = xs
                    pushes, es, ec = grads_fn(
                        state, slot_of_vocab, alias_prob, alias_idx,
                        t, s, c, h, k)
                    if num is None:
                        return apply_fn(state, pushes), (es, ec)
                    return apply_fn(state, pushes), (
                        es, ec, obs_numerics.spec_stats(pushes, n_hot))

                state, outs = jax.lax.scan(
                    body, state, (tokens_s, sids_s, cpos_s, half_s, keys))
                if num is None:
                    es, ec = outs
                else:
                    es, ec, stats = outs
                    obs_numerics.stage_step(
                        num, state0, state,
                        tuple(s.sum() for s in stats),
                        es.sum(), ec.sum(), gfields)
                return state, es.sum(), ec.sum()

            return multi_st

        @partial(jax.jit, donate_argnums=0)
        def multi(state, slot_of_vocab, alias_prob, alias_idx,
                  centers_s, contexts_s, masks_s, key):
            keys = jax.random.split(key, n_inner)
            state0 = state

            def body(state, xs):
                c, x, m, k = xs
                pushes, es, ec = grads_fn(
                    state, slot_of_vocab, alias_prob, alias_idx, c, x, m, k)
                if num is None:
                    return apply_fn(state, pushes), (es, ec)
                return apply_fn(state, pushes), (
                    es, ec, obs_numerics.spec_stats(pushes, n_hot))

            state, outs = jax.lax.scan(
                body, state, (centers_s, contexts_s, masks_s, keys))
            if num is None:
                es, ec = outs
            else:
                es, ec, stats = outs
                obs_numerics.stage_step(
                    num, state0, state, tuple(s.sum() for s in stats),
                    es.sum(), ec.sum(), gfields)
            return state, es.sum(), ec.sum()

        return multi

    def _build_multi_step_windowed(self, n_inner: int, grads_fn):
        """Window-coalesced fused scan ([cluster] push_window = W > 1):
        steps inside a window compute gradients against the FROZEN
        window-start state (scan carries it unchanged) and stack their
        PushSpecs as scan outputs; the window then applies each push
        family with ONE ``transfer.push_window`` exchange.  A Python loop
        walks the ceil(n_inner / W) windows inside the same jit, so the
        dispatch count per fused group is unchanged while collective
        dispatches drop ~W-fold.  Staleness is bounded by W-1 steps (see
        docs/ARCHITECTURE.md "Window-coalesced push")."""
        W = self.push_window_size
        apply_window = self._build_apply_window()
        bounds = [(s, min(s + W, n_inner)) for s in range(0, n_inner, W)]
        mesh = getattr(self.cluster, "mesh", None)
        replicated = (jax.sharding.NamedSharding(
            mesh, jax.sharding.PartitionSpec()) if mesh is not None else None)
        # numerics plane: the stacked (W, ...) push buffers already
        # exist per window, so armed stats fold over them with no extra
        # scan outputs; off (num None) traces the legacy program
        from swiftmpi_tpu.obs import numerics as obs_numerics
        num = self._numerics
        n_hot = self.table.n_hot
        gfields = tuple(self.access.grad_fields)

        def run_windows(state, statics, keys, xs_all):
            es_tot, ec_tot = jnp.float32(0), jnp.float32(0)
            state0 = state
            if num is not None:
                gacc = (jnp.float32(0), jnp.float32(0), jnp.int32(0))
            for s, e in bounds:
                xs = tuple(x[s:e] for x in xs_all) + (keys[s:e],)

                def body(carry, x):
                    # carry is the window-start state, returned untouched:
                    # every step in the window sees the same snapshot
                    pushes, es, ec = grads_fn(carry, *statics, *x)
                    return carry, (pushes, es, ec)

                _, (pushes_s, es, ec) = jax.lax.scan(body, state, xs)
                if replicated is not None:
                    # the stacked (W, ...) push buffers must stay
                    # replicated: letting GSPMD infer a sharding for them
                    # from the row-sharded scatter consumer miscompiles
                    # the partitioned scatter (wrong sums on the emulated
                    # mesh) — pin them before the window apply
                    pushes_s = jax.tree_util.tree_map(
                        lambda x: jax.lax.with_sharding_constraint(
                            x, replicated), pushes_s)
                if num is not None:
                    w = obs_numerics.spec_stats(pushes_s, n_hot)
                    gacc = tuple(a + b for a, b in zip(gacc, w))
                state = apply_window(state, pushes_s)
                es_tot += es.sum()
                ec_tot += ec.sum()
            if num is not None:
                obs_numerics.stage_step(num, state0, state, gacc,
                                        es_tot, ec_tot, gfields)
            return state, es_tot, ec_tot

        if self.stencil:
            @partial(jax.jit, donate_argnums=0)
            def multi_st(state, slot_of_vocab, alias_prob, alias_idx,
                         tokens_s, sids_s, cpos_s, half_s, key):
                keys = jax.random.split(key, n_inner)
                return run_windows(state,
                                   (slot_of_vocab, alias_prob, alias_idx),
                                   keys, (tokens_s, sids_s, cpos_s, half_s))

            return multi_st

        @partial(jax.jit, donate_argnums=0)
        def multi(state, slot_of_vocab, alias_prob, alias_idx,
                  centers_s, contexts_s, masks_s, key):
            keys = jax.random.split(key, n_inner)
            return run_windows(state,
                               (slot_of_vocab, alias_prob, alias_idx),
                               keys, (centers_s, contexts_s, masks_s))

        return multi

    def _build_apply_window(self):
        """Window analogue of :meth:`_build_apply`: each stacked (W, ...)
        PushSpec family goes through ONE ``transfer.push_window`` call.
        Dense (capacity-shaped) specs have no deferred-window semantics —
        their grads are already normalized against live state — so
        dense_logits mode is rejected at trace time rather than silently
        de-coalesced."""
        access = self.access
        transfer = self.transfer

        def apply_window(state, pushes):
            for spec in pushes:
                if getattr(spec, "dense", False):
                    raise ValueError(
                        "[cluster] push_window > 1 cannot coalesce dense "
                        "(capacity-shaped) pushes — disable [word2vec] "
                        "dense_logits or set push_window: 1")
                state = transfer.push_window(
                    state, spec.slots, spec.grads, access,
                    mean=spec.mean,
                    counts=getattr(spec, "counts", None))
            return state

        return apply_window

    def _build_hogwild_step(self, n_inner: int):
        """Genuinely unsynchronized async SGD — the TPU rendering of the
        reference's async/global variant (word2vec_global.h:577-651),
        where worker threads pull/push against the server with NO barrier
        and gradients are arbitrarily stale.

        SPMD can't express literal thread races, but it can express their
        semantics: every device becomes an independent worker with a FULL
        replica of the table (the reference's LocalParamCache, taken to
        its limit), trains ``n_inner`` batches on its own stream — own
        negatives, own AdaGrad accumulation, zero cross-device traffic —
        then every worker's RAW GRADIENT pushes are all_gathered and
        applied to the shared base SEQUENTIALLY through the access
        method, exactly as the reference server applies each thread's
        push in arrival order against the live accumulators
        (server.h:159-176; worker-major order here is one valid
        linearization of the nondeterministic arrival order).

        Why not psum the replicas' deltas (this mode's first rendering):
        each delta composes that worker's AdaGrad trajectory from the
        SAME base accumulator, so summing them applies every worker's
        full-size early steps to shared hot rows — an effective
        n_workers-times overstep on frequent words that measurably
        diverges (parity soak: hogwild loss rising by epoch 3, +72% vs
        sync).  Sequential re-application lets each push see the accum
        state the previous pushes grew, like the reference.  Staleness
        bound = ``n_inner`` batches x ``n_devices`` workers (the
        reference's is unbounded only by thread scheduling).

        Trades the row-sharded layout for replication during the async
        phase (a vocab-scale table fits one device by orders of
        magnitude); the ``data``/``model`` sharded layout is the sync
        path's concern.  Memory note: reconciliation rings the STATE
        through the workers (each applies its own, locally-held pushes
        to the passing chain), so peak extra memory is one table-state
        copy (O(capacity x d), ~27MB at demo.conf scale) on top of the
        worker's own push sequence (O(local_steps x push_rows x d),
        which the gradient scan holds anyway) — no n_workers-scaled
        materialization.  Time note: the apply is inherently SEQUENTIAL
        over all ``n_workers x local_steps`` pushes (that is its
        semantics — each AdaGrad apply must see the accumulators the
        previous pushes grew), and every device runs the full chain
        redundantly (each computing a different rotation, only the
        worker-major one kept); reconciliation wall-time therefore grows
        linearly with worker count, so large fleets amortize it with
        bigger ``local_steps`` or prefer the snapshot
        (``local_steps``-only) async mode."""
        if getattr(self.transfer, "name", "") in ("tpu", "hybrid"):
            raise ValueError(
                "async_mode=hogwild requires the gather/scatter 'xla' "
                "transfer: each worker replica trains locally, and the "
                "'tpu'/'hybrid' backends' shard_map routing cannot nest "
                "inside the per-worker mesh (set [cluster] transfer: xla)")
        # Single-process SPMD mode: the worker axis spans this process's
        # devices.  Multi-process runs are routed by train() to the
        # snapshot bounded-staleness mode (measured loss envelope within
        # +0.02% of hogwild at realistic scale — docs/ARCHITECTURE.md
        # "Async modes") rather than refused.
        grads_fn = self._build_grads()
        apply_fn = self._build_apply()
        mesh = self.cluster.mesh
        workers = mesh.devices.reshape(-1)
        wmesh = jax.sharding.Mesh(workers, ("worker",))
        n_workers = len(workers)

        from jax.sharding import PartitionSpec as P

        @partial(jax.shard_map, mesh=wmesh,
                 in_specs=(P(), P(), P(), P(),
                           P("worker"), P("worker"), P("worker"), P()),
                 out_specs=(P(), P(), P()), check_vma=False)
        def _workers(state, slot_of_vocab, alias_prob, alias_idx,
                     centers_s, contexts_s, masks_s, key):
            wid = jax.lax.axis_index("worker")
            keys = jax.random.split(jax.random.fold_in(key, wid), n_inner)
            # local batch-stack view is already (n_inner, B, ...): the
            # global (n_workers * n_inner, ...) leading axis is sharded
            centers_l, contexts_l, masks_l = centers_s, contexts_s, masks_s

            def body(local, xs):
                c, x, m, k = xs
                pushes, es, ec = grads_fn(
                    local, slot_of_vocab, alias_prob, alias_idx, c, x, m, k)
                # the local replica evolves with this worker's own pushes
                # (its stale view); the same pushes are also carried out
                # for the shared sequential apply
                return apply_fn(local, pushes), (pushes, es, ec)

            _, (pushes_l, es, ec) = jax.lax.scan(
                body, state, (centers_l, contexts_l, masks_l, keys))
            # reconcile: every worker's push sequence, applied to the
            # shared base one push at a time (worker-major) so each
            # AdaGrad application sees the accumulators the previous
            # pushes grew — the reference server's arrival-order apply.
            # RING THE STATE, NOT THE PUSHES (round-2 all_gathered every
            # sequence to every device: 2.2GB at 16K-batch/8-worker/
            # 2-step): each device applies its OWN pushes to the chain
            # state passing through, so push data never crosses the
            # ring and per-round traffic is one table state (~27MB at
            # demo.conf scale).  After round 0 (own apply) + n-1
            # shift+apply rounds, the device with the highest id holds
            # exactly A_{n-1}(...A_1(A_0(base))) — the worker-major
            # linearization — and one masked psum broadcasts it.
            shift = [(i, (i + 1) % n_workers)
                     for i in range(n_workers)]

            def apply_own(st):
                def apply_step(st, s_pushes):
                    return apply_fn(st, s_pushes), None
                st, _ = jax.lax.scan(apply_step, st, pushes_l)
                return st

            chain = apply_own(state)
            for _ in range(n_workers - 1):
                chain = jax.tree_util.tree_map(
                    lambda x: jax.lax.ppermute(x, "worker", shift),
                    chain)
                chain = apply_own(chain)
            is_last = wid == n_workers - 1
            new_state = jax.tree_util.tree_map(
                lambda x: jax.lax.psum(
                    jnp.where(is_last, x, jnp.zeros_like(x)), "worker"),
                chain)
            return new_state, jax.lax.psum(es.sum(), "worker"), \
                jax.lax.psum(ec.sum(), "worker")

        @partial(jax.jit, donate_argnums=0)
        def step(state, slot_of_vocab, alias_prob, alias_idx,
                 centers_s, contexts_s, masks_s, key):
            return _workers(state, slot_of_vocab, alias_prob, alias_idx,
                            centers_s, contexts_s, masks_s, key)

        return obs.costs.track("w2v_hogwild", step,
                               steps_per_call=n_inner), n_workers

    def _build_grads(self):
        """Gradient phase of the step: pull rows, CBOW- or skip-gram-NS
        math, per-key mean normalization — no push.  Split out so the async
        (``local_steps``) mode can compute grads against a *stale* state
        snapshot while pushes land on the live state."""
        if self.stencil:
            if self.sg:
                raise ValueError(
                    "stencil is a CBOW-only rendering (span positions "
                    "index a center's context window); drop sg or "
                    "stencil")
            if self.dense_logits:
                raise ValueError(
                    "dense_logits and stencil are two different "
                    "renderings of the gather working set — pick one")
            if getattr(self.transfer, "name", "") not in ("xla", "hybrid"):
                raise ValueError(
                    "the stencil rendering pushes its span family "
                    "through push_span (XlaTransfer, or HybridTransfer's "
                    "split hot/tail span paths) — set [cluster] "
                    "transfer: xla or hybrid")
            if self.shared_negatives:
                self.resolved_rendering = "stencil_shared"
                return self._build_grads_stencil(shared=True)
            self.resolved_rendering = "stencil"
            return self._build_grads_stencil(shared=False)
        if self.sg:
            if self.dense_logits:
                raise ValueError(
                    "dense_logits is a CBOW-only rendering; with sg: 1 "
                    "the per-pair skip-gram phase would ignore it — "
                    "drop one of the two flags")
            if self.shared_negatives:
                self.resolved_rendering = "sg_shared"
                return self._build_grads_sg_shared()
            self.resolved_rendering = "sg"
            return self._build_grads_sg()
        if self.dense_logits and self.shared_negatives:
            raise ValueError(
                "dense_logits and shared_negatives are two different "
                "renderings of the negative-sampling phase — pick one")
        if self.shared_negatives:
            self.resolved_rendering = "shared"
            return self._build_grads_shared()
        dense = self.dense_logits
        if dense is None:             # "auto": measurement-driven
            from swiftmpi_tpu.ops import calibration

            # the (B, capacity) buffers bound the regime; passed as the
            # gate's `fits` so SMTPU_DENSE_LOGITS=1 force-on keeps the
            # same semantics as the Pallas kernel gates (force beats
            # every auto condition except fit)
            fits = (self.table is not None
                    and self.table.capacity <= 20_000)
            dense = (getattr(self.transfer, "name", "")
                     not in ("tpu", "hybrid")
                     and calibration.gated("dense_logits",
                                           "SMTPU_DENSE_LOGITS", fits))
        # which rendering actually resolved — benches label their
        # numbers with this so A/B verdicts can't compare mismatched
        # baselines (the dense-promotion feedback-loop hazard)
        self.resolved_rendering = "dense" if dense else "gather"
        if dense:
            return self._build_grads_dense()
        access = self.access
        transfer = self.transfer
        K = self.negative
        alpha = self.alpha
        d = self.len_vec

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     centers, contexts, ctx_mask, key):
            B, W2 = contexts.shape
            t_slots, ctx_slots, t_valid = _cbow_targets(
                slot_of_vocab, alias_prob, alias_idx, centers, contexts,
                ctx_mask, key, K)
            t_slots = jnp.where(t_valid, t_slots, -1)

            # split pulls: targets need only h, contexts only v —
            # pulling both fields for the union of slots would gather
            # twice the bytes and discard half (fp32 upcast restores
            # precision when the table stores bf16)
            h_t = transfer.pull(
                state, t_slots.reshape(-1), access, fields=("h",)
            )["h"].reshape(B, K + 1, d).astype(jnp.float32)
            v_ctx = transfer.pull(
                state, ctx_slots.reshape(-1), access, fields=("v",)
            )["v"].reshape(B, W2, d).astype(jnp.float32)

            neu1 = jnp.sum(v_ctx * ctx_mask[..., None], axis=1)   # (B, d)
            f = jnp.einsum("bd,bkd->bk", neu1, h_t)
            labels = jnp.concatenate(
                [jnp.ones((B, 1)), jnp.zeros((B, K))], axis=1)
            g = (labels - sigmoid_clipped(f)) * alpha
            g = jnp.where(t_valid, g, 0.0)                        # (B, K+1)

            h_contrib = g[..., None] * neu1[:, None, :]           # (B,K+1,d)
            neu1e = jnp.einsum("bk,bkd->bd", g, h_t)              # (B, d)
            v_contrib = jnp.where(ctx_mask[..., None],
                                  neu1e[:, None, :], 0.0)         # (B,2W,d)

            pushes = _assemble_push(
                t_slots.reshape(-1), ctx_slots.reshape(-1),
                h_contrib.reshape(-1, d), v_contrib.reshape(-1, d))

            err_sum = jnp.sum(1e4 * g * g)          # word2vec.h:593
            err_cnt = t_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_grads_dense(self):
        """Dense-logits rendering of the parity CBOW-NS gradient phase.

        SAME sampling stream, same clipped sigmoid, same mean-normalized
        update semantics as ``_build_grads`` — only the memory shape of
        the h (target) side changes.  The parity step is transaction-
        bound on its B*(K+1) random row gather + scatter (measured
        ~14ns/row, docs/ARCHITECTURE.md); with a small table
        (demo.conf: 17K rows) the same math fits the MXU instead:

            F      = neu1 @ h.T                  (B, cap) logits
            f[b,k] = F[b, t[b,k]]                row-LOCAL pair gather
            G      = scatter g into (B, cap)     row-local scalar scatter
            h_grad = G.T @ neu1                  (cap, d) — ARRIVES DENSE
            neu1e  = G @ h                       (B, d)

        so the random-row traffic disappears entirely: the h push skips
        the transfer scatter (PushSpec(dense=True)) and normalization
        uses the scattered count plane.  Cost moves to O(B*cap) MXU
        FLOPs + (B, cap) buffers, profitable exactly when cap is small
        — the regime the reference's demo targets.  Decision data:
        ``scripts/gather_micro.py --dense-only`` on chip.  Context
        (v) side is unchanged — its B*2W gather is ~10x smaller.

        Reference math being reproduced: word2vec.h:550-615 (the same
        f/g/neu1e quantities, batched).
        """
        if getattr(self.transfer, "name", "") in ("tpu", "hybrid"):
            raise ValueError(
                "dense_logits computes the h-grad as a full-capacity "
                "matmul and applies it directly — the 'tpu'/'hybrid' "
                "backends' row-sharded routing doesn't apply (set "
                "[cluster] transfer: xla)")
        access = self.access
        transfer = self.transfer
        K = self.negative
        alpha = self.alpha
        d = self.len_vec

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     centers, contexts, ctx_mask, key):
            B, W2 = contexts.shape
            cap = state["h"].shape[0]
            t_slots, ctx_slots, t_valid = _cbow_targets(
                slot_of_vocab, alias_prob, alias_idx, centers, contexts,
                ctx_mask, key, K)
            safe_t = jnp.clip(jnp.where(t_valid, t_slots, 0), 0, cap - 1)

            v_ctx = transfer.pull(
                state, ctx_slots.reshape(-1), access, fields=("v",)
            )["v"].reshape(B, W2, d).astype(jnp.float32)
            neu1 = jnp.sum(v_ctx * ctx_mask[..., None], axis=1)  # (B, d)

            h_all = state["h"].astype(jnp.float32)        # (cap, d)
            F = neu1 @ h_all.T                            # (B, cap) MXU
            f = jnp.take_along_axis(F, safe_t, axis=1)    # (B, K+1)
            labels = jnp.concatenate(
                [jnp.ones((B, 1)), jnp.zeros((B, K))], axis=1)
            g = (labels - sigmoid_clipped(f)) * alpha
            g = jnp.where(t_valid, g, 0.0)

            rows = jnp.arange(B)[:, None]
            G = jnp.zeros((B, cap), jnp.float32).at[rows, safe_t].add(g)
            # counts scatter straight to (cap,): 344K scalar adds are
            # noise next to the three O(B*cap) matmuls, and a (B, cap)
            # count plane would cost another ~1.1GB buffer at bench
            # shape just to be row-summed away
            counts = jnp.zeros((cap,), jnp.float32).at[
                safe_t.reshape(-1)].add(
                t_valid.reshape(-1).astype(jnp.float32), mode="drop")
            h_grad = (G.T @ neu1) / jnp.maximum(counts, 1.0)[:, None]
            neu1e = G @ h_all                             # (B, d)
            v_contrib = jnp.where(ctx_mask[..., None],
                                  neu1e[:, None, :], 0.0)

            pushes = (PushSpec(None, {"h": h_grad}, dense=True),
                      PushSpec(ctx_slots.reshape(-1),
                               {"v": v_contrib.reshape(-1, d)},
                               mean=True))

            err_sum = jnp.sum(1e4 * g * g)
            err_cnt = t_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_grads_shared(self):
        """CBOW-NS with batch-shared negatives — the TPU-first rendering
        of negative sampling (opt-in, ``shared_negatives: 1``).

        The reference draws K negatives per center (word2vec.h:577-586),
        which on TPU costs a B*(K+1)-row random gather — the measured
        bottleneck (row gathers run ~5% of HBM peak; see
        docs/ARCHITECTURE.md).  Sharing one K-negative set across the
        batch — standard practice in modern embedding trainers, same
        expected gradient for the negative term up to sampling variance —
        restructures the math MXU-first:

          h gather:   B + K rows instead of B*(K+1)   (~20x less)
          f_neg:      neu1 @ h_neg^T    — a (B,d)x(d,K) matmul
          gh_neg:     g_neg^T @ neu1    — a (K,B)x(B,d) matmul, DENSE
                      per-negative grads (no scatter at all for negs)
          neu1e:      g_pos*h_pos + g_neg @ h_neg — matmul again

        Per-key mean normalization and the (negative == center) skip are
        preserved; the error metric is the same accu(1e4 g^2).  NOT
        loss-parity with the reference's RNG stream (different negative
        correlation structure) — the parity mode stays the default and
        the oracle tests pin it."""
        access = self.access
        transfer = self.transfer
        K = self.shared_pool
        alpha = self.alpha
        d = self.len_vec

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     centers, contexts, ctx_mask, key):
            B, W2 = contexts.shape
            negs = sample_alias(key, alias_prob, alias_idx, (K,))
            c_slots = slot_of_vocab[centers]                  # (B,)
            n_slots = slot_of_vocab[negs]                     # (K,)
            ctx_slots = jnp.where(ctx_mask, slot_of_vocab[contexts], -1)
            row_valid = ctx_mask.any(axis=1)

            pulled_h = transfer.pull(
                state, jnp.concatenate([c_slots, n_slots]), access,
                fields=("h",))["h"].astype(jnp.float32)
            h_pos = pulled_h[:B]                              # (B, d)
            h_neg = pulled_h[B:B + K]                         # (K, d)
            v_ctx = transfer.pull(
                state, ctx_slots.reshape(-1), access, fields=("v",)
            )["v"].reshape(B, W2, d).astype(jnp.float32)

            neu1 = jnp.sum(v_ctx * ctx_mask[..., None], axis=1)
            f_pos = jnp.einsum("bd,bd->b", neu1, h_pos)       # (B,)
            f_neg = neu1 @ h_neg.T                            # (B, K) MXU
            g_pos = (1.0 - sigmoid_clipped(f_pos)) * alpha
            g_pos = jnp.where(row_valid, g_pos, 0.0)
            # negative == center skipped (word2vec.h:584-586)
            n_valid = (negs[None, :] != centers[:, None]) \
                & row_valid[:, None]
            g_neg = jnp.where(n_valid,
                              (0.0 - sigmoid_clipped(f_neg)) * alpha, 0.0)
            # keep the objective's positive/negative balance at the
            # configured `negative` draws per center: the pool evaluates
            # K pairs per center, so each carries weight negative/K
            gw = g_neg * (self.negative / K)

            gh_pos = g_pos[:, None] * neu1                    # (B, d)
            gh_neg = gw.T @ neu1                              # (K, d) MXU
            neu1e = g_pos[:, None] * h_pos + gw @ h_neg       # (B, d) MXU
            v_contrib = jnp.where(ctx_mask[..., None],
                                  neu1e[:, None, :], 0.0)

            # Three push families.  Positives and contexts keep the
            # reference's per-key mean normalization.  The pool rows are
            # pushed as their OWN family with SUM semantics: each row
            # already carries the sum of its ~B per-pair contributions —
            # the exact gradient of the pairwise NS objective — and it
            # must NOT share a count vector with the centers, or a
            # frequent word appearing hundreds of times as a center in
            # the same batch would have its one summed negative row
            # divided by that count (~100-1000x attenuation at bench
            # shapes: exactly the 'negatives stop training' collapse
            # documented above, smuggled back in through normalization).
            # Duplicate pool draws of one key sum too — each draw is a
            # sample, as in the reference's per-center draws.
            pos_slots = jnp.where(row_valid, c_slots, -1)
            neg_slots = jnp.where(n_valid.any(axis=0), n_slots, -1)
            cslots_flat = ctx_slots.reshape(-1)
            v_flat = v_contrib.reshape(-1, d)
            pushes = (PushSpec(pos_slots, {"h": gh_pos}, mean=True),
                      PushSpec(neg_slots, {"h": gh_neg}),
                      PushSpec(cslots_flat, {"v": v_flat}, mean=True))

            # loss terms carry the same negative/K weighting as the
            # gradients (advisor r04, both shared-pool variants): a
            # center contributes ~1 positive + ~`negative` weighted pool
            # terms, keeping the reported loss scale-comparable with the
            # per-center parity CBOW rendering
            ratio = self.negative / K
            err_sum = jnp.sum(1e4 * g_pos * g_pos) \
                + ratio * jnp.sum(1e4 * g_neg * g_neg)
            err_cnt = row_valid.sum() + ratio * n_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_grads_stencil(self, shared: bool):
        """Positional-stencil rendering of the CBOW gradient phase
        (opt-in, ``stencil: 1``): collapse the context gather to the
        batch's UNIQUE stream-span rows.

        Consecutive centers in a sequential stream share context
        tokens, so the per-pair rendering's (B, 2W) context gather
        touches at most S = B + 2W unique rows — ~16.4K instead of
        ~131K at bench shape, ~8x fewer HBM transactions against the
        measured 28ns/row random-gather floor (docs/ROUND5_NOTES.md).
        The batcher emits positions over the span (data/text.py
        ``StencilBatch``; the native loader emits the identical wire
        format) and the context sum becomes a fixed-stencil
        sliding-window reduction:

          v_span  = pull span rows            — ONE ≤(B+2W)-row gather
          ctx_idx = center_pos ± {1..W}       — static stencil offsets
          masks   = in-span ∧ same-sentence ∧ |offset| ≤ half ∧ valid
          neu1    = Σ_offsets v_span[ctx_idx]·mask   — gathered from
                    the span ARRAY, not the capacity table

        The v-gradient inverts the same stencil: per-pair context
        grads scatter onto SPAN positions (batch-local dense indices),
        then one position-indexed push dedups duplicate tokens WITHOUT
        the generic path's 151K-key sort (transfer/xla.py
        ``push_span``).  Sentence boundaries and the reference's
        dynamic window shrink (word2vec.h:556) are masks, equal by
        construction to the per-pair batcher's expansion —
        data/text.py ``stencil_to_cbow`` is the executable statement
        of that equivalence and the parity tests pin it.

        ``shared=False``: per-center K negatives drawn from the SAME
        sampling stream as the parity gather rendering — directly
        checkable against the numpy oracle.  ``shared=True``
        (``shared_negatives: 1``): the batch-shared pool of
        ``_build_grads_shared`` on the h side — the 1M-vocab bench
        cell's composition."""
        access = self.access
        transfer = self.transfer
        W = self.window
        alpha = self.alpha
        d = self.len_vec
        K = self.shared_pool if shared else self.negative
        data_plane = self.data_plane
        p_itemsize = jnp.dtype(self.param_dtype).itemsize

        offsets = jnp.concatenate(
            [jnp.arange(-W, 0), jnp.arange(1, W + 1)])      # (2W,)

        def stencil_parts(state, slot_of_vocab, tokens, sent_id,
                          center_pos, half):
            S = tokens.shape[0]
            B = center_pos.shape[0]
            span_valid = sent_id >= 0
            span_slots = jnp.where(span_valid, slot_of_vocab[tokens], -1)
            row_valid = center_pos >= 0
            cp = jnp.clip(center_pos, 0, S - 1)
            centers = tokens[cp]                             # (B,) vocab
            c_slots = jnp.where(row_valid, span_slots[cp], -1)
            ctx_idx = cp[:, None] + offsets[None, :]         # (B, 2W)
            ci = jnp.clip(ctx_idx, 0, S - 1)
            ctx_mask = ((ctx_idx >= 0) & (ctx_idx < S)
                        & (sent_id[ci] == sent_id[cp][:, None])
                        & (jnp.abs(offsets)[None, :] <= half[:, None])
                        & row_valid[:, None])
            # data_plane routing (trace-time static): the fused Pallas
            # kernel collapses pull + span gather + masked sum into one
            # call over the raw table (xla transfer only — the hybrid
            # split has no single table array for "v"); same
            # contribution set, matmul reduction order.
            if (transfer.name == "xla"
                    and pallas_stencil.use_fused_stencil(
                        S, B, d, p_itemsize, W, mode=data_plane)):
                lo, wmask = pallas_stencil.stencil_window_inputs(
                    sent_id, center_pos, half, W)
                with jax.named_scope("pallas_gather_stencil"):
                    neu1 = pallas_stencil.fused_stencil_gather(
                        state["v"], span_slots, lo, wmask)
                return (span_slots, centers, c_slots, ci, ctx_mask,
                        neu1)
            # THE gather this rendering exists for: ≤ B + 2W unique rows
            v_span = transfer.pull(
                state, span_slots, access, fields=("v",)
            )["v"].astype(jnp.float32)                       # (S, d)
            v_ctx = v_span[ci]        # span-local gather, not HBM rows
            neu1 = jnp.sum(v_ctx * ctx_mask[..., None], axis=1)
            return span_slots, centers, c_slots, ci, ctx_mask, neu1

        def v_push(span_slots, ci, ctx_mask, neu1e, S):
            # invert the stencil: per-pair context grads land on SPAN
            # positions (dense batch-local indices, not a capacity
            # scatter); contribution counts ride along so push_span's
            # mean normalization divides by the true pair count
            contrib = jnp.where(ctx_mask[..., None],
                                neu1e[:, None, :], 0.0)
            vg = jnp.zeros((S, d), jnp.float32).at[
                ci.reshape(-1)].add(contrib.reshape(-1, d))
            vc = jnp.zeros((S,), jnp.float32).at[
                ci.reshape(-1)].add(
                ctx_mask.reshape(-1).astype(jnp.float32))
            return PushSpec(span_slots, {"v": vg}, mean=True, counts=vc)

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     tokens, sent_id, center_pos, half, key):
            S = tokens.shape[0]
            B = center_pos.shape[0]
            (span_slots, centers, c_slots, ci, ctx_mask,
             neu1) = stencil_parts(state, slot_of_vocab, tokens,
                                   sent_id, center_pos, half)
            row_valid = center_pos >= 0
            if shared:
                negs = sample_alias(key, alias_prob, alias_idx, (K,))
                n_slots = slot_of_vocab[negs]                # (K,)
                pulled_h = transfer.pull(
                    state, jnp.concatenate([c_slots, n_slots]), access,
                    fields=("h",))["h"].astype(jnp.float32)
                h_pos = pulled_h[:B]
                h_neg = pulled_h[B:B + K]
                f_pos = jnp.einsum("bd,bd->b", neu1, h_pos)
                f_neg = neu1 @ h_neg.T                       # (B, K) MXU
                g_pos = jnp.where(
                    row_valid, (1.0 - sigmoid_clipped(f_pos)) * alpha,
                    0.0)
                # negative == center skipped (word2vec.h:584-586)
                n_valid = (negs[None, :] != centers[:, None]) \
                    & row_valid[:, None]
                g_neg = jnp.where(
                    n_valid, (0.0 - sigmoid_clipped(f_neg)) * alpha, 0.0)
                gw = g_neg * (self.negative / K)
                gh_pos = g_pos[:, None] * neu1
                gh_neg = gw.T @ neu1                         # (K, d) MXU
                neu1e = g_pos[:, None] * h_pos + gw @ h_neg
                neg_slots = jnp.where(n_valid.any(axis=0), n_slots, -1)
                # pool rows push as their own SUM family; see the
                # normalization-collapse note in _build_grads_shared
                pushes = (PushSpec(c_slots, {"h": gh_pos}, mean=True),
                          PushSpec(neg_slots, {"h": gh_neg}),
                          v_push(span_slots, ci, ctx_mask, neu1e, S))
                ratio = self.negative / K
                err_sum = jnp.sum(1e4 * g_pos * g_pos) \
                    + ratio * jnp.sum(1e4 * g_neg * g_neg)
                err_cnt = row_valid.sum() + ratio * n_valid.sum()
                return pushes, err_sum, err_cnt
            # parity negatives: per-center draws from the SAME sampling
            # stream as _cbow_targets — the oracle test's anchor
            negs, neg_slots = sample_alias_slots(
                key, alias_prob, alias_idx, slot_of_vocab, (B, K))
            t_slots = jnp.concatenate(
                [c_slots[:, None], neg_slots], axis=1)       # (B, K+1)
            t_valid = jnp.concatenate(
                [jnp.ones((B, 1), bool), negs != centers[:, None]],
                axis=1)
            t_valid = t_valid & row_valid[:, None]
            t_slots = jnp.where(t_valid, t_slots, -1)
            h_t = transfer.pull(
                state, t_slots.reshape(-1), access, fields=("h",)
            )["h"].reshape(B, K + 1, d).astype(jnp.float32)
            f = jnp.einsum("bd,bkd->bk", neu1, h_t)
            labels = jnp.concatenate(
                [jnp.ones((B, 1)), jnp.zeros((B, K))], axis=1)
            g = (labels - sigmoid_clipped(f)) * alpha
            g = jnp.where(t_valid, g, 0.0)                   # (B, K+1)
            h_contrib = g[..., None] * neu1[:, None, :]      # (B,K+1,d)
            neu1e = jnp.einsum("bk,bkd->bd", g, h_t)         # (B, d)
            pushes = (PushSpec(t_slots.reshape(-1),
                               {"h": h_contrib.reshape(-1, d)},
                               mean=True),
                      v_push(span_slots, ci, ctx_mask, neu1e, S))
            err_sum = jnp.sum(1e4 * g * g)          # word2vec.h:593
            err_cnt = t_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_grads_sg(self):
        """Skip-gram gradient phase.  Pair axis (B, 2W): input v[context],
        targets h[center]+K negatives sampled fresh *per pair* (word2vec.c
        semantics; the reference's learn_instance is the CBOW specialization
        of the same loop, word2vec.h:550-615).  Masked pairs (window
        padding) contribute nothing."""
        access = self.access
        transfer = self.transfer
        K = self.negative
        alpha = self.alpha
        d = self.len_vec

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     centers, contexts, ctx_mask, key):
            B, W2 = contexts.shape
            negs, neg_slots = sample_alias_slots(
                key, alias_prob, alias_idx, slot_of_vocab, (B, W2, K))
            # negative == center is skipped (word2vec.h:584-586); padding
            # pairs are fully dead.
            t_valid = jnp.concatenate(
                [jnp.ones((B, W2, 1), bool),
                 negs != centers[:, None, None]], axis=2)
            t_valid = t_valid & ctx_mask[..., None]
            c_slots = jnp.broadcast_to(
                slot_of_vocab[centers][:, None, None], (B, W2, 1))
            t_slots = jnp.where(
                t_valid, jnp.concatenate([c_slots, neg_slots], axis=2), -1)
            ctx_slots = jnp.where(ctx_mask, slot_of_vocab[contexts], -1)

            h_t = transfer.pull(
                state, t_slots.reshape(-1), access, fields=("h",)
            )["h"].reshape(B, W2, K + 1, d).astype(jnp.float32)
            v_in = transfer.pull(
                state, ctx_slots.reshape(-1), access, fields=("v",)
            )["v"].reshape(B, W2, d).astype(jnp.float32)

            f = jnp.einsum("bwd,bwkd->bwk", v_in, h_t)
            labels = jnp.concatenate(
                [jnp.ones((B, W2, 1)), jnp.zeros((B, W2, K))], axis=2)
            g = (labels - sigmoid_clipped(f)) * alpha
            g = jnp.where(t_valid, g, 0.0)                    # (B, W2, K+1)

            h_contrib = g[..., None] * v_in[:, :, None, :]    # (B,W2,K+1,d)
            v_contrib = jnp.einsum("bwk,bwkd->bwd", g, h_t)   # (B, W2, d)
            v_contrib = jnp.where(ctx_mask[..., None], v_contrib, 0.0)

            pushes = _assemble_push(
                t_slots.reshape(-1), ctx_slots.reshape(-1),
                h_contrib.reshape(-1, d), v_contrib.reshape(-1, d))

            err_sum = jnp.sum(1e4 * g * g)          # word2vec.h:593
            err_cnt = t_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_grads_sg_shared(self):
        """Skip-gram with a batch-shared negative pool (opt-in,
        ``sg: 1`` + ``shared_negatives: 1``) — the TPU-first rendering
        of BASELINE config #2's per-pair sampler.

        The parity sg phase draws K negatives per PAIR
        (word2vec.h:550-615 semantics), a B*2W*(K+1)-row random target
        gather — measured 96.5ms/step vs CBOW's 11.68ms on v5e, ~8x,
        entirely gather-bound (round-3 verdict Weak #6).  Sharing one
        K-negative pool across every pair in the batch keeps the same
        expected negative-term gradient (each pool pair weighted
        negative/K, the `_build_grads_shared` argument) and collapses
        the target gather to B + K rows:

          h gather:  B centers + K pool   instead of B*2W*(K+1)
          f_neg:     einsum (B,2W,d)x(K,d) -> (B,2W,K)   — MXU matmul
          gh_neg:    einsum (B,2W,K)x(B,2W,d) -> (K,d)   — DENSE, no
                     scatter for the pool at all
          v grads:   g_pos*h[center] + gw @ h_neg        — matmul

        Positive pairs and context rows keep per-key mean
        normalization; pool rows push as their own SUM family (the
        normalization-collapse hazard documented in
        _build_grads_shared applies identically here).  NOT loss-parity
        with the reference RNG stream — the parity sg mode stays the
        default; benches label this rendering ``sg_shared``."""
        access = self.access
        transfer = self.transfer
        K = self.shared_pool
        alpha = self.alpha
        d = self.len_vec

        def grads_fn(state, slot_of_vocab, alias_prob, alias_idx,
                     centers, contexts, ctx_mask, key):
            B, W2 = contexts.shape
            negs = sample_alias(key, alias_prob, alias_idx, (K,))
            c_slots = slot_of_vocab[centers]                  # (B,)
            n_slots = slot_of_vocab[negs]                     # (K,)
            ctx_slots = jnp.where(ctx_mask, slot_of_vocab[contexts], -1)

            pulled_h = transfer.pull(
                state, jnp.concatenate([c_slots, n_slots]), access,
                fields=("h",))["h"].astype(jnp.float32)
            h_pos = pulled_h[:B]                              # (B, d)
            h_neg = pulled_h[B:B + K]                         # (K, d)
            v_in = transfer.pull(
                state, ctx_slots.reshape(-1), access, fields=("v",)
            )["v"].reshape(B, W2, d).astype(jnp.float32)

            # positive pair (b, w): v[context] . h[center_b]
            f_pos = jnp.einsum("bwd,bd->bw", v_in, h_pos)     # (B, W2)
            g_pos = (1.0 - sigmoid_clipped(f_pos)) * alpha
            g_pos = jnp.where(ctx_mask, g_pos, 0.0)

            f_neg = jnp.einsum("bwd,kd->bwk", v_in, h_neg)    # MXU
            # negative == center skipped (word2vec.h:584-586); padding
            # pairs are fully dead
            n_valid = (negs[None, None, :] != centers[:, None, None]) \
                & ctx_mask[..., None]
            g_neg = jnp.where(n_valid,
                              (0.0 - sigmoid_clipped(f_neg)) * alpha, 0.0)
            # keep the objective's positive/negative balance at the
            # configured `negative` draws per pair
            gw = g_neg * (self.negative / K)                  # (B, W2, K)

            # per-pair positive grads -> h[center], per-key mean (same
            # normalization the parity sg push applies per pair)
            gh_pos = g_pos[..., None] * v_in                  # (B, W2, d)
            gh_neg = jnp.einsum("bwk,bwd->kd", gw, v_in)      # (K, d) MXU
            v_contrib = g_pos[..., None] * h_pos[:, None, :] \
                + gw @ h_neg                                  # (B, W2, d)
            v_contrib = jnp.where(ctx_mask[..., None], v_contrib, 0.0)

            pos_slots = jnp.where(
                ctx_mask, jnp.broadcast_to(c_slots[:, None], (B, W2)), -1)
            neg_slots = jnp.where(n_valid.any(axis=(0, 1)), n_slots, -1)
            pushes = (PushSpec(pos_slots.reshape(-1),
                               {"h": gh_pos.reshape(-1, d)}, mean=True),
                      PushSpec(neg_slots, {"h": gh_neg}),
                      PushSpec(ctx_slots.reshape(-1),
                               {"v": v_contrib.reshape(-1, d)}, mean=True))

            # loss terms carry the SAME negative/K weighting as the
            # gradients (advisor r04): a pair contributes ~1 positive +
            # ~`negative` weighted pool terms, so the reported loss is
            # scale-comparable with the per-pair parity sg rendering
            # instead of ~K/negative times off
            ratio = self.negative / K
            err_sum = jnp.sum(1e4 * g_pos * g_pos) \
                + ratio * jnp.sum(1e4 * g_neg * g_neg)
            err_cnt = ctx_mask.sum() + ratio * n_valid.sum()
            return pushes, err_sum, err_cnt

        return grads_fn

    def _build_apply(self):
        access = self.access
        transfer = self.transfer

        def apply_fn(state, pushes):
            for spec in pushes:
                if getattr(spec, "dense", False):
                    # capacity-shaped, pre-normalized grads (dense-logits
                    # mode): apply the access rule directly — untouched
                    # rows carry exact zero and are no-ops
                    new_fields = access.apply_push(state, spec.grads)
                    state = dict(state)
                    state.update(new_fields)
                elif getattr(spec, "counts", None) is not None:
                    # position-indexed span family (stencil rendering):
                    # rows are pre-summed with data counts — sort-free
                    # dedup path
                    state = transfer.push_span(
                        state, spec.slots, spec.grads, spec.counts,
                        access, mean=spec.mean)
                else:
                    state = transfer.push(state, spec.slots, spec.grads,
                                          access, mean=spec.mean)
            return state

        return apply_fn

    # -- training (word2vec.h:475-547) -------------------------------------
    def _epoch_items(self, batcher, batch_size: int, stencil: bool,
                     fuse: bool):
        """Render one epoch into a stream of work items: ``('group',
        host-stacked fields, [n_words...])`` for fuse groups and
        ``('single', fields, n_words)`` otherwise.  Pure host-side
        rendering — NO RNG (key splits stay with the consumer, in
        consumption order) and no device calls — so the stream is
        identical whether it is consumed inline or through the
        prefetch pipeline: the determinism contract of
        ``[worker] pipeline``."""
        inner = self.inner_steps
        group = []
        # control-plane frequency sketch: observe the center/token ids
        # HERE, on the rendering side (host numpy, thread-safe observe)
        # — consumption may see already-transferred device arrays when
        # the pipeline is on
        sketch = self._control_sketch

        def group_item():
            n_words = [b.n_words for b in group]
            fields = (_stack_group_host_stencil(group) if stencil
                      else _stack_group_host(group))
            if sketch is not None:
                sketch.observe(fields[0])
            return ("group", fields, n_words)

        epoch_iter = (batcher.epoch_stencil(batch_size) if stencil
                      else batcher.epoch(batch_size))
        for batch in epoch_iter:
            # every stencil batch is fixed-shape (padded span), so all
            # of them group-fuse, tails included
            if fuse and (stencil or len(batch.centers) == batch_size):
                group.append(batch)
                if len(group) == inner:
                    yield group_item()
                    group = []
                continue
            # odd-shaped batch: flush pending fused batches first so
            # the update order matches the unfused loop
            if group:
                yield group_item()
                group = []
            if stencil:
                fields = (batch.tokens, batch.sent_id,
                          batch.center_pos, batch.half)
            else:
                fields = (batch.centers, batch.contexts,
                          batch.ctx_mask)
            if sketch is not None:
                sketch.observe(fields[0])
            yield ("single", fields, batch.n_words)
        if group:                  # leftover partial group
            yield group_item()

    def train(self, data=None, niters: int = 1,
              batch_size: Optional[int] = None,
              checkpoint_path: Optional[str] = None,
              checkpoint_every: int = 1,
              checkpoint_retain: int = 1,
              start_iter: int = 0,
              batcher=None) -> List[float]:
        """``data``: corpus path or list of key-list sentences.  Returns
        per-iteration mean error (reference Error::norm per train_iter,
        word2vec.h:491).

        ``checkpoint_path``: mid-training full-fidelity checkpoints
        (optimizer state included) every ``checkpoint_every`` iterations —
        a capability the reference lacks (SURVEY.md §5: checkpoint-out only
        at exit, optimizer state dropped).  Resume with ``resume()``.
        ``checkpoint_retain`` keeps a last-k generation window on disk so
        a corrupted latest checkpoint can rewind (io/checkpoint.py).

        Every iteration reports to the fault/observability bus
        (``testing.faults.step_event``) — chaos plans and the resume
        loop's hang watchdog both hook there.

        ``batcher``: custom batch source with an ``epoch(batch_size)``
        iterator (e.g. the native C++ ``NativeCBOWBatcher``); its vocab
        indexing must match this model's vocab (both pipelines sort by
        (count desc, key asc), so python- and native-built vocabs agree)."""
        if batcher is None:
            if isinstance(data, str):
                data = load_corpus(data, min_sentence_length=max(
                    self.min_sentence_length, 1))
            if data is None:
                raise ValueError("train() needs data or a batcher")
            if self.vocab is None:
                self.build(data)
        elif self.vocab is None:
            if hasattr(batcher, "vocab"):
                self.build_from_vocab(batcher.vocab)
            else:
                raise RuntimeError(
                    "call build()/build_from_vocab() before train() with a "
                    "vocab-less batcher")
        hogwild = self.async_mode == "hogwild"
        nprocs = jax.process_count()
        if hogwild and nprocs > 1:
            # hogwild's worker axis spans ONE process's devices; the
            # measured multi-host substitute is the snapshot bounded-
            # staleness mode — loss envelope within +0.02% of hogwild
            # at realistic scale (docs/ARCHITECTURE.md "Async modes"),
            # so route there with a notice instead of refusing the run
            self.local_steps = max(self.local_steps, 2)
            log.warning(
                "async_mode=hogwild spans a single process's devices; "
                "multi-process run falls back to snapshot bounded "
                "staleness (local_steps=%d; measured loss envelope "
                "+0.02%% vs hogwild at realistic scale — see "
                "docs/ARCHITECTURE.md)", self.local_steps)
            hogwild = False
        stencil = bool(self.stencil)
        if stencil and hogwild:
            raise ValueError(
                "async_mode=hogwild drives per-pair batches; the stencil "
                "rendering composes with the snapshot (local_steps) "
                "async mode instead")
        if stencil and nprocs > 1:
            raise ValueError(
                "the stencil rendering is single-process for now "
                "(DistributedBatcher shards per-pair batches); drop "
                "stencil or run single-process")
        sync = self.local_steps <= 1 and not hogwild
        # fused multi-step only makes sense single-process (distributed
        # batches are global arrays that cannot be host-stacked)
        fuse = sync and self.inner_steps > 1 and nprocs == 1
        batch_size = batch_size or max(
            256, self.minibatch // (2 * self.window))
        if batcher is None:
            sents = data
            seed = 2008
            if nprocs > 1:
                # per-rank data shard + rank-decorrelated sampling: the
                # reference's "one file per node" distribution
                from swiftmpi_tpu.data.distributed import shard_sentences
                sents = shard_sentences(data)
                seed += jax.process_index()
            batcher = CBOWBatcher(sents, self.vocab, self.window,
                                  self.sample, seed=seed)
        if nprocs > 1:
            from swiftmpi_tpu.data.distributed import DistributedBatcher
            if not isinstance(batcher, DistributedBatcher):
                batcher = DistributedBatcher(batcher, self.cluster.mesh)
        # serving plane ([serve] every, serve/): arm the snapshot
        # publisher so concurrent EmbeddingReaders can pull bounded-
        # staleness views while this loop trains
        if self.serve_every > 0:
            self.serving_publisher()
        state = self.table.state
        frozen = state   # stale snapshot for the async mode
        losses = []
        meter = Throughput()
        step_i = 0
        hogwild_dropped = 0
        # telemetry plane ([worker] telemetry, obs/): reuse an outer
        # recorder (bench harness, trainer) or own one for this call.
        # The Throughput meter and transfer ledger keep their own
        # cumulative state, so they bridge into the registry through a
        # pre-snapshot sampler (set_total keeps the counters monotonic).
        tel_rec = obs.get_recorder()
        owns_rec = tel_rec is None
        if owns_rec:
            tel_rec = obs.configure(self.config, run="word2vec")
        if tel_rec is not None:
            def _tel_sample(reg, _m=meter):
                reg.counter("train/host_stall_ms_total").set_total(
                    _m.host_stall_ms())
                reg.counter("train/device_ms_total").set_total(
                    _m.device_ms())
                reg.gauge("train/words_per_sec").set(_m.rate())
            tel_rec.add_sampler(_tel_sample)
        if self.numerics_on and tel_rec is not None:
            self._arm_numerics(tel_rec)
        # wire tracer hot-key attribution ([obs] trace): the control
        # sketch's decayed counts replace the reservoir touch estimates.
        # build() armed control before obs.configure installed the
        # tracer, so the attach happens here too.
        _tracer = obs.get_tracer()
        if _tracer is not None and self._control_sketch is not None:
            _tracer.attach_sketch(self._control_sketch)
        # The tracer's window records are fed from the wire ledger's
        # landing points, which are behind the count_traffic opt-in
        # (one extra host reduce per push, no traced-value change) —
        # arm it so `[obs] trace: 1` records through the CLI without a
        # second knob.
        if _tracer is not None and hasattr(self.transfer, "count_traffic"):
            self.transfer.count_traffic = True
        # step compile AFTER numerics arming: the builders close over
        # self._numerics at trace time, and a first-time arm drops any
        # step compiled without the bundle
        if self._step is None:
            self._fused_cache = {}
            if hogwild:
                self._step = self._build_hogwild_step(
                    max(self.local_steps, 1))
            elif sync:
                self._step = self._build_step()
            else:
                self._step = (
                    obs.costs.track("w2v_grads",
                                    jax.jit(self._build_grads())),
                    obs.costs.track("w2v_apply",
                                    jax.jit(self._build_apply())))
        # -- input pipeline setup (tentpole: prefetch-rendered,
        # pre-transferred batches).  The producer is gated to paths
        # where it can own rendering wholesale: hogwild does its own
        # grouping, and multi-process batches are global jax.Arrays
        # already placed by DistributedBatcher.
        pipelined = (self.pipeline_depth > 0 and not hogwild
                     and nprocs == 1)
        if self.pipeline_depth > 0 and not pipelined:
            log.warning(
                "[worker] pipeline=%d requested but %s — running the "
                "synchronous input loop", self.pipeline_depth,
                "hogwild groups its own batches" if hogwild
                else "multi-process batches are already-placed global "
                     "arrays")
        dispatch_bound = resolve_dispatch_bound(self.dispatch_depth,
                                                pipelined=pipelined)
        transfer_fn = None
        pipe_stats = None
        if pipelined:
            from swiftmpi_tpu.io.pipeline import (PrefetchIterator,
                                                  device_put_transfer)
            # committed replicated input sharding, captured HERE on the
            # consumer thread: jax.default_device is thread-local and
            # must never be consulted by the producer
            input_sharding = jax.sharding.NamedSharding(
                self.cluster.mesh, jax.sharding.PartitionSpec())
            transfer_fn = device_put_transfer(input_sharding)
            pipe_stats = {"produced": 0, "consumed": 0,
                          "peak_queue_depth": 0, "stall_s": 0.0,
                          "transfer_s": 0.0}
        for it in range(niters):
            # global step: cumulative across resumed runs, so a fault
            # plan's crash-at-step-k means "after k completed steps"
            # regardless of how many attempts it took to get there
            faults.step_event(start_iter + it)
            if faults.consume_nan():
                state = self._poison_row(state)
                frozen = state
            if hogwild:
                err_sum, err_cnt, it_dropped = self._hogwild_epoch(
                    batcher, batch_size, meter)
                hogwild_dropped += it_dropped
                state = self.table.state
                # hogwild groups its own dispatches; publish at epoch
                # granularity (the mode's natural consistency point)
                self._serve_on_steps(1)
            else:
                # Per-batch loss scalars are QUEUED as device arrays
                # and fetched once at epoch end: a float(es) per batch
                # is a blocking round trip that serializes dispatch
                # (through the axon tunnel that is ~5ms/batch of pure
                # stall).  Summed host-side in Python ints at the end —
                # an on-device int32 accumulator would wrap at ~2.1e9
                # target pairs, i.e. exactly the corpus sizes this
                # optimization targets.
                es_q, ec_q = _LossAccum(dispatch_bound), _LossAccum(None)

                def run_single(fields, n_words):
                    nonlocal state, frozen, step_i
                    self._key, sub = jax.random.split(self._key)
                    args = (self._slot_of_vocab, self._alias_prob,
                            self._alias_idx,
                            *(_dev(f) for f in fields), sub)
                    if sync:
                        with obs.span("dispatch"):
                            state, es, ec = self._step(state, *args)
                        # the step donates (deletes) the input state
                        # buffers; repoint the table at the live ones
                        # immediately so an abnormal exit (raise, Ctrl-C)
                        # never strands the model with deleted arrays
                        self.table.state = state
                    else:
                        # async/global variant, bounded-staleness flavor
                        # (word2vec_global.h:577-651): grads computed
                        # against a stale snapshot, pushes land
                        # immediately; snapshot refreshes every
                        # local_steps batches => bounded staleness.
                        grads_fn, apply_fn = self._step
                        with obs.span("dispatch"):
                            pushes, es, ec = grads_fn(frozen, *args)
                            state = apply_fn(state, pushes)
                        self.table.state = state
                        step_i += 1
                        if step_i % self.local_steps == 0:
                            frozen = state
                    es_q.add(es)
                    ec_q.add(ec)
                    meter.record(n_words)
                    obs.record_step(1)
                    self._serve_on_steps(1)
                    if self._control_on_steps(1):
                        # an applied decision re-laid out the table (or
                        # rebuilt the step): repoint the loop-local
                        # state — and the async snapshot, whose rows sit
                        # at pre-repartition slots — at the remapped one
                        state = self.table.state
                        frozen = state

                def run_group(fields, n_words):
                    # update ORDER is preserved either way: a group runs
                    # its batches sequentially inside one scan dispatch.
                    # Partial groups (the epoch tail) fuse too, via the
                    # per-length compiled cache — a small corpus's epoch
                    # is a handful of batches, and dispatching them
                    # one-by-one pays ~5ms tunnel latency each (round-3
                    # verdict Weak #4).  A lone batch uses the already-
                    # compiled single step.
                    nonlocal state
                    L = len(n_words)
                    fused = self._fused_for(L) if L > 1 else None
                    if fused is None:
                        # lone batch, or an uncached tail length while
                        # tail-fuse compiles are frozen (timed regions):
                        # peel the stacked fields back into singles —
                        # the producer never needs to know compile-cache
                        # state, so the item stream stays deterministic
                        for i in range(L):
                            run_single(tuple(f[i] for f in fields),
                                       n_words[i])
                        return
                    self._key, sub = jax.random.split(self._key)
                    with obs.span("dispatch"):
                        state, es, ec = fused(
                            state, self._slot_of_vocab, self._alias_prob,
                            self._alias_idx,
                            *(_dev(f) for f in fields), sub)
                    self.table.state = state
                    es_q.add(es)
                    ec_q.add(ec)
                    # a fused group is ONE dispatch but L train steps;
                    # stall_ms_per_step stays per-step across fuse modes
                    meter.record(sum(n_words), steps=L)
                    obs.record_step(L)
                    self._serve_on_steps(L)
                    if self._control_on_steps(L):
                        state = self.table.state

                items = self._epoch_items(batcher, batch_size, stencil,
                                          fuse)
                pipe = None
                if pipelined:
                    pipe = PrefetchIterator(
                        items, depth=self.pipeline_depth,
                        transfer=transfer_fn)
                    items = pipe
                try:
                    items = iter(items)
                    while True:
                        # the stall clock covers exactly the input
                        # wait: inline it times rendering + stacking,
                        # pipelined it times empty-queue waits — one
                        # meter for both, so host_stall_ms is directly
                        # comparable across the two modes
                        with meter.stalling():
                            nxt = next(items, None)
                        if nxt is None:
                            break
                        kind, fields, n_words = nxt
                        if kind == "group":
                            run_group(fields, n_words)
                        else:
                            run_single(fields, n_words)
                finally:
                    if pipe is not None:
                        pipe.close()
                        for k, v in pipe.stats().items():
                            if k == "peak_queue_depth":
                                pipe_stats[k] = max(pipe_stats[k], v)
                            elif k != "depth":
                                pipe_stats[k] += v
                err_sum = es_q.total()
                err_cnt = int(round(ec_q.total()))
            loss = err_sum / max(err_cnt, 1)
            losses.append(loss)
            log.info("iter %d: error %.5f  (%.0f words/s)",
                     it, loss, meter.rate())
            if checkpoint_path and (it + 1) % checkpoint_every == 0:
                self.table.state = state
                from swiftmpi_tpu.io.checkpoint import (npz_path,
                                                        save_checkpoint)
                # cumulative iteration: a resumed run must not rewind the
                # counter, or a later resume re-trains finished iters
                ck_extra = {"iter": np.int64(start_iter + it + 1)}
                if self._numerics is not None \
                        and self._numerics.detector is not None:
                    # baselines ride along so a resumed run scores its
                    # first windows against the learned regime instead
                    # of re-warming (and false-alarming) from scratch
                    self._numerics.sync()
                    ck_extra["numerics"] = \
                        self._numerics.detector.state_bytes()
                save_checkpoint(
                    self.table, checkpoint_path,
                    extra=ck_extra,
                    retain=checkpoint_retain)
                log.info("checkpoint @ iter %d -> %s", start_iter + it + 1,
                         checkpoint_path)
                faults.checkpoint_event(npz_path(checkpoint_path))
        self.table.state = state
        # final publish: readers see the trained state no matter where
        # the every-K cadence landed
        self._serve_publish()
        # observability surface (returned data, not just logs): the
        # hogwild drop bound is testable and the hybrid backend's
        # traffic counters ride along for bench detail fields
        self.train_metrics = {
            "hogwild_skipped_tail_words": hogwild_dropped,
            # host-stall vs device-time split (utils.timers.Throughput):
            # which side of the step loop is the bottleneck
            "host_stall_ms": meter.host_stall_ms(),
            "device_ms": meter.device_ms(),
            "stall_ms_per_step": meter.stall_ms_per_step(),
            "words_per_sec": meter.rate(),
            "pipeline_depth": self.pipeline_depth if pipelined else 0}
        if pipe_stats is not None:
            self.train_metrics["pipeline"] = dict(pipe_stats)
        if self.controller is not None:
            self.train_metrics["control"] = {
                **self.controller.summary(),
                "recompiles": self._control_recompiles}
        if hasattr(self.transfer, "traffic"):
            # traffic() drains queued eager counts through _accum_wire,
            # so the registry mirror is exact before the summary lands
            self.train_metrics["transfer_traffic"] = \
                self.transfer.traffic()
        if self._numerics is not None:
            # drain in-flight bundle callbacks (safe point: dispatches
            # retired), then disarm the process-global quant tap — a
            # numerics-off model training next in this process must
            # trace (and book) nothing
            from swiftmpi_tpu.transfer import api as transfer_api
            self._numerics.sync()
            transfer_api.clear_numerics_tap()
            det = self._numerics.detector
            self.train_metrics["numerics"] = {
                "bundles": self._numerics.bundles,
                "anomalies": det.anomalies_emitted if det else 0}
        prof = obs.get_profiler()
        if prof is not None:
            # training ended inside a capture window: stop the trace
            # and land the summary artifact anyway (short runs,
            # profile_at near the end)
            prof.close()
        if owns_rec and tel_rec is not None:
            tel_rec.close()
            obs.uninstall_recorder()
        return losses

    def _hogwild_epoch(self, batcher, batch_size: int, meter) -> tuple:
        """One epoch in hogwild mode: group ``n_workers * local_steps``
        fixed-shape batches per dispatch, one per worker-step.  A tail
        too short for a full group is dropped, logged, AND returned (the
        third element of the result; summed into
        ``train_metrics["hogwild_skipped_tail_words"]``).  Workers in
        the reference's async mode likewise end an iteration unevenly —
        word2vec_global.h:630-651 joins threads wherever they ran out.

        Drop bound: per epoch at most ``group - 1`` full batches plus
        the partial batches the batcher emits — under
        ``group * batch_size * (1 + 2*window)`` words, a vanishing
        fraction of any corpus large enough to satisfy the no-group
        RuntimeError below.  The documented-drop-bound route is chosen
        over pad+mask, which would compile a second (padded) step shape
        per epoch to recover that fraction."""
        step, n_workers = self._step
        group = n_workers * max(self.local_steps, 1)
        state = self.table.state
        es_q, ec_q = _LossAccum(), _LossAccum(None)
        buf = []
        dropped = 0
        for batch in batcher.epoch(batch_size):
            if len(batch.centers) != batch_size:
                dropped += batch.n_words
                continue
            buf.append(batch)
            if len(buf) < group:
                continue
            self._key, sub = jax.random.split(self._key)
            c, x, m = _stack_group(buf)
            state, es, ec = step(state, self._slot_of_vocab,
                                 self._alias_prob, self._alias_idx,
                                 c, x, m, sub)
            self.table.state = state
            es_q.add(es)
            ec_q.add(ec)
            meter.record(sum(b.n_words for b in buf), steps=len(buf))
            obs.record_step(len(buf))
            buf = []
        if buf:
            dropped += sum(b.n_words for b in buf)
        err_sum = es_q.total()
        err_cnt = int(round(ec_q.total()))
        if err_cnt == 0:
            raise RuntimeError(
                f"hogwild epoch dispatched NO group: the corpus yielded "
                f"fewer than {group} full batches of {batch_size} centers "
                f"(group = {group // max(self.local_steps, 1)} workers x "
                f"{max(self.local_steps, 1)} local_steps).  Lower "
                f"batch_size/local_steps or use more data — otherwise the "
                f"run would silently train nothing")
        if dropped:
            log.info("hogwild: %d tail words skipped this iter (need "
                     "full groups of %d batches x %d centers)",
                     dropped, group, batch_size)
        return err_sum, err_cnt, dropped

    def grow(self, new_capacity_per_shard: int) -> None:
        """Mid-run table growth (reference dense_hash_map self-growth,
        sparsetable.h:17-149 — here an explicit HBM re-layout).  Owns the
        post-grow fixups a bare ``table.grow()`` would leave stale: the
        jitted step bakes in the old capacity (the push scatter
        bounds), and the cached vocab->slot map holds old-layout slots —
        either one silently corrupts scatters if kept."""
        self.table.grow(new_capacity_per_shard)
        self._step = None
        if self.vocab is not None:
            slots = self.table.key_index.lookup(self.vocab.keys)
            self._slot_of_vocab = jnp.asarray(slots, jnp.int32)

    def resume(self, checkpoint_path: str) -> int:
        """Restore a mid-training checkpoint; returns the iteration it was
        taken at.  The cached vocab->slot map is rebuilt against the
        restored key index so continued training touches the right rows
        even if the checkpoint's slot assignment differs from build()'s."""
        from swiftmpi_tpu.io.checkpoint import load_checkpoint
        if self.table is None:
            raise RuntimeError("build() or load() the model before resume()")
        extra = load_checkpoint(self.table, checkpoint_path)
        # load_checkpoint grows the table for post-grow() checkpoints; any
        # cached jitted step baked in the old capacity (the push
        # scatter bounds), so force a rebuild
        self._step = None
        # a restore can rewind the @rowver plane; a warm pull cache
        # could then false-hit on a re-used version stamp.  A resumed
        # worker always restarts cold (pull_cache.py invalidation
        # contract; the chaos test pins this).
        self.transfer.pull_shadow_flush()
        if self.vocab is not None:
            slots = self.table.key_index.lookup(self.vocab.keys)
            self._slot_of_vocab = jnp.asarray(slots, jnp.int32)
        num_state = extra.get("numerics")
        if num_state is not None:
            # detector baselines ride the checkpoint (ISSUE 13): loaded
            # now if the plane is already armed, else stashed for
            # _arm_numerics — either way the first post-restore window
            # scores against the learned regime, not a cold baseline
            if self._numerics is not None \
                    and self._numerics.detector is not None:
                self._numerics.detector.load_state_bytes(num_state)
            else:
                self._numerics_restore = num_state
        return int(extra.get("iter", 0))

    # -- embeddings out/in (word2vec.h:100-117; cluster.h:41-54) -----------
    def save(self, path: str) -> int:
        # reference WParam layout: v TAB h (word2vec.h:100-110); fields mode
        # routes through the native C++ writer when available
        return dump_table_text(self.table, path, fields=("v", "h"))

    def load(self, path: str) -> int:
        if self.table is None:
            if self._capacity_per_shard is None:
                raise RuntimeError("set capacity_per_shard before load()")
            self.table = self.cluster.create_table(
                "w2v", self.access, self._capacity_per_shard)
        n = load_table_text(self.table, path, fields=("v", "h"))
        self._step = None    # text load may have grown the table
        if self.vocab is not None:
            # growth remaps slots (KeyIndex.grow re-lays out
            # shard*cap+local); a stale cached map would make
            # embedding_index()/the fused step gather unrelated rows
            slots = self.table.key_index.lookup(self.vocab.keys)
            self._slot_of_vocab = jnp.asarray(slots, jnp.int32)
        return n

    def embedding(self, key: int) -> Optional[np.ndarray]:
        """Input-side (v) vector for an external key, or None."""
        if key not in self.table.key_index:
            return None
        slot = self.table.key_index.slot(key)
        n_hot = self.table.n_hot
        if slot < n_hot:            # replicated hot head (hybrid)
            from swiftmpi_tpu.parameter.sparse_table import hot_name
            return np.asarray(self.table.state[hot_name("v")][slot])
        return np.asarray(
            self.table.state["v"][slot - n_hot])  # one-row transfer

    def serving_publisher(self):
        """The model's :class:`~swiftmpi_tpu.serve.snapshot
        .SnapshotPublisher` — armed on first call (or by ``train()``
        when ``[serve] every > 0``).  Attach
        :class:`~swiftmpi_tpu.serve.reader.EmbeddingReader` instances to
        it from any number of query threads; ``train()`` publishes a
        versioned snapshot of the table (state + key→slot map) every
        ``[serve] every`` consumed steps."""
        if self.serve_publisher is None:
            from swiftmpi_tpu.serve.snapshot import SnapshotPublisher
            self.serve_publisher = SnapshotPublisher(
                every=max(self.serve_every, 1), depth=self.serve_depth)
        return self.serve_publisher

    def _serve_on_steps(self, n: int) -> None:
        """Trainer-thread publication hook: account ``n`` consumed steps
        and publish when the staleness bound is reached.  The key→slot
        view is captured HERE, on the trainer thread — a ``grow()`` can
        never be mid-flight, so readers always see a matched
        (state, key map) pair."""
        pub = self.serve_publisher
        if pub is None:
            return
        pub.on_steps(self.table, n=n, keys=lambda: self.vocab.keys,
                     slots=lambda: np.asarray(self._slot_of_vocab),
                     meta={"query_field": "v"})

    def _serve_publish(self) -> None:
        """Unconditional publish (end of train(): readers should see the
        final state regardless of where the every-K cadence landed)."""
        pub = self.serve_publisher
        if pub is None:
            return
        pub.publish(self.table, keys=lambda: self.vocab.keys,
                    slots=lambda: np.asarray(self._slot_of_vocab),
                    meta={"query_field": "v"})

    # -- adaptive control plane (control/; [control] section) --------------
    def _arm_control(self) -> None:
        """Construct the control plane for this model: the decayed
        id-frequency sketch (seeded from the build-time vocab counts so
        evaluation 0 reproduces the static calibration — no startup
        flap), the knob registry, and the controller.  Knob appliers
        own the safe-point machinery: re-partition via
        ``SparseTable.repartition`` plus the grow()-style cache fixups."""
        from swiftmpi_tpu.control import Controller, DecayedSketch, Knob
        st = self.control_settings
        keys = np.asarray(self.vocab.keys, np.uint64)
        self._control_key_order = np.argsort(keys, kind="stable")
        self._control_sorted_keys = keys[self._control_key_order]
        self._control_sketch = DecayedSketch(
            len(self.vocab), decay=st.decay,
            seed_counts=self.vocab.counts)
        self._control_recompiles = 0
        knobs = []
        if getattr(self.transfer, "name", "") == "hybrid":
            knobs.append(Knob(
                "hot_k",
                current=lambda: int(self.table.key_index.n_hot),
                propose=self._propose_hot_k,
                apply=self._apply_hot_k,
                describe=lambda p: {"n_hot": int(p.n_hot),
                                    "head_mass": p.head_mass}))
        if self.inner_steps > 1 and hasattr(self.transfer,
                                            "push_window"):
            knobs.append(Knob(
                "push_window",
                current=lambda: int(self.push_window_size),
                propose=self._propose_push_window,
                apply=self._apply_push_window))
            knobs.append(Knob(
                "wire_format",
                current=lambda: float(
                    self.transfer.window_expected_unique or 0.0),
                propose=self._propose_wire,
                apply=self._apply_wire))
        if (self.collective_mode != "psum"
                and getattr(self.transfer, "name", "") == "hybrid"
                and self.inner_steps > 1
                and hasattr(self.transfer, "push_window")):
            # collective crossover input: the hot-touch density the
            # sparse-allreduce pricing reads (transfer/plan.py
            # compile_hot_plan keys its cache on it, so an apply is a
            # reprice, not an invalidation protocol)
            knobs.append(Knob(
                "collective",
                current=lambda: float(
                    self.transfer.hot_touched_fraction or 0.0),
                propose=self._propose_collective,
                apply=self._apply_collective))
        self.controller = Controller(st, transfer=self.transfer,
                                     sketch=self._control_sketch,
                                     knobs=knobs)
        # wire tracer hot-key attribution: the sketch's decayed counts
        # replace the reservoir's touch estimates (obs/trace.py)
        tracer = obs.get_tracer()
        if tracer is not None:
            tracer.attach_sketch(self._control_sketch)

    def _control_on_steps(self, n: int) -> bool:
        """Trainer-thread control hook — called at the same safe points
        the serving plane publishes at (no dispatch in flight, table
        state current).  Returns True when an applied decision re-laid
        out the table or rebuilt the compiled step, i.e. the train
        loop must refresh its local state reference."""
        ctl = self.controller
        if ctl is None:
            return False
        self._control_dirty = False
        ctl.on_steps(n)
        return self._control_dirty

    def _control_mass(self, keys_arr, counts) -> float:
        """Sketch mass carried by a key set (keys must be vocab keys)."""
        keys_arr = np.asarray(keys_arr, np.uint64).ravel()
        if keys_arr.size == 0:
            return 0.0
        pos = np.searchsorted(self._control_sorted_keys, keys_arr)
        pos = np.minimum(pos, self._control_sorted_keys.size - 1)
        return float(counts[self._control_key_order[pos]].sum())

    def _rebuild_step(self) -> None:
        """Safe-point recompile: a knob change that moves rows or
        reshapes the window program invalidates every compiled step
        (capacity, n_hot and the window layout are baked in at trace
        time) — the ``grow()`` fixup contract, owned here for the
        control-plane appliers."""
        self._fused_cache = {}
        if self.async_mode == "hogwild":
            # control hooks never fire on the hogwild path; a stale
            # step cannot be reached, but drop it anyway for symmetry
            self._step = None
        elif self.local_steps <= 1:
            self._step = self._build_step()
        else:
            self._step = (
                obs.costs.track("w2v_grads",
                                jax.jit(self._build_grads())),
                obs.costs.track("w2v_apply",
                                jax.jit(self._build_apply())))
        self._control_recompiles += 1
        self._control_dirty = True

    def _propose_hot_k(self, counts, delta):
        """Re-run the hot/cold calibration on the decayed histogram.
        Win = token-mass points the re-derived hot set captures over
        the current one, under the CURRENT traffic distribution."""
        if counts is None:
            return None
        total = float(counts.sum())
        if total <= 0:
            return None
        from swiftmpi_tpu.control import Proposal
        from swiftmpi_tpu.parameter.key_index import HotColdPartition
        # x1024: from_counts quantizes to int64 — keep ~10 fractional
        # bits of the decayed histogram instead of truncating it
        part = HotColdPartition.from_counts(
            self.vocab.keys, counts * 1024.0, batch_rows=self.minibatch)
        cur = self.table.key_index.partition
        if cur is not None and part == cur:
            return None
        new_mass = self._control_mass(part.hot_keys, counts) / total
        cur_mass = (self._control_mass(cur.hot_keys, counts) / total
                    if cur is not None and cur.n_hot else 0.0)
        return Proposal(part, new_mass - cur_mass, {
            "old_n_hot": int(cur.n_hot) if cur is not None else 0,
            "new_n_hot": int(part.n_hot),
            "old_head_mass": cur_mass, "new_head_mass": new_mass,
            "sketch_observed": int(self._control_sketch.observed)})

    def _apply_hot_k(self, part, evidence) -> bool:
        """Re-partition at the safe point.  A shard without room for
        the demoted rows rejects the decision (CapacityError is raised
        before any mutation — the table is untouched)."""
        from swiftmpi_tpu.parameter.key_index import CapacityError
        try:
            plan = self.table.repartition(part)
        except CapacityError as e:
            evidence["error"] = str(e)
            return False
        evidence["moved_rows"] = int(plan.moved_rows)
        slots = self.table.key_index.lookup(self.vocab.keys)
        self._slot_of_vocab = jnp.asarray(slots, jnp.int32)
        self._rebuild_step()
        return True

    def _propose_push_window(self, counts, delta):
        """Retune the window width over {W/2, W, 2W} (capped at
        inner_steps — the staleness bound W-1 never exceeds one fused
        group).  Cost = expected unique rows on the wire per train
        step, E[U(w*B)]/w — row_bytes cancels out of the comparison."""
        if counts is None:
            return None
        from swiftmpi_tpu.cluster.hashfrag import expected_unique_rows
        from swiftmpi_tpu.control import Proposal
        W = self.push_window_size
        B = self.minibatch
        cands = sorted({max(1, W // 2), W,
                        min(2 * W, max(self.inner_steps, 1))})
        if len(cands) == 1:
            return None

        def cost(w):
            return expected_unique_rows(counts, w * B) / w

        cur_cost = cost(W)
        if cur_cost <= 0:
            return None
        best = min(cands, key=cost)
        if best == W:
            return None
        return Proposal(int(best), (cur_cost - cost(best)) / cur_cost, {
            "old_w": int(W), "new_w": int(best),
            "rows_per_step_old": cur_cost,
            "rows_per_step_new": cost(best)})

    def _apply_push_window(self, w, evidence) -> bool:
        w = int(w)
        self.push_window_size = w
        if hasattr(self.transfer, "window_expected_unique"):
            from swiftmpi_tpu.cluster.hashfrag import \
                expected_unique_rows
            self.transfer.window_expected_unique = (
                expected_unique_rows(self._control_sketch.counts,
                                     w * self.minibatch)
                if w > 1 else None)
        self._rebuild_step()
        return True

    def _propose_wire(self, counts, delta):
        """Refresh the per-window wire-format crossover input: the
        expected unique-row count under the DECAYED histogram.  Win =
        relative drift of E[U] since it was last baked in.  Evidence
        carries the priced format the crossover would pick under the old
        vs the new estimate (a representative one-field window family),
        so a decision log shows when a retune actually flips the baked
        format rather than just nudging the estimate."""
        if counts is None or self.push_window_size <= 1:
            return None
        old = getattr(self.transfer, "window_expected_unique", None)
        if old is None:
            return None
        from swiftmpi_tpu.cluster.hashfrag import expected_unique_rows
        from swiftmpi_tpu.control import Proposal
        from swiftmpi_tpu.parameter.key_index import window_wire_format
        new = expected_unique_rows(
            counts, self.push_window_size * self.minibatch)
        d = self.len_vec
        row_bytes = 4 + 4 * d + 4          # i32 index + f32 row + counts
        qrb = 4 + (d + 4 if self.wire_quant == "int8" else 2 * d) + 4 \
            if self.wire_quant != "off" else None
        rows = self.push_window_size * self.minibatch

        def _fmt(eu):
            return window_wire_format(
                rows, self.table.capacity, row_bytes,
                dense_ratio=self.transfer.wire_dense_ratio("window"),
                expected_unique=eu, quant=self.wire_quant,
                quant_row_bytes=qrb,
                quant_guard=self.transfer.wire_quant_guard,
                sketch=bool(getattr(self.transfer, "wire_sketch", False)))

        return Proposal(float(new), abs(new - old) / max(float(old), 1.0),
                        {"old_expected_unique": float(old),
                         "new_expected_unique": float(new),
                         "old_format": _fmt(float(old)),
                         "new_format": _fmt(float(new))})

    def _propose_collective(self, counts, delta):
        """Refresh the hot-touch density the collective crossover
        prices by (key_index.price_hot_collectives): recompute the
        expected touched fraction of the hot head under the DECAYED
        histogram — the same saturation model the build seeds from the
        static vocab counts.  Win = relative drift of the fraction.
        Evidence carries the collective the crossover would pick under
        the old vs new density (a representative one-field family, like
        _propose_wire's), so the decision log shows when a retune flips
        the baked collective rather than just nudging the signal."""
        if counts is None or self.push_window_size <= 1:
            return None
        n_hot = int(self.table.key_index.n_hot)
        if n_hot <= 0:
            return None
        old = getattr(self.transfer, "hot_touched_fraction", None)
        if old is None:
            return None
        from swiftmpi_tpu.control import Proposal
        from swiftmpi_tpu.parameter.key_index import price_hot_collectives
        c = np.asarray(counts, np.float64).ravel()
        total = c.sum()
        if total <= 0:
            return None
        head = np.sort(c)[::-1][:n_hot] / total
        draws = self.push_window_size * self.minibatch
        new = min(float(np.sum(-np.expm1(
            draws * np.log1p(-np.minimum(head, 1.0))))) / n_hot, 1.0)

        def _pick(frac):
            decision, _ = price_hot_collectives(
                n_hot, 4 * self.len_vec + 4, frac,
                sparse_ar_ratio=self.transfer.sparse_ar_ratio)
            return decision

        return Proposal(float(new),
                        abs(new - old) / max(float(old), 1e-6),
                        {"old_touched_fraction": float(old),
                         "new_touched_fraction": float(new),
                         "old_collective": _pick(float(old)),
                         "new_collective": _pick(float(new))})

    def _apply_collective(self, frac, evidence) -> bool:
        self.transfer.hot_touched_fraction = float(frac)
        # the collective is baked into the compiled reconcile at trace
        # time; the hot plan cache keys on the density signal, so this
        # write IS the reprice — recompile so it takes effect at this
        # safe point
        self._rebuild_step()
        return True

    def _apply_wire(self, eu, evidence) -> bool:
        self.transfer.window_expected_unique = float(eu)
        # the wire-format decision is host-static, baked at trace time
        # (the TrafficPlan compiled in transfer/api.py's window
        # interpreter; the plan cache keys on expected_unique, so this
        # write invalidates the cached plan) — recompile so the new
        # crossover takes effect at this safe point
        self._rebuild_step()
        return True

    # -- numerics health plane (obs/numerics.py; [obs] numerics) -----------
    def _arm_numerics(self, tel_rec) -> None:
        """Arm the numerics health plane for this train() call: build
        the collector + detector once, restore checkpointed baselines,
        install the registry sampler on the recorder, point the
        transfer-wide quantization-error tap at the collector, and
        register the Controller demote hook.  A first-time arm drops
        any step compiled before it — the traced bundle is baked in at
        trace time, so train() compiles AFTER this runs."""
        from swiftmpi_tpu.obs import numerics as obs_numerics
        from swiftmpi_tpu.transfer import api as transfer_api
        if self._numerics is None:
            det = obs_numerics.detector_from_config(self.config)
            if self._numerics_restore is not None:
                det.load_state_bytes(self._numerics_restore)
                self._numerics_restore = None
            self._numerics = obs_numerics.NumericsCollector(detector=det)
            self._step = None
            self._fused_cache = {}
            if self.controller is not None:
                self.controller.attach_numerics(det, self._numerics_demote)
        transfer_api.set_numerics_tap(self._numerics.quant_tap)
        if id(tel_rec) != self._numerics_rec_id:
            # one sampler per recorder: train() may be called repeatedly
            # against the same long-lived recorder (bench harness)
            tel_rec.add_sampler(self._numerics.sampler)
            self._numerics_rec_id = id(tel_rec)

    def _poison_row(self, state: dict) -> dict:
        """``nan`` fault consumption (testing/faults.py): overwrite one
        live parameter row with NaN — the injectable stand-in for a
        numerics blow-up the health plane must catch.  Returns the new
        state (also installed on the table)."""
        f = self.access.grad_fields[0]
        state = dict(state)
        state[f] = jnp.asarray(state[f]).at[0].set(jnp.nan)
        self.table.state = state
        log.warning("fault injection: poisoned %s row 0 with NaN", f)
        return state

    def _numerics_demote(self, anomaly: dict) -> Optional[str]:
        """Controller-applied numerics action: sustained EF-residual
        runaway drops ``wire_quant`` to lossless at the control plane's
        safe point — the quantizer is banking error faster than the
        residual drains, and kept on int8 the model walks away from the
        lossless trajectory.  ``pull_quant`` is demoted on the same
        trigger (the read-side quantizer feeds the same forward pass;
        OPERATIONS.md documents this as the pull plane's escape hatch —
        the lossless pull cache stays armed).  Returns the previous
        setting (for the decision event) or None when already
        lossless."""
        old_w, old_p = self.wire_quant, self.pull_quant
        if old_w == "off" and old_p == "off":
            return None
        log.warning(
            "numerics: sustained EF residual runaway on %s — demoting "
            "wire_quant %s -> off, pull_quant %s -> off",
            anomaly.get("series"), old_w, old_p)
        self.wire_quant = "off"
        self.pull_quant = "off"
        if hasattr(self.transfer, "wire_quant"):
            self.transfer.wire_quant = "off"
        self.transfer.pull_quant = "off"
        self._rebuild_step()
        return old_w if old_w != "off" else f"pull:{old_p}"

    def embedding_index(self, field: str = "v"):
        """Cosine-similarity index over the LIVE table (no dump round
        trip): ``model.embedding_index().neighbors(key)`` /
        ``.analogy(a, b, c)``.  Snapshot semantics — build after
        training (or rebuild to see newer updates).  The reference has
        no in-process query path at all (dump + external scripts)."""
        from swiftmpi_tpu.models.embedding import EmbeddingIndex

        if self.vocab is None:
            # load() restores table rows but not a vocab; a dump-only
            # workflow should index the dump file directly
            raise RuntimeError(
                "no vocab; build()/build_from_vocab() first (after a "
                "bare load(), use EmbeddingIndex.from_text on the dump)")
        slots = np.asarray(self._slot_of_vocab)
        vecs = self.table.unified_rows_host(field)[slots]
        return EmbeddingIndex(self.vocab.keys, vecs)
