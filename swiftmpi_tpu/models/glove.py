"""GloVe (global word-vector factorization) on the TPU parameter server.

Beyond the reference's app set (SURVEY.md §2.5 lists LR, word2vec,
sent2vec) — included to show the framework's worker API generalizes past
its three ported apps: GloVe's original trainer is **server-side AdaGrad
over a sharded sparse table**, exactly the reference's parameter-server
contract (accessmethod.h plugins + pull/push), so the whole model is an
access-method schema plus one fused jitted step.

Math (Pennington et al. 2014): for each co-occurrence count x_ij,

    J_ij = w_i . wt_j + b_i + bt_j - log(x_ij)
    loss = f(x_ij) * J_ij^2,   f(x) = min((x / x_max)^alpha, 1)

with symmetric-window counts weighted 1/distance, trained by AdaGrad on
(w, b) of the focus word and (wt, bt) of the context word.  The final
embedding is the standard w + wt sum.

TPU-first shape: the co-occurrence set is built ONCE host-side as COO
arrays, then every epoch is a shuffled `lax.scan` over fused minibatch
steps — two row gathers, elementwise math, two mean-normalized pushes
through the transfer layer (the same path word2vec's h/v families
take).  No per-pair host work, no dynamic shapes.

Config section ``[glove]``: ``len_vec`` (default 100), ``window`` (10),
``x_max`` (100), ``alpha`` (0.75), ``learning_rate`` (0.05),
``minibatch`` (4096), plus ``[worker] inner_steps`` like the other
models.
"""

from __future__ import annotations

from typing import Dict, List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.cluster.cluster import Cluster
from swiftmpi_tpu.data.text import Vocab, build_vocab
from swiftmpi_tpu.io.checkpoint import dump_table_text
from swiftmpi_tpu.parameter.access import (AdaGradAccess, AdaGradRule,
                                           FieldSpec, vec_rand_init,
                                           zeros_init)
from swiftmpi_tpu.utils.config import ConfigParser, global_config
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)


def glove_access(learning_rate: float, len_vec: int) -> AdaGradAccess:
    """One table keyed by word: focus (w, b) and context (wt, bt)
    families with per-element AdaGrad sums — the optimizer GloVe
    shipped with, already the framework's native access method."""
    return AdaGradAccess(
        learning_rate,
        rules=(AdaGradRule("w", "w2sum", "w"),
               AdaGradRule("wt", "wt2sum", "wt"),
               AdaGradRule("b", "b2sum", "b"),
               AdaGradRule("bt", "bt2sum", "bt")),
        fields={"w": FieldSpec(len_vec, vec_rand_init),
                "wt": FieldSpec(len_vec, vec_rand_init),
                "b": FieldSpec(1, zeros_init),
                "bt": FieldSpec(1, zeros_init),
                "w2sum": FieldSpec(len_vec, zeros_init),
                "wt2sum": FieldSpec(len_vec, zeros_init),
                "b2sum": FieldSpec(1, zeros_init),
                "bt2sum": FieldSpec(1, zeros_init)},
        pull_fields=("w", "wt", "b", "bt"),
    )


def cooccurrence(sentences, vocab: Vocab, window: int
                 ) -> Tuple[np.ndarray, np.ndarray, np.ndarray]:
    """Symmetric-window co-occurrence counts, weight ``1/distance``
    (the GloVe paper's decreasing weighting).  Returns COO arrays
    (focus_idx, ctx_idx, weight) over VOCAB indices, deduplicated.

    Vectorized per offset: for distance k every in-sentence token pair
    (t, t+k) contributes 1/k to BOTH (i,j) and (j,i); pairs are folded
    by combined int64 key with ``np.unique`` — no per-pair python."""
    V = len(vocab.keys)
    idx_rows: List[np.ndarray] = []
    wts: List[np.ndarray] = []
    for sent in sentences:
        ids = [vocab.index_of(k) for k in sent]
        t = np.asarray([i for i in ids if i is not None], np.int64)
        if len(t) < 2:
            continue
        for k in range(1, min(window, len(t) - 1) + 1):
            a, b = t[:-k], t[k:]
            idx_rows.append(a * V + b)
            idx_rows.append(b * V + a)
            w = np.full(len(a), 1.0 / k, np.float32)
            wts.append(w)
            wts.append(w)
    if not idx_rows:
        return (np.zeros(0, np.int32), np.zeros(0, np.int32),
                np.zeros(0, np.float32))
    combined = np.concatenate(idx_rows)
    weights = np.concatenate(wts)
    uniq, inv = np.unique(combined, return_inverse=True)
    x = np.zeros(len(uniq), np.float32)
    np.add.at(x, inv, weights)
    return ((uniq // V).astype(np.int32), (uniq % V).astype(np.int32), x)


class GloVe:
    def __init__(self, config: Optional[ConfigParser] = None,
                 cluster: Optional[Cluster] = None,
                 capacity_per_shard: Optional[int] = None, seed: int = 0):
        self.config = config if config is not None else global_config()
        g = self.config.get_or
        self.len_vec = g("glove", "len_vec", 100).to_int32()
        self.window = g("glove", "window", 10).to_int32()
        self.x_max = g("glove", "x_max", 100.0).to_float()
        self.alpha = g("glove", "alpha", 0.75).to_float()
        lr = g("glove", "learning_rate", 0.05).to_float()
        self.minibatch = g("glove", "minibatch", 4096).to_int32()
        self.inner_steps = g("worker", "inner_steps", 1).to_int32()
        # [worker] pipeline / dispatch_depth: same knobs as word2vec —
        # K > 0 stages+transfers groups on a producer thread
        # (io/pipeline.py); epoch permutations are still drawn on the
        # consumer thread in epoch order, so results are identical
        self.pipeline_depth = g("worker", "pipeline", 0).to_int32()
        self.dispatch_depth = g("worker", "dispatch_depth",
                                "auto").to_string()
        self.cluster = cluster or Cluster(self.config).initialize()
        self.access = glove_access(lr, self.len_vec)
        self.transfer = self.cluster.transfer
        self.seed = seed
        self._capacity_per_shard = capacity_per_shard
        self.table = None
        self.vocab: Optional[Vocab] = None
        self._slot_of_vocab = None
        self._coo = None
        self._step = None
        # per-train() observability: stall/device time split (+ the
        # pipeline depth the run actually used) — see utils.timers
        self.train_metrics: dict = {}
        # [obs] numerics (obs/numerics.py): off constructs and traces
        # nothing — same bit-identity contract as word2vec
        from swiftmpi_tpu.obs import numerics as obs_numerics
        self.numerics_on = obs_numerics.enabled(self.config)
        self._numerics = None
        self._numerics_rec_id: Optional[int] = None

    # -- build: vocab + co-occurrence + table ------------------------------
    def build(self, sentences) -> "GloVe":
        self.vocab = build_vocab(sentences)
        V = len(self.vocab.keys)
        cap = self._capacity_per_shard or max(
            64, int(V * 1.3) // self.cluster.n_servers + 1)
        self.table = self.cluster.create_table(
            "glove", self.access, cap, seed=self.seed)
        slots = self.table.key_index.lookup(self.vocab.keys)
        self._slot_of_vocab = jnp.asarray(slots, jnp.int32)
        fi, ci, x = cooccurrence(sentences, self.vocab, self.window)
        self._coo = (fi, ci, x)
        log.info("glove: vocab %d, %d co-occurrence cells (window %d)",
                 V, len(x), self.window)
        return self

    # -- fused step --------------------------------------------------------
    def _build_step(self):
        # fx/logx arrive precomputed from train() — the weighting
        # function itself never enters the jitted step
        access, transfer = self.access, self.transfer
        from swiftmpi_tpu.obs import numerics as obs_numerics
        num = self._numerics
        n_hot = self.table.n_hot if num is not None else 0

        def one(state, fs, cs, logx, fx):
            rows_f = transfer.pull(state, fs, access, fields=("w", "b"))
            rows_c = transfer.pull(state, cs, access, fields=("wt", "bt"))
            w, b = rows_f["w"], rows_f["b"][:, 0]
            wt, bt = rows_c["wt"], rows_c["bt"][:, 0]
            J = jnp.sum(w * wt, axis=1) + b + bt - logx
            g = fx * J                                   # dJ/d(dot)
            loss = jnp.sum(fx * J * J)
            # AdaGradAccess ADDS lr*g (the reference's ascent
            # convention, lr.cpp:68-75) — push the NEGATIVE gradient
            gw = (-g)[:, None] * wt
            gwt = (-g)[:, None] * w
            gb = (-g)[:, None]
            stats = None
            if num is not None:
                s1, h1, n1 = obs_numerics.push_stats(
                    fs, {"w": gw, "b": gb}, n_hot)
                s2, h2, n2 = obs_numerics.push_stats(
                    cs, {"wt": gwt, "bt": gb}, n_hot)
                stats = (s1 + s2, h1 + h2, n1 + n2)
            state = transfer.push(state, fs, {"w": gw, "b": gb},
                                  access, mean=True)
            state = transfer.push(state, cs, {"wt": gwt, "bt": gb},
                                  access, mean=True)
            return state, loss, stats

        def multi(state, fs, cs, logx, fx):
            if num is None:
                def body(st, xs):
                    st, loss, _ = one(st, *xs)
                    return st, loss
                state, losses = jax.lax.scan(body, state,
                                             (fs, cs, logx, fx))
                return state, losses.sum()
            state0 = state

            def body(st, xs):
                st, loss, stats = one(st, *xs)
                return st, (loss, stats)
            state, (losses, stats) = jax.lax.scan(body, state,
                                                  (fs, cs, logx, fx))
            obs_numerics.stage_step(
                num, state0, state, tuple(s.sum() for s in stats),
                losses.sum(), jnp.float32(fs.shape[0] * fs.shape[1]),
                ("w", "wt", "b", "bt"))
            return state, losses.sum()

        from swiftmpi_tpu import obs
        return obs.costs.track("glove_step",
                               jax.jit(multi, donate_argnums=(0,)),
                               steps_per_call=max(1, self.inner_steps))

    # -- minibatch staging -------------------------------------------------
    def stage_host(self, sel: np.ndarray, inner: int, B: int):
        """COO selection -> host ``(fs, cs, logx, fx)`` stacks of shape
        (inner, B): the ONE definition of slot mapping and the
        f(x) = min((x/x_max)^alpha, 1) weighting, shared by train() and
        the benchmark cell so a weighting change can't silently fork.
        Pure numpy — this is what the input pipeline's producer thread
        runs off the critical path."""
        fi, ci, x = self._coo
        sov = np.asarray(self._slot_of_vocab)
        sel = np.resize(sel, inner * B)
        xs = x[sel]
        return (sov[fi[sel]].reshape(inner, B),
                sov[ci[sel]].reshape(inner, B),
                np.log(xs).reshape(inner, B),
                np.minimum((xs / self.x_max) ** self.alpha,
                           1.0).astype(np.float32).reshape(inner, B))

    def stage(self, sel: np.ndarray, inner: int, B: int):
        """Device-side ``stage_host`` (kept as the bench cell's API)."""
        return tuple(jnp.asarray(f)
                     for f in self.stage_host(sel, inner, B))

    # -- training ----------------------------------------------------------
    def train(self, sentences=None, niters: int = 1) -> List[float]:
        if self.table is None:
            if sentences is None:
                raise RuntimeError("build() first or pass sentences")
            self.build(sentences)
        n = len(self._coo[2])
        if n == 0:
            raise RuntimeError("empty co-occurrence set")
        B = min(self.minibatch, n)
        inner = max(1, self.inner_steps)
        rng = np.random.default_rng(self.seed)
        state = self.table.state
        losses = []
        from swiftmpi_tpu.utils.timers import Throughput
        meter = Throughput()
        from swiftmpi_tpu import obs
        tel_rec = obs.get_recorder()
        owns_rec = tel_rec is None
        if owns_rec:
            tel_rec = obs.configure(self.config, run="glove")
        if tel_rec is not None:
            def _tel_sample(reg, _m=meter):
                reg.counter("train/host_stall_ms_total").set_total(
                    _m.host_stall_ms())
                reg.counter("train/device_ms_total").set_total(
                    _m.device_ms())
            tel_rec.add_sampler(_tel_sample)
        if self.numerics_on and tel_rec is not None:
            self._arm_numerics(tel_rec)
        # compile AFTER arming: _build_step closes over self._numerics
        # at trace time (a first-time arm drops any pre-arm step)
        if self._step is None:
            self._step = self._build_step()
        transfer_fn = None
        if self.pipeline_depth > 0:
            from swiftmpi_tpu.io.pipeline import device_put_transfer
            sharding = jax.sharding.NamedSharding(
                self.cluster.mesh, jax.sharding.PartitionSpec())
            transfer_fn = device_put_transfer(sharding)

        def staged_groups(order):
            # the epoch permutation was already drawn (consumer thread,
            # epoch order) — from here on the staging is pure numpy, so
            # it can run ahead on the producer thread
            for gstart in range(0, len(order), B * inner):
                yield self.stage_host(order[gstart:gstart + B * inner],
                                      inner, B)

        for it in range(niters):
            order = rng.permutation(n)
            # pad the tail by CYCLING the permutation (static shapes,
            # via stage_host()'s np.resize — holds even when one fused
            # group exceeds n); repeats are extra stochastic samples
            # of real cells, and per-slot mean normalization keeps
            # their scale right
            n_groups = -(-n // (B * inner))
            order = np.resize(order, n_groups * B * inner)
            total = 0.0
            groups = staged_groups(order)
            pipe = None
            if self.pipeline_depth > 0:
                from swiftmpi_tpu.io.pipeline import PrefetchIterator
                pipe = PrefetchIterator(groups,
                                        depth=self.pipeline_depth,
                                        transfer=transfer_fn)
                groups = pipe
            try:
                groups = iter(groups)
                while True:
                    with meter.stalling():
                        fields = next(groups, None)
                    if fields is None:
                        break
                    with obs.span("dispatch"):
                        state, loss = self._step(
                            state, *(jnp.asarray(f) if not isinstance(
                                f, jax.Array) else f for f in fields))
                    # the step donates the state buffers: reassign NOW,
                    # not after the loop, or an exception mid-epoch
                    # (staging error, KeyboardInterrupt) leaves
                    # self.table.state pointing at donated/deleted
                    # device buffers and a previously valid model can
                    # no longer save() (round-3 advisor)
                    self.table.state = state
                    total += float(loss)
                    meter.record(B * inner)
                    obs.record_step(inner)
            finally:
                if pipe is not None:
                    pipe.close()
            mean_loss = total / len(order)
            losses.append(mean_loss)
            log.info("glove iter %d: %d cells  loss %.6f", it, n, mean_loss)
        self.train_metrics = {
            "host_stall_ms": meter.host_stall_ms(),
            "device_ms": meter.device_ms(),
            "stall_ms_per_step": meter.stall_ms_per_step(),
            "pipeline_depth": self.pipeline_depth}
        if self._numerics is not None:
            from swiftmpi_tpu.transfer import api as transfer_api
            self._numerics.sync()
            transfer_api.clear_numerics_tap()
            det = self._numerics.detector
            self.train_metrics["numerics"] = {
                "bundles": self._numerics.bundles,
                "anomalies": det.anomalies_emitted if det else 0}
        if owns_rec and tel_rec is not None:
            tel_rec.close()
            obs.uninstall_recorder()
        return losses

    def _arm_numerics(self, tel_rec) -> None:
        """Arm the numerics plane (observe-only here: GloVe has no
        control plane, so anomalies are telemetry events, never knob
        actions).  Mirrors Word2Vec._arm_numerics minus the controller
        and checkpoint-carry pieces."""
        from swiftmpi_tpu.obs import numerics as obs_numerics
        from swiftmpi_tpu.transfer import api as transfer_api
        if self._numerics is None:
            self._numerics = obs_numerics.NumericsCollector(
                detector=obs_numerics.detector_from_config(self.config))
            self._step = None
        transfer_api.set_numerics_tap(self._numerics.quant_tap)
        if id(tel_rec) != self._numerics_rec_id:
            tel_rec.add_sampler(self._numerics.sampler)
            self._numerics_rec_id = id(tel_rec)

    # -- outputs -----------------------------------------------------------
    def _vectors(self) -> np.ndarray:
        """The exported embedding: standard w + wt sum, vocab order —
        ONE definition shared by the live index and the dump."""
        if self.vocab is None:
            raise RuntimeError("build() first")
        slots = np.asarray(self._slot_of_vocab)
        return (np.asarray(self.table.state["w"])[slots]
                + np.asarray(self.table.state["wt"])[slots])

    def embedding_index(self):
        """Cosine index over the standard w + wt embedding sum."""
        from swiftmpi_tpu.models.embedding import EmbeddingIndex

        return EmbeddingIndex(self.vocab.keys, self._vectors())

    def save(self, path: str) -> int:
        """``key TAB (w + wt)-vector`` — the standard GloVe export, in
        the single-vector dump layout ``w2v_eval`` indexes directly."""
        vecs = self._vectors()
        n = 0
        with open(path, "w") as f:
            for key, vec in zip(self.vocab.keys, vecs):
                f.write(f"{int(key)}\t"
                        + " ".join(repr(float(v)) for v in vec) + "\n")
                n += 1
        return n

    def save_full(self, path: str) -> int:
        """All fields (both families + AdaGrad sums) in the reference
        checkpoint text format."""
        return dump_table_text(self.table, path,
                               fields=("w", "wt", "b", "bt"))
