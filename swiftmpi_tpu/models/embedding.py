"""Cosine-similarity index over trained embeddings (shared by the
live-model query API and the ``w2v_eval`` CLI).

TPU-first: the whole similarity pass is ONE normalized matmul
``(V, d) @ (d, Q)`` on the MXU plus a ``top_k`` (module-cached jit);
exclusions are handled host-side by over-fetch + drop so no ``(Q, V)``
mask is ever materialized.  The reference has no embedding eval at all
(its word2vec README ends at the text dump; row layout
word2vec.h:100-110).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Sequence, Tuple

import numpy as np


def _topk_scores(vecs, qt, k):
    """One (V, d) @ (d, Q) matmul + top_k.  Module-level and jitted
    with static k so repeated queries against the same index reuse the
    compiled program (a per-call closure would re-trace every query).
    Exclusions are handled host-side by the caller (over-fetch + drop)
    so no (Q, V) mask is ever materialized."""
    import jax

    global _topk_scores_jit
    if _topk_scores_jit is None:
        @partial(jax.jit, static_argnames=("k",))
        def f(vecs, qt, k):
            return jax.lax.top_k((vecs @ qt).T, k)   # (Q, V) — MXU
        _topk_scores_jit = f
    return _topk_scores_jit(vecs, qt, k)


_topk_scores_jit = None


class EmbeddingIndex:
    """In-memory cosine-similarity index over dumped embeddings.

    Rows are L2-normalized once at construction; every query batch is a
    single ``(V, d) @ (d, Q)`` matmul + ``top_k``.
    """

    def __init__(self, keys: np.ndarray, vecs: np.ndarray):
        if len(keys) != len(vecs):
            raise ValueError(f"{len(keys)} keys vs {len(vecs)} vectors")
        self.keys = np.asarray(keys, np.uint64)
        vecs = np.asarray(vecs, np.float32)
        norms = np.linalg.norm(vecs, axis=1, keepdims=True)
        self.vecs = vecs / np.maximum(norms, 1e-12)
        self._row_of = {int(k): i for i, k in enumerate(self.keys)}

    def __len__(self) -> int:
        return len(self.keys)

    @classmethod
    def from_text(cls, path: str, field: str = "v") -> "EmbeddingIndex":
        """Parse a ``dump_table_text`` w2v dump: ``key TAB v-floats TAB
        h-floats`` per row (reference WParam operator<< layout,
        word2vec.h:100-110).  ``field`` picks the input-side (``v``) or
        output-side (``h``) vectors.  Single-vector dumps — sent2vec's
        ``sent_id TAB vec`` output (sent2vec.cpp:82-86) or an LR weight
        dump — parse as ``v`` (requesting ``h`` from one is an error)."""
        if field not in ("v", "h"):
            raise ValueError(f"field must be 'v' or 'h', got {field!r}")
        col = 1 if field == "v" else 2
        # native C++ reader (the same one load_table_text routes
        # through): millions of Python float() calls vs one pass
        dims = None
        with open(path) as f:
            for line in f:
                parts = line.rstrip("\n").split("\t")
                if len(parts) > col:
                    dims = [len(p.split()) for p in parts[1:]]
                break
        if dims:
            from swiftmpi_tpu.data import native

            if native.available():
                try:
                    keys_np, arrs = native.load_rows_native(path, dims)
                    if len(keys_np):
                        return cls(keys_np, arrs[col - 1])
                except Exception:
                    pass          # fall through to the python parser
        keys: List[int] = []
        rows: List[np.ndarray] = []
        with open(path) as f:
            for ln, line in enumerate(f, 1):
                line = line.rstrip("\n")
                if not line:
                    continue
                parts = line.split("\t")
                if len(parts) <= col:
                    raise ValueError(
                        f"{path}:{ln}: expected key\\tv\\th layout")
                keys.append(int(parts[0]) & ((1 << 64) - 1))
                rows.append(np.array(parts[col].split(), np.float32))
        if not rows:
            raise ValueError(f"{path}: no embedding rows")
        return cls(np.array(keys, np.uint64), np.stack(rows))

    def row(self, key: int) -> Optional[int]:
        return self._row_of.get(int(key) & ((1 << 64) - 1))

    def topk(self, queries: np.ndarray, k: int = 10,
             exclude_rows: Sequence[Sequence[int]] = ()) -> Tuple[
                 np.ndarray, np.ndarray]:
        """Top-k cosine neighbors for each query VECTOR.

        ``queries``: (Q, d).  ``exclude_rows``: per-query row indices to
        mask out (e.g. the query word itself).  Returns (keys (Q, k'),
        scores (Q, k')) with ``k' = min(k, rows)``.  A query with fewer
        survivors than k' (its exclusions ate into the fetch, or every
        fetched row was excluded) pads its tail with -inf scores —
        callers drop those by score, and the batched wrappers below do
        so automatically."""
        import jax.numpy as jnp

        q = np.asarray(queries, np.float32)
        q = q / np.maximum(np.linalg.norm(q, axis=1, keepdims=True), 1e-12)
        # no dense (Q, V) exclusion mask (10GB at Q=10K over a 1M-row
        # table): over-fetch k + max_excluded, drop excluded host-side
        max_excl = max((len(r) for r in exclude_rows), default=0)
        k_fetch = min(k + max_excl, len(self))
        scores, idx = _topk_scores(jnp.asarray(self.vecs),
                                   jnp.asarray(q.T), k_fetch)
        idx, scores = np.asarray(idx), np.asarray(scores)
        Q = q.shape[0]
        k_out = min(k, len(self))
        # per-query survivor count (round-3 advisor: a uniform
        # min(k, V - max_excl) silently shrank k for EVERY query in a
        # mixed-exclusion batch, and an all-excluded query crashed)
        out_i = np.zeros((Q, k_out), np.int64)
        out_s = np.full((Q, k_out), -np.inf, np.float32)
        for qi in range(Q):
            excl = set(exclude_rows[qi]) if qi < len(exclude_rows) \
                else set()
            keep = [j for j in range(k_fetch)
                    if idx[qi, j] not in excl][:k_out]
            if keep:
                out_i[qi, :len(keep)] = idx[qi, keep]
                out_s[qi, :len(keep)] = scores[qi, keep]
        return self.keys[out_i], out_s

    def neighbors(self, key: int, k: int = 10) -> Tuple[np.ndarray,
                                                        np.ndarray]:
        """Top-k neighbors of one stored key (itself excluded)."""
        ks, ss = self.neighbors_batch([key], k)
        return ks[0], ss[0]

    def neighbors_batch(self, keys: Sequence[int], k: int = 10) -> Tuple[
            List[np.ndarray], List[np.ndarray]]:
        """Neighbors for MANY stored keys in ONE matmul + top_k
        dispatch (each query's own row excluded); -inf (masked-out)
        entries are dropped per query."""
        rows = []
        for key in keys:
            r = self.row(key)
            if r is None:
                raise KeyError(f"key {int(key)} not in embeddings")
            rows.append(r)
        ks, ss = self.topk(self.vecs[np.array(rows)], k,
                           exclude_rows=[[r] for r in rows])
        kept = [np.isfinite(s) for s in ss]
        return ([kk[m] for kk, m in zip(ks, kept)],
                [s[m] for s, m in zip(ss, kept)])

    def analogy(self, a: int, b: int, c: int, k: int = 5) -> Tuple[
            np.ndarray, np.ndarray]:
        """``a - b + c`` in embedding space (a:b :: result:c), query
        words excluded from candidates."""
        rows = [self.row(x) for x in (a, b, c)]
        missing = [x for x, r in zip((a, b, c), rows) if r is None]
        if missing:
            raise KeyError(f"keys not in embeddings: {missing}")
        q = (self.vecs[rows[0]] - self.vecs[rows[1]] + self.vecs[rows[2]])
        ks, ss = self.topk(q[None, :], k, exclude_rows=[rows])
        m = np.isfinite(ss[0])
        return ks[0][m], ss[0][m]


