"""sent2vec: paragraph-vector (PV-DM-style) inference over frozen word
vectors.

Re-design of `/root/reference/src/apps/sent2vec/sent2vec.cpp`: load a
pre-trained word2vec table (``load_word_vector`` → server load,
sent2vec.cpp:32-35), then for each sentence initialize a random sentence
vector and run ``niters`` gradient passes updating **only** that vector —
word gradients are never pushed (``WordMiniBatch::push() = delete``,
sent2vec.cpp:6-12).

Per position (sent2vec.cpp:108-181):
    neu1 = sent_vec + sum of context word v-vectors  (random-shrunk window)
    for target in {center(1), K negatives(0)}:  skip neg == center
        g = (label - sigmoid_clipped(neu1 . h_target)) * alpha
        neu1e += g * h_target
    sent_vec += alpha * neu1e          # note: alpha applied twice, as in
                                       # the reference (g already carries it)

TPU shape: sentences are batched ``(S, L)`` and the position loop is a
``lax.scan`` carrying ``sent_vec`` — bit-faithful sequential-within-pass
semantics, vectorized across the batch; fresh negatives are drawn on device
each pass like the reference redraws per ``learn_instance`` call.

Sentence ids are the BKDR hash of the raw line (sent2vec.cpp:75) and the
output format is ``sent_id\\tv0 v1 ...`` (sent2vec.cpp:82-86).
"""

from __future__ import annotations

from functools import partial
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from swiftmpi_tpu.data.text import tokenize
from swiftmpi_tpu.models.word2vec import Word2Vec
from swiftmpi_tpu.ops.sampling import (build_unigram_alias,
                                       sample_alias_slots)
from swiftmpi_tpu.ops.sigmoid import sigmoid_clipped
from swiftmpi_tpu.utils.config import ConfigParser
from swiftmpi_tpu.utils.hashing import bkdr_hash
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.timers import Error

log = get_logger(__name__)


class Sent2Vec:
    def __init__(self, word_model: Word2Vec,
                 config: Optional[ConfigParser] = None, seed: int = 0):
        """``word_model``: a Word2Vec whose table holds the frozen word
        vectors (train it, or ``load()`` a dump)."""
        self.config = config if config is not None else word_model.config
        g = self.config.get_or
        self.window = g("word2vec", "window", 4).to_int32()
        self.negative = g("word2vec", "negative", 20).to_int32()
        self.alpha = g("word2vec", "learning_rate", 0.05).to_float()
        self.batchsize = g("worker", "minibatch", 256).to_int32()
        self.word_model = word_model
        self.len_vec = word_model.len_vec
        self._key = jax.random.key(seed ^ 0xD0C)
        self._infer = None
        self.error = Error()
        # serving plane: attach a serve.SnapshotPublisher and
        # infer_sentences() publishes the finished sentence vectors as a
        # {"sent": (S, d)} snapshot keyed by sentence id — the top-k
        # query path then answers nearest-sentence queries
        self.serve_publisher = None

    # -- the jitted inference kernel ---------------------------------------
    def _build_infer(self):
        W, K, d, alpha = (self.window, self.negative, self.len_vec,
                          self.alpha)
        offsets = np.array([o for o in range(-W, W + 1) if o != 0],
                           np.int32)

        @partial(jax.jit, static_argnums=8)  # niters is a scan length
        def infer(h_table, v_table, word_slots, word_mask, alias_prob,
                  alias_idx, slot_of_vocab, vocab_of_pos, niters, key):
            """word_slots: (S, L) table slots; vocab_of_pos: (S, L) vocab
            ids (for neg==center masking); returns (S, d) sentence vecs."""
            S, L = word_slots.shape
            V_all = jnp.take(v_table, jnp.maximum(word_slots, 0), axis=0)
            V_all = V_all * word_mask[..., None]            # (S, L, d)
            k_init, key = jax.random.split(key)
            # Vec::random init, (U(0,1)-0.5)/len  (vec1.h:229-232)
            sent0 = (jax.random.uniform(k_init, (S, d)) - 0.5) / d

            def one_pass(carry, _):
                sent_vec, key = carry
                key, kb, kn = jax.random.split(key, 3)
                b = jax.random.randint(kb, (S, L), 0, W)    # window shrink
                # fused draw+slot lookup: (S, L, K) negatives per pass
                # is the dominant transaction count of the whole
                # inference — see ops/sampling.sample_alias_slots
                negs_v, neg_slots = sample_alias_slots(
                    kn, alias_prob, alias_idx, slot_of_vocab, (S, L, K))

                def pos_step(sv, p):
                    ctx_idx = p + offsets                    # (2W,)
                    in_range = (ctx_idx >= 0) & (ctx_idx < L)
                    ctx_idx_c = jnp.clip(ctx_idx, 0, L - 1)
                    ctx_v = V_all[:, ctx_idx_c, :]           # (S, 2W, d)
                    half = W - b[:, p]                       # (S,)
                    ok = (in_range[None, :]
                          & (jnp.abs(offsets)[None, :] <= half[:, None])
                          & word_mask[:, ctx_idx_c])
                    neu1 = sv + jnp.sum(ctx_v * ok[..., None], axis=1)
                    center_slot = word_slots[:, p]           # (S,)
                    t_slots = jnp.concatenate(
                        [center_slot[:, None], neg_slots[:, p, :]], axis=1)
                    h_t = jnp.take(h_table, jnp.maximum(t_slots, 0),
                                   axis=0)                   # (S, K+1, d)
                    f = jnp.einsum("sd,skd->sk", neu1, h_t)
                    labels = jnp.concatenate(
                        [jnp.ones((S, 1)), jnp.zeros((S, K))], axis=1)
                    g = (labels - sigmoid_clipped(f)) * alpha
                    valid = jnp.concatenate(
                        [jnp.ones((S, 1), bool),
                         negs_v[:, p, :] != vocab_of_pos[:, p][:, None]],
                        axis=1) & word_mask[:, p][:, None]
                    g = jnp.where(valid, g, 0.0)
                    neu1e = jnp.einsum("sk,skd->sd", g, h_t)
                    sv = sv + alpha * neu1e
                    return sv, jnp.sum(g * g)

                sent_vec, gg = jax.lax.scan(
                    pos_step, sent_vec, jnp.arange(L))
                return (sent_vec, key), jnp.sum(gg)

            (sent_vec, _), errs = jax.lax.scan(
                one_pass, (sent0, key), None, length=niters)
            return sent_vec, errs[-1]

        return infer

    # -- driver (sent2vec.cpp:37-104) --------------------------------------
    def infer_sentences(self, lines: List[str], niters: int = 10,
                        tokenize_mode: str = "int", snapshot=None
                        ) -> List[Tuple[int, np.ndarray]]:
        """``snapshot``: a serve.TableSnapshot of the word table — when
        given, inference reads h/v and the key→slot map from that frozen
        published view instead of the live table, so it can run
        concurrently with a training loop (bounded staleness, never a
        torn mid-push state)."""
        wm = self.word_model
        if wm.vocab is None:
            raise RuntimeError(
                "word model has no vocab; train it in-process or load a "
                "dump via build_word_model_from_dump()")
        if self._infer is None:
            self._infer = self._build_infer()
        if snapshot is not None:
            h_table, v_table = (snapshot.tail_array("h"),
                                snapshot.tail_array("v"))
            slot_of_vocab = jnp.asarray(
                snapshot.lookup(wm.vocab.keys), jnp.int32)
        else:
            h_table, v_table = wm.table.state["h"], wm.table.state["v"]
            slot_of_vocab = wm._slot_of_vocab
        prob, alias = build_unigram_alias(wm.vocab.counts)
        # All-OOV lines are skipped entirely, like the reference skips
        # unparseable lines (sent2vec.cpp:71-74) — no garbage vectors.
        kept: List[Tuple[str, List[int]]] = []
        for ln in lines:
            t = [i for i in (wm.vocab.index_of(k)
                             for k in tokenize(ln, tokenize_mode))
                 if i is not None]
            if t:
                kept.append((ln, t))
        dropped = len(lines) - len(kept)
        if dropped:
            log.warning("sent2vec: skipped %d all-OOV sentence(s)", dropped)
        # Bounded dispatch pipeline: keep a window of batches in flight
        # and fetch the oldest as new ones are dispatched — a float(err)
        # + np.asarray(vecs) per batch is two blocking device round trips
        # (~5ms each through the axon tunnel) that serialize what XLA
        # would otherwise pipeline, while an unbounded queue would hold
        # every batch's output on the device at once (O(input) HBM).
        MAX_IN_FLIGHT = 16
        queued = []
        out: List[Tuple[int, np.ndarray]] = []

        def drain_one():
            chunk, vecs, err = queued.pop(0)
            self.error.accu(float(err), len(chunk))
            vecs = np.asarray(vecs)
            for i, (ln, _) in enumerate(chunk):
                out.append((bkdr_hash(ln), vecs[i]))

        for start in range(0, len(kept), self.batchsize):
            chunk = kept[start:start + self.batchsize]
            S = self.batchsize          # pad tail: one compiled shape per L
            max_len = max(len(t) for _, t in chunk)
            L = 1 << (max_len - 1).bit_length()  # bucket to power of two
            vocab_pos = np.zeros((S, L), np.int32)
            mask = np.zeros((S, L), bool)
            for i, (_, t) in enumerate(chunk):
                vocab_pos[i, :len(t)] = t
                mask[i, :len(t)] = True
            slots = np.asarray(slot_of_vocab)[vocab_pos]
            self._key, sub = jax.random.split(self._key)
            vecs, err = self._infer(
                h_table, v_table,
                jnp.asarray(slots), jnp.asarray(mask),
                jnp.asarray(prob), jnp.asarray(alias),
                slot_of_vocab, jnp.asarray(vocab_pos),
                niters, sub)
            queued.append((chunk, vecs, err))
            while len(queued) >= MAX_IN_FLIGHT:
                drain_one()
        while queued:
            drain_one()
        log.info("sent2vec: %d sentences, error %.5f",
                 len(out), self.error.norm())
        if self.serve_publisher is not None and out:
            # publish the finished sentence vectors as a snapshot keyed
            # by sentence id — serve.query answers nearest-sentence
            # queries over it exactly like word neighbors
            self.serve_publisher.publish(
                {"sent": np.stack([v for _, v in out])},
                keys=np.array([s for s, _ in out], np.uint64),
                slots=np.arange(len(out), dtype=np.int64),
                meta={"query_field": "sent"})
        return out

    def write(self, results, path: str) -> None:
        """``sent_id\\tv0 v1 ...`` lines (sent2vec.cpp:82-86)."""
        with open(path, "w") as f:
            for sid, vec in results:
                f.write(f"{sid}\t" + " ".join(repr(float(x)) for x in vec)
                        + "\n")


def build_word_model_from_dump(dump_path: str, config: ConfigParser,
                               capacity_per_shard: int = 1 << 16
                               ) -> Word2Vec:
    """Load a word2vec text dump as the frozen word table, rebuilding the
    vocab bookkeeping sent2vec needs (counts default to 1 — the dump
    format, like the reference's, does not carry frequencies, so negative
    sampling over a loaded dump is uniform; train-in-process keeps true
    counts)."""
    model = Word2Vec(config=config, capacity_per_shard=capacity_per_shard)
    model.load(dump_path)
    keys = np.fromiter(model.table.key_index.keys(), np.uint64,
                       count=len(model.table.key_index))
    from swiftmpi_tpu.data.text import Vocab
    model.vocab = Vocab(keys, np.ones(len(keys), np.int64),
                        {int(k): i for i, k in enumerate(keys)})
    slots = model.table.key_index.lookup(keys)
    model._slot_of_vocab = jnp.asarray(slots, jnp.int32)
    return model
