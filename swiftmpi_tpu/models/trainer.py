"""Transformer training loop: optimizer, schedule, sharding, checkpoints.

The reference has no dense-model trainer at all (its optimizer lives
server-side as the AdaGrad push rule, accessmethod.h) — this is the
framework's training infrastructure for the transformer family, composed
the idiomatic TPU way:

* optimizer = optax (adamw/sgd + warmup-cosine), state sharded like the
  params so dp/tp carry over to the optimizer for free;
* one jitted, donated ``train_step``: loss, grads, update — GSPMD inserts
  every collective from the shardings alone;
* ``remat`` in TransformerConfig turns on per-block ``jax.checkpoint``
  (activation memory O(layers) -> O(1); recompute cost depends on
  ``remat_policy`` — "dots" saves matmul outputs and re-executes only
  elementwise ops and attention scores, "full" re-executes everything
  at ~1/3 extra FLOPs);
* checkpoints are flat npz (multihost-safe: collective gather, process-0
  writes — same policy as io/checkpoint.py), resume-exact including
  optimizer state and step counter.
"""

from __future__ import annotations

from dataclasses import dataclass
from functools import partial
from typing import Any, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np
import optax
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swiftmpi_tpu.cluster.bootstrap import host_array, is_writer
from swiftmpi_tpu.io.checkpoint import (atomic_savez, npz_path,
                                        prune_generations,
                                        rotate_before_write,
                                        verify_checkpoint)
from swiftmpi_tpu.testing import faults
from swiftmpi_tpu.models.transformer import (TransformerConfig, init_params,
                                             lm_loss, param_shardings)
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.pipeline import (DispatchWindow,
                                         resolve_dispatch_bound)
from swiftmpi_tpu.utils.timers import Throughput

log = get_logger(__name__)


@dataclass
class TrainState:
    params: Any
    opt_state: Any
    step: jax.Array          # replicated scalar int32

    def tree(self):
        return {"params": self.params, "opt_state": self.opt_state,
                "step": self.step}


def make_optimizer(name: str = "adamw", learning_rate: float = 3e-4,
                   warmup_steps: int = 100, decay_steps: int = 10_000,
                   weight_decay: float = 0.01,
                   grad_clip: float = 1.0) -> optax.GradientTransformation:
    """Warmup-cosine schedule + global-norm clip around adamw/sgd."""
    sched = optax.warmup_cosine_decay_schedule(
        0.0, learning_rate, warmup_steps, max(decay_steps, warmup_steps + 1))
    if name == "adamw":
        opt = optax.adamw(sched, weight_decay=weight_decay)
    elif name == "sgd":
        opt = optax.sgd(sched, momentum=0.9)
    else:
        raise ValueError(f"unknown optimizer {name!r}")
    return optax.chain(optax.clip_by_global_norm(grad_clip), opt)


class Trainer:
    """Owns params + optimizer state and the jitted step.

    ``mesh`` (optional) applies ``param_shardings`` (tp over ``model``) to
    params AND optimizer state; tokens fed to ``step`` shard over
    ``data``.  Without a mesh everything is single-device.
    """

    def __init__(self, cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                 optimizer: str = "adamw", aux_weight: float = 0.01,
                 **opt_kwargs):
        self.cfg = cfg
        self.mesh = mesh
        self.optimizer = make_optimizer(optimizer, **opt_kwargs)
        self.aux_weight = aux_weight
        self._step_fn = None
        # host-side step counter for the fault/observability bus: the
        # device-side state.step would cost a sync per step to read.
        # Counts CONSUMED steps — with the input pipeline on, batches a
        # producer has rendered but the loop has not dispatched yet do
        # not advance it, so fault plans and the hang watchdog keep
        # their step semantics
        self._host_steps = 0
        # host-stall vs device-time split: step() books its token
        # reshard (the H2D transfer the pipeline hides) as stall
        self.meter = Throughput()
        self.pipeline_stats: dict = {}
        # serving plane: attach a serve.SnapshotPublisher here and
        # step() publishes a params-only snapshot every K steps (dense
        # params carry no key map — readers use the pytree directly)
        self.serve_publisher = None
        # control plane: attach a control.Controller here (dense params
        # have no placement knobs, so the useful mode is observe-only —
        # no sketch, no knobs — which emits control/evaluation events
        # with the traffic delta each cadence tick)
        self.controller = None
        # numerics health plane: arm_numerics() a NumericsCollector and
        # the step ships grad/update/param mass + nonfinite counts per
        # dispatch.  None (default) traces nothing extra
        self._numerics = None

    # -- state ------------------------------------------------------------
    def init_state(self, key) -> TrainState:
        params = init_params(key, self.cfg)
        if self.mesh is not None:
            shardings = param_shardings(params, self.cfg, self.mesh)
            params = jax.jit(lambda p: p, out_shardings=shardings)(params)
            # optimizer state mirrors param shapes -> mirror the shardings
            opt_state = jax.jit(
                self.optimizer.init,
                out_shardings=self._opt_shardings(params, shardings))(
                    params)
        else:
            opt_state = jax.jit(self.optimizer.init)(params)
        return TrainState(params, opt_state,
                          jnp.zeros((), jnp.int32))

    def _opt_shardings(self, params, param_sh):
        """Shardings for the optimizer state: optax states embed
        param-shaped pytrees (adam's mu/nu, sgd's trace) with the SAME
        treedef as the params — any subtree matching that structure gets
        the param shardings, everything else (counts, schedule steps)
        replicates."""
        shapes = jax.eval_shape(self.optimizer.init, params)
        repl = NamedSharding(self.mesh, P())
        params_treedef = jax.tree.structure(params)

        def walk(node):
            try:
                if jax.tree.structure(node) == params_treedef:
                    return param_sh
            except Exception:
                pass
            if isinstance(node, tuple) and hasattr(node, "_fields"):
                return type(node)(*(walk(v) for v in node))
            if isinstance(node, tuple):
                return tuple(walk(v) for v in node)
            if isinstance(node, list):
                return [walk(v) for v in node]
            if isinstance(node, dict):
                return {k: walk(v) for k, v in node.items()}
            return repl

        return walk(shapes)

    # -- numerics health plane (obs/numerics.py) --------------------------
    def arm_numerics(self, collector) -> None:
        """Arm the numerics plane: ``collector`` (a
        ``NumericsCollector``) receives one bundle per dispatched step.
        Drops the compiled step — the bundle is baked in at trace
        time.  Call with None to disarm (also recompiles)."""
        self._numerics = collector
        self._step_fn = None

    # -- the step ---------------------------------------------------------
    def _build_step(self):
        cfg, mesh, opt = self.cfg, self.mesh, self.optimizer
        aux_w = self.aux_weight
        num = self._numerics

        @partial(jax.jit, donate_argnums=(0, 1, 2))
        def train_step(params, opt_state, step, tokens):
            loss, grads = jax.value_and_grad(lm_loss)(
                params, tokens, cfg, mesh, aux_weight=aux_w)
            updates, opt_state = opt.update(grads, opt_state, params)
            if num is not None:
                from swiftmpi_tpu.obs import numerics as obs_numerics
                obs_numerics.stage_dense(num, params, grads, updates,
                                         loss)
            params = optax.apply_updates(params, updates)
            return params, opt_state, step + 1, loss

        from swiftmpi_tpu import obs
        return obs.costs.track("trainer_step", train_step)

    def step(self, state: TrainState, tokens) -> Tuple[TrainState,
                                                       jax.Array]:
        faults.step_event(self._host_steps)
        self._host_steps += 1
        if self._step_fn is None:
            self._step_fn = self._build_step()
        if self.mesh is not None:
            want = NamedSharding(self.mesh, P("data", None))
            if not (isinstance(tokens, jax.Array)
                    and tokens.sharding == want):
                # reshard whatever we got so dp is never silently
                # dropped; booked as HOST STALL — this is the H2D
                # transfer run() hides by pre-transferring on the
                # producer thread (pre-transferred tokens skip this
                # branch entirely).  Multi-process: host tokens are
                # this process's LOCAL rows of the global batch
                # (device_put would wrongly assume the same full value
                # on every host)
                with self.meter.stalling():
                    if jax.process_count() > 1:
                        tokens = jax.make_array_from_process_local_data(
                            want, np.asarray(tokens))
                    else:
                        tokens = jax.device_put(jnp.asarray(tokens), want)
        from swiftmpi_tpu import obs
        with obs.span("dispatch"):
            params, opt_state, step, loss = self._step_fn(
                state.params, state.opt_state, state.step, tokens)
        self.meter.record(int(np.prod(tokens.shape)))
        obs.record_step(1)
        out = TrainState(params, opt_state, step)
        if self.serve_publisher is not None:
            self.serve_publisher.on_steps(out.params, n=1)
        if self.controller is not None:
            self.controller.on_steps(1)
        return out, loss

    def run(self, state: TrainState, batches, pipeline: int = 0,
            dispatch_depth="auto") -> Tuple[TrainState, list]:
        """Consume an iterable of host token batches through ``step``.

        ``pipeline=K`` (single-process, meshed) prefetches K batches on
        a producer thread and eagerly ``device_put``s them with the
        step's committed ``P("data", None)`` input sharding, so H2D DMA
        overlaps the previous step's compute and ``step``'s reshard
        branch is skipped.  Loss scalars stay on device; a
        ``DispatchWindow`` (``dispatch_depth`` watermark) keeps the
        number of in-flight donated steps bounded.  Batch order and
        values are untouched, so ``pipeline=0`` is bit-identical.
        Returns ``(state, losses)`` with ``losses`` still device
        scalars — ``float()`` them after the epoch, not per step.
        """
        pipelined = (pipeline > 0 and self.mesh is not None
                     and jax.process_count() == 1)
        window = DispatchWindow(
            resolve_dispatch_bound(dispatch_depth, pipelined=pipelined))
        pipe = None
        it = batches
        if pipelined:
            from swiftmpi_tpu.io.pipeline import (PrefetchIterator,
                                                  device_put_transfer)
            want = NamedSharding(self.mesh, P("data", None))
            pipe = PrefetchIterator(it, depth=pipeline,
                                    transfer=device_put_transfer(want))
            it = pipe
        losses = []
        try:
            it = iter(it)
            while True:
                with self.meter.stalling():
                    tokens = next(it, None)
                if tokens is None:
                    break
                state, loss = self.step(state, tokens)
                losses.append(loss)
                window.push(loss)
        finally:
            if pipe is not None:
                pipe.close()
                self.pipeline_stats = pipe.stats()
        return state, losses

    # -- checkpoints (multihost-safe, atomic, CRC-validated) ---------------
    def save(self, state: TrainState, path: str, retain: int = 1) -> None:
        from swiftmpi_tpu import obs
        with obs.span("checkpoint_save"):
            self._save(state, path, retain)

    def _save(self, state: TrainState, path: str, retain: int) -> None:
        flat, treedef = jax.tree.flatten(state.tree())
        # every process gathers (host_array is a collective); only the
        # writer touches the disk — and logs from the gathered copy, so no
        # collective runs after non-writers have returned
        payload = {f"leaf_{i}": host_array(v) for i, v in enumerate(flat)}
        if not is_writer():
            return
        payload["treedef"] = np.frombuffer(
            repr(treedef).encode(), dtype=np.uint8)
        dst = npz_path(path)
        rotate_before_write(dst, retain)
        atomic_savez(dst, payload)
        prune_generations(dst, retain)
        step_i = next(i for i, v in enumerate(flat) if v is state.step)
        log.info("trainer checkpoint -> %s (step %d)", dst,
                 int(payload[f"leaf_{step_i}"]))
        faults.checkpoint_event(dst)

    def load(self, path: str, key=None, verify: bool = True) -> TrainState:
        """Rebuild a TrainState from ``save`` output.  The tree structure
        comes from a fresh ``init_state`` (cfg must match); leaf order is
        the flatten order, so shapes are validated leaf-by-leaf.
        ``verify`` CRC-checks every array first (CheckpointCorruptError
        on a torn/bit-rotted file) — restoring damaged optimizer state
        silently poisons the whole downstream run."""
        state = self.init_state(key if key is not None
                                else jax.random.key(0))
        flat, treedef = jax.tree.flatten(state.tree())
        dst = npz_path(path)
        if verify:
            verify_checkpoint(dst)
        with np.load(dst) as z:
            saved_def = z["treedef"].tobytes().decode()
            if saved_def != repr(treedef):
                raise ValueError(
                    "checkpoint state tree does not match this trainer "
                    "(optimizer/config mismatch?): saved "
                    f"{saved_def[:120]}... != {repr(treedef)[:120]}...")
            loaded = [z[f"leaf_{i}"] for i in range(len(flat))]
        for i, (have, want) in enumerate(zip(loaded, flat)):
            if tuple(have.shape) != tuple(want.shape):
                raise ValueError(
                    f"checkpoint leaf {i} shape {have.shape} != "
                    f"model {tuple(want.shape)} (config mismatch?)")
        def put(arr, ref):
            if isinstance(ref, jax.Array):
                # make_array_from_callback works for multi-process global
                # shardings too (device_put would require addressability)
                return jax.make_array_from_callback(
                    arr.shape, ref.sharding, lambda idx: arr[idx])
            return arr

        tree = jax.tree.unflatten(
            treedef, [put(a, r) for a, r in zip(loaded, flat)])
        return TrainState(tree["params"], tree["opt_state"], tree["step"])
