"""Decoder-only transformer LM: the long-context / multi-axis model family.

The reference stops at shallow embedding models (LR, word2vec, sent2vec —
SURVEY.md §2.5); this model exists so every parallelism axis the framework
provides is exercised by a real trainable model, the way a modern user of
the framework would compose them:

* **dp**   — batch sharded over ``data``; gradient combine is implicit in
  GSPMD (jit over global arrays inserts the psums).
* **tp**   — Megatron-style tensor parallelism via sharding *annotations*
  (``param_shardings``): attention heads and the FFN hidden dim shard over
  ``model``; XLA/GSPMD inserts the all-reduces.  No hand-written
  collectives — the idiomatic TPU expression of TP.
* **sp/cp** — attention runs as ``ring_attention`` / ``ulysses_attention``
  over a ``seq`` axis (parallel/ring_attention.py) for sequences that
  don't fit one chip.
* **pp**   — the block trunk is homogeneous, so it drops into
  ``pipeline_apply`` over a ``stage`` axis (parallel/pipeline.py).
* **ep**   — the FFN can be a routed mixture-of-experts over an ``expert``
  axis (parallel/moe.py).

Architecture: pre-RMSNorm, RoPE positions, causal multi-head attention,
SiLU-gated or MoE FFN, weight-tied output head.  bfloat16-friendly: all
matmuls are MXU-shaped; norms/softmax accumulate in f32.
"""

from __future__ import annotations

import math
from dataclasses import dataclass
from functools import partial
from typing import Any, Dict, Optional

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from swiftmpi_tpu.parallel.moe import (MoEParams, init_moe_params, moe_ffn,
                                       moe_ffn_reference)
from swiftmpi_tpu.parallel.pipeline import (pipeline_apply,
                                            stack_stage_params)
from swiftmpi_tpu.parallel.ring_attention import (full_attention,
                                                  ring_attention,
                                                  ulysses_attention)


@dataclass(frozen=True)
class TransformerConfig:
    vocab_size: int
    d_model: int = 128
    n_layers: int = 2
    n_heads: int = 4
    d_ff: int = 512
    max_seq: int = 2048
    attention: str = "full"          # full | ring | ulysses
    n_experts: int = 0               # 0 => dense SiLU-gated FFN
    moe_top_k: int = 2
    moe_capacity_factor: float = 2.0
    rope_base: float = 10_000.0
    remat: bool = False              # jax.checkpoint each block: trade
                                     # recompute FLOPs for HBM (activation
                                     # memory goes O(L) -> O(1) blocks)
    remat_policy: str = "dots"       # dots: keep projection/FFN matmul
                                     # outputs, recompute only the cheap
                                     # elementwise ops and the S x S
                                     # attention scores (flash-style) —
                                     # the recompute bill drops from
                                     # every-matmul to ~score-matmuls.
                                     # "full": recompute everything.
    dtype: Any = jnp.float32

    @property
    def head_dim(self) -> int:
        assert self.d_model % self.n_heads == 0
        return self.d_model // self.n_heads


# -- params ----------------------------------------------------------------

def init_params(key, cfg: TransformerConfig) -> Dict[str, Any]:
    """Block params are stacked on a leading (n_layers) axis — the layout
    both ``lax.scan`` over layers and ``pipeline_apply`` want."""
    k_emb, k_blk = jax.random.split(key)
    s = 1.0 / math.sqrt(cfg.d_model)

    def one_block(k):
        ks = jax.random.split(k, 7)
        d, h = cfg.d_model, cfg.d_ff
        blk = {
            "ln1": jnp.ones((d,), cfg.dtype),
            "ln2": jnp.ones((d,), cfg.dtype),
            "wq": jax.random.normal(ks[0], (d, d), cfg.dtype) * s,
            "wk": jax.random.normal(ks[1], (d, d), cfg.dtype) * s,
            "wv": jax.random.normal(ks[2], (d, d), cfg.dtype) * s,
            "wo": jax.random.normal(ks[3], (d, d), cfg.dtype) * s,
        }
        if cfg.n_experts:
            blk["moe"] = init_moe_params(ks[4], d, h, cfg.n_experts,
                                         cfg.dtype)
        else:
            blk["w_gate"] = jax.random.normal(ks[4], (d, h), cfg.dtype) * s
            blk["w_up"] = jax.random.normal(ks[5], (d, h), cfg.dtype) * s
            blk["w_down"] = (jax.random.normal(ks[6], (h, d), cfg.dtype)
                             / math.sqrt(h))
        return blk

    blocks = [one_block(k) for k in jax.random.split(k_blk, cfg.n_layers)]
    return {
        "embed": jax.random.normal(
            k_emb, (cfg.vocab_size, cfg.d_model), cfg.dtype) * s,
        "blocks": stack_stage_params(blocks),
        "ln_f": jnp.ones((cfg.d_model,), cfg.dtype),
    }


def param_shardings(params, cfg: TransformerConfig, mesh: Mesh,
                    *, model_axis: str = "model",
                    data_axis: str = "data") -> Any:
    """Megatron-style TP as GSPMD annotations: FFN hidden dim and QKV/O
    head dim shard over ``model_axis``; embeddings shard rows over it.
    Returns a NamedSharding pytree matching ``params``."""
    del data_axis  # params are never dp-sharded; activations are

    def spec(path: str, leaf) -> P:
        if path in ("wq", "wk", "wv", "w_gate", "w_up"):
            return P(None, None, model_axis)      # (L, d, d|dff) col-shard
        if path in ("wo", "w_down"):
            return P(None, model_axis, None)      # (L, dff|d, d) row-shard
        if path == "w_in":
            return P(None, None, None, model_axis)   # (L, E, d, dff)
        if path == "w_out":
            return P(None, None, model_axis, None)   # (L, E, dff, d)
        if path == "embed":
            return P(model_axis, None)
        return P()

    def walk(tree, name=""):
        if isinstance(tree, MoEParams):
            return MoEParams(*(walk(v, f) for f, v in
                               zip(tree._fields, tree)))
        if isinstance(tree, dict):
            return {k: walk(v, k) for k, v in tree.items()}
        return NamedSharding(mesh, spec(name, tree))

    return walk(params)


# -- forward ---------------------------------------------------------------

def _rms_norm(x, g, eps=1e-6):
    x32 = x.astype(jnp.float32)
    r = jax.lax.rsqrt(jnp.mean(x32 * x32, -1, keepdims=True) + eps)
    return (x32 * r).astype(x.dtype) * g


def _rope(x, base: float):
    """(B, S, H, D) rotary position embedding."""
    B, S, H, D = x.shape
    half = D // 2
    freqs = base ** (-jnp.arange(half, dtype=jnp.float32) / half)
    ang = jnp.arange(S, dtype=jnp.float32)[:, None] * freqs[None]  # (S, h)
    cos, sin = jnp.cos(ang), jnp.sin(ang)
    x1, x2 = x[..., :half], x[..., half:]
    rot1 = x1 * cos[None, :, None] - x2 * sin[None, :, None]
    rot2 = x2 * cos[None, :, None] + x1 * sin[None, :, None]
    return jnp.concatenate([rot1, rot2], -1).astype(x.dtype)


def _attention(blk, x, cfg: TransformerConfig, mesh: Optional[Mesh],
               seq_axis: str):
    B, S, d = x.shape
    H, Dh = cfg.n_heads, cfg.head_dim
    h = _rms_norm(x, blk["ln1"])
    q = (h @ blk["wq"]).reshape(B, S, H, Dh)
    k = (h @ blk["wk"]).reshape(B, S, H, Dh)
    v = (h @ blk["wv"]).reshape(B, S, H, Dh)
    q, k = _rope(q, cfg.rope_base), _rope(k, cfg.rope_base)
    # like _ffn: the collective variants need their axis on the mesh;
    # otherwise fall back to the numerically identical local computation
    has_seq = mesh is not None and seq_axis in mesh.axis_names
    if cfg.attention == "ring" and has_seq:
        o = ring_attention(q, k, v, mesh, axis=seq_axis, causal=True)
    elif cfg.attention == "ulysses" and has_seq:
        o = ulysses_attention(q, k, v, mesh, axis=seq_axis, causal=True)
    else:
        o = full_attention(q, k, v, causal=True)
    return x + o.reshape(B, S, d) @ blk["wo"]


def _ffn(blk, x, cfg: TransformerConfig, mesh: Optional[Mesh],
         expert_axis: str):
    B, S, d = x.shape
    h = _rms_norm(x, blk["ln2"])
    if cfg.n_experts:
        tokens = h.reshape(B * S, d)
        if mesh is not None and expert_axis in mesh.axis_names:
            y, aux = moe_ffn(blk["moe"], tokens, mesh, axis=expert_axis,
                             k=cfg.moe_top_k,
                             capacity_factor=cfg.moe_capacity_factor)
        else:
            y, aux = moe_ffn_reference(blk["moe"], tokens, k=cfg.moe_top_k)
        return x + y.reshape(B, S, d), aux
    y = (jax.nn.silu(h @ blk["w_gate"]) * (h @ blk["w_up"])) @ blk["w_down"]
    return x + y, jnp.float32(0.0)


def _remat_policy(cfg: TransformerConfig):
    """checkpoint policy for the block body.  "dots": save dot outputs
    that have no batch dims — i.e. the wq/wk/wv/wo and FFN weight
    matmuls — while the (b, h)-batched score/PV einsums (the S x S
    intermediates, the memory remat exists to shed) are recomputed.
    "full": save nothing (the round-5 pre-policy behavior; its measured
    B=256 cell recomputed every matmul)."""
    if cfg.remat_policy == "full":
        return None
    if cfg.remat_policy == "dots":
        return jax.checkpoint_policies.dots_with_no_batch_dims_saveable
    raise ValueError(f"unknown remat_policy: {cfg.remat_policy!r}")


def block_apply(blk, x, cfg: TransformerConfig, mesh: Optional[Mesh] = None,
                *, seq_axis: str = "seq", expert_axis: str = "expert"):
    x = _attention(blk, x, cfg, mesh, seq_axis)
    return _ffn(blk, x, cfg, mesh, expert_axis)


def forward(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, *, seq_axis: str = "seq",
            expert_axis: str = "expert"):
    """tokens (B, S) int32 -> (logits (B, S, V), aux_loss)."""
    x = params["embed"][tokens]

    # one compiled block body regardless of depth: scan over the stacked
    # (n_layers, ...) params instead of unrolling n_layers copies
    def body(carry, blk):
        x, aux = carry
        x, a = block_apply(blk, x, cfg, mesh, seq_axis=seq_axis,
                           expert_axis=expert_axis)
        return (x, aux + a), None

    if cfg.remat:
        body = jax.checkpoint(body, policy=_remat_policy(cfg))
    (x, aux), _ = jax.lax.scan(body, (x, jnp.float32(0.0)),
                               params["blocks"])
    x = _rms_norm(x, params["ln_f"])
    return x @ params["embed"].T, aux


def forward_pipelined(params, tokens, cfg: TransformerConfig, mesh: Mesh,
                      *, stage_axis: str = "stage",
                      num_microbatches: int = 4):
    """Same function, trunk run as a stage pipeline over ``stage_axis``
    (one block per stage: n_layers must equal the axis size).  Embed and
    head stay outside the pipelined trunk (homogeneous-activation rule).
    Dense-FFN, local attention — the pipeline composes with dp, not with
    the collective attention variants (one shard_map at a time)."""
    if cfg.n_experts or cfg.attention != "full":
        raise ValueError("pipelined trunk requires full attention and "
                         "dense FFN (nested shard_map is not supported)")
    x = params["embed"][tokens]

    def stage_fn(blk, act):
        out, _ = block_apply(blk, act, cfg, None)
        return out

    if cfg.remat:
        stage_fn = jax.checkpoint(stage_fn, policy=_remat_policy(cfg))

    x = pipeline_apply(stage_fn, params["blocks"], x, mesh,
                       axis=stage_axis, num_microbatches=num_microbatches)
    x = _rms_norm(x, params["ln_f"])
    return x @ params["embed"].T, jnp.float32(0.0)


# -- training --------------------------------------------------------------

def lm_loss(params, tokens, cfg: TransformerConfig,
            mesh: Optional[Mesh] = None, aux_weight: float = 0.01,
            **fwd_kwargs):
    """Next-token cross entropy (+ weighted MoE aux)."""
    logits, aux = forward(params, tokens[:, :-1], cfg, mesh, **fwd_kwargs)
    targets = tokens[:, 1:]
    logp = jax.nn.log_softmax(logits.astype(jnp.float32), -1)
    nll = -jnp.take_along_axis(logp, targets[..., None], -1).mean()
    return nll + aux_weight * aux


@partial(jax.jit, static_argnames=("cfg", "lr"), donate_argnums=0)
def sgd_step(params, tokens, cfg: TransformerConfig, lr: float = 0.1):
    """One SGD training step.  Under a mesh, dp/tp come from the shardings
    of ``params``/``tokens`` (GSPMD inserts the collectives); no
    parallelism code appears here at all — the point of the design."""
    loss, grads = jax.value_and_grad(lm_loss)(params, tokens, cfg)
    new = jax.tree.map(lambda p, g: p - lr * g.astype(p.dtype),
                       params, grads)
    return new, loss
