"""Measurement-driven kernel selection (autotuning verdicts).

The round-2 on-chip profile showed XLA's HBM row gather is
transaction-bound at ~3.5% of HBM peak — but whether the Pallas
VMEM-resident alternative actually beats it is a *measurement*, not a
judgment call, and the answer may differ per platform/generation.  This
module is the tiny persistence layer that lets microbenchmarks
(scripts/gather_micro.py, scripts/scatter_micro.py) record their A/B
verdicts and lets hot paths (transfer/xla.py) consult them at trace
time:

    record("vmem_gather", "tpu", {"win": True, "pallas_ms": ..,
                                  "xla_ms": ..})
    lookup("vmem_gather", "tpu")  -> dict | None

Verdicts live in ``.bench_cache/calibration.json`` at the repo root —
the same evidence directory bench.py uses for chip results; the session
workflow commits it with the round's measurement evidence so a checkout
on the same hardware class inherits the verdicts.  Absent the file,
every gate defaults to the XLA path, so a cold environment can never
get slower.

The reference has no analogue (its hot loop is fixed C++); this is the
TPU-first replacement for hand-tuning.
"""

from __future__ import annotations

import json
import os
import sys
import threading
from typing import Optional

_LOCK = threading.Lock()
_CACHE: Optional[dict] = None
_STACK: Optional[dict] = None
_STALE_WARNED: set = set()


def _path() -> str:
    env = os.environ.get("SMTPU_CALIBRATION")
    if env:
        return env
    repo = os.path.dirname(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    return os.path.join(repo, ".bench_cache", "calibration.json")


def _load() -> dict:
    global _CACHE
    if _CACHE is None:
        try:
            with open(_path()) as f:
                _CACHE = json.load(f)
        except (OSError, ValueError):
            _CACHE = {}
    return _CACHE


def stack_key() -> dict:
    """The software stack a verdict was measured under: jaxlib and
    libtpu versions.  A kernel's win/loss (or even its lowerability —
    see the recorded ``taa``/``take`` Mosaic rejections) can flip
    across compiler releases, so the stack is part of a verdict's
    identity just like the device kind already in the key.  Resolved
    without initializing a JAX backend, so the ``--stale-check`` CLI
    stays cheap enough for ``run_tier1.sh``."""
    global _STACK
    if _STACK is None:
        try:
            import jaxlib
            jl = getattr(jaxlib, "__version__", "unknown")
        except Exception:
            jl = "unknown"
        lt = "none"
        try:
            from importlib import metadata
            for dist in ("libtpu", "libtpu-nightly"):
                try:
                    lt = metadata.version(dist)
                    break
                except metadata.PackageNotFoundError:
                    continue
        except Exception:
            lt = "unknown"
        _STACK = {"jaxlib": jl, "libtpu": lt}
    return dict(_STACK)


def _stale_reason(verdict: dict) -> Optional[str]:
    """Why a verdict must not steer a gate on this stack, or None."""
    got = verdict.get("stack")
    if not isinstance(got, dict):
        return "recorded without a stack stamp (pre-stamp format)"
    cur = stack_key()
    diffs = [f"{k} {got.get(k, '?')} -> {cur[k]}"
             for k in cur if got.get(k) != cur[k]]
    if diffs:
        return "recorded on a different stack: " + ", ".join(diffs)
    return None


def lookup(name: str, platform: str) -> Optional[dict]:
    """Most recent verdict for (kernel, platform), or None.

    A verdict recorded under a different jaxlib/libtpu stack (or
    before stamps existed) is rejected with a loud re-calibrate
    message: the device kind in the key already pins the chip, and the
    stamp pins the compiler — a stale A/B result must never silently
    steer a data-plane gate."""
    key = f"{name}:{platform}"
    verdict = _load().get(key)
    if verdict is None:
        return None
    reason = _stale_reason(verdict)
    if reason is not None:
        if key not in _STALE_WARNED:
            _STALE_WARNED.add(key)
            print(f"calibration: STALE verdict ignored for {key} "
                  f"({reason}) — RE-CALIBRATE via "
                  f"scripts/gather_micro.py --ab-only and "
                  f"scripts/scatter_micro.py --ab-only",
                  file=sys.stderr, flush=True)
        return None
    return verdict


def stale_keys() -> list:
    """``[(key, reason)]`` for every stored verdict this stack must
    reject — the ``run_tier1.sh`` advisory and the ``--stale-check``
    CLI read this without going through per-gate lookups."""
    out = []
    for key, verdict in sorted(_load().items()):
        if not isinstance(verdict, dict):
            continue
        reason = _stale_reason(verdict)
        if reason is not None:
            out.append((key, reason))
    return out


def record(name: str, platform: str, verdict: dict) -> None:
    """Persist a verdict stamped with the current jaxlib/libtpu stack;
    merges with the existing file under a lock."""
    global _CACHE
    verdict = dict(verdict)
    verdict.setdefault("stack", stack_key())
    with _LOCK:
        path = _path()
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            data = {}
        data[f"{name}:{platform}"] = verdict
        os.makedirs(os.path.dirname(path), exist_ok=True)
        tmp = path + ".tmp"
        with open(tmp, "w") as f:
            json.dump(data, f, indent=1, sort_keys=True)
        os.replace(tmp, path)
        _CACHE = data


def clear(name: str) -> None:
    """Remove every recorded verdict for ``name`` (all device kinds) —
    the rollback path when a kernel that won its microbench A/B then
    breaks the full step (the gate must fail open to the XLA path)."""
    global _CACHE
    with _LOCK:
        path = _path()
        try:
            with open(path) as f:
                data = json.load(f)
        except (OSError, ValueError):
            # match the on-disk state just observed: the in-process
            # memo must not keep serving a verdict the caller believes
            # was cleared
            _CACHE = {}
            return
        kept = {k: v for k, v in data.items()
                if not k.startswith(name + ":")}
        if kept != data:
            tmp = path + ".tmp"
            with open(tmp, "w") as f:
                json.dump(kept, f, indent=1, sort_keys=True)
            os.replace(tmp, path)
        _CACHE = kept


def reset_cache() -> None:
    """Drop the in-process memo (tests; or after an external write)."""
    global _CACHE
    _CACHE = None
    _STALE_WARNED.clear()


def device_key() -> str:
    """Calibration key for the current accelerator: the device *kind*
    (e.g. ``TPU v5 lite``), not the bare platform — a win measured on
    one TPU generation must not gate the kernel on another."""
    import jax

    return jax.devices()[0].device_kind


def on_tpu() -> bool:
    """Is the default device a TPU?  Checked via the DEVICE platform,
    not ``jax.default_backend()`` — a PJRT plugin (e.g. the axon
    tunnel) may register under its own backend name while its devices
    still report platform ``tpu``; trusting the backend name would
    silently leave every kernel in interpret mode on the real chip."""
    import jax

    try:
        return jax.devices()[0].platform == "tpu"
    except Exception:
        return False


def ab_verdict(name: str, xla_ms: float, pallas_ms: float = None,
               correct: bool = None, shape: str = None,
               error: str = None, extra: dict = None) -> dict:
    """Build the standard A/B verdict (shared by the gather and scatter
    microbench harnesses) and record it when running on a real chip:
    a win requires the kernel to be CORRECT on-device and >=10% faster
    than the XLA path; any lowering failure is a loud non-win.
    ``extra`` merges additional keys (e.g. the winning kernel variant)
    into the recorded verdict."""
    if error is not None:
        verdict = {"win": False, "error": error,
                   "xla_ms": round(xla_ms, 3)}
    else:
        verdict = {"win": bool(correct and pallas_ms < 0.9 * xla_ms),
                   "correct": bool(correct),
                   "pallas_ms": round(pallas_ms, 3),
                   "xla_ms": round(xla_ms, 3)}
        if shape:
            verdict["shape"] = shape
    verdict.update(extra or {})
    import jax

    if os.environ.get("SMTPU_AB_RECORD", "1") == "0":
        # rollback mode (chip_session verdict_rollback): measure and
        # print, but never re-arm a verdict diagnosed as breaking the
        # full step in this session
        print(f"calibration NOT recorded (SMTPU_AB_RECORD=0): "
              f"{name} -> {verdict}", flush=True)
        return verdict
    if jax.devices()[0].platform == "tpu":
        key = device_key()
        record(name, key, verdict)
        print(f"calibration recorded: {name}:{key} -> {verdict}",
              flush=True)
    return verdict


# every Pallas kernel behind a measurement gate; pallas_status walks
# this list so a new kernel cannot silently count as validated
_PALLAS_KERNELS = ("vmem_gather", "vmem_scatter", "replica_scatter",
                   "stencil_fused", "ring_push")

#: pseudo device-kind for interpret-mode (off-chip) oracle runs — a
#: correctness exercise, never a performance verdict
INTERPRET_KIND = "interpret"


def record_interpret(name: str, correct: bool, shape: str = None,
                     extra: dict = None) -> dict:
    """Record an interpret-mode numpy-oracle exercise for a kernel.

    This is the off-chip half of the validation story: it proves the
    kernel's *semantics* (against a host oracle, interpret=True) without
    touching a chip, so ``pallas_status`` can distinguish "never
    exercised" from "exercised off-chip, awaiting on-chip A/B".  It
    carries no timing and can never flip a ``gated()`` decision — the
    gate only consults the real device kind."""
    verdict = {"correct": bool(correct), "interpret": True}
    if shape:
        verdict["shape"] = shape
    verdict.update(extra or {})
    record(name, INTERPRET_KIND, verdict)
    return verdict


def pallas_status(kind: Optional[str] = None) -> str:
    """One-line Pallas validation status for a device kind (r5 verdict
    Next #6): the kernels count as a hardware capability ONLY once a
    measured on-chip A/B verdict (pallas_ms vs xla_ms) exists for the
    key — until then bench/calibration output must carry the explicit
    ``unvalidated-on-tpu`` marker instead of implying the capability.
    A recorded lowering *error* is an attempt, not a validation, and an
    interpret-mode oracle pass (``record_interpret``) upgrades the
    marker to "exercised off-chip" without clearing it."""
    if kind is None:
        kind = device_key()
    verdicts = {n: lookup(n, kind) for n in _PALLAS_KERNELS}
    measured = {n: v for n, v in verdicts.items()
                if v and "pallas_ms" in v and "xla_ms" in v}
    if not measured:
        errs = sorted(n for n, v in verdicts.items() if v and "error" in v)
        if errs:
            return ("unvalidated-on-tpu (attempted, lowering failed: "
                    + ", ".join(errs) + ")")
        interp = sorted(
            n for n in _PALLAS_KERNELS
            if (lookup(n, INTERPRET_KIND) or {}).get("correct"))
        if interp:
            return ("unvalidated-on-tpu (exercised off-chip, "
                    "interpret-mode correct: " + ", ".join(interp) + ")")
        return "unvalidated-on-tpu"
    wins = sorted(n for n, v in measured.items() if v.get("win"))
    if wins:
        return "validated: win (" + ", ".join(wins) + ")"
    return "validated: no-win"


def gated(name: str, env_var: str, fits: bool,
          manual: bool = False) -> bool:
    """The shared measurement-driven gate policy (one copy for all
    Pallas kernels): env force-off beats everything; a kernel that
    doesn't fit never routes; env force-on is the caller's explicit
    override (tests/experiments); auto requires TPU backend, a single
    device (the kernels are single-core VMEM programs — sharded
    operands would be re-laid-out or rejected by the partitioner), and
    a recorded on-chip win for this device kind.

    ``manual=True`` relaxes the single-device requirement: the caller
    is inside ``shard_map`` where operands are already per-device local
    arrays, so the partitioner hazard doesn't exist and the single-chip
    verdict is the right proxy for each core's kernel."""
    import jax

    mode = os.environ.get(env_var, "auto").lower()
    if mode in ("0", "off", "false"):
        return False
    if not fits:
        return False
    if mode in ("1", "on", "true"):
        return True
    if not on_tpu():
        return False
    if not manual and jax.device_count() != 1:
        return False
    verdict = lookup(name, device_key())
    return bool(verdict and verdict.get("win"))


#: legal values of the ``[cluster] data_plane:`` knob
DATA_PLANE_MODES = ("auto", "pallas", "xla")


def data_plane_gated(mode: str, name: str, env_var: str, fits: bool,
                     manual: bool = False) -> bool:
    """Resolve the ``[cluster] data_plane:`` knob for one kernel.

    The per-process env var stays the strongest signal (it is the
    experiment/test override, exactly as for the other gates); below
    it, ``xla`` pins the knob off, ``pallas`` forces the kernel on for
    any shape that fits (an explicit operator decision — no verdict
    required), and ``auto`` defers to the measured-verdict policy in
    :func:`gated`, so absent a recorded on-chip win the XLA path
    stays."""
    if mode not in DATA_PLANE_MODES:
        raise ValueError(
            f"[cluster] data_plane must be one of {DATA_PLANE_MODES}, "
            f"got {mode!r}")
    if os.environ.get(env_var) is not None:
        return gated(name, env_var, fits, manual=manual)
    if mode == "xla":
        return False
    if mode == "pallas":
        return bool(fits)
    return gated(name, env_var, fits, manual=manual)


def main(argv=None) -> int:
    """``python -m swiftmpi_tpu.ops.calibration --stale-check``: print
    an advisory staleness report for the verdict file; exits 0 so
    run_tier1.sh prints this next to the pytest verdict without ever
    changing it.  ``--stale-check=strict`` promotes the report to a hard
    gate (exit 1 on any stale verdict): a serving deployment preflights
    with it to refuse to start on another stack's verdicts rather than
    silently fall back to the uncalibrated path under live traffic."""
    argv = list(sys.argv[1:] if argv is None else argv)
    strict = "--stale-check=strict" in argv
    path = _path()
    if not os.path.exists(path):
        print(f"calibration: no verdict file at {path}")
        return 0
    stale = stale_keys()
    total = len([v for v in _load().values() if isinstance(v, dict)])
    if not stale:
        print(f"calibration: {total} verdict(s) at {path} match the "
              f"current stack {stack_key()}")
        return 0
    label = "GATE" if strict else "ADVISORY"
    print(f"calibration {label}: {len(stale)}/{total} verdict(s) at "
          f"{path} are STALE on this stack {stack_key()} — gates fall "
          f"back to the XLA path; re-calibrate on-chip via "
          f"scripts/gather_micro.py --ab-only and "
          f"scripts/scatter_micro.py --ab-only:")
    for key, reason in stale:
        print(f"  {key}: {reason}")
    return 1 if strict else 0


if __name__ == "__main__":
    sys.exit(main())
