"""Pallas TPU kernel: fused span gather + sliding-window context sum.

The stencil w2v step (PR 2) pulls the span's unique rows and then
builds each center's context sum with a gather->mask->sum XLA chain:
``v_span = pull(span_slots)``, ``v_ctx = v_span[ctx_idx]`` (a (B, 2W)
row gather re-reading every span row ~2W times), then a masked
reduction.  On chip that chain is three HBM-traffic passes over data
that is only ``S = B + 2W`` unique rows of width d — ~6.6MB at the 1M
bench shape, comfortably VMEM-resident.

``fused_stencil_gather`` is the CBOW inner loop as ONE kernel:

* **Phase A** (first grid step only): double-buffered per-row
  HBM->VMEM DMA of the ≤ B+2W unique span rows, addressed by SMEM
  scalars — the ``loop`` addressing idiom from ``pallas_gather.py``,
  the one form chip round 3 proved Mosaic lowers (vector-value index
  extraction and equal-shape ``take_along_axis`` are both rejected).
  The span scratch persists across grid steps.
* **Phase B** (every grid step, ``block_b`` centers at a time): for
  each center b, one dynamic ref slice ``vspan[lo[b] : lo[b]+2W+1]``
  and a (1, 2W+1) x (2W+1, d) mask-row matmul produce the context sum.
  Sentence boundaries, per-row dynamic window radius ``half``, the
  ``off != 0`` center exclusion and pad rows are all carried by the
  precomputed window mask — the kernel itself is branch-free.

The window mask lives in the *window frame* (positions ``lo[b]..
lo[b]+2W``) rather than the offset frame the XLA path uses;
:func:`stencil_window_inputs` builds it from the stream-span batch and
is shared by the call site (models/word2vec.py) and the parity tests.
Contributions are identical set-for-set to the XLA chain; only the
floating-point reduction order differs (matmul vs ordered adds).

Routing: ``use_fused_stencil`` resolves the ``[cluster] data_plane:``
knob through ``calibration.data_plane_gated`` — absent a measured
on-chip win recorded by the ``w2v_1m_fused`` bench cell or
``scripts/gather_micro.py --stencil-ab``, the XLA chain stays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftmpi_tpu.ops import calibration

#: in-flight row-DMA depth of the phase-A span stage
_NBUF = 4
#: default centers per grid step (bounds the neu1/wmask VMEM blocks)
_DEF_BLOCK_B = 2048


def _stencil_kernel(slots_ref, lo_ref, wmask_ref, table_ref,
                    neu1_ref, vspan_ref, sems):
    S = vspan_ref.shape[0]
    cap = table_ref.shape[0]
    K = wmask_ref.shape[1]            # 2W + 1
    nbuf = min(_NBUF, S)

    def row_copy(i, slot):
        return pltpu.make_async_copy(
            table_ref.at[pl.ds(slot, 1), :],
            vspan_ref.at[pl.ds(i, 1), :],
            sems.at[i % nbuf])

    def start(i):
        # clip keeps pad slots (-1) defined; pad rows are never read
        # unmasked (their wmask column is 0 for every center)
        row_copy(i, jnp.clip(slots_ref[i], 0, cap - 1)).start()

    @pl.when(pl.program_id(0) == 0)
    def _stage_span():
        # double-buffered: keep nbuf row DMAs in flight, wait in order
        for i in range(nbuf):
            start(i)

        def body(i, _):
            row_copy(i, jnp.clip(slots_ref[i], 0, cap - 1)).wait()

            @pl.when(i + nbuf < S)
            def _():
                start(i + nbuf)
            return 0

        jax.lax.fori_loop(0, S, body, 0)

    def center(b, _):
        lo = jnp.clip(lo_ref[b], 0, S - K)
        win = vspan_ref[pl.ds(lo, K), :].astype(jnp.float32)   # (K, d)
        m = wmask_ref[pl.ds(b, 1), :]                          # (1, K)
        neu1_ref[pl.ds(b, 1), :] = jnp.dot(
            m, win, preferred_element_type=jnp.float32)
        return 0

    jax.lax.fori_loop(0, neu1_ref.shape[0], center, 0)


@functools.partial(jax.jit, static_argnames=("interpret", "block_b"))
def fused_stencil_gather(table: jax.Array, slots: jax.Array,
                         lo: jax.Array, wmask: jax.Array,
                         interpret: bool | None = None,
                         block_b: int = _DEF_BLOCK_B) -> jax.Array:
    """Fused ``sum_k wmask[b,k] * table[slots[lo[b]+k]]`` -> (B, d) f32.

    ``table`` stays in HBM (ANY); only the (S, d) span scratch, one
    (block_b, d) output block and one (block_b, 2W+1) mask block are
    VMEM-resident — callers check :func:`fits_vmem` first.  ``slots``
    is the span's slot ids (pad rows -1), ``lo``/``wmask`` come from
    :func:`stencil_window_inputs`.
    """
    S = slots.shape[0]
    B = lo.shape[0]
    d = table.shape[1]
    K = wmask.shape[1]
    if interpret is None:
        interpret = not calibration.on_tpu()
    bb = min(block_b, B)
    pad = (-B) % bb
    if pad:
        lo = jnp.concatenate([lo, jnp.zeros((pad,), lo.dtype)])
        wmask = jnp.concatenate(
            [wmask, jnp.zeros((pad, K), wmask.dtype)])
    out = pl.pallas_call(
        _stencil_kernel,
        grid=((B + pad) // bb,),
        in_specs=[
            pl.BlockSpec((S,), lambda i: (0,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bb,), lambda i: (i,), memory_space=pltpu.SMEM),
            pl.BlockSpec((bb, K), lambda i: (i, 0)),
            pl.BlockSpec(memory_space=pltpu.ANY),
        ],
        out_specs=pl.BlockSpec((bb, d), lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((B + pad, d), jnp.float32),
        scratch_shapes=[pltpu.VMEM((S, d), table.dtype),
                        pltpu.SemaphoreType.DMA((min(_NBUF, S),))],
        interpret=interpret,
    )(slots, lo, wmask, table)
    return out[:B]


def stencil_window_inputs(sent_id: jax.Array, center_pos: jax.Array,
                          half: jax.Array, window: int):
    """Window-frame inputs ``(lo, wmask)`` for the fused kernel, from
    the stream-span batch (XLA ops, traced into the step).

    ``lo[b]`` anchors a fixed (2W+1)-row window inside the span so the
    kernel's ref slice is always in-bounds; ``wmask[b, k]`` is 1 iff
    span position ``lo[b] + k`` is a true context of center b — same
    offset/sentence/radius/pad conditions as the XLA chain's
    ``ctx_mask``, re-expressed in window coordinates.  Every true
    contribution (|off| <= half <= W, same sentence, in-span) lands in
    the window exactly once: lo = clip(cp - W, 0, S - 2W - 1) keeps
    ``cp - lo`` within [0, 2W] for any in-span context index.
    """
    S = sent_id.shape[0]
    K = 2 * window + 1
    row_valid = center_pos >= 0
    cp = jnp.clip(center_pos, 0, S - 1)
    lo = jnp.clip(cp - window, 0, max(S - K, 0)).astype(jnp.int32)
    k = jnp.arange(K, dtype=jnp.int32)
    j = lo[:, None] + k[None, :]                    # (B, K) span pos
    off = j - cp[:, None]
    sid_c = jnp.take(sent_id, cp)
    wmask = ((off != 0)
             & (jnp.abs(off) <= half[:, None])
             & (jnp.take(sent_id, j.reshape(-1)).reshape(j.shape)
                == sid_c[:, None])
             & row_valid[:, None])
    return lo, wmask.astype(jnp.float32)


def fits_vmem(S: int, B: int, d: int, itemsize: int = 4,
              window: int = 4, block_b: int = _DEF_BLOCK_B,
              budget_bytes: int = 12 << 20) -> bool:
    """Conservative VMEM check: the (S, d) span scratch plus one
    (block_b, d) f32 output block and one (block_b, 2W+1) f32 mask
    block under ~12MB (headroom of the ~16MB/core) — the table itself
    never leaves HBM."""
    bb = min(block_b, B)
    span = S * d * itemsize
    blk = bb * d * 4 + bb * (2 * window + 1) * 4
    return span + blk <= budget_bytes


def use_fused_stencil(S: int, B: int, d: int, itemsize: int,
                      window: int, mode: str = "auto") -> bool:
    """Should the stencil step route neu1 through the fused kernel?
    ``mode`` is the ``[cluster] data_plane:`` knob; the per-process
    ``SMTPU_STENCIL_FUSED`` env var overrides it (tests/experiments),
    and ``auto`` requires a recorded on-chip win for this device kind
    (``manual=True``: the operands are already per-device local under
    the stencil step's single-device or shard_map context)."""
    return calibration.data_plane_gated(
        mode, "stencil_fused", "SMTPU_STENCIL_FUSED",
        fits_vmem(S, B, d, itemsize, window), manual=True)
