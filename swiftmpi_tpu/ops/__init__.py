"""Device-side ops: sampling, activation kernels, (later) Pallas kernels."""

from swiftmpi_tpu.ops.sampling import (build_unigram_alias, sample_alias,
                                       subsample_keep_prob)
from swiftmpi_tpu.ops.sigmoid import MAX_EXP, sigmoid_clipped

__all__ = ["build_unigram_alias", "sample_alias", "subsample_keep_prob",
           "MAX_EXP", "sigmoid_clipped"]
