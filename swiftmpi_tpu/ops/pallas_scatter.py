"""Pallas TPU experiment: scatter-add with the accumulator resident in VMEM.

The push half of the parity-mode word2vec step is a scatter-add of
~475K duplicated gradient rows into a capacity-sized accumulator
(transfer/xla.py ``_push_dense``).  On-chip round-2 measurements showed
XLA's scatter is even more transaction-bound than its gather (33ms
standalone at the bench shape, though far better when fused into the
step).  When the accumulator fits VMEM (demo.conf scale: 17K rows), the
whole reduction can run on-chip: stream index/grad blocks through the
grid and read-modify-write accumulator rows at VMEM latency.

Same contract as the gather experiment (ops/pallas_gather.py): the
kernel is correctness-tested in interpret mode on CPU; the on-chip A/B
lives in ``scripts/scatter_micro.py`` and records a calibration verdict
(ops/calibration.py) that gates wiring into the push path — absent a
measured win the XLA path is untouched.

Reference context: this replaces the server-side grad apply of
``MiniBatch::push`` (/root/reference/src/apps/word2vec/word2vec.h:314-317,
167-191), whose "accumulator" is the dense_hash_map row the handler
mutates in place.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftmpi_tpu.ops import calibration

_DEF_IDX_BLOCK = 4096


def _scatter_kernel(idx_ref, g_ref, out_ref):
    """One grid step: sequential RMW of one accumulator row per gradient
    row.  Duplicates within and across blocks are correct because the
    TPU grid and the fori_loop are both sequential.  The accumulator
    block revisits every step (constant index_map), so it stays resident
    and carries partial sums across the grid."""

    @pl.when(pl.program_id(0) == 0)
    def _init():
        out_ref[...] = jnp.zeros_like(out_ref)

    idx = idx_ref[...]

    def body(j, _):
        row = idx[j]
        g = g_ref[pl.ds(j, 1), :]
        out_ref[pl.ds(row, 1), :] = out_ref[pl.ds(row, 1), :] + g
        return 0

    jax.lax.fori_loop(0, idx.shape[0], body, 0)


@functools.partial(jax.jit,
                   static_argnames=("capacity", "idx_block", "interpret"))
def vmem_scatter_add(idx: jax.Array, grads: jax.Array, capacity: int,
                     idx_block: int = _DEF_IDX_BLOCK,
                     interpret: bool | None = None) -> jax.Array:
    """``zeros((capacity+1, W)).at[idx].add(grads)`` with the accumulator
    VMEM-resident.  ``idx`` must be pre-clipped to ``[0, capacity]`` —
    row ``capacity`` is the dump row for padding/invalid entries (the
    caller slices it off), mirroring the XLA path's ``mode="drop"``.
    ``idx`` length must be a multiple of ``idx_block``."""
    n = idx.shape[0]
    if n % idx_block:
        raise ValueError(f"idx length {n} not a multiple of {idx_block}")
    if interpret is None:
        interpret = not calibration.on_tpu()
    W = grads.shape[1]
    grid = (n // idx_block,)
    return pl.pallas_call(
        _scatter_kernel,
        grid=grid,
        in_specs=[
            pl.BlockSpec((idx_block,), lambda i: (i,)),
            pl.BlockSpec((idx_block, W), lambda i: (i, 0)),
        ],
        out_specs=pl.BlockSpec((capacity + 1, W), lambda i: (0, 0),
                               memory_space=pltpu.VMEM),
        out_shape=jax.ShapeDtypeStruct((capacity + 1, W), grads.dtype),
        interpret=interpret,
    )(idx, grads)


def fits_vmem(capacity: int, width: int, itemsize: int = 4,
              idx_block: int = _DEF_IDX_BLOCK,
              budget_bytes: int = 12 << 20) -> bool:
    """Accumulator (+1 dump row, lane-padded width) + one idx/grad block
    under the conservative VMEM budget."""
    lanes = ((width + 127) // 128) * 128
    acc = (capacity + 1) * lanes * itemsize
    blk = idx_block * (4 + lanes * itemsize)
    return acc + blk <= budget_bytes


def use_vmem_scatter(capacity: int, width: int) -> bool:
    """Measurement-driven gate, same contract as
    ``pallas_gather.use_vmem_gather`` (shared policy in
    ``calibration.gated``): env ``SMTPU_PALLAS_SCATTER`` force-on/off;
    auto = single TPU device + fits VMEM + recorded chip win."""
    return calibration.gated("vmem_scatter", "SMTPU_PALLAS_SCATTER",
                             fits_vmem(capacity, width))


def masked_vmem_scatter_add(slots: jax.Array, valid: jax.Array,
                            grads: jax.Array, capacity: int) -> jax.Array:
    """Drop-in for the push path's dense scatter: routes invalid AND
    out-of-range slots to the dump row (exactly XLA's ``mode="drop"`` —
    an OOB slot must not corrupt the last real row), pads to an
    index-block multiple (padding also dumped), and returns the
    ``(capacity, W)`` accumulator."""
    n = slots.shape[0]
    ok = valid & (slots >= 0) & (slots < capacity)
    safe = jnp.where(ok, slots, capacity)
    pad = (-n) % _DEF_IDX_BLOCK
    if pad:
        safe = jnp.concatenate(
            [safe, jnp.full((pad,), capacity, slots.dtype)])
        grads = jnp.concatenate(
            [grads, jnp.zeros((pad, grads.shape[1]), grads.dtype)])
    acc = vmem_scatter_add(safe, grads, capacity)
    return acc[:capacity]
