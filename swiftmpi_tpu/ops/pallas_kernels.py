"""Pallas TPU kernels for the server-side update path.

``adagrad_update`` is the fused server-side optimizer kernel — the
reference's ``apply_push_value`` hot loop (word2vec.h:177-185,
lr.cpp:68-75) as a single VMEM pass:

    accum' = accum + g^2
    param' = param + lr * g * rsqrt(accum' + fudge)

XLA already fuses this chain well; the Pallas version pins the execution
shape — elementwise over a flat ``(rows, 128)`` lane-aligned view with one
VMEM pass per block, and declares input/output aliasing for the pallas
call.  Whether the aliasing actually elides the table copy depends on the
caller: inside the framework's jitted training step the whole table state
is donated (``_build_step``'s ``donate_argnums=0``), so XLA can satisfy
the alias in place; called standalone (as the tests do), the jit keeps its
inputs valid and a copy is inserted.  (The flat view may also cost a
relayout copy for widths that are not lane-aligned; for 128-multiple
embeddings the reshape is layout-free.  The kernel exists as the
framework's optimizer-kernel extension point, not because the jnp rule is
slow.)

On non-TPU backends the kernel runs in Pallas interpret mode (numerics
identical), which the tests use to pin it against the pure-jnp rule.
"""

from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

LANES = 128
_DEF_BLOCK_ROWS = 512


def _adagrad_kernel(lr: float, fudge: float, p_ref, a_ref, g_ref,
                    po_ref, ao_ref):
    g = g_ref[:]
    a = a_ref[:] + g * g
    ao_ref[:] = a
    po_ref[:] = p_ref[:] + lr * g * jax.lax.rsqrt(a + fudge)


@functools.partial(jax.jit, static_argnames=("lr", "fudge", "block_rows",
                                             "interpret"))
def adagrad_update(param: jax.Array, accum: jax.Array, grad: jax.Array,
                   lr: float, fudge: float = 1e-6,
                   block_rows: int = _DEF_BLOCK_ROWS,
                   interpret: bool = False
                   ) -> Tuple[jax.Array, jax.Array]:
    """Fused in-place AdaGrad over arbitrarily-shaped (same-shape) arrays."""
    shape, dtype = param.shape, param.dtype
    n = param.size
    block = block_rows * LANES
    padded = pl.cdiv(n, block) * block
    rows = padded // LANES

    def flat(x):
        x = x.reshape(-1)
        if padded != n:
            x = jnp.pad(x, (0, padded - n))
        return x.reshape(rows, LANES)

    p2, a2, g2 = flat(param), flat(accum), flat(grad)
    grid = (rows // block_rows,)
    spec = pl.BlockSpec((block_rows, LANES), lambda i: (i, 0),
                        memory_space=pltpu.VMEM)
    po, ao = pl.pallas_call(
        functools.partial(_adagrad_kernel, lr, fudge),
        out_shape=(jax.ShapeDtypeStruct((rows, LANES), dtype),
                   jax.ShapeDtypeStruct((rows, LANES), dtype)),
        grid=grid,
        in_specs=[spec, spec, spec],
        out_specs=(spec, spec),
        input_output_aliases={0: 0, 1: 1},
        interpret=interpret,
    )(p2, a2, g2)
    return (po.reshape(-1)[:n].reshape(shape),
            ao.reshape(-1)[:n].reshape(shape))


def default_interpret() -> bool:
    """Interpret mode off only on real TPU devices (checked via the
    device platform, not the backend name — see calibration.on_tpu)."""
    from swiftmpi_tpu.ops.calibration import on_tpu

    return not on_tpu()
