"""Pallas TPU experiment: row gather with the table resident in VMEM.

The on-chip profile (docs/ARCHITECTURE.md "Measured on TPU v5e") shows
XLA's HBM row gather is *transaction-bound* at ~69M rows/s (~14ns/row,
invariant to dtype/alignment/batch) — the hard floor of the per-pair
word2vec step, whose B*(K+1) target rows are drawn with ~20x duplication
from a table that is often small (demo.conf scale: 17K rows x 100 dims
= 6.9MB).  A table that fits VMEM (~16MB/core on v5e) can instead be
staged on-chip once per kernel and gathered at VMEM latency.

This module is the honest experiment VERDICT round 1 asked for ("weak:
Pallas surface — with zero chip measurements nobody knows whether XLA
falls short"): ``vmem_gather(table, idx)`` stages the whole table into
VMEM via the BlockSpec pipeline and gathers index blocks with
``jnp.take`` inside the kernel (Mosaic's dynamic-gather path).  The
A/B against XLA's native gather runs as the final cell of
``scripts/gather_micro.py``; wiring into ``XlaTransfer.pull`` is gated
on that A/B showing a real win on hardware — on CPU the kernel runs in
interpret mode and is for correctness only.

Reference context: the gather this replaces is the pull half of
``MiniBatch::pull`` (/root/reference/src/apps/word2vec/word2vec.h:303-311);
the reference's equivalent "staging" is every worker thread's hot
LocalParamCache in L2.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftmpi_tpu.ops import calibration

_DEF_IDX_BLOCK = 4096


def _gather_kernel(table_ref, idx_ref, out_ref):
    """One grid step: gather ``idx_block`` rows from the VMEM-resident
    table.  ``jnp.take`` on a VMEM value lowers to Mosaic's dynamic
    gather; clip keeps OOB/padding indices defined (callers mask)."""
    idx = jnp.clip(idx_ref[...], 0, table_ref.shape[0] - 1)
    out_ref[...] = jnp.take(table_ref[...], idx, axis=0)


def _gather_loop_kernel(table_ref, idx_ref, out_ref):
    """Fallback form: sequential per-row dynamic-slice copies.  Exists
    because Mosaic's vectorized dynamic-gather path (``jnp.take`` above)
    may be rejected for some shapes/generations — the A/B harness tries
    ``take`` first and records whichever lowers and wins (same pattern
    as ops/pallas_scatter's RMW loop, which is inherently per-row)."""
    idx = jnp.clip(idx_ref[...], 0, table_ref.shape[0] - 1)

    def body(j, _):
        out_ref[pl.ds(j, 1), :] = table_ref[pl.ds(idx[j], 1), :]
        return 0

    jax.lax.fori_loop(0, idx.shape[0], body, 0)


@functools.partial(jax.jit,
                   static_argnames=("idx_block", "interpret", "method"))
def vmem_gather(table: jax.Array, idx: jax.Array,
                idx_block: int = _DEF_IDX_BLOCK,
                interpret: bool | None = None,
                method: str = "take") -> jax.Array:
    """``table[idx]`` with the table staged in VMEM.

    ``idx`` length must be a multiple of ``idx_block`` (pad with any
    in-range value and discard).  Requires the table (plus one index and
    one output block) to fit the ~16MB VMEM budget — callers check
    ``fits_vmem(table)`` first.  ``method``: ``take`` (vectorized
    dynamic gather) or ``loop`` (per-row dynamic slices; the lowering
    fallback)."""
    n = idx.shape[0]
    if n % idx_block:
        raise ValueError(f"idx length {n} not a multiple of {idx_block}")
    if method not in ("take", "loop"):
        # a stale/hand-edited calibration file must fail loudly, not
        # silently select the slow loop kernel on the production path
        raise ValueError(f"unknown vmem_gather method {method!r}")
    if interpret is None:
        interpret = not calibration.on_tpu()
    grid = (n // idx_block,)
    return pl.pallas_call(
        _gather_kernel if method == "take" else _gather_loop_kernel,
        grid=grid,
        in_specs=[
            # whole table every step: the pipeline loads it once and the
            # revisiting steps reuse the resident copy
            pl.BlockSpec(table.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            pl.BlockSpec((idx_block,), lambda i: (i,)),
        ],
        out_specs=pl.BlockSpec((idx_block, table.shape[1]),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, table.shape[1]), table.dtype),
        interpret=interpret,
    )(table, idx)


def fits_vmem(table: jax.Array, idx_block: int = _DEF_IDX_BLOCK,
              budget_bytes: int = 12 << 20) -> bool:
    """Conservative VMEM-residency check: table + one index block + one
    output block under ~12MB (leaving headroom of the ~16MB/core)."""
    t = table.shape[0] * table.shape[1] * table.dtype.itemsize
    blk = idx_block * (4 + table.shape[1] * table.dtype.itemsize)
    return t + blk <= budget_bytes


# --------------------------------------------------------------------------
# the wired-in path: masked gather + measurement-driven gate
# --------------------------------------------------------------------------

def use_vmem_gather(table: jax.Array) -> bool:
    """Should the pull path route this gather through the VMEM kernel?

    Env override ``SMTPU_PALLAS_GATHER``: ``1/on`` forces it whenever the
    table fits, ``0/off`` disables.  Default (``auto``): single TPU
    device only, and only when a recorded on-chip A/B verdict
    (scripts/gather_micro.py -> ops/calibration.py) for this device kind
    says the kernel actually wins — absent evidence, XLA's gather stays
    (a cold environment can never get slower)."""
    return calibration.gated("vmem_gather", "SMTPU_PALLAS_GATHER",
                             fits_vmem(table))


def gather_method() -> str:
    """The kernel variant the recorded verdict crowned for this device
    kind (``take`` when no verdict names one or names an unknown)."""
    v = calibration.lookup("vmem_gather", calibration.device_key())
    m = (v or {}).get("method", "take")
    return m if m in ("take", "loop") else "take"


def masked_vmem_gather(table: jax.Array, slots: jax.Array,
                       valid: jax.Array) -> jax.Array:
    """Drop-in for the pull path's masked ``jnp.take``: pads ``slots`` to
    an index-block multiple, gathers from the VMEM-resident table, and
    zeroes invalid rows — identical semantics to
    ``transfer.xla._masked_gather`` (clip keeps padding defined)."""
    n = slots.shape[0]
    safe = jnp.where(valid, slots, 0)
    pad = (-n) % _DEF_IDX_BLOCK
    if pad:
        safe = jnp.concatenate(
            [safe, jnp.zeros((pad,), slots.dtype)])
    rows = vmem_gather(table, safe, method=gather_method())[:n]
    return jnp.where(valid[:, None], rows, 0)
