"""Pallas TPU experiment: row gather with the table resident in VMEM.

The on-chip profile (docs/ARCHITECTURE.md "Measured on TPU v5e") shows
XLA's HBM row gather is *transaction-bound* at ~69M rows/s (~14ns/row,
invariant to dtype/alignment/batch) — the hard floor of the per-pair
word2vec step, whose B*(K+1) target rows are drawn with ~20x duplication
from a table that is often small (demo.conf scale: 17K rows x 100 dims
= 6.9MB).  A table that fits VMEM (~16MB/core on v5e) can instead be
staged on-chip once per kernel and gathered at VMEM latency.

This module is the honest experiment VERDICT round 1 asked for ("weak:
Pallas surface — with zero chip measurements nobody knows whether XLA
falls short"): ``vmem_gather(table, idx)`` stages the whole table into
VMEM via the BlockSpec pipeline and gathers index blocks with
``jnp.take`` inside the kernel (Mosaic's dynamic-gather path).  The
A/B against XLA's native gather runs as the final cell of
``scripts/gather_micro.py``; wiring into ``XlaTransfer.pull`` is gated
on that A/B showing a real win on hardware — on CPU the kernel runs in
interpret mode and is for correctness only.

Reference context: the gather this replaces is the pull half of
``MiniBatch::pull`` (/root/reference/src/apps/word2vec/word2vec.h:303-311);
the reference's equivalent "staging" is every worker thread's hot
LocalParamCache in L2.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftmpi_tpu.ops import calibration

_DEF_IDX_BLOCK = 4096
_TAA_IDX_BLOCK = 1024


def _gather_kernel(table_ref, idx_ref, out_ref):
    """One grid step: gather ``idx_block`` rows from the VMEM-resident
    table.  ``jnp.take`` on a VMEM value lowers to Mosaic's dynamic
    gather; clip keeps OOB/padding indices defined (callers mask).

    Round-3 chip A/B: Mosaic REJECTS this form ("Shape mismatch in
    input, indices and output") — its TC gather lowering
    (jax/_src/pallas/mosaic/lowering.py _gather_lowering_rule) supports
    only the equal-shape take_along_axis pattern.  Kept for the record
    and for generations whose Mosaic may accept it; the `taa` variant
    below is the form that lowers today."""
    idx = jnp.clip(idx_ref[...], 0, table_ref.shape[0] - 1)
    out_ref[...] = jnp.take(table_ref[...], idx, axis=0)


def _gather_taa_kernel(table_ref, idx_ref, out_ref):
    """Equal-shape ``take_along_axis`` form: ``tpu.dynamic_gather``
    requires input, indices and output to share one 2D shape, so the
    kernel walks the VMEM-resident table in ``idx_block``-row chunks
    (static unroll) and accumulates the masked equal-shape gather of
    each chunk.  Round-3 chipless-AOT finding: Mosaic STILL rejects it
    ("Multiple source vregs along gather dimension") — the primitive is
    a within-vreg shuffle (8 sublanes for f32), not a table gather, so
    any chunk big enough to be useful spans many vregs.  Kept for the
    A/B record and for future Mosaic versions; ``loop`` is the variant
    that lowers today.

        out[i, :] = sum_c  [c*B <= idx[i] < (c+1)*B] * chunk_c[idx[i] - c*B, :]

    Vector work is N * (table_rows/idx_block) lane-gathers — all VMEM
    register traffic, no HBM transactions, which is the entire point
    vs XLA's transaction-bound 400B-row fetches."""
    n_blk = out_ref.shape[0]
    d = out_ref.shape[1]
    rows = table_ref.shape[0]
    idx = jnp.clip(idx_ref[...], 0, rows - 1)
    # masks are born 2D from 32-bit values: reshaping a 1-bit vector
    # 1D->2D ("insertion of minor dim") is rejected by Mosaic
    idx2 = jnp.broadcast_to(idx[:, None], (n_blk, d))
    acc = jnp.zeros((n_blk, d), table_ref.dtype)
    for c in range(rows // n_blk):
        chunk = table_ref[c * n_blk:(c + 1) * n_blk, :]
        li2 = idx2 - c * n_blk
        inb2 = (li2 >= 0) & (li2 < n_blk)
        g = jnp.take_along_axis(chunk, jnp.where(inb2, li2, 0), axis=0,
                                mode="promise_in_bounds")
        acc = acc + jnp.where(inb2, g, jnp.zeros((), table_ref.dtype))
    out_ref[...] = acc


def _gather_loop_kernel(table_ref, idx_ref, out_ref):
    """Fallback form: sequential per-row copies, indices read as SMEM
    scalars.  The round-2 rendering extracted ``idx[j]`` from a vector
    value — that lowers to ``dynamic_slice``, which Mosaic TC rejects
    (round-3 chip A/B); scalar reads from an SMEM ref are the supported
    addressing path, and the row copies are ref dynamic slices (DMA-
    addressable), not vector-value slices."""

    unroll = 8

    def body(j, _):
        # unrolled x8: the per-row copies are independent; amortizes
        # the fori_loop bookkeeping over 8 VMEM row moves
        for u in range(unroll):
            r = j * unroll + u
            i = jnp.clip(idx_ref[r], 0, table_ref.shape[0] - 1)
            out_ref[pl.ds(r, 1), :] = table_ref[pl.ds(i, 1), :]
        return 0

    jax.lax.fori_loop(0, out_ref.shape[0] // unroll, body, 0)


_METHODS = ("taa", "take", "loop")


@functools.partial(jax.jit,
                   static_argnames=("idx_block", "interpret", "method"))
def vmem_gather(table: jax.Array, idx: jax.Array,
                idx_block: int | None = None,
                interpret: bool | None = None,
                method: str = "taa") -> jax.Array:
    """``table[idx]`` with the table staged in VMEM.

    ``idx`` length must be a multiple of ``idx_block`` (pad with any
    in-range value and discard).  Requires the table (plus one index and
    one output block) to fit the ~16MB VMEM budget — callers check
    ``fits_vmem(table)`` first.  ``method``: ``taa`` (chunked
    equal-shape take_along_axis — the form Mosaic TC lowers, see
    kernel docstrings), ``take`` (whole-table vectorized gather;
    rejected by today's Mosaic, kept for the A/B), or ``loop``
    (per-row ref slices addressed by SMEM scalars)."""
    n = idx.shape[0]
    if idx_block is None:
        idx_block = _TAA_IDX_BLOCK if method == "taa" else _DEF_IDX_BLOCK
    if n % idx_block:
        raise ValueError(f"idx length {n} not a multiple of {idx_block}")
    if method not in _METHODS:
        # a stale/hand-edited calibration file must fail loudly, not
        # silently select the slow loop kernel on the production path
        raise ValueError(f"unknown vmem_gather method {method!r}")
    if interpret is None:
        interpret = not calibration.on_tpu()
    if method == "taa":
        # the equal-shape gather walks the table in idx_block-row
        # chunks, so the resident copy is padded to a chunk multiple
        pad_rows = (-table.shape[0]) % idx_block
        if pad_rows:
            table = jnp.concatenate(
                [table, jnp.zeros((pad_rows, table.shape[1]),
                                  table.dtype)])
    grid = (n // idx_block,)
    kernel = {"taa": _gather_taa_kernel,
              "take": _gather_kernel,
              "loop": _gather_loop_kernel}[method]
    if method == "loop":
        # indices as SMEM scalars: vector-value extraction lowers to
        # dynamic_slice, which Mosaic TC rejects; SMEM scalar reads
        # are the supported per-row addressing path
        idx_spec = pl.BlockSpec((idx_block,), lambda i: (i,),
                                memory_space=pltpu.SMEM)
    else:
        idx_spec = pl.BlockSpec((idx_block,), lambda i: (i,))
    return pl.pallas_call(
        kernel,
        grid=grid,
        in_specs=[
            # whole table every step: the pipeline loads it once and the
            # revisiting steps reuse the resident copy
            pl.BlockSpec(table.shape, lambda i: (0, 0),
                         memory_space=pltpu.VMEM),
            idx_spec,
        ],
        out_specs=pl.BlockSpec((idx_block, table.shape[1]),
                               lambda i: (i, 0)),
        out_shape=jax.ShapeDtypeStruct((n, table.shape[1]), table.dtype),
        interpret=interpret,
    )(table, idx)


def fits_vmem(table: jax.Array, idx_block: int = _DEF_IDX_BLOCK,
              budget_bytes: int = 12 << 20) -> bool:
    """Conservative VMEM-residency check: table + one index block + one
    output block under ~12MB (leaving headroom of the ~16MB/core)."""
    t = table.shape[0] * table.shape[1] * table.dtype.itemsize
    blk = idx_block * (4 + table.shape[1] * table.dtype.itemsize)
    return t + blk <= budget_bytes


# --------------------------------------------------------------------------
# the wired-in path: masked gather + measurement-driven gate
# --------------------------------------------------------------------------

def use_vmem_gather(table: jax.Array) -> bool:
    """Should the pull path route this gather through the VMEM kernel?

    Env override ``SMTPU_PALLAS_GATHER``: ``1/on`` forces it whenever the
    table fits, ``0/off`` disables.  Default (``auto``): single TPU
    device only, and only when a recorded on-chip A/B verdict
    (scripts/gather_micro.py -> ops/calibration.py) for this device kind
    says the kernel actually wins — absent evidence, XLA's gather stays
    (a cold environment can never get slower)."""
    return calibration.gated("vmem_gather", "SMTPU_PALLAS_GATHER",
                             fits_vmem(table))


def gather_method() -> str:
    """The kernel variant the recorded verdict crowned for this device
    kind (``taa`` when no verdict names one or names an unknown)."""
    v = calibration.lookup("vmem_gather", calibration.device_key())
    m = (v or {}).get("method", "taa")
    return m if m in _METHODS else "taa"


def gather_idx_block() -> int | None:
    """The index-block size the verdict crowned (None = the method's
    default) — taa's chunk redundancy scales with table_rows/idx_block,
    so the A/B measures more than one block size."""
    v = calibration.lookup("vmem_gather", calibration.device_key())
    b = (v or {}).get("idx_block")
    return int(b) if b else None


def masked_vmem_gather(table: jax.Array, slots: jax.Array,
                       valid: jax.Array) -> jax.Array:
    """Drop-in for the pull path's masked ``jnp.take``: pads ``slots`` to
    an index-block multiple, gathers from the VMEM-resident table, and
    zeroes invalid rows — identical semantics to
    ``transfer.xla._masked_gather`` (clip keeps padding defined)."""
    n = slots.shape[0]
    method = gather_method()
    blk = gather_idx_block() or (
        _TAA_IDX_BLOCK if method == "taa" else _DEF_IDX_BLOCK)
    safe = jnp.where(valid, slots, 0)
    pad = (-n) % blk
    if pad:
        safe = jnp.concatenate(
            [safe, jnp.zeros((pad,), slots.dtype)])
    rows = vmem_gather(table, safe, idx_block=blk, method=method)[:n]
    return jnp.where(valid[:, None], rows, 0)
