"""Pallas TPU kernel: DMA ring exchange for the sparse push buckets.

``TpuTransfer._build_push`` ships each device's per-home-shard request
ids and (grads | count) buckets with two dense ``jax.lax.all_to_all``
calls.  On an ICI ring that is a synchronous, XLA-scheduled exchange;
SNIPPETS.md [1] and Near-Optimal Sparse Allreduce (PAPERS.md) show the
alternative: stream each bucket to its home shard directly with
``pltpu.make_async_remote_copy`` steps so the NIC-side DMA engines
overlap all n-1 transfers instead of round-tripping through one fused
collective.

``ring_exchange(x, axis, n)`` is a drop-in for
``jax.lax.all_to_all(x, axis, 0, 0, tiled=True)`` on a (n, C, ...)
operand inside ``shard_map``: block j of the result is the block this
device received from device j.  Schedule: the local block is copied
VMEM-locally; then, at ring step s = 1..n-1, this device RDMA-sends
block ``(my_id + s) % n`` of its operand into slot ``my_id`` of the
receiver's output — every device sends to distance-s neighbor at step
s, so each step is a pure ring shift and the n-1 steps saturate both
ICI directions.  All sends start before any wait (the per-step DMA
semaphore pairs keep completion accounting exact).

Device addressing uses scalar ``DeviceIdType.LOGICAL`` ids — the mesh
must be 1-D over ``axis`` (``use_ring_push`` refuses otherwise), which
keeps the logical id equal to the axis index on chip and is the only
form the interpret-mode discharge rule supports, so the 8-device CPU
parity tests exercise the identical kernel.

Routing: ``use_ring_push`` resolves the ``[cluster] data_plane:`` knob
via ``calibration.data_plane_gated`` (kernel name ``ring_push``, env
``SMTPU_RING_PUSH``) — absent a measured on-chip win on a real
multi-chip mesh, the ``all_to_all`` wire exchange stays.
"""

from __future__ import annotations

import functools

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

from swiftmpi_tpu.ops import calibration


def _ring_kernel(n: int, my_id_ref, x_ref, out_ref, send_sem, recv_sem):
    my_id = my_id_ref[0]
    # local block: straight VMEM copy, no wire
    out_ref[pl.ds(my_id, 1)] = x_ref[pl.ds(my_id, 1)]

    def step(s):
        dst = jax.lax.rem(my_id + s, n)
        return pltpu.make_async_remote_copy(
            src_ref=x_ref.at[pl.ds(dst, 1)],
            dst_ref=out_ref.at[pl.ds(my_id, 1)],
            send_sem=send_sem.at[s - 1],
            recv_sem=recv_sem.at[s - 1],
            device_id=dst,
            device_id_type=pltpu.DeviceIdType.LOGICAL,
        )

    # start all n-1 sends, then wait all: the DMA engines overlap the
    # transfers; per-step semaphores keep each send/recv pair exact
    for s in range(1, n):
        step(s).start()
    for s in range(1, n):
        step(s).wait()


@functools.partial(jax.jit, static_argnames=("axis", "n", "interpret"))
def ring_exchange(x: jax.Array, axis: str, n: int,
                  interpret: bool | None = None) -> jax.Array:
    """``jax.lax.all_to_all(x, axis, 0, 0, tiled=True)`` by DMA ring.

    ``x`` is this device's (n, C, ...) operand under ``shard_map``
    (first axis indexed by destination device); the result's block j is
    the block received from device j.  ``n`` must equal the size of
    ``axis`` and the mesh must be 1-D (see module docstring)."""
    if x.shape[0] != n:
        raise ValueError(
            f"ring_exchange: leading dim {x.shape[0]} != axis size {n}")
    if interpret is None:
        interpret = not calibration.on_tpu()
    my_id = jax.lax.axis_index(axis).reshape((1,)).astype(jnp.int32)
    return pl.pallas_call(
        functools.partial(_ring_kernel, n),
        in_specs=[pl.BlockSpec(memory_space=pltpu.SMEM),
                  pl.BlockSpec(memory_space=pltpu.ANY)],
        out_specs=pl.BlockSpec(memory_space=pltpu.ANY),
        out_shape=jax.ShapeDtypeStruct(x.shape, x.dtype),
        scratch_shapes=[pltpu.SemaphoreType.DMA((max(n - 1, 1),)),
                        pltpu.SemaphoreType.DMA((max(n - 1, 1),))],
        compiler_params=pltpu.TPUCompilerParams(collective_id=0),
        interpret=interpret,
    )(my_id, x)


def use_ring_push(n: int, single_axis: bool, mode: str = "auto") -> bool:
    """Should the push wire exchange route through the DMA ring?
    Requires a real exchange (n > 1) and a 1-D mesh over the shard
    axis (``single_axis`` — LOGICAL device ids equal axis indices only
    there; the hybrid data x shard mesh keeps ``all_to_all``).  Above
    that, the ``[cluster] data_plane:`` knob / ``SMTPU_RING_PUSH`` env
    resolution is the shared measured-verdict policy (``manual=True``:
    the caller is inside ``shard_map``, operands are device-local)."""
    fits = n > 1 and single_axis
    return calibration.data_plane_gated(
        mode, "ring_push", "SMTPU_RING_PUSH", fits, manual=True)


def ring_supported(mesh, axis: str) -> bool:
    """Capability probe: can the ring kernel actually run on this
    mesh/backend (interpret discharge on CPU, Mosaic on chip)?  Runs a
    tiny exchange under ``shard_map`` and reports success — the parity
    tests and call sites use this to skip rather than crash on
    environments whose pallas build lacks remote-DMA support."""
    try:
        from swiftmpi_tpu.utils import jax_compat  # noqa: F401  (shim)
        n = mesh.shape[axis]
        if n < 2:
            return False
        from jax.sharding import PartitionSpec as P

        @functools.partial(jax.shard_map, mesh=mesh, in_specs=P(axis),
                           out_specs=P(axis), check_vma=False)
        def tiny(x):
            return ring_exchange(x[0], axis, n)[None]

        x = jnp.arange(n * n * 8, dtype=jnp.float32).reshape(n, n, 8)
        want = jax.jit(tiny)(x)
        ref = x.reshape(n, n, 8).transpose(1, 0, 2)
        return bool(jnp.allclose(want.reshape(n, n, 8), ref))
    except Exception:
        return False
