"""Clipped sigmoid matching the reference ExpTable semantics.

The reference precomputes sigmoid on [-MAX_EXP, MAX_EXP] (1000 buckets,
MAX_EXP=6) and hard-clips outside (`/root/reference/src/apps/word2vec/
word2vec.h:237-267,591-598`):

    f >  MAX_EXP  ->  g = (label - 1) * alpha
    f < -MAX_EXP  ->  g = (label - 0) * alpha
    else          ->  g = (label - sigmoid(f)) * alpha

``sigmoid_clipped`` reproduces exactly that branch structure with the exact
sigmoid in place of the table lookup (the table is a discretization whose
max error is ~1e-3; XLA computes the exact value at the same cost — the
clip, which *does* change gradients materially, is preserved).
"""

from __future__ import annotations

import jax.numpy as jnp

MAX_EXP = 6.0


def sigmoid_clipped(f: jnp.ndarray) -> jnp.ndarray:
    """sigma(f) with the reference's saturation to exactly 0/1 beyond
    +/-MAX_EXP."""
    s = 1.0 / (1.0 + jnp.exp(-jnp.clip(f, -MAX_EXP, MAX_EXP)))
    s = jnp.where(f > MAX_EXP, 1.0, s)
    return jnp.where(f < -MAX_EXP, 0.0, s)
