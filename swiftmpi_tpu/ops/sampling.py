"""Negative sampling and subsampling for word2vec.

The reference materializes a 10^8-entry unigram^0.75 table and draws
negatives by LCG index (`/root/reference/src/apps/word2vec/word2vec.h:
398-425,577-589`; regenerated **per minibatch** in the sync variant, once
globally in the async variant).  On TPU that table would be 400MB of HBM
serving random scalar reads; the alias method gives draws from the *exact*
same categorical distribution in O(1) with two vocab-sized arrays — so the
device samples (B, K) negatives per step with ``jax.random`` and no host
round-trip.  (Distribution equality, not stream equality: the reference's
table is itself only a 1e8-bucket discretization — SURVEY.md §7 hard
part (c).)

Subsampling follows the reference rule (word2vec.h:621-630): keep word w
with probability ``min(1, sqrt(sample/freq_w))`` where ``freq_w`` is the
in-corpus frequency.  Like the reference (word2vec.h:561-562), the gate
applies only to *center* positions — subsampled words still appear in
their neighbors' context windows; the batcher enforces this.
"""

from __future__ import annotations

from typing import Tuple

import jax
import jax.numpy as jnp
import numpy as np


def build_unigram_alias(counts: np.ndarray, power: float = 0.75
                        ) -> Tuple[np.ndarray, np.ndarray]:
    """Walker alias tables for the unigram^power distribution.

    Returns (prob, alias): float32 (V,) acceptance thresholds and int32 (V,)
    alias targets.  Sampling: draw bucket j ~ U[0,V), accept j if
    u < prob[j] else take alias[j].
    """
    counts = np.asarray(counts, np.float64)
    if counts.ndim != 1 or len(counts) == 0:
        raise ValueError("counts must be a non-empty 1-D array")
    w = counts ** power
    p = w / w.sum() * len(w)  # mean 1
    prob = np.ones(len(w), np.float64)
    alias = np.arange(len(w), dtype=np.int32)
    small = [i for i, x in enumerate(p) if x < 1.0]
    large = [i for i, x in enumerate(p) if x >= 1.0]
    while small and large:
        s, l = small.pop(), large.pop()
        prob[s] = p[s]
        alias[s] = l
        p[l] = p[l] - (1.0 - p[s])
        (small if p[l] < 1.0 else large).append(l)
    for i in small + large:
        prob[i] = 1.0
    return prob.astype(np.float32), alias


def _alias_draw_packed(key, prob, extra_cols, shape):
    """Shared draw core: packs ``(prob_bits, *extra_cols)`` into one
    (V, 1+len(extra_cols)) int32 table and resolves each draw with ONE
    row gather.  The round-3 chip profile showed scalar gathers are
    transaction-bound (~10ns each regardless of width), so collapsing
    the per-draw lookups (prob, alias, and optionally the vocab->slot
    map) into a single row halves-to-quarters the sampling phase.  One
    copy of the (j, u, accept) sequence keeps every caller's draw
    stream bit-identical by construction — the parity tests reproduce
    training negatives through ``sample_alias`` while training itself
    uses ``sample_alias_slots``.

    Returns ``(j, accept, rows)``: bucket draws, acceptance mask, and
    the gathered packed rows (prob bits in column 0)."""
    k1, k2 = jax.random.split(key)
    V = prob.shape[0]
    j = jax.random.randint(k1, shape, 0, V)
    u = jax.random.uniform(k2, shape)
    packed = jnp.stack(
        [jax.lax.bitcast_convert_type(prob, jnp.int32)] + extra_cols,
        axis=1)
    rows = packed[j]                              # (*shape, 1+len(extra))
    pj = jax.lax.bitcast_convert_type(rows[..., 0], jnp.float32)
    return j, u < pj, rows


def sample_alias(key: jax.Array, prob: jax.Array, alias: jax.Array,
                 shape: Tuple[int, ...]) -> jax.Array:
    """Device-side categorical draws from alias tables.  Draws are
    bit-identical to the textbook two-gather form (same j, u, same
    compared values; prob bits round-trip exactly through the pack's
    bitcast)."""
    j, accept, rows = _alias_draw_packed(key, prob, [alias], shape)
    return jnp.where(accept, j, rows[..., 1]).astype(jnp.int32)


def sample_alias_slots(key: jax.Array, prob: jax.Array, alias: jax.Array,
                       slot_of_vocab: jax.Array, shape: Tuple[int, ...]
                       ) -> Tuple[jax.Array, jax.Array]:
    """Alias draws fused with the vocab->slot mapping: returns
    ``(negs, neg_slots)`` with ``neg_slots == slot_of_vocab[negs]``.

    One (V, 4) row — ``(prob_bits, alias, slot, slot_of_alias)`` — per
    vocab id turns what was FOUR transaction-bound scalar gathers per
    draw (prob, alias, then slot_of_vocab on the result) into one row
    gather.  The pack itself is (V, 4) work, loop-invariant, and
    hoisted out of inner-step scans by XLA; draw stream is bit-identical
    to ``sample_alias`` + ``slot_of_vocab[negs]``."""
    V = prob.shape[0]
    j, accept, rows = _alias_draw_packed(
        key, prob, [alias, slot_of_vocab[:V], slot_of_vocab[alias]],
        shape)
    negs = jnp.where(accept, j, rows[..., 1]).astype(jnp.int32)
    neg_slots = jnp.where(accept, rows[..., 2],
                          rows[..., 3]).astype(jnp.int32)
    return negs, neg_slots


def subsample_keep_prob(counts: np.ndarray, sample: float) -> np.ndarray:
    """P(keep) per word (reference to_sample, word2vec.h:621-630):
    ran = 1 - sqrt(sample/freq); keep iff uniform > ran
    => P(keep) = min(1, sqrt(sample/freq)).  sample < 0 disables."""
    counts = np.asarray(counts, np.float64)
    if sample < 0:
        return np.ones(len(counts), np.float32)
    freq = counts / max(counts.sum(), 1.0)
    with np.errstate(divide="ignore"):
        keep = np.sqrt(sample / np.where(freq > 0, freq, 1.0))
    return np.minimum(keep, 1.0).astype(np.float32)
