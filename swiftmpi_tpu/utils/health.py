"""Device health probing — the failure-*detection* half of resilience.

The reference cannot detect a dead peer at all: a node death hangs the
pull/push StateBarrier forever (SURVEY.md §5; utils/Barrier.h:90-101 has an
unused timeout hook).  Here detection is explicit and bounded: each device
runs a tiny round-trip computation under a deadline; a device that errors
or exceeds the deadline is reported unhealthy, and the caller decides
(typically: restart from checkpoint via io.resilience on a healthy mesh).
"""

from __future__ import annotations

import threading
from dataclasses import dataclass
from typing import List, Optional

import numpy as np

from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)


class DeviceHangError(RuntimeError):
    """Training made no step progress within its watchdog deadline (see
    io.resilience.train_with_resume's ``hang_timeout_s``).  The
    ``recoverable`` attribute says whether the stalled attempt
    acknowledged cancellation (True: restart from checkpoint in-process)
    or is wedged in native code (False: only a process restart — the
    supervised launcher — can recover)."""

    recoverable: bool = True


@dataclass
class DeviceHealth:
    device: str
    ok: bool
    latency_s: float
    error: Optional[str] = None


def _probe(device) -> float:
    import jax
    import jax.numpy as jnp
    import time
    x = np.arange(256, dtype=np.float32).reshape(16, 16)
    t0 = time.perf_counter()
    y = jax.device_put(x, device)
    z = jnp.dot(y, y).sum()
    z.block_until_ready()
    if not np.isfinite(float(z)):
        raise RuntimeError("non-finite probe result")
    return time.perf_counter() - t0


def check_devices(devices=None, timeout_s: float = 30.0
                  ) -> List[DeviceHealth]:
    """Round-trip a small matmul on every device with a deadline.  Probes
    run on daemon threads so a hung device is truly abandoned after
    ``timeout_s`` — it neither blocks this call nor interpreter exit (the
    process is presumed about to restart from checkpoint anyway)."""
    import jax
    devices = list(devices if devices is not None else jax.devices())
    if not devices:
        return []
    results: List[Optional[DeviceHealth]] = [None] * len(devices)
    threads = []
    for i, d in enumerate(devices):
        def probe_one(i=i, d=d):
            try:
                dt = _probe(d)
                results[i] = DeviceHealth(str(d), True, dt)
            except Exception as e:  # noqa: BLE001 — any failure = unhealthy
                results[i] = DeviceHealth(str(d), False, 0.0, repr(e))
        t = threading.Thread(target=probe_one, daemon=True,
                             name=f"health-probe-{i}")
        t.start()
        threads.append(t)
    import time
    t_end = time.monotonic() + timeout_s  # one wall clock for all joins
    for t in threads:
        t.join(max(0.0, t_end - time.monotonic()))
    out = [r if r is not None
           else DeviceHealth(str(d), False, timeout_s, "probe timed out")
           for r, d in zip(results, devices)]
    from swiftmpi_tpu import obs
    reg = obs.get_registry()
    if reg.enabled:
        for h in out:
            reg.counter("health/probe_ok" if h.ok
                        else "health/probe_fail").inc()
            if h.ok:
                reg.histogram("health/probe_ms").observe(h.latency_s * 1e3)
    bad = [h for h in out if not h.ok]
    if bad:
        log.warning("unhealthy devices: %s",
                    [(h.device, h.error) for h in bad])
    return out


def all_healthy(devices=None, timeout_s: float = 30.0) -> bool:
    return all(h.ok for h in check_devices(devices, timeout_s))
