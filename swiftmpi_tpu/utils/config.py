"""INI-compatible configuration system.

TPU-native re-implementation of the reference config layer
(`/root/reference/src/utils/ConfigParser.h:84-115`): the same on-disk format —
``[section]`` headers, ``key: value`` (or ``key value``) entries, ``#``
comments, and ``import <path>`` includes — so reference ``demo.conf`` files
parse unchanged.  Typed access mirrors ``Item::to_int32/to_float/to_string/
to_bool`` (ConfigParser.h:28-48); a process-wide ``global_config()`` singleton
mirrors ConfigParser.h:130-133.

Differences by design (not a port):
  * values are stored per-(section, key); the reference flattens late.
  * missing keys raise ``KeyError`` with the section/key named instead of a
    glog CHECK-abort.
  * ``as_dict()`` and programmatic ``set()`` support config-from-code, which
    the tests and apps use heavily (no global mutable state required).
"""

from __future__ import annotations

import os
import threading
from typing import Dict, Iterator, Optional, Tuple


class ConfigError(KeyError):
    """Raised when a requested key/section is absent or untyped."""


class Item:
    """A single typed config value (reference ConfigParser.h:21-50)."""

    __slots__ = ("raw",)

    def __init__(self, raw: str):
        self.raw = raw.strip()

    def to_string(self) -> str:
        return self.raw

    def to_int32(self) -> int:
        return int(self.raw)

    def to_float(self) -> float:
        return float(self.raw)

    def to_bool(self) -> bool:
        v = self.raw.lower()
        if v in ("1", "true", "yes", "on"):
            return True
        if v in ("0", "false", "no", "off", ""):
            return False
        raise ConfigError(f"not a bool: {self.raw!r}")

    def __repr__(self) -> str:  # pragma: no cover
        return f"Item({self.raw!r})"


class ConfigParser:
    """Sectioned key/value config with ``import`` includes.

    Accepts both ``key: value`` and ``key value`` line forms, ``#`` comments
    (full-line or trailing), and nested ``import path`` directives resolved
    relative to the importing file (reference ConfigParser.h:84-115).
    """

    def __init__(self, path: Optional[str] = None):
        self._values: Dict[Tuple[str, str], Item] = {}
        self._lock = threading.Lock()
        if path is not None:
            self.load_conf(path)
            self.parse()

    # -- loading ----------------------------------------------------------
    def load_conf(self, path: str) -> "ConfigParser":
        self._pending_path = path
        return self

    def parse(self) -> "ConfigParser":
        path = getattr(self, "_pending_path", None)
        if path is None:
            raise ConfigError("load_conf() must be called before parse()")
        self._parse_file(path)
        return self

    def _parse_file(self, path: str, section: str = "") -> str:
        """Parse one file; returns the trailing section so that, as in the
        reference parser's mutable ``cur_session`` state, a section opened
        inside an imported file stays current after the import returns."""
        base = os.path.dirname(os.path.abspath(path))
        with open(path, "r") as f:
            for lineno, line in enumerate(f, 1):
                line = line.split("#", 1)[0].strip()
                if not line:
                    continue
                if line.startswith("[") and line.endswith("]"):
                    section = line[1:-1].strip()
                    continue
                if line.split(None, 1)[0] == "import":
                    target = line[len("import"):].strip()
                    if not os.path.isabs(target):
                        target = os.path.join(base, target)
                    section = self._parse_file(target, section)
                    continue
                if ":" in line:
                    key, _, value = line.partition(":")
                else:
                    parts = line.split(None, 1)
                    if len(parts) != 2:
                        raise ConfigError(
                            f"{path}:{lineno}: cannot parse line {line!r}")
                    key, value = parts
                self.set(section, key.strip(), value.strip())
        return section

    # -- access -----------------------------------------------------------
    def set(self, section: str, key: str, value) -> None:
        with self._lock:
            self._values[(section, key)] = Item(str(value))

    def get(self, section: str, key: str) -> Item:
        with self._lock:
            try:
                return self._values[(section, key)]
            except KeyError:
                raise ConfigError(
                    f"config key [{section}] {key} not set") from None

    def has(self, section: str, key: str) -> bool:
        with self._lock:
            return (section, key) in self._values

    def get_or(self, section: str, key: str, default) -> Item:
        if not self.has(section, key):
            return Item(str(default))
        return self.get(section, key)

    def section(self, section: str) -> Dict[str, Item]:
        with self._lock:
            return {k: v for (s, k), v in self._values.items()
                    if s == section}

    def update(self, mapping: Dict[str, Dict[str, object]]) -> "ConfigParser":
        """Bulk-set from ``{section: {key: value}}`` (config-from-code)."""
        for sec, kv in mapping.items():
            for k, v in kv.items():
                self.set(sec, k, v)
        return self

    def as_dict(self) -> Dict[str, Dict[str, str]]:
        out: Dict[str, Dict[str, str]] = {}
        with self._lock:
            for (sec, key), item in self._values.items():
                out.setdefault(sec, {})[key] = item.raw
        return out

    def clear(self) -> None:
        with self._lock:
            self._values.clear()

    def __iter__(self) -> Iterator[Tuple[str, str, str]]:
        with self._lock:
            items = list(self._values.items())
        for (sec, key), item in items:
            yield sec, key, item.raw

    def __repr__(self) -> str:  # pragma: no cover
        lines = [f"[{s}] {k}: {v}" for s, k, v in self]
        return "ConfigParser(\n  " + "\n  ".join(lines) + "\n)"


_GLOBAL_CONFIG: Optional[ConfigParser] = None
_GLOBAL_LOCK = threading.Lock()


def global_config() -> ConfigParser:
    """Process-wide config singleton (reference ConfigParser.h:130-133)."""
    global _GLOBAL_CONFIG
    with _GLOBAL_LOCK:
        if _GLOBAL_CONFIG is None:
            _GLOBAL_CONFIG = ConfigParser()
        return _GLOBAL_CONFIG


def reset_global_config() -> None:
    """Testing hook: drop the singleton so each test starts clean."""
    global _GLOBAL_CONFIG
    with _GLOBAL_LOCK:
        _GLOBAL_CONFIG = None
