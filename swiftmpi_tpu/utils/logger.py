"""Logging setup.

The reference uses glog ``LOG/RAW_LOG`` everywhere (SURVEY.md §5); here a
stdlib logger with a glog-like single-line format plays that role.  Hot paths
should use ``log.debug`` (compiled out by level, the moral equivalent of the
reference's ``NDEBUG``-gated ``DLOG``).
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(levelname).1s%(asctime)s %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_configured = False


def get_logger(name: str = "swiftmpi_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("SWIFTMPI_TPU_LOGLEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        root = logging.getLogger("swiftmpi_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    # Names outside the package hierarchy are adopted under it so they get
    # the configured handler/level instead of logging's WARNING-only
    # lastResort fallback.
    if name != "swiftmpi_tpu" and not name.startswith("swiftmpi_tpu."):
        name = f"swiftmpi_tpu.{name}"
    return logging.getLogger(name)
