"""Logging setup.

The reference uses glog ``LOG/RAW_LOG`` everywhere (SURVEY.md §5); here a
stdlib logger with a glog-like single-line format plays that role.  Hot paths
should use ``log.debug`` (compiled out by level, the moral equivalent of the
reference's ``NDEBUG``-gated ``DLOG``).
"""

from __future__ import annotations

import logging
import os
import sys

_FORMAT = "%(levelname).1s%(asctime)s %(ident)s %(name)s] %(message)s"
_DATEFMT = "%m%d %H:%M:%S"

_configured = False


class _IdentFilter(logging.Filter):
    """Stamp each record with the process identity (``r<rank>`` under
    launch.py's supervisor, ``p<pid>`` standalone) so interleaved logs
    from a multi-process cell stay attributable.  Resolved per record —
    the supervisor re-execs children with fresh ranks and tests
    monkeypatch the env, so nothing may be cached at configure time."""

    def filter(self, record: logging.LogRecord) -> bool:
        from swiftmpi_tpu.obs.identity import process_ident
        record.ident = process_ident()
        return True


def get_logger(name: str = "swiftmpi_tpu") -> logging.Logger:
    global _configured
    if not _configured:
        level = os.environ.get("SWIFTMPI_TPU_LOGLEVEL", "INFO").upper()
        handler = logging.StreamHandler(sys.stderr)
        handler.setFormatter(logging.Formatter(_FORMAT, _DATEFMT))
        handler.addFilter(_IdentFilter())
        root = logging.getLogger("swiftmpi_tpu")
        root.addHandler(handler)
        root.setLevel(level)
        root.propagate = False
        _configured = True
    # Names outside the package hierarchy are adopted under it so they get
    # the configured handler/level instead of logging's WARNING-only
    # lastResort fallback.
    if name != "swiftmpi_tpu" and not name.startswith("swiftmpi_tpu."):
        name = f"swiftmpi_tpu.{name}"
    return logging.getLogger(name)
