"""Random number generation.

Two worlds live here deliberately:

* ``Random`` — the reference's word2vec-C linear congruential generator
  (`/root/reference/src/utils/random.h:20-42`): ``next = next * 25214903917 +
  11`` over 64 bits, plus the separate float LCG (``* 4903917 + 11`` over 64
  bits, seeded at ``ULONG_MAX/2``).  Host-side code that wants reference-
  faithful sampling behavior (negative-sampling table draws, subsampling
  coin flips, LR weight init) uses this, including the process singleton
  ``global_random()`` seeded 2008 (random.h:44-47).
* JAX PRNG helpers — everything on-device uses counter-based ``jax.random``
  keys (splittable, order-independent, SPMD-safe); the LCG is sequential by
  construction and would serialize a TPU program.  Loss parity only needs
  equality in distribution, not in stream.

``Random.batch`` materializes the next n LCG states with a plain sequential
loop — host callers only draw small batches; bulk sampling belongs on-device
with ``jax.random``.
"""

from __future__ import annotations

import numpy as np

_LCG_MUL = 25214903917
_LCG_INC = 11
_MASK64 = (1 << 64) - 1
_FLOAT_MUL = 4903917
_FLOAT_INC = 11
_ULONG_MAX = (1 << 64) - 1


class Random:
    """Reference-faithful scalar LCG (random.h:25-42)."""

    def __init__(self, seed: int = 2008):
        self.next_random = seed & _MASK64
        self.next_float_random = _ULONG_MAX // 2

    def __call__(self) -> int:
        self.next_random = (self.next_random * _LCG_MUL + _LCG_INC) & _MASK64
        return self.next_random

    def gen_float(self) -> float:
        self.next_float_random = (
            self.next_float_random * _FLOAT_MUL + _FLOAT_INC) & _MASK64
        return float(self.next_float_random) / _ULONG_MAX

    # -- batched draws ----------------------------------------------------
    def batch(self, n: int) -> np.ndarray:
        """Next ``n`` values of the integer LCG as uint64, advancing state."""
        out = np.empty(n, dtype=np.uint64)
        x = self.next_random
        for i in range(n):  # simple loop; n is small on host paths
            x = (x * _LCG_MUL + _LCG_INC) & _MASK64
            out[i] = x
        self.next_random = x
        return out

    def batch_float(self, n: int) -> np.ndarray:
        out = np.empty(n, dtype=np.float64)
        x = self.next_float_random
        for i in range(n):
            x = (x * _FLOAT_MUL + _FLOAT_INC) & _MASK64
            out[i] = x / _ULONG_MAX
        self.next_float_random = x
        return out


_GLOBAL_RANDOM = None


def global_random() -> Random:
    """Singleton seeded 2008, mirroring reference random.h:44-47."""
    global _GLOBAL_RANDOM
    if _GLOBAL_RANDOM is None:
        _GLOBAL_RANDOM = Random(2008)
    return _GLOBAL_RANDOM


def reset_global_random(seed: int = 2008) -> None:
    global _GLOBAL_RANDOM
    _GLOBAL_RANDOM = Random(seed)
