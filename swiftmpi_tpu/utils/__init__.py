"""Runtime primitives: config, CLI flags, hashing, RNG, buffers, timing.

TPU-native equivalent of the reference utils layer
(`/root/reference/src/utils/all.h`).  Components with no meaning off the
socket/pthread substrate (SpinLock/RWLock, AsynExec thread pools,
StateBarrier, ZMQ/MPI wrappers, local-IP discovery) intentionally have no
counterpart: SPMD program order is the barrier, XLA is the thread pool, the
mesh is the cluster (see swiftmpi_tpu.cluster).
"""

from swiftmpi_tpu.utils.config import (ConfigParser, ConfigError, Item,
                                       global_config, reset_global_config)
from swiftmpi_tpu.utils.cmdline import CMDLine
from swiftmpi_tpu.utils.hashing import (get_hash_code, get_hash_code_np,
                                        bkdr_hash, bkdr_hash_batch)
from swiftmpi_tpu.utils.rng import Random, global_random, reset_global_random
from swiftmpi_tpu.utils.buffer import BinaryBuffer, TextBuffer
from swiftmpi_tpu.utils.timers import (Timer, Error, Throughput, Metrics,
                                       global_metrics)
from swiftmpi_tpu.utils.logger import get_logger
from swiftmpi_tpu.utils.health import (DeviceHangError, DeviceHealth,
                                       all_healthy, check_devices)

__all__ = [
    "ConfigParser", "ConfigError", "Item", "global_config",
    "reset_global_config", "CMDLine", "get_hash_code", "get_hash_code_np",
    "bkdr_hash", "bkdr_hash_batch", "Random", "global_random",
    "reset_global_random", "BinaryBuffer", "TextBuffer", "Timer", "Error",
    "Throughput", "Metrics", "global_metrics", "get_logger",
    "DeviceHangError", "DeviceHealth", "all_healthy", "check_devices",
]
