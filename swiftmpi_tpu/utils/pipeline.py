"""Async-dispatch pipeline bounding.

On the virtual multi-device CPU mesh an unbounded pipeline of sharded
programs starves XLA:CPU's shared thread pool: devices of one in-flight
program occupy the threads another program's collective rendezvous is
waiting for, and past the rendezvous timeout the whole process
CHECK-aborts ("Fatal Python error: Aborted" at a harmless-looking
dispatch).  ``DispatchWindow`` bounds the depth as a ROLLING window —
past N tracked arrays, each push blocks on the OLDEST (its completion
implies every earlier dependent dispatch ran, and ~N newer programs
stay in flight, so there is no pipeline bubble).

The ``"auto"`` policy applies the bound only on the cpu backend: a real
TPU chip runs one program at a time and needs no bound.  Shared by
``word2vec._LossAccum``, the LR train loop, and anything else that
queues device results without fetching them.
"""

from __future__ import annotations

from typing import Optional, Union

import jax

AUTO_BOUND = 16


class DispatchWindow:
    def __init__(self, bound: Union[str, int, None] = "auto"):
        if bound == "auto":
            bound = AUTO_BOUND if jax.default_backend() == "cpu" else None
        self._bound: Optional[int] = bound
        self._window: list = []

    def push(self, x) -> None:
        """Track one in-flight device value; block on the oldest tracked
        value once more than ``bound`` are outstanding."""
        if self._bound is None:
            return
        self._window.append(x)
        if len(self._window) > self._bound:
            jax.block_until_ready(self._window.pop(0))

    def clear(self) -> None:
        self._window.clear()


def resolve_dispatch_bound(depth: Union[str, int, None],
                           pipelined: bool = False) -> Union[str, int, None]:
    """Resolve the ``[worker] dispatch_depth`` knob into a
    ``DispatchWindow`` bound.

    ``"auto"`` keeps the backend policy above — EXCEPT when the input
    pipeline is on: with prefetched batches the consumer can dispatch
    as fast as it renders nothing, so without a finite watermark async
    dispatch outruns HBM (every in-flight program pins its donated
    state copy + inputs).  Pipelined ``"auto"`` therefore bounds every
    backend at ``AUTO_BOUND``.  An explicit integer (or ``0`` meaning
    unbounded) always wins.
    """
    if depth == "auto" or depth is None:
        return AUTO_BOUND if pipelined else "auto"
    depth = int(depth)
    return None if depth <= 0 else depth
