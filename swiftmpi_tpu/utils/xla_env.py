"""XLA environment setup for the emulated multi-device CPU platform.

Must run BEFORE the first jax import in the process (env-var flags are
read at backend init).  Importing this module is side-effect free and
jax-free, so test conftests and entry scripts can call it first thing.
"""

from __future__ import annotations

import os


def ensure_cpu_mesh_flags(n_devices: int | None = None,
                          force_device_count: bool = False) -> None:
    """Idempotently append the virtual-CPU-mesh XLA flags.

    * ``--xla_force_host_platform_device_count=N`` (when ``n_devices``
      is given) — the standard JAX fake-multi-device trick.
      ``force_device_count=True`` appends even when the flag is already
      present (XLA parses last-occurrence-wins, so the append overrides
      the earlier value) — the test suite uses this so a developer's
      leftover device-count export can never silently shrink the mesh
      and skip every ``devices8`` test.
    * Collective rendezvous timeouts: on an oversubscribed host the
      virtual devices' collective threads can miss XLA:CPU's in-process
      rendezvous window, and the default 40s terminate timeout
      CHECK-aborts the whole process ("Fatal Python error: Aborted" at
      a harmless-looking dispatch — see utils/pipeline.py for the
      full failure mode).  Warn at 60s, abort only at 600s.

    Every append is guarded by a substring check so a caller's own
    XLA_FLAGS value wins (XLA parses flags last-occurrence-wins; an
    unconditional append would silently override it).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices is not None and (
            force_device_count
            or "--xla_force_host_platform_device_count" not in flags):
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    # each timeout flag guarded on ITS OWN substring: a caller who set
    # only one of the pair keeps their value (last-occurrence-wins would
    # otherwise silently override it — round-2 advisor finding)
    if "--xla_cpu_collective_call_warn_stuck_timeout_seconds" not in flags:
        flags += " --xla_cpu_collective_call_warn_stuck_timeout_seconds=60"
    if "--xla_cpu_collective_call_terminate_timeout_seconds" not in flags:
        flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    os.environ["XLA_FLAGS"] = flags
