"""XLA environment setup for the emulated multi-device CPU platform.

Must run BEFORE the first jax import in the process (env-var flags are
read at backend init).  Importing this module is side-effect free and
jax-free, so test conftests and entry scripts can call it first thing.
"""

from __future__ import annotations

import glob
import importlib.util
import mmap
import os

_FLAG_SUPPORT_CACHE: dict = {}


def _xla_extension_path():
    """Locate jaxlib's xla_extension shared object WITHOUT importing
    jaxlib (find_spec only reads metadata — this module must stay
    import-side-effect free and jax-free, see test_utils.py)."""
    try:
        spec = importlib.util.find_spec("jaxlib")
    except (ImportError, ValueError):
        return None
    if spec is None or not spec.submodule_search_locations:
        return None
    for d in spec.submodule_search_locations:
        # the binary only — the same prefix also matches the .pyi stub
        # package dir, whose bytes say nothing about registered flags
        hits = sorted(glob.glob(os.path.join(d, "xla_extension*.so"))
                      + glob.glob(os.path.join(d, "xla_extension*.pyd")))
        if hits:
            return hits[0]
    return None


def xla_flag_supported(flag: str) -> bool:
    """True if the installed jaxlib's XLA recognises ``flag``.

    XLA calls ``abort()`` on ANY unknown name in XLA_FLAGS
    (parse_flags_from_env.cc) — on jaxlib 0.4.x that kills the process at
    backend init with "Fatal Python error: Aborted", so every
    version-dependent flag must be probed before it is appended.  Probe:
    registered flag names are embedded verbatim in the xla_extension
    binary (they come from the DebugOptions proto descriptor), so a
    substring scan of the .so decides without spawning a subprocess or
    initialising a backend.  Unknown/unprobeable → False: not appending
    a flag is always safe, appending an unknown one never is.
    """
    name = flag.lstrip("-").split("=", 1)[0]
    cached = _FLAG_SUPPORT_CACHE.get(name)
    if cached is not None:
        return cached
    ok = False
    path = _xla_extension_path()
    if path:
        try:
            with open(path, "rb") as f, \
                    mmap.mmap(f.fileno(), 0, access=mmap.ACCESS_READ) as m:
                ok = m.find(name.encode()) != -1
        except (OSError, ValueError):
            ok = False
    _FLAG_SUPPORT_CACHE[name] = ok
    return ok


def ensure_cpu_mesh_flags(n_devices: int | None = None,
                          force_device_count: bool = False) -> None:
    """Idempotently append the virtual-CPU-mesh XLA flags.

    * ``--xla_force_host_platform_device_count=N`` (when ``n_devices``
      is given) — the standard JAX fake-multi-device trick.
      ``force_device_count=True`` appends even when the flag is already
      present (XLA parses last-occurrence-wins, so the append overrides
      the earlier value) — the test suite uses this so a developer's
      leftover device-count export can never silently shrink the mesh
      and skip every ``devices8`` test.
    * Collective rendezvous timeouts: on an oversubscribed host the
      virtual devices' collective threads can miss XLA:CPU's in-process
      rendezvous window, and the default 40s terminate timeout
      CHECK-aborts the whole process ("Fatal Python error: Aborted" at
      a harmless-looking dispatch — see utils/pipeline.py for the
      full failure mode).  Warn at 60s, abort only at 600s.

    Every append is guarded by a substring check so a caller's own
    XLA_FLAGS value wins (XLA parses flags last-occurrence-wins; an
    unconditional append would silently override it).
    """
    flags = os.environ.get("XLA_FLAGS", "")
    if n_devices is not None and (
            force_device_count
            or "--xla_force_host_platform_device_count" not in flags):
        flags += f" --xla_force_host_platform_device_count={n_devices}"
    # each timeout flag guarded on ITS OWN substring: a caller who set
    # only one of the pair keeps their value (last-occurrence-wins would
    # otherwise silently override it — round-2 advisor finding).  Both
    # are probed against the installed jaxlib: older XLAs (e.g. jaxlib
    # 0.4.36) don't know them and abort() the whole process at backend
    # init on any unknown XLA_FLAGS entry.
    if ("--xla_cpu_collective_call_warn_stuck_timeout_seconds"
            not in flags and xla_flag_supported(
                "xla_cpu_collective_call_warn_stuck_timeout_seconds")):
        flags += " --xla_cpu_collective_call_warn_stuck_timeout_seconds=60"
    if ("--xla_cpu_collective_call_terminate_timeout_seconds"
            not in flags and xla_flag_supported(
                "xla_cpu_collective_call_terminate_timeout_seconds")):
        flags += " --xla_cpu_collective_call_terminate_timeout_seconds=600"
    os.environ["XLA_FLAGS"] = flags
