"""Key hashing, bit-compatible with the reference routing functions.

Two hashes matter for parity because they decide which shard owns a key:

* ``get_hash_code`` — the 64-bit MurmurHash3 finalizer (public-domain
  avalanche constants), used by the reference for shard routing
  (`/root/reference/src/utils/HashFunction.h:16-24`, applied at
  sparsetable.h:143 and, via ``hash_fn``, hashfrag.h:51-55).
* ``bkdr_hash`` — the seed-13131 polynomial string hash used to map words to
  integer keys in the async word2vec variant
  (`/root/reference/src/utils/string.h:130-137`).

Both are provided as scalars and as numpy-vectorized batch versions (the
batch versions are what the data pipeline uses; hashing happens host-side —
on-device arrays are indexed by dense slot ids, never by raw keys).
"""

from __future__ import annotations

import numpy as np

_M1 = np.uint64(0xFF51AFD7ED558CCD)
_M2 = np.uint64(0xC4CEB9FE1A85EC53)
_SHIFT = np.uint64(33)
_MASK64 = (1 << 64) - 1


def get_hash_code(x: int) -> int:
    """Scalar murmur64 finalizer; matches reference HashFunction.h:16-24."""
    x &= _MASK64
    x ^= x >> 33
    x = (x * 0xFF51AFD7ED558CCD) & _MASK64
    x ^= x >> 33
    x = (x * 0xC4CEB9FE1A85EC53) & _MASK64
    x ^= x >> 33
    return x


def get_hash_code_np(keys: np.ndarray) -> np.ndarray:
    """Vectorized murmur64 finalizer over a uint64 array."""
    x = np.asarray(keys, dtype=np.uint64).copy()
    with np.errstate(over="ignore"):
        x ^= x >> _SHIFT
        x *= _M1
        x ^= x >> _SHIFT
        x *= _M2
        x ^= x >> _SHIFT
    return x


def bkdr_hash(s: str, seed: int = 13131, bits: int = 32) -> int:
    """Polynomial string hash; matches reference string.h:130-137.

    The reference instantiates ``BKDRHash<T>`` with the app key type:
    ``unsigned int`` by default, ``size_t`` for async word2vec keys.
    ``bits`` selects the wrap width (32 or 64).
    """
    mask = (1 << bits) - 1
    h = 0
    for ch in s.encode("utf-8"):
        h = (h * seed + ch) & mask
    return h


def bkdr_hash_batch(words, seed: int = 13131, bits: int = 32) -> np.ndarray:
    """BKDR over a list of strings (host data pipeline)."""
    out = np.empty(len(words), dtype=np.uint64)
    for i, w in enumerate(words):
        out[i] = bkdr_hash(w, seed, bits)
    return out
