"""Profiling hooks (the subsystem the reference lacks — SURVEY.md §5
"Tracing/profiling: No", just an unused Timer).

Thin wrappers over ``jax.profiler``: ``trace(logdir)`` captures a
TensorBoard-loadable device trace around a code block; ``annotate(name)``
labels host spans so steps show up named in the trace; ``StepTimer``
measures steady-state step latency with device sync, the number the
benchmarks report.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device/host profile into ``logdir`` (view in TensorBoard
    or xprof)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span context (TraceAnnotation) for host-side phases."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock per-step stats with an explicit device barrier."""

    def __init__(self):
        self._times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *sync_on) -> float:
        if sync_on:
            jax.block_until_ready(sync_on)
        dt = time.perf_counter() - self._t0
        self._times.append(dt)
        return dt

    @property
    def mean(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    @property
    def p50(self) -> float:
        if not self._times:
            return 0.0
        return sorted(self._times)[len(self._times) // 2]
