"""Profiling hooks (the subsystem the reference lacks — SURVEY.md §5
"Tracing/profiling: No", just an unused Timer).

Thin wrappers over ``jax.profiler``: ``trace(logdir)`` captures a
TensorBoard-loadable device trace around a code block; ``annotate(name)``
labels host spans so steps show up named in the trace; ``StepTimer``
measures steady-state step latency with device sync, the number the
benchmarks report.
"""

from __future__ import annotations

import contextlib
import time
from typing import Iterator, List, Optional

import jax


@contextlib.contextmanager
def trace(logdir: str) -> Iterator[None]:
    """Capture a device/host profile into ``logdir`` (view in TensorBoard
    or xprof)."""
    jax.profiler.start_trace(logdir)
    try:
        yield
    finally:
        jax.profiler.stop_trace()


def annotate(name: str):
    """Named span context (TraceAnnotation) for host-side phases."""
    return jax.profiler.TraceAnnotation(name)


class StepTimer:
    """Wall-clock per-step stats with an explicit device barrier.

    Keeps every sample (bench loops are a few hundred steps at most),
    so percentiles are exact order statistics, not bucket
    interpolations — this is the ground truth the registry histogram's
    interpolated quantiles are validated against in tests."""

    def __init__(self):
        self._times: List[float] = []
        self._t0: Optional[float] = None

    def start(self) -> None:
        self._t0 = time.perf_counter()

    def stop(self, *sync_on) -> float:
        if sync_on:
            jax.block_until_ready(sync_on)
        dt = time.perf_counter() - self._t0
        self._times.append(dt)
        return dt

    def __len__(self) -> int:
        return len(self._times)

    @property
    def mean(self) -> float:
        return sum(self._times) / len(self._times) if self._times else 0.0

    def percentile(self, q: float) -> float:
        """Exact order-statistic percentile (``q`` in [0, 1]), linear
        interpolation between adjacent samples — numpy's default rule,
        without pulling in an array round-trip per call."""
        if not self._times:
            return 0.0
        xs = sorted(self._times)
        if len(xs) == 1:
            return xs[0]
        pos = q * (len(xs) - 1)
        lo = int(pos)
        hi = min(lo + 1, len(xs) - 1)
        return xs[lo] + (xs[hi] - xs[lo]) * (pos - lo)

    @property
    def p50(self) -> float:
        return self.percentile(0.50)

    @property
    def p95(self) -> float:
        return self.percentile(0.95)

    @property
    def p99(self) -> float:
        return self.percentile(0.99)

    def summary_ms(self) -> dict:
        """Mean/p50/p95/p99 in milliseconds — the bench-cell latency
        fields (mean-only latency hides tail regressions; the p99 is
        what a serving SLO would gate on)."""
        return {"mean": self.mean * 1e3, "p50": self.p50 * 1e3,
                "p95": self.p95 * 1e3, "p99": self.p99 * 1e3}

    def publish(self, name: str = "step_ms", **labels) -> None:
        """Feed every recorded sample into the telemetry registry's
        ``<name>{labels}`` histogram (no-op when telemetry is off), so
        bench latency distributions land in the same sink as training
        phase timings."""
        from swiftmpi_tpu import obs
        reg = obs.get_registry()
        if not reg.enabled:
            return
        h = reg.histogram(name, **labels)
        for dt in self._times:
            h.observe(dt * 1e3)
