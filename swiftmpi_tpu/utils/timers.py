"""Timing + metrics accumulation.

``Timer`` mirrors the reference chrono stopwatch
(`/root/reference/src/utils/Timer.h:14-44`) including ``timeout()``; the rest
is the metrics system the reference lacks (SURVEY.md §5 "Metrics: No"):
``Error`` reproduces the loss accumulator used for per-iteration training
error (reference word2vec.h:442-457), and ``Meter``/``Throughput`` provide
the words/sec style counters the benchmarks report.
"""

from __future__ import annotations

import threading
import time
from typing import Dict


class Timer:
    def __init__(self, time_limit_s: float = 0.0):
        self._start = time.monotonic()
        self._limit = time_limit_s

    def restart(self) -> None:
        self._start = time.monotonic()

    def elapsed(self) -> float:
        return time.monotonic() - self._start

    def timeout(self) -> bool:
        return self._limit > 0 and self.elapsed() > self._limit


class Error:
    """Running-mean loss accumulator (reference word2vec.h:442-457)."""

    def __init__(self):
        self._sum = 0.0
        self._count = 0
        self._lock = threading.Lock()

    def accu(self, value: float, n: int = 1) -> None:
        with self._lock:
            self._sum += float(value)
            self._count += n

    def norm(self) -> float:
        with self._lock:
            return self._sum / self._count if self._count else 0.0

    def reset(self) -> None:
        with self._lock:
            self._sum = 0.0
            self._count = 0


class Throughput:
    """Cumulative items/sec meter since construction or last reset().

    A single wall-clock rate hides WHICH side of the step loop is the
    bottleneck, so the meter also splits elapsed time into **host
    stall** (time the consumer spent waiting on input — rendering, H2D
    transfer, an empty prefetch queue; reported via ``add_stall`` /
    the ``stalling()`` context manager) and **device time** (everything
    else: dispatch + on-device compute).  ``stats()`` packages the
    split for train metrics and bench detail fields.
    """

    def __init__(self):
        self._items = 0
        self._steps = 0
        self._stall_s = 0.0
        self._timer = Timer()

    def record(self, n: int, steps: int = 1) -> None:
        self._items += n
        self._steps += steps

    def add_stall(self, seconds: float) -> None:
        """Account ``seconds`` of host-side input stall."""
        self._stall_s += seconds

    def stalling(self):
        """Context manager timing a host-stall region::

            with meter.stalling():
                batch = next(batches)
        """
        return _StallScope(self)

    def rate(self) -> float:
        dt = self._timer.elapsed()
        return self._items / dt if dt > 0 else 0.0

    def host_stall_ms(self) -> float:
        return self._stall_s * 1e3

    def device_ms(self) -> float:
        """Elapsed wall-clock minus host stall, in ms (clamped at 0)."""
        return max(0.0, self._timer.elapsed() - self._stall_s) * 1e3

    def stall_ms_per_step(self) -> float:
        return self.host_stall_ms() / self._steps if self._steps else 0.0

    def stats(self) -> Dict[str, float]:
        return {"items": float(self._items),
                "steps": float(self._steps),
                "rate": self.rate(),
                "host_stall_ms": self.host_stall_ms(),
                "device_ms": self.device_ms(),
                "stall_ms_per_step": self.stall_ms_per_step()}

    def reset(self) -> None:
        self._items = 0
        self._steps = 0
        self._stall_s = 0.0
        self._timer.restart()


class _StallScope:
    def __init__(self, meter: Throughput):
        self._meter = meter

    def __enter__(self):
        self._t0 = time.monotonic()
        return self

    def __exit__(self, *exc):
        self._meter.add_stall(time.monotonic() - self._t0)


class Metrics:
    """Named scalar registry; the framework-wide metrics sink."""

    def __init__(self):
        self._values: Dict[str, float] = {}
        self._lock = threading.Lock()

    def set(self, name: str, value: float) -> None:
        with self._lock:
            self._values[name] = float(value)

    def incr(self, name: str, delta: float = 1.0) -> None:
        with self._lock:
            self._values[name] = self._values.get(name, 0.0) + delta

    def get(self, name: str, default: float = 0.0) -> float:
        return self._values.get(name, default)

    def snapshot(self) -> Dict[str, float]:
        with self._lock:
            return dict(self._values)

    def to_json(self) -> str:
        import json
        return json.dumps(self.snapshot(), sort_keys=True)

    def dump(self, path: str) -> None:
        """Structured metrics export (one JSON object), for scraping by
        external monitors — the observability surface the reference's
        log-line-only story lacks.  Written atomically (temp + rename) so
        a concurrent scrape never sees a partial document."""
        import os
        tmp = f"{path}.tmp.{os.getpid()}"
        with open(tmp, "w") as f:
            f.write(self.to_json() + "\n")
        os.replace(tmp, path)


_GLOBAL_METRICS = Metrics()


def global_metrics() -> Metrics:
    return _GLOBAL_METRICS
