"""Binary / text serialization buffers.

Equivalent of the reference wire-format layer
(`/root/reference/src/utils/Buffer.h`): ``BinaryBuffer`` is a growable byte
buffer with a read cursor and raw little-endian scalar encoding (no tags, no
lengths — Buffer.h:169-230); ``TextBuffer`` is the line/token-oriented
variant (Buffer.h:236-318).

In the TPU framework there is no socket wire, so these exist for (a) binary
checkpoint blobs, (b) byte-exact interchange with artifacts produced by the
reference's BinaryBuffer, and (c) the component-inventory contract.  Python's
``struct`` provides the same little-endian memcpy semantics.  Unlike the
reference, buffer growth is delegated to ``bytearray`` (amortized doubling —
same complexity as Buffer.h:219-228 without manual ``new[]``/``delete``).
"""

from __future__ import annotations

import struct
from typing import Union

import numpy as np

_FMT = {
    "int16": "<h", "uint16": "<H",
    "int32": "<i", "uint32": "<I",
    "int64": "<q", "uint64": "<Q",
    "float32": "<f", "float64": "<d",
    "bool": "<?", "byte": "<B", "char": "<b",
}


class BinaryBuffer:
    """Growable byte buffer with a read cursor (Buffer.h:15-116,169-230)."""

    def __init__(self, data: Union[bytes, bytearray, None] = None):
        self._buf = bytearray(data or b"")
        self._cursor = 0

    # -- writes -----------------------------------------------------------
    def put(self, value, dtype: str) -> "BinaryBuffer":
        self._buf += struct.pack(_FMT[dtype], value)
        return self

    def put_int32(self, v): return self.put(int(v), "int32")
    def put_uint32(self, v): return self.put(int(v), "uint32")
    def put_int64(self, v): return self.put(int(v), "int64")
    def put_uint64(self, v): return self.put(int(v), "uint64")
    def put_float(self, v): return self.put(float(v), "float32")
    def put_double(self, v): return self.put(float(v), "float64")
    def put_bool(self, v): return self.put(bool(v), "bool")

    def put_array(self, arr: np.ndarray) -> "BinaryBuffer":
        """Raw contiguous dump, matching repeated scalar << in the reference
        (e.g. word2vec.h:120-132 serializes vectors element by element)."""
        self._buf += np.ascontiguousarray(arr).tobytes()
        return self

    # -- reads ------------------------------------------------------------
    def get(self, dtype: str):
        fmt = _FMT[dtype]
        size = struct.calcsize(fmt)
        (value,) = struct.unpack_from(fmt, self._buf, self._cursor)
        self._cursor += size
        return value

    def get_int32(self): return self.get("int32")
    def get_uint32(self): return self.get("uint32")
    def get_int64(self): return self.get("int64")
    def get_uint64(self): return self.get("uint64")
    def get_float(self): return self.get("float32")
    def get_double(self): return self.get("float64")
    def get_bool(self): return self.get("bool")

    def get_array(self, count: int, dtype) -> np.ndarray:
        dt = np.dtype(dtype)
        nbytes = count * dt.itemsize
        arr = np.frombuffer(
            bytes(self._buf[self._cursor:self._cursor + nbytes]),
            dtype=dt, count=count)
        self._cursor += nbytes
        return arr

    # -- bookkeeping ------------------------------------------------------
    @property
    def size(self) -> int:
        return len(self._buf)

    @property
    def read_finished(self) -> bool:
        """Reference ``finished()``: cursor consumed the whole buffer."""
        return self._cursor >= len(self._buf)

    def to_bytes(self) -> bytes:
        return bytes(self._buf)

    def clear(self) -> None:
        self._buf.clear()
        self._cursor = 0


class TextBuffer:
    """Line/token text buffer (Buffer.h:236-318)."""

    def __init__(self, text: str = ""):
        self._parts = [text] if text else []

    def put(self, *values) -> "TextBuffer":
        for v in values:
            self._parts.append(str(v))
        return self

    def put_line(self, line: str) -> "TextBuffer":
        self._parts.append(line + "\n")
        return self

    def to_string(self) -> str:
        return "".join(self._parts)

    def tokens(self):
        return self.to_string().split()

    def clear(self) -> None:
        self._parts.clear()
