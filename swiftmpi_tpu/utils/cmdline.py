"""``-key value`` command-line parser.

Equivalent of the reference's libFM-derived ``fms::CMDLine``
(`/root/reference/src/utils/CMDLine.h`): flags are registered with help text,
parsed from ``-key value`` pairs (a bare trailing flag is treated as
value-less), and queried with ``get_value``/``has_parameter``.  Built on top
of plain argv handling rather than argparse so the reference CLIs'
single-dash long flags (``-config``, ``-data``, ``-niters``, ``-output``,
``-mode``) work verbatim (reference w2v.cpp:8-17, lr.cpp:413-447).
"""

from __future__ import annotations

import sys
from typing import Dict, List, Optional, Sequence


class CMDLine:
    def __init__(self, argv: Optional[Sequence[str]] = None):
        argv = list(sys.argv if argv is None else argv)
        self._help: Dict[str, str] = {}
        self._values: Dict[str, str] = {}
        self._prog = argv[0] if argv else ""
        def is_flag(tok: str) -> bool:
            # "-key" is a flag; "-0.5" / "-3" are (negative-number) values.
            return (tok.startswith("-") and len(tok) > 1
                    and not tok[1].isdigit() and tok[1] != ".")

        i = 1
        while i < len(argv):
            tok = argv[i]
            if is_flag(tok):
                key = tok.lstrip("-")
                if i + 1 < len(argv) and not is_flag(argv[i + 1]):
                    self._values[key] = argv[i + 1]
                    i += 2
                else:
                    self._values[key] = ""
                    i += 1
            else:
                i += 1

    def register_parameter(self, key: str, help_text: str) -> str:
        self._help[key] = help_text
        return key

    # libFM-style camelCase aliases used by the reference call sites
    registerParameter = register_parameter

    def has_parameter(self, key: str) -> bool:
        return key in self._values

    hasParameter = has_parameter

    def get_value(self, key: str, default: Optional[str] = None) -> str:
        if key in self._values:
            return self._values[key]
        if default is not None:
            return default
        raise KeyError(f"missing command-line flag -{key}")

    getValue = get_value

    def print_help(self, out=sys.stdout) -> None:
        out.write(f"usage: {self._prog} [options]\n")
        for key, text in self._help.items():
            out.write(f"  -{key:<12} {text}\n")

    def keys(self) -> List[str]:
        return list(self._values)
