"""JAX version compatibility shims.

The codebase targets the current JAX surface (``jax.shard_map`` with the
``check_vma`` flag).  Older releases still in circulation (<= 0.4.x) only
export ``jax.experimental.shard_map.shard_map`` and spell the replication
check ``check_rep``.  Rather than branching at every one of the ~10
shard_map call sites, this module installs a top-level ``jax.shard_map``
alias with the modern keyword when the runtime lacks one.  Imported for its
side effect by every module that calls ``jax.shard_map`` (all of which
import jax at module level already), so call sites can assume the modern
spelling.  NOT imported from ``swiftmpi_tpu.utils.__init__`` — that chain
must stay jax-free so ``utils.xla_env`` can set XLA flags before backend
init (test_utils.py::test_xla_env_import_is_jax_free).
"""

from __future__ import annotations

import jax


def _install_shard_map_alias() -> None:
    try:
        jax.shard_map  # modern runtime: nothing to do
        return
    except AttributeError:
        pass
    from jax.experimental.shard_map import shard_map as _legacy

    def shard_map(f=None, /, **kwargs):
        if "check_vma" in kwargs:
            kwargs["check_rep"] = kwargs.pop("check_vma")
        if f is None:
            return lambda g: _legacy(g, **kwargs)
        return _legacy(f, **kwargs)

    jax.shard_map = shard_map


def _install_axis_size_alias() -> None:
    if hasattr(jax.lax, "axis_size"):
        return
    from jax import core as _core

    def axis_size(axis_name):
        # legacy axis env: core.axis_frame(name) IS the (static) size
        if isinstance(axis_name, (tuple, list)):
            out = 1
            for a in axis_name:
                out *= int(_core.axis_frame(a))
            return out
        return int(_core.axis_frame(axis_name))

    jax.lax.axis_size = axis_size


_install_shard_map_alias()
_install_axis_size_alias()
