"""Checkpoint out / load for sparse tables.

Two formats:

* **Text** — line-per-key ``key\\t<value>`` dumps, the reference's only
  checkpoint format (`/root/reference/src/parameter/sparsetable.h:119-132`,
  written at ``finalize``; value layout is app-defined via ``operator<<``,
  e.g. word2vec writes ``v... \\t h...`` — word2vec.h:100-110).  ``load``
  supports the reference's ownership filter (``ClusterServer::load`` keeps
  only rows the local server owns, server.h:49-62) via ``shard_filter``.
* **Binary (npz)** — full-fidelity mid-training checkpoints including
  optimizer state and the key index, which the reference cannot do (its
  dump drops h2sum/v2sum — SURVEY.md §5 "Checkpoint/resume: partial").

Formatters/parsers turn a ``{field: row}`` dict into the app's text value
and back; models provide reference-compatible ones.
"""

from __future__ import annotations

import glob
import os
import time
import zlib
from typing import Callable, Dict, List, Optional

import numpy as np

from swiftmpi_tpu import obs
from swiftmpi_tpu.cluster.bootstrap import host_array, is_writer
from swiftmpi_tpu.parameter.sparse_table import (ROWVER_KEY, SparseTable,
                                                 base_field, hot_name,
                                                 is_ef_field)

Formatter = Callable[[Dict[str, np.ndarray]], str]
Parser = Callable[[str], Dict[str, np.ndarray]]


def default_formatter(fields) -> Formatter:
    """Space-joined values per field, tab between fields, in given order."""
    def fmt(row: Dict[str, np.ndarray]) -> str:
        return "\t".join(
            " ".join(repr(float(x)) for x in np.ravel(row[f]))
            for f in fields)
    return fmt


def default_parser(fields) -> Parser:
    def parse(text: str) -> Dict[str, np.ndarray]:
        parts = text.split("\t")
        return {f: np.array([float(x) for x in p.split()], np.float32)
                for f, p in zip(fields, parts)}
    return parse


# -- text (reference-compatible) ------------------------------------------

def _lookup_growing(table: SparseTable, keys) -> np.ndarray:
    """key_index.lookup that grows the table on capacity exhaustion — a
    checkpoint written after auto-growth must load back into a model built
    with the original (smaller) capacity."""
    from swiftmpi_tpu.parameter.key_index import CapacityError

    while True:
        try:
            return table.key_index.lookup(keys)
        except CapacityError:
            table.grow()


def _index_arrays(key_index):
    n = len(key_index)
    keys = np.empty(n, np.uint64)
    slots = np.empty(n, np.int64)
    for i, (k, s) in enumerate(key_index.items()):
        keys[i] = k
        slots[i] = s
    return keys, slots


def dump_table_text(table: SparseTable, path: str,
                    formatter: Optional[Formatter] = None,
                    fields: Optional[tuple] = None) -> int:
    """Write ``key\\tvalue`` lines for every occupied row; returns count.

    With no custom ``formatter`` the value layout is the ``fields`` order
    (default: the access method's pull fields), each a space-joined float
    vector, tab-separated — and the write runs through the native C++
    writer (io.cpp smtpu_dump_rows) when available."""
    fields = tuple(fields or table.access.pull_fields)
    os.makedirs(os.path.dirname(os.path.abspath(path)), exist_ok=True)
    if formatter is None:
        from swiftmpi_tpu.data import native
        if native.available():
            keys, slots = _index_arrays(table.key_index)
            # unified_rows_host is a collective in multi-process runs:
            # gather on every process, write once.  Unified view: hot
            # rows first, tail rows offset — slots index it directly.
            arrs = [table.unified_rows_host(f)[slots] for f in fields]
            if not is_writer():
                return len(keys)
            return native.dump_rows_native(path, keys, arrs)
        formatter = default_formatter(fields)
    rows = {f: table.unified_rows_host(f) for f in table.access.fields}
    if not is_writer():
        return len(table.key_index)
    n = 0
    with open(path, "w") as f:
        for key, slot in table.key_index.items():
            row = {name: arr[slot] for name, arr in rows.items()}
            f.write(f"{key}\t{formatter(row)}\n")
            n += 1
    return n


def load_table_text(table: SparseTable, path: str,
                    parser: Optional[Parser] = None,
                    shard_filter: Optional[int] = None,
                    fields: Optional[tuple] = None) -> int:
    """Stream ``key\\tvalue`` lines into the table, creating slots lazily;
    with ``shard_filter`` keep only keys owned by that shard (the reference
    per-server load filter, server.h:49-62).  Returns rows loaded.

    With no custom ``parser``, rows are fixed-layout ``fields`` float
    vectors and parsing runs through the native C++ reader when
    available."""
    fields = tuple(fields or table.access.pull_fields)
    if parser is None:
        from swiftmpi_tpu.data import native
        if native.available():
            dims = [int(np.prod(
                np.atleast_1d(table.access.fields[f].dim))) for f in fields]
            key_arr, arrs = native.load_rows_native(path, dims)
            if not len(key_arr):
                return 0
            if shard_filter is not None:
                keep = table.key_index.shard_of(key_arr) == shard_filter
                key_arr = key_arr[keep]
                arrs = [a[keep] for a in arrs]
                if not len(key_arr):
                    return 0
            idx = np.asarray(_lookup_growing(table, key_arr), np.int32)
            state = dict(table.state)
            for fname, block in zip(fields, arrs):
                _scatter_unified(table, state, fname, idx,
                                 block.reshape(len(idx), -1))
            table.state = state
            return len(key_arr)
        parser = default_parser(fields)
    keys: list = []
    rests: list = []
    with open(path) as f:
        for line in f:
            line = line.rstrip("\n")
            if not line:
                continue
            key_s, _, rest = line.partition("\t")
            keys.append(int(key_s))
            rests.append(rest)
    if not keys:
        return 0
    key_arr = np.asarray(keys, np.uint64)
    if shard_filter is not None:
        keep = table.key_index.shard_of(key_arr) == shard_filter
        key_arr = key_arr[keep]
        rests = [r for r, k in zip(rests, keep) if k]
        if not len(key_arr):
            return 0
    all_slots = _lookup_growing(table, key_arr)
    updates: Dict[str, list] = {f: [] for f in table.access.fields}
    for rest in rests:
        for fname, value in parser(rest).items():
            updates[fname].append(np.asarray(value, np.float32))
    n = len(key_arr)
    slots = all_slots.tolist()
    idx = np.asarray(slots, np.int32)
    state = dict(table.state)
    for fname, vals in updates.items():
        if not vals:
            continue
        block = np.stack(vals).reshape(len(slots), -1)
        _scatter_unified(table, state, fname, idx, block)
    table.state = state
    return n


def _scatter_unified(table: SparseTable, state: dict, fname: str,
                     idx: np.ndarray, block: np.ndarray) -> None:
    """Scatter ``block`` rows at UNIFIED slots ``idx`` into ``state``,
    splitting between the replicated hot array (``slot < n_hot``) and the
    sharded tail (rebased by ``-n_hot``).  Mutates ``state`` in place.
    host_array, not np.asarray, on the read side: state may be a
    non-fully-addressable global array in multi-process runs (the gather
    is collective — every process reaches this line)."""
    n_hot = table.n_hot
    tail_sel = idx >= n_hot
    arr = host_array(state[fname]).copy()
    arr[idx[tail_sel] - n_hot] = block[tail_sel]
    state[fname] = _replace(table, fname, arr)
    if n_hot and not tail_sel.all():
        hn = hot_name(fname)
        harr = host_array(state[hn]).copy()
        harr[idx[~tail_sel]] = block[~tail_sel]
        state[hn] = _replace(table, hn, harr)


def _replace(table: SparseTable, fname: str, arr: np.ndarray):
    import jax
    sharding = table.field_sharding(fname)
    if sharding is None:
        return jax.numpy.asarray(arr)
    return jax.device_put(arr, sharding)


# -- binary (full fidelity, mid-training) ----------------------------------

# orphaned tmp files older than this are swept on the next save; younger
# ones may belong to a concurrent writer mid-savez and must be left alone
_TMP_SWEEP_AGE_S = 300.0
# beyond this age a tmp is swept even if its embedded pid is alive: the
# pid has almost certainly been recycled by an unrelated long-lived
# process (no real savez runs for days), and without a cap such orphans
# would accumulate forever
_TMP_SWEEP_FORCE_AGE_S = 7 * 86400.0


def npz_path(path: str) -> str:
    """Canonical on-disk name for a binary checkpoint (np.savez appends
    .npz itself; every reader/writer must agree on the same name)."""
    return path if path.endswith(".npz") else path + ".npz"


def _writer_alive(tmp_name: str) -> bool:
    """True if the pid embedded in ``<dst>.<pid>.tmp.npz`` is a live
    process — its in-progress write must not be swept (a large-table
    savez can legitimately outlast the normal age threshold; only the
    multi-day force cap overrides this, guarding against pid reuse)."""
    try:
        pid = int(tmp_name.rsplit(".tmp.npz", 1)[0].rsplit(".", 1)[1])
        os.kill(pid, 0)
        return True
    except (ValueError, IndexError, ProcessLookupError):
        return False
    except PermissionError:     # exists, owned by someone else
        return True


# every payload array gets a sibling ``__crc__<name>`` uint32 so loaders
# can detect torn/bit-rotted writes (zip CRCs exist but np.load never
# checks them on the read path we use)
_CRC_PREFIX = "__crc__"


class CheckpointCorruptError(RuntimeError):
    """A checkpoint failed CRC validation or is structurally unreadable.
    Recovery: fall back to an older generation
    (:func:`find_latest_valid_checkpoint`) or restart from scratch."""


def _crc32(arr: np.ndarray) -> int:
    return zlib.crc32(np.ascontiguousarray(arr).tobytes()) & 0xFFFFFFFF


def atomic_savez(dst: str, payload: Dict[str, np.ndarray]) -> None:
    """Crash-safe npz write: savez to a pid-unique tmp, fsync, then
    rename (+ directory fsync), so a crash mid-write never clobbers the
    last good checkpoint and a rename survives power loss.  Every array
    gains a ``__crc__<name>`` checksum entry for load-time validation.
    Sweeps orphan tmps from killed writers — only when the writing pid
    is dead AND the file has aged (pid check guards long-running
    concurrent writers; the age threshold guards pid reuse)."""
    os.makedirs(os.path.dirname(os.path.abspath(dst)), exist_ok=True)
    tmp = f"{dst}.{os.getpid()}.tmp.npz"   # unique per writer
    now = time.time()
    for stale in glob.glob(glob.escape(dst) + ".*.tmp.npz"):
        if stale == tmp:
            continue
        try:
            age = now - os.path.getmtime(stale)
            if age > _TMP_SWEEP_FORCE_AGE_S or (
                    age > _TMP_SWEEP_AGE_S and not _writer_alive(stale)):
                os.unlink(stale)
        except OSError:
            pass
    full = dict(payload)
    for k in list(payload):
        full[_CRC_PREFIX + k] = np.uint32(_crc32(np.asarray(payload[k])))
    try:
        np.savez(tmp, **full)
        fd = os.open(tmp, os.O_RDONLY)
        try:
            os.fsync(fd)
        finally:
            os.close(fd)
        os.replace(tmp, dst)
        try:
            dfd = os.open(os.path.dirname(os.path.abspath(dst)),
                          os.O_RDONLY)
            try:
                os.fsync(dfd)      # make the rename itself durable
            finally:
                os.close(dfd)
        except OSError:
            pass                   # some filesystems refuse dir fsync
    finally:
        if os.path.exists(tmp):
            os.unlink(tmp)


def verify_checkpoint(path: str) -> None:
    """Validate every checksummed array in an npz checkpoint; raises
    :class:`CheckpointCorruptError` on any mismatch or on a structurally
    unreadable file.  Pre-CRC checkpoints (no ``__crc__*`` entries) pass
    — there is nothing to check them against.  A missing file raises
    ``FileNotFoundError`` (absence is not corruption)."""
    p = npz_path(path)
    if not os.path.exists(p):
        raise FileNotFoundError(p)
    try:
        with np.load(p) as z:
            names = set(z.files)
            for name in sorted(names):
                if name.startswith(_CRC_PREFIX):
                    continue
                crc_key = _CRC_PREFIX + name
                if crc_key not in names:
                    continue
                want = int(z[crc_key])
                got = _crc32(z[name])
                if got != want:
                    raise CheckpointCorruptError(
                        f"{p}: array {name!r} CRC mismatch "
                        f"(stored {want:#010x}, computed {got:#010x})")
    except CheckpointCorruptError:
        raise
    except Exception as e:   # noqa: BLE001 — zip/zlib/pickle damage
        raise CheckpointCorruptError(f"{p}: unreadable npz: {e!r}") from e


def _gen_path(dst: str, n: int) -> str:
    """Retained-generation name: ``ckpt.npz`` -> ``ckpt.g<n>.npz`` (must
    keep the .npz suffix so npz_path() round-trips the name)."""
    return f"{dst[:-len('.npz')]}.g{n}.npz"


def _gen_files(dst: str) -> List[int]:
    """Existing generation numbers for ``dst``, ascending."""
    stem = glob.escape(dst[:-len(".npz")])
    gens = []
    for p in glob.glob(stem + ".g*.npz"):
        tail = p[len(dst) - len(".npz") + 2:-len(".npz")]
        try:
            gens.append(int(tail))
        except ValueError:
            continue
    return sorted(gens)


def rotate_before_write(dst: str, retain: int) -> None:
    """Retention step 1, called right before an atomic overwrite of
    ``dst``: rename the current live checkpoint to the next generation
    (``ckpt.g<n>.npz``) so the overwrite cannot destroy the only valid
    copy.  No-op for ``retain <= 1`` or when ``dst`` does not exist."""
    if retain <= 1 or not os.path.exists(dst):
        return
    gens = _gen_files(dst)
    os.replace(dst, _gen_path(dst, (gens[-1] + 1) if gens else 1))


def prune_generations(dst: str, retain: int) -> None:
    """Retention step 2, called after a successful write: drop all but
    the newest ``retain - 1`` generations (live file + k-1 gens = k)."""
    if retain <= 1:
        return
    for n in _gen_files(dst)[:-(retain - 1)]:
        try:
            os.unlink(_gen_path(dst, n))
        except OSError:
            pass


def find_latest_valid_checkpoint(path: str) -> Optional[str]:
    """Newest checkpoint for ``path`` that passes CRC validation: the
    live file if valid, else retained generations newest-first (written
    by ``save_checkpoint(..., retain=k)``).  Returns a loadable path or
    None.  Corrupt candidates are logged and skipped — this is the
    fallback scan ``train_with_resume`` rewinds through."""
    from swiftmpi_tpu.utils.logger import get_logger
    log = get_logger(__name__)
    dst = npz_path(path)
    candidates = [dst] + [_gen_path(dst, n)
                          for n in reversed(_gen_files(dst))]
    for cand in candidates:
        if not os.path.exists(cand):
            continue
        try:
            verify_checkpoint(cand)
            return cand
        except CheckpointCorruptError as e:
            log.warning("skipping corrupt checkpoint %s: %s", cand, e)
    return None


def save_checkpoint(table: SparseTable, path: str,
                    extra: Optional[Dict[str, np.ndarray]] = None,
                    retain: int = 1) -> None:
    """npz with all fields (incl. optimizer state), the key index, and any
    extra arrays (e.g. step counters) — resume-exact, unlike the reference
    text dump which drops h2sum/v2sum (word2vec.h:100-110).

    ``retain > 1`` keeps a last-k window: before the atomic replace, the
    previous live checkpoint is renamed to ``<path>.g<n>.npz`` and
    generations beyond ``retain - 1`` are pruned — so a checkpoint that
    lands corrupted (torn write, bit rot, injected fault) still leaves
    ``find_latest_valid_checkpoint`` an older valid file to rewind to."""
    with obs.span("checkpoint_save"):
        keys = np.fromiter(table.key_index.keys(), dtype=np.uint64,
                           count=len(table.key_index))
        slots = np.fromiter((table.key_index.slot(int(k)) for k in keys),
                            dtype=np.int64, count=len(keys))
        payload = {}
        for f, v in table.state.items():
            arr = host_array(v)
            if arr.dtype.name == "bfloat16":
                # np.savez has no bfloat16: it round-trips as raw '|V2' and
                # load explodes.  fp32 is an exact superset of bf16, so
                # upcast here and cast back at load — bit-identical.
                arr = arr.astype(np.float32)
            payload[f"field__{f}"] = arr
        payload["keys"] = keys
        payload["slots"] = slots
        payload["num_shards"] = np.int64(table.key_index.num_shards)
        payload["capacity_per_shard"] = np.int64(
            table.key_index.capacity_per_shard)
        # hybrid placement: the hot-head size travels with the checkpoint so
        # load can refuse a table built under a different frequency split
        # (the @hot field arrays are in the field__ payload like any other)
        payload["n_hot"] = np.int64(table.n_hot)
        for k, v in (extra or {}).items():
            payload[f"extra__{k}"] = np.asarray(v)
        if not is_writer():        # gather above was the collective part
            return
        dst = npz_path(path)
        rotate_before_write(dst, retain)
        # atomic: a crash mid-write must never clobber the last good
        # checkpoint (it is the only thing auto-resume can rewind to)
        atomic_savez(dst, payload)
        prune_generations(dst, retain)
        reg = obs.get_registry()
        if reg.enabled:
            reg.counter("checkpoint/saves").inc()


def load_checkpoint(table: SparseTable, path: str,
                    verify: bool = True) -> Dict[str, np.ndarray]:
    """Restore table state + key index from ``save_checkpoint`` output;
    returns the ``extra`` arrays.  ``verify`` (default on) CRC-validates
    every array first and raises :class:`CheckpointCorruptError` instead
    of silently restoring damaged state — callers with a retention window
    catch it and rewind via ``find_latest_valid_checkpoint``."""
    with obs.span("checkpoint_restore"):
        extra = _load_checkpoint(table, path, verify)
    reg = obs.get_registry()
    if reg.enabled:
        reg.counter("checkpoint/restores").inc()
    return extra


def _load_checkpoint(table: SparseTable, path: str,
                     verify: bool) -> Dict[str, np.ndarray]:
    if verify:
        verify_checkpoint(path)
    with np.load(npz_path(path)) as z:
        if int(z["num_shards"]) != table.key_index.num_shards:
            raise ValueError(
                f"checkpoint has {int(z['num_shards'])} shards, table has "
                f"{table.key_index.num_shards}")
        saved_cap = int(z["capacity_per_shard"])
        if saved_cap > table.key_index.capacity_per_shard:
            # checkpoint written after SparseTable.grow(): adopt its
            # capacity (the text path auto-grows for the same case; only
            # shrink remains an error).  Bookkeeping only — state arrays
            # and the index are overwritten from the npz just below, so
            # SparseTable.grow()'s device-side remap (which transiently
            # doubles HBM use) would be wasted work.
            table.key_index.grow(saved_cap)
        elif saved_cap < table.key_index.capacity_per_shard:
            raise ValueError(
                f"checkpoint capacity_per_shard {saved_cap} is smaller "
                f"than the table's {table.key_index.capacity_per_shard}; "
                "shrinking on load is not supported")
        saved_hot = int(z["n_hot"]) if "n_hot" in z.files else 0
        if saved_hot != table.n_hot:
            raise ValueError(
                f"checkpoint has n_hot={saved_hot}, table has "
                f"n_hot={table.n_hot} — the hot/cold partition is fixed "
                "at vocab build; rebuild the model under the same "
                "frequency split before restoring")
        saved_ef = {zname[len("field__"):] for zname in z.files
                    if zname.startswith("field__")
                    and is_ef_field(zname[len("field__"):])}
        table_ef = set(table.ef_fields)
        if saved_ef != table_ef:
            # a silent mismatch either drops pending residuals (EF
            # checkpoint into a quant-off run: unapplied gradient mass
            # vanishes) or zero-seeds planes mid-stream (non-EF
            # checkpoint into an EF run: fine mathematically but almost
            # always a misconfigured resume) — refuse loudly either way
            raise ValueError(
                f"checkpoint EF residual planes {sorted(saved_ef)} do "
                f"not match the table's {sorted(table_ef)} — restore "
                "with the same [cluster] wire_quant setting the "
                "checkpoint was written under (or rebuild the model "
                "with matching error-feedback arming)")
        state = {}
        for zname in z.files:
            if not zname.startswith("field__"):
                continue
            name = zname[len("field__"):]
            arr = z[zname]
            if is_ef_field(name) or name == ROWVER_KEY:
                # EF residual planes (f32) and the @rowver version
                # plane (int32) are not access fields — no FieldSpec,
                # no dtype cast.  Restoring @rowver as saved keeps
                # versions counting up across restarts, so a resumed
                # worker's cold cache can never collide with a re-used
                # stamp (pull_cache.py invalidation contract).
                state[name] = _replace(table, name, arr)
                continue
            # @hot arrays restore next to their base field with the same
            # storage dtype (and their replicated placement, via
            # _replace's per-name sharding)
            fs = table.access.fields[base_field(name)]
            if arr.dtype != fs.dtype:
                # bf16 fields were saved upcast to fp32 (npz has no
                # bfloat16); restore the table's storage dtype exactly
                arr = arr.astype(fs.dtype)
            state[name] = _replace(table, name, arr)
        had_rowver = ROWVER_KEY in table.state
        table.state = state
        table.key_index.restore(z["keys"], z["slots"])
        if had_rowver and ROWVER_KEY not in state:
            # pre-delta-pull checkpoint into a pull_cache-armed table:
            # re-arm a zero plane (version 0 = "never applied") rather
            # than silently dropping the cache for the rest of the run.
            # Safe — the resume path flushes every worker shadow, so
            # the reset stamps cannot false-hit.
            table.ensure_row_versions()
        return {k[len("extra__"):]: z[k] for k in z.files
                if k.startswith("extra__")}
