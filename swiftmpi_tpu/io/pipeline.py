"""Asynchronous input pipeline: prefetch-rendered, pre-transferred batches.

The reference overlaps I/O and compute by construction — its multithreaded
minibatch SGD keeps pulling rows while other worker threads grind batches
(word2vec.h:475-547 spawns one thread per core over AsynExec's bounded
queue).  The JAX port's training loops used to render every batch
(Python/native stencil batcher + ``np.stack``) and ``device_put`` its
arrays *inline on the dispatch thread*, so the device idled through
host-side rendering and H2D transfer between fused-scan groups — the
devices-starved failure mode Parallax (1808.02621) identifies for sparse
data-parallel training.

:class:`PrefetchIterator` is the one producer/consumer primitive every
loop shares:

* a **producer thread** walks the source iterator ``depth`` items ahead
  into a bounded FIFO queue.  Rendering (batcher ``next``, ``np.stack``)
  and the optional ``transfer`` hook (eager ``device_put`` with the
  step's committed input sharding, so H2D DMA overlaps the previous
  group's compute) both run on the producer's clock;
* the **consumer** iterates as usual.  Order is exactly the source
  iterator's order — single producer, FIFO queue — and the producer owns
  NO RNG (key splitting stays in the consumer, in consumption order), so
  a pipelined run is bit-identical to the synchronous one;
* time the consumer spends blocked on an empty queue is recorded as
  **host stall** (``stats().stall_s``) — the quantity the pipeline
  exists to drive to zero.  ``utils.timers.Throughput`` reports it as
  ``host_stall_ms`` next to ``device_ms``.

Bounding the *output* side (in-flight dispatches the consumer issues
against prefetched inputs) is the consumer's job — see
``utils.pipeline.DispatchWindow`` and ``resolve_dispatch_bound``; the
two bounds compose into the ``[worker] pipeline: K`` /
``dispatch_depth: D`` watermark pair so async dispatch never outruns
HBM: at most K rendered+transferred groups and D undispatched-result
programs are in flight at once.

Failure semantics: a producer exception is captured and re-raised at the
consumer's next ``__next__`` (training crash paths — fault injection,
flaky batchers — behave as if the loop were synchronous).  ``close()``
(also the context-manager exit and the GC hook) unblocks and joins the
producer, so a consumer that dies mid-epoch never leaks a thread that
keeps rendering into a dead queue.
"""

from __future__ import annotations

import queue
import threading
import time
from typing import Any, Callable, Dict, Iterable, Iterator, Optional

from swiftmpi_tpu import obs

_DONE = object()          # producer sentinel: source exhausted
_CLOSED = object()        # close() sentinel: wake a blocked consumer


class PipelineError(RuntimeError):
    """Producer-side failure, re-raised on the consumer thread with the
    original exception chained (``__cause__``)."""


class PrefetchIterator:
    """Iterate ``source`` through a ``depth``-bounded background queue.

    ``transfer`` (optional) maps each item on the producer thread —
    the eager ``device_put`` hook.  ``depth`` counts fully rendered and
    transferred items the producer may run ahead; the queue slot the
    producer is rendering *into* is not yet visible to the consumer, so
    peak host memory is ``depth + 1`` items.
    """

    def __init__(self, source: Iterable, depth: int = 2,
                 transfer: Optional[Callable[[Any], Any]] = None,
                 name: str = "input-pipeline"):
        if depth < 1:
            raise ValueError(f"pipeline depth must be >= 1, got {depth}")
        self.depth = int(depth)
        self._source = iter(source)
        self._transfer = transfer
        self._q: queue.Queue = queue.Queue(maxsize=self.depth)
        self._stop = threading.Event()
        self._error: Optional[BaseException] = None
        self._done = False
        # observability — read via stats()
        self._produced = 0
        self._consumed = 0
        self._stall_s = 0.0
        self._transfer_s = 0.0
        self._peak_depth = 0
        self._thread = threading.Thread(
            target=self._produce, name=name, daemon=True)
        self._thread.start()

    # -- producer ----------------------------------------------------------
    def _produce(self) -> None:
        try:
            src = self._source
            while True:
                if self._stop.is_set():
                    return
                # "render" / "h2d" phase spans: the producer thread is
                # exactly where batch rendering and eager H2D transfer
                # happen, so the telemetry phases are measured here (the
                # concurrent-write side of the registry's thread-safety
                # contract)
                with obs.span("render"):
                    try:
                        item = next(src)
                    except StopIteration:
                        break
                if self._transfer is not None:
                    t0 = time.monotonic()
                    with obs.span("h2d"):
                        item = self._transfer(item)
                    self._transfer_s += time.monotonic() - t0
                # bounded put that stays responsive to close(): a plain
                # blocking put on a full queue would deadlock the join
                # when the consumer is already gone
                while not self._stop.is_set():
                    try:
                        self._q.put(item, timeout=0.1)
                        self._produced += 1
                        self._peak_depth = max(self._peak_depth,
                                               self._q.qsize())
                        reg = obs.get_registry()
                        if reg.enabled:
                            reg.counter("pipeline/produced").inc()
                            reg.gauge("pipeline/queue_depth").set(
                                self._q.qsize())
                        break
                    except queue.Full:
                        continue
        except BaseException as e:            # noqa: BLE001 — re-raised
            self._error = e                   # on the consumer thread
        finally:
            self._done = True
            # land _DONE AFTER every real item (never displace one —
            # a full queue means we wait for the consumer to drain a
            # slot), unless close() already took over wake-up duty
            while not self._stop.is_set():
                try:
                    self._q.put(_DONE, timeout=0.1)
                    break
                except queue.Full:
                    continue

    # -- consumer ----------------------------------------------------------
    def __iter__(self) -> Iterator:
        return self

    def __next__(self):
        if self._stop.is_set():
            raise StopIteration
        t0 = time.monotonic()
        with obs.span("input_wait"):
            item = self._q.get()
        self._stall_s += time.monotonic() - t0
        if item is _DONE or item is _CLOSED:
            # drain-order guarantee: _DONE lands after every real item
            if self._error is not None:
                err, self._error = self._error, None
                self.close()
                raise PipelineError(
                    f"input-pipeline producer failed: {err!r}") from err
            self.close()
            raise StopIteration
        self._consumed += 1
        reg = obs.get_registry()
        if reg.enabled:
            reg.counter("pipeline/consumed").inc()
        return item

    # -- lifecycle ---------------------------------------------------------
    def close(self) -> None:
        """Stop the producer and join it.  Idempotent; safe to call from
        ``finally`` around a consumer loop that may have crashed."""
        if self._stop.is_set():
            return
        self._stop.set()
        # unblock a producer stuck on a full queue
        while True:
            try:
                self._q.get_nowait()
            except queue.Empty:
                break
        self._thread.join(timeout=5.0)
        try:
            self._q.put_nowait(_CLOSED)       # wake any blocked consumer
        except queue.Full:
            pass

    def __enter__(self) -> "PrefetchIterator":
        return self

    def __exit__(self, *exc) -> None:
        self.close()

    def __del__(self):  # pragma: no cover — GC backstop
        try:
            self.close()
        except Exception:
            pass

    # -- observability -----------------------------------------------------
    def stats(self) -> Dict[str, float]:
        """Counters for train metrics / bench detail fields."""
        return {"depth": self.depth,
                "produced": self._produced,
                "consumed": self._consumed,
                "peak_queue_depth": self._peak_depth,
                "stall_s": self._stall_s,
                "transfer_s": self._transfer_s}


def device_put_transfer(sharding) -> Callable[[Any], Any]:
    """Producer ``transfer`` hook: eagerly ``device_put`` every array
    leaf of a work item with the step's committed input ``sharding`` (a
    ``jax.sharding.Sharding`` or a ``jax.Device``), so H2D DMA issues
    from the producer thread and overlaps the previous group's compute.

    Non-array leaves (ints, strings, item-kind tags) pass through.  The
    sharding is captured by the CONSUMER at pipeline build time —
    ``jax.default_device`` is thread-local context, so the producer
    thread must never rely on it.
    """
    import jax
    import numpy as np

    def put(item):
        def leaf(x):
            if isinstance(x, (np.ndarray, jax.Array)):
                return jax.device_put(x, sharding)
            return x
        return jax.tree_util.tree_map(leaf, item)

    return put
