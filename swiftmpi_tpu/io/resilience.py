"""Failure recovery: elastic checkpoint reshard + supervised auto-resume.

The reference has none of this — its hashfrag header says "without
Replication, Fault Tolerance and Repair" (`/root/reference/src/cluster/
hashfrag.h:13`) and a dead node hangs the pull/push barrier forever
(SURVEY.md §5).  On an SPMD TPU deployment the failure model is different: a
chip/host failure kills the whole program, so recovery means *restart from
checkpoint* — these utilities make that path first-class:

* ``load_checkpoint_elastic`` — restore a full-fidelity npz checkpoint into
  a table with a **different shard count / capacity** (scale the mesh up or
  down between runs).  The strict ``load_checkpoint`` refuses mismatched
  geometry because exact resume must be bit-stable; the elastic variant
  re-keys every row through the new table's KeyIndex instead.
* ``train_with_resume`` — wrap a model's train loop with
  checkpoint-every-k-iterations and automatic reload-and-retry on failure
  (bounded restarts), turning the mid-training checkpoints
  (io/checkpoint.py) into actual fault tolerance.  Resumes pick the newest
  checkpoint that passes CRC validation (a corrupted latest falls back to
  an older retained generation), failures can optionally trigger a device
  health sweep (utils/health.py), and a hang watchdog bounds the time an
  attempt may go without step progress — a stuck collective becomes a
  checkpoint-restart instead of an infinite wait.

Chaos scenarios are injected through ``testing/faults.py``: pass a
``FaultPlan`` and the crash/hang/corruption you want to survive happens
deterministically inside the wrapped training run.
"""

from __future__ import annotations

import threading
import time
from typing import Dict, Optional

import numpy as np

from swiftmpi_tpu.cluster.bootstrap import host_array
from swiftmpi_tpu.io.checkpoint import (_replace,
                                        find_latest_valid_checkpoint,
                                        npz_path, save_checkpoint,
                                        verify_checkpoint)
from swiftmpi_tpu.parameter.sparse_table import SparseTable
from swiftmpi_tpu.testing import faults
from swiftmpi_tpu.utils.health import DeviceHangError, check_devices
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)


def load_checkpoint_elastic(table: SparseTable, path: str,
                            verify: bool = True) -> Dict[str, np.ndarray]:
    """Restore an npz checkpoint into a table whose shard geometry may
    differ from the checkpoint's: every key is re-routed through the new
    table's KeyIndex (new hashfrag, new slot ranges) and its row moved to
    the new slot.  Optimizer state travels with the row, so training
    continues exactly (up to row placement) after a mesh resize.

    ``verify`` CRC-validates the file first (CheckpointCorruptError on
    damage) — an elastic restore is usually a recovery action, exactly
    when silently loading bit-rot would hurt most.

    Returns the checkpoint's ``extra`` arrays (e.g. the iteration counter).
    Raises ``CapacityError`` if the new geometry cannot hold all rows.
    """
    if verify:
        verify_checkpoint(path)
    with np.load(npz_path(path)) as z:
        keys = z["keys"]
        old_slots = z["slots"]
        new_slots = np.asarray(table.key_index.lookup(keys), np.int64)
        state = dict(table.state)
        for name in table.access.fields:
            # host_array, not np.asarray: state may be a non-fully-
            # addressable global array in multi-process runs
            arr = host_array(state[name]).copy()
            arr[new_slots] = z[f"field__{name}"][old_slots]
            state[name] = _replace(table, name, arr)
        table.state = state
        log.info("elastic restore: %d rows re-keyed from %d-shard "
                 "checkpoint into %d-shard table", len(keys),
                 int(z["num_shards"]), table.key_index.num_shards)
        return {k[len("extra__"):]: z[k] for k in z.files
                if k.startswith("extra__")}


class _AttemptAbandoned(Exception):
    """Raised inside an abandoned attempt thread (via the fault-bus
    observer) at its next step event, so a watchdog-cancelled trainer
    stops instead of racing the restarted one for the model state."""


def _attempt(model, call_kwargs: dict, hang_timeout_s: Optional[float],
             probe_timeout_s: float):
    """One training attempt.  Without a hang timeout this is just
    ``model.train(**call_kwargs)``.  With one, the attempt runs on a
    worker thread while this thread watches the fault-bus heartbeat
    (every ``step_event`` from the training loop beats it); silence
    longer than ``hang_timeout_s`` triggers a device health sweep
    (utils/health.py) and a ``DeviceHangError``.  The stalled worker is
    cancelled cooperatively — its next step event raises — and must
    acknowledge within a grace period; if it never does (a truly wedged
    native call), the error is marked non-recoverable so the caller
    escalates to a process restart (the supervised launcher's job)
    instead of racing a zombie trainer for the model state."""
    if not hang_timeout_s:
        return model.train(**call_kwargs)

    result: dict = {}
    beat = {"t": time.monotonic()}
    cancel = threading.Event()

    def obs(event, payload):
        beat["t"] = time.monotonic()
        if cancel.is_set():
            raise _AttemptAbandoned("attempt cancelled by hang watchdog")

    def worker():
        try:
            result["losses"] = model.train(**call_kwargs)
        except BaseException as e:  # noqa: BLE001 — relayed to caller
            result["error"] = e

    faults.add_observer(obs)
    t = threading.Thread(target=worker, daemon=True,
                         name="train-attempt")
    t.start()
    try:
        while t.is_alive():
            t.join(0.05)
            if not t.is_alive():
                break
            stalled = time.monotonic() - beat["t"]
            if stalled <= hang_timeout_s:
                continue
            # no step progress within the deadline: classify via device
            # probes, cancel the attempt, and hand the failure to the
            # resume loop as a restartable error
            report = check_devices(timeout_s=probe_timeout_s)
            bad = [(h.device, h.error) for h in report if not h.ok]
            cancel.set()
            grace = max(hang_timeout_s, 5.0)
            t.join(grace)
            recoverable = not t.is_alive()
            msg = (f"no training progress for {stalled:.1f}s "
                   f"(deadline {hang_timeout_s:.1f}s); "
                   + (f"unhealthy devices: {bad}" if bad
                      else "device probes healthy (stalled host loop)"))
            if not recoverable:
                msg += ("; attempt thread did not acknowledge "
                        f"cancellation within {grace:.0f}s — escalate to "
                        "process restart")
            err = DeviceHangError(msg)
            err.recoverable = recoverable
            raise err
    finally:
        faults.remove_observer(obs)
    if "error" in result:
        err = result["error"]
        if isinstance(err, _AttemptAbandoned):
            # the worker acked a cancellation raised AFTER it already
            # finished hanging — the watchdog error was raised instead
            raise DeviceHangError("attempt cancelled by hang watchdog")
        raise err
    return result["losses"]


def train_with_resume(model, data=None, niters: int = 1,
                      checkpoint_path: str = "ckpt",
                      checkpoint_every: int = 1,
                      max_restarts: int = 2,
                      batcher=None,
                      retain: int = 2,
                      fault_plan: Optional[faults.FaultPlan] = None,
                      probe_devices: bool = False,
                      probe_timeout_s: float = 30.0,
                      hang_timeout_s: Optional[float] = None,
                      **train_kwargs):
    """Run ``model.train`` to ``niters`` total iterations with periodic
    checkpoints, resuming from the latest *valid* checkpoint after a
    failure (up to ``max_restarts`` times).  If a checkpoint already
    exists at ``checkpoint_path``, training continues from it — so
    re-running the same command after a crash (the SPMD failure model:
    the process dies) also picks up where it left off.

    The model must provide ``train(..., checkpoint_path,
    checkpoint_every, checkpoint_retain)`` and ``resume(path) ->
    start_iter`` (Word2Vec does).  Returns the per-iteration losses of
    the final successful ``train`` call, i.e. of iterations
    ``start..niters`` (failed attempts' partial losses are lost with the
    exception; a resumed run reports only the iterations it ran).

    Robustness knobs:

    * ``retain`` — checkpoint generations kept on disk (last-k window).
      Every resume scans newest-to-oldest for the first file that passes
      CRC validation, so a corrupted latest checkpoint rewinds one
      generation instead of aborting the run.
    * ``fault_plan`` — a ``testing.faults.FaultPlan`` installed for the
      duration of the call: chaos (crash at step k, hang, checkpoint
      corruption) becomes a reproducible test instead of a manual poke.
    * ``probe_devices`` — after every failure, sweep the device mesh
      with bounded health probes and log the verdict before retrying.
    * ``hang_timeout_s`` — watchdog deadline on step progress; a stalled
      attempt (hung device, stuck collective) is detected, probed, and
      restarted from checkpoint instead of waiting forever.
    """
    installed_plan = None
    if fault_plan is not None:
        installed_plan = faults.install(fault_plan)
    try:
        start = 0
        best = find_latest_valid_checkpoint(checkpoint_path)
        if best is not None:
            start = int(model.resume(best))
            log.info("found valid checkpoint %s at iter %d; continuing",
                     best, start)
        elif getattr(model, "table", None) is not None:
            # iter-0 snapshot: a crash before the first periodic
            # checkpoint must rewind to the true initial state, not
            # retrain on top of partially-updated rows
            save_checkpoint(model.table, checkpoint_path,
                            extra={"iter": np.int64(0)}, retain=retain)
        restarts = 0
        losses = []
        while True:
            remaining = niters - start
            if remaining <= 0:
                return losses
            call_kwargs = dict(
                data=data, niters=remaining,
                checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every,
                checkpoint_retain=retain, start_iter=start,
                batcher=batcher, **train_kwargs)
            try:
                losses = _attempt(model, call_kwargs, hang_timeout_s,
                                  probe_timeout_s)
                return losses
            except Exception as e:  # noqa: BLE001 — retry any failure
                if isinstance(e, DeviceHangError) and \
                        not getattr(e, "recoverable", True):
                    log.error("unrecoverable hang — escalating to the "
                              "process supervisor: %s", e)
                    raise
                restarts += 1
                if restarts > max_restarts:
                    log.error("giving up after %d restarts: %s",
                              max_restarts, e)
                    raise
                if probe_devices and not isinstance(e, DeviceHangError):
                    # hang path already probed; probe organic failures
                    # too so the log shows WHAT died, not just that
                    # something did
                    report = check_devices(timeout_s=probe_timeout_s)
                    bad = [(h.device, h.error)
                           for h in report if not h.ok]
                    if bad:
                        log.warning("post-failure probe: unhealthy "
                                    "devices %s", bad)
                best = find_latest_valid_checkpoint(checkpoint_path)
                if best is None:
                    # no valid checkpoint to rewind to (table was not
                    # built before the crash, or every generation is
                    # corrupt) — retrying would train on mutated state
                    log.error("no valid checkpoint to rewind to; "
                              "re-raising")
                    raise
                start = int(model.resume(best))
                log.warning("training failed (%s); restart %d/%d from "
                            "iter %d (%s)", e, restarts, max_restarts,
                            start, best)
    finally:
        if installed_plan is not None:
            faults.install(None)
