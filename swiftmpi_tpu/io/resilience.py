"""Failure recovery: elastic checkpoint reshard + auto-resuming training.

The reference has none of this — its hashfrag header says "without
Replication, Fault Tolerance and Repair" (`/root/reference/src/cluster/
hashfrag.h:13`) and a dead node hangs the pull/push barrier forever
(SURVEY.md §5).  On an SPMD TPU deployment the failure model is different: a
chip/host failure kills the whole program, so recovery means *restart from
checkpoint* — these utilities make that path first-class:

* ``load_checkpoint_elastic`` — restore a full-fidelity npz checkpoint into
  a table with a **different shard count / capacity** (scale the mesh up or
  down between runs).  The strict ``load_checkpoint`` refuses mismatched
  geometry because exact resume must be bit-stable; the elastic variant
  re-keys every row through the new table's KeyIndex instead.
* ``train_with_resume`` — wrap a model's train loop with
  checkpoint-every-k-iterations and automatic reload-and-retry on failure
  (bounded restarts), turning the mid-training checkpoints
  (io/checkpoint.py) into actual fault tolerance.
"""

from __future__ import annotations

import os
from typing import Dict

import numpy as np

from swiftmpi_tpu.cluster.bootstrap import host_array
from swiftmpi_tpu.io.checkpoint import _replace, npz_path, save_checkpoint
from swiftmpi_tpu.parameter.sparse_table import SparseTable
from swiftmpi_tpu.utils.logger import get_logger

log = get_logger(__name__)


def load_checkpoint_elastic(table: SparseTable, path: str
                            ) -> Dict[str, np.ndarray]:
    """Restore an npz checkpoint into a table whose shard geometry may
    differ from the checkpoint's: every key is re-routed through the new
    table's KeyIndex (new hashfrag, new slot ranges) and its row moved to
    the new slot.  Optimizer state travels with the row, so training
    continues exactly (up to row placement) after a mesh resize.

    Returns the checkpoint's ``extra`` arrays (e.g. the iteration counter).
    Raises ``CapacityError`` if the new geometry cannot hold all rows.
    """
    with np.load(npz_path(path)) as z:
        keys = z["keys"]
        old_slots = z["slots"]
        new_slots = np.asarray(table.key_index.lookup(keys), np.int64)
        state = dict(table.state)
        for name in table.access.fields:
            # host_array, not np.asarray: state may be a non-fully-
            # addressable global array in multi-process runs
            arr = host_array(state[name]).copy()
            arr[new_slots] = z[f"field__{name}"][old_slots]
            state[name] = _replace(table, name, arr)
        table.state = state
        log.info("elastic restore: %d rows re-keyed from %d-shard "
                 "checkpoint into %d-shard table", len(keys),
                 int(z["num_shards"]), table.key_index.num_shards)
        return {k[len("extra__"):]: z[k] for k in z.files
                if k.startswith("extra__")}


def train_with_resume(model, data=None, niters: int = 1,
                      checkpoint_path: str = "ckpt",
                      checkpoint_every: int = 1,
                      max_restarts: int = 2,
                      batcher=None, **train_kwargs):
    """Run ``model.train`` to ``niters`` total iterations with periodic
    checkpoints, resuming from the latest checkpoint after a failure (up to
    ``max_restarts`` times).  If a checkpoint already exists at
    ``checkpoint_path``, training continues from it — so re-running the
    same command after a crash (the SPMD failure model: the process dies)
    also picks up where it left off.

    The model must provide ``train(..., checkpoint_path, checkpoint_every)``
    and ``resume(path) -> start_iter`` (Word2Vec does).  Returns the
    per-iteration losses of the final successful ``train`` call, i.e. of
    iterations ``start..niters`` (failed attempts' partial losses are lost
    with the exception; a resumed run reports only the iterations it ran).
    """
    npz = npz_path(checkpoint_path)
    start = 0
    if os.path.exists(npz):
        start = int(model.resume(checkpoint_path))
        log.info("found checkpoint %s at iter %d; continuing", npz, start)
    elif getattr(model, "table", None) is not None:
        # iter-0 snapshot: a crash before the first periodic checkpoint
        # must rewind to the true initial state, not retrain on top of
        # partially-updated rows
        save_checkpoint(model.table, checkpoint_path,
                        extra={"iter": np.int64(0)})
    restarts = 0
    losses = []
    while True:
        remaining = niters - start
        if remaining <= 0:
            return losses
        try:
            losses = model.train(
                data, niters=remaining, checkpoint_path=checkpoint_path,
                checkpoint_every=checkpoint_every, start_iter=start,
                batcher=batcher, **train_kwargs)
            return losses
        except Exception as e:  # noqa: BLE001 — retry any training failure
            restarts += 1
            if restarts > max_restarts:
                log.error("giving up after %d restarts: %s", max_restarts, e)
                raise
            if not os.path.exists(npz):
                # no checkpoint to rewind to (table was not built before
                # the crash) — retrying would train on mutated state
                log.error("no checkpoint exists to rewind to; re-raising")
                raise
            start = int(model.resume(checkpoint_path))
            log.warning("training failed (%s); restart %d/%d from iter %d",
                        e, restarts, max_restarts, start)


