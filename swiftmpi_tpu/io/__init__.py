"""Checkpoint I/O: reference-compatible text dumps + binary resume."""

from swiftmpi_tpu.io.checkpoint import (default_formatter, default_parser,
                                        dump_table_text, load_checkpoint,
                                        load_table_text, save_checkpoint)

__all__ = ["default_formatter", "default_parser", "dump_table_text",
           "load_checkpoint", "load_table_text", "save_checkpoint"]
