"""Checkpoint I/O + resilience + input pipeline: text dumps, binary
resume, elastic reshard, CRC-validated crash-safe checkpoints with a
retained last-k window, and the asynchronous prefetch pipeline."""

from swiftmpi_tpu.io.pipeline import (PipelineError, PrefetchIterator,
                                      device_put_transfer)
from swiftmpi_tpu.io.checkpoint import (CheckpointCorruptError, atomic_savez,
                                        default_formatter, default_parser,
                                        dump_table_text,
                                        find_latest_valid_checkpoint,
                                        load_checkpoint, load_table_text,
                                        save_checkpoint, verify_checkpoint)
from swiftmpi_tpu.io.resilience import (load_checkpoint_elastic,
                                        train_with_resume)

__all__ = ["CheckpointCorruptError", "atomic_savez", "default_formatter",
           "default_parser", "dump_table_text",
           "find_latest_valid_checkpoint", "load_checkpoint",
           "load_table_text", "save_checkpoint", "verify_checkpoint",
           "load_checkpoint_elastic", "train_with_resume",
           "PipelineError", "PrefetchIterator", "device_put_transfer"]
