"""Checkpoint I/O + resilience: text dumps, binary resume, elastic reshard."""

from swiftmpi_tpu.io.checkpoint import (default_formatter, default_parser,
                                        dump_table_text, load_checkpoint,
                                        load_table_text, save_checkpoint)
from swiftmpi_tpu.io.resilience import (load_checkpoint_elastic,
                                        train_with_resume)

__all__ = ["default_formatter", "default_parser", "dump_table_text",
           "load_checkpoint", "load_table_text", "save_checkpoint",
           "load_checkpoint_elastic", "train_with_resume"]
