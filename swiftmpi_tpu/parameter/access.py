"""Pluggable pull/push access methods (server-side op plugins).

TPU-native equivalent of the reference's ``PullAccessMethod`` /
``PushAccessMethod`` plugin pair (`/root/reference/src/parameter/
accessmethod.h:7-35`): an ``AccessMethod`` bundles

* the table schema it needs (parameter fields + optimizer-state fields),
* the initial-value distribution for lazily created rows
  (``init_param``, accessmethod.h:14-16),
* which fields a ``pull`` returns to workers (``get_pull_value`` — e.g.
  word2vec pulls h,v but not the AdaGrad sums, word2vec.h:160-165),
* the pure update rule ``apply_push`` applied to pushed gradients
  (``apply_push_value``).

Where the reference mutates one row behind a pointer, here ``apply_push`` is
a pure, vectorized function over ``(n, d)`` row batches, traceable under
``jit`` and identical per-row math.

Sign convention: like the reference apps, gradients are pushed in the
*ascent* direction and the update **adds** (word2vec.h:177-185,
lr.cpp:68-75).
"""

from __future__ import annotations

from dataclasses import dataclass, field
from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp

Initializer = Callable[[jax.Array, Tuple[int, ...]], jax.Array]


def zeros_init(key: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    return jnp.zeros(shape, jnp.float32)


def uniform01_init(key: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    """U(0,1) — the reference LR weight init draws ``gen_float()``
    (lr.cpp:48-50)."""
    return jax.random.uniform(key, shape, jnp.float32)


def vec_rand_init(key: jax.Array, shape: Tuple[int, ...]) -> jax.Array:
    """(U(0,1) - 0.5) / dim — the reference ``Vec::randInit`` embedding
    init (vec1.h:229-232)."""
    dim = shape[-1]
    return (jax.random.uniform(key, shape, jnp.float32) - 0.5) / dim


@dataclass(frozen=True)
class FieldSpec:
    dim: int
    init: Initializer = zeros_init
    dtype: jnp.dtype = jnp.float32


class AccessMethod:
    """Base: schema + init + pull view + push rule."""

    #: name -> FieldSpec; the full server-side row (params + optimizer state)
    fields: Dict[str, FieldSpec] = {}
    #: subset of ``fields`` a pull returns (worker-visible view)
    pull_fields: Tuple[str, ...] = ()
    #: gradient entries a push must provide
    grad_fields: Tuple[str, ...] = ()

    def apply_push(self, params: Dict[str, jax.Array],
                   grads: Dict[str, jax.Array]) -> Dict[str, jax.Array]:
        """Pure row-batch update: (fields, grads) -> the UPDATED fields
        only.  ``grads`` may carry a subset of ``grad_fields`` — rules
        whose grad is absent are skipped, so a caller can push gradient
        families independently (e.g. word2vec h-grads keyed by target
        slots and v-grads keyed by context slots in separate pushes,
        rather than zero-padding both into one combined batch)."""
        raise NotImplementedError

    def touched_fields(self, grad_fields) -> Tuple[str, ...]:
        """Fields ``apply_push`` READS OR WRITES given these grad entries
        — sparse push paths gather exactly these rows and re-scatter the
        written subset.  An access method whose rule reads a field it
        does not update must include it here, or the row-batched
        ``params`` handed to ``apply_push`` will be missing it."""
        return tuple(self.fields)


@dataclass
class AdaGradRule:
    """One (param, accumulator, grad) triple updated AdaGrad-style."""
    param: str
    accum: str
    grad: str


class AdaGradAccess(AccessMethod):
    """Server-side AdaGrad, the reference's only optimizer.

    Per element (word2vec.h:177-185 / lr.cpp:68-75, fudge_factor 1e-6):
        accum += g^2
        param += lr * g / sqrt(accum + fudge)      # accum already updated
    """

    def __init__(self, learning_rate: float,
                 rules: Tuple[AdaGradRule, ...],
                 fields: Dict[str, FieldSpec],
                 pull_fields: Tuple[str, ...],
                 fudge_factor: float = 1e-6):
        self.learning_rate = float(learning_rate)
        self.rules = tuple(rules)
        self.fields = dict(fields)
        self.pull_fields = tuple(pull_fields)
        self.grad_fields = tuple(r.grad for r in self.rules)
        self.fudge_factor = float(fudge_factor)
        for r in self.rules:
            if r.param not in self.fields or r.accum not in self.fields:
                raise ValueError(f"rule {r} references unknown field")

    def apply_push(self, params, grads):
        out = {}
        for r in self.rules:
            if r.grad not in grads:
                continue
            g = grads[r.grad].astype(jnp.float32)
            accum = params[r.accum] + jnp.square(g)
            out[r.accum] = accum
            p = params[r.param]
            out[r.param] = (p.astype(jnp.float32) + (
                self.learning_rate * g
                * jax.lax.rsqrt(accum + self.fudge_factor))
            ).astype(p.dtype)      # fp32 math, one rounding on store
        return out

    def touched_fields(self, grad_fields):
        gf = set(grad_fields)
        out = []
        for r in self.rules:
            if r.grad in gf:
                out += [r.param, r.accum]
        return tuple(out)


class PallasAdaGradAccess(AdaGradAccess):
    """AdaGradAccess with the update rule executed by the fused Pallas TPU
    kernel (ops/pallas_kernels.adagrad_update).  The kernel declares
    input/output aliasing; the update is truly in-place when the enclosing
    training step donates the table state (as ``Word2Vec._build_step``
    does).  Numerics identical to the base rule; interpret mode keeps it
    runnable on CPU."""

    def apply_push(self, params, grads):
        from swiftmpi_tpu.ops.pallas_kernels import (adagrad_update,
                                                     default_interpret)
        interpret = default_interpret()
        out = {}
        for r in self.rules:
            if r.grad not in grads:
                continue
            g = grads[r.grad].astype(jnp.float32)
            p2, a2 = adagrad_update(
                params[r.param], params[r.accum], g,
                lr=self.learning_rate, fudge=self.fudge_factor,
                interpret=interpret)
            out[r.param] = p2
            out[r.accum] = a2
        return out


def lr_access(learning_rate: float) -> AdaGradAccess:
    """Logistic-regression row: scalar weight + grad²-sum
    (reference LRParam, lr.cpp:14-22,60-81)."""
    return AdaGradAccess(
        learning_rate,
        rules=(AdaGradRule("val", "grad2sum", "val"),),
        fields={"val": FieldSpec(1, uniform01_init),
                "grad2sum": FieldSpec(1, zeros_init)},
        pull_fields=("val",),
    )


def w2v_access(learning_rate: float, len_vec: int,
               param_dtype=jnp.float32) -> AdaGradAccess:
    """word2vec row: h,v embeddings + per-element AdaGrad sums
    (reference WParam, word2vec.h:32-46,167-191).

    ``param_dtype=bfloat16`` stores the embedding fields at half width —
    on TPU the row gathers/scatters are the measured bottleneck and move
    half the HBM bytes; pulls are upcast to fp32 before any math and the
    AdaGrad accumulators stay fp32 (the update rule computes in fp32 and
    rounds once on store)."""
    return AdaGradAccess(
        learning_rate,
        rules=(AdaGradRule("h", "h2sum", "h"),
               AdaGradRule("v", "v2sum", "v")),
        fields={"h": FieldSpec(len_vec, vec_rand_init, param_dtype),
                "v": FieldSpec(len_vec, vec_rand_init, param_dtype),
                "h2sum": FieldSpec(len_vec, zeros_init),
                "v2sum": FieldSpec(len_vec, zeros_init)},
        pull_fields=("h", "v"),
    )


class SGDAccess(AccessMethod):
    """Plain additive SGD (no accumulator) — not in the reference, but the
    natural second access method and the cheapest push path."""

    def __init__(self, learning_rate: float, fields: Dict[str, FieldSpec],
                 pull_fields: Tuple[str, ...],
                 grad_fields: Tuple[str, ...]):
        self.learning_rate = float(learning_rate)
        self.fields = dict(fields)
        self.pull_fields = tuple(pull_fields)
        self.grad_fields = tuple(grad_fields)

    def apply_push(self, params, grads):
        out = {}
        for name in self.grad_fields:
            if name in grads:
                out[name] = params[name] + self.learning_rate * grads[name]
        return out

    def touched_fields(self, grad_fields):
        return tuple(f for f in self.grad_fields if f in set(grad_fields))
