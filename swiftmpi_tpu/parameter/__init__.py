"""Parameter layer: sharded table, access methods, key index, worker cache.

TPU-native equivalent of `/root/reference/src/parameter/` (SURVEY.md §2.4).
"""

from swiftmpi_tpu.parameter.access import (AccessMethod, AdaGradAccess,
                                           AdaGradRule, FieldSpec, SGDAccess,
                                           lr_access, uniform01_init,
                                           vec_rand_init, w2v_access,
                                           zeros_init)
from swiftmpi_tpu.parameter.cache import LocalParamCache
from swiftmpi_tpu.parameter.key_index import CapacityError, KeyIndex
from swiftmpi_tpu.parameter.sparse_table import SparseTable, TableState

__all__ = [
    "AccessMethod", "AdaGradAccess", "AdaGradRule", "FieldSpec", "SGDAccess",
    "lr_access", "uniform01_init", "vec_rand_init", "w2v_access",
    "zeros_init", "LocalParamCache", "CapacityError", "KeyIndex",
    "SparseTable", "TableState",
]
