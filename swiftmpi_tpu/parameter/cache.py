"""Worker-side minibatch parameter cache.

Equivalent of the reference ``LocalParamCache``
(`/root/reference/src/parameter/param.h:13-68`): the pulled rows for the
current key working set plus accumulated gradients, with per-key
accumulation counts for the mean-normalization the reference applies when
staging a push (word2vec.h:120-132: ``grad /= count`` inside operator<<;
lr.cpp:32-38 same for LR).

Implementation is aligned-array, not map-of-rows: keys are positions in a
dense ``(n, d)`` block, so the worker compute path stays vectorized.  The
fused SPMD training steps bypass this class entirely (their "cache" is the
gathered rows inside the jitted step); this host cache serves the app-level
gather → pull → compute → push loop and sent2vec-style local updates.
"""

from __future__ import annotations

from typing import Dict, Iterable, Optional

import numpy as np


class LocalParamCache:
    def __init__(self, pull_fields: Dict[str, int],
                 grad_fields: Optional[Dict[str, int]] = None):
        """``pull_fields``/``grad_fields``: name -> row width."""
        self._pull_fields = dict(pull_fields)
        self._grad_fields = dict(grad_fields or pull_fields)
        self._keys = np.empty(0, dtype=np.uint64)
        self._pos: Dict[int, int] = {}
        self.params: Dict[str, np.ndarray] = {}
        self.grads: Dict[str, np.ndarray] = {}
        self.counts: Dict[str, np.ndarray] = {}

    # -- lifecycle (param.h:24-37) ----------------------------------------
    def init_keys(self, keys: Iterable[int]) -> None:
        self._keys = np.fromiter(
            dict.fromkeys(int(k) for k in keys), dtype=np.uint64)
        n = len(self._keys)
        self._pos = {int(k): i for i, k in enumerate(self._keys)}
        self.params = {f: np.zeros((n, d), np.float32)
                       for f, d in self._pull_fields.items()}
        self.reset_grads()

    def reset_grads(self) -> None:
        n = len(self._keys)
        self.grads = {f: np.zeros((n, d), np.float32)
                      for f, d in self._grad_fields.items()}
        self.counts = {f: np.zeros(n, np.int64) for f in self._grad_fields}

    def clear(self) -> None:
        self.init_keys([])

    # -- access -----------------------------------------------------------
    @property
    def keys(self) -> np.ndarray:
        return self._keys

    def __len__(self) -> int:
        return len(self._keys)

    def position(self, key: int) -> int:
        return self._pos[int(key)]

    def positions(self, keys) -> np.ndarray:
        return np.fromiter((self._pos[int(k)] for k in keys),
                           dtype=np.int64, count=len(keys))

    def set_params(self, rows: Dict[str, np.ndarray]) -> None:
        """Install pulled rows (the pull-response write,
        global_pull_access.h:80-101)."""
        for f, block in rows.items():
            self.params[f][...] = block

    def accumulate(self, field: str, positions, grad_rows) -> None:
        """grads[field][pos] += row; counts[field][pos] += 1
        (reference WLocalGrad::accu_h/accu_v, word2vec.h:75-84)."""
        positions = np.asarray(positions, dtype=np.int64)
        np.add.at(self.grads[field], positions,
                  np.asarray(grad_rows, dtype=np.float32))
        np.add.at(self.counts[field], positions, 1)

    def normalized_grads(self) -> Dict[str, np.ndarray]:
        """Mean-normalized accumulated grads, the exact quantity the
        reference serializes into a push (word2vec.h:120-132)."""
        out = {}
        for f, g in self.grads.items():
            c = np.maximum(self.counts[f], 1).astype(np.float32)
            out[f] = g / c[:, None]
        return out
