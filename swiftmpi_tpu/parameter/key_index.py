"""Host-side key → dense-slot index for the sharded parameter table.

The reference stores parameters in a ``dense_hash_map<key, value>`` per
server shard (`/root/reference/src/parameter/sparsetable.h:17-149`) and
creates rows lazily on first pull (accessmethod.h:63-70).  XLA wants static
shapes and integer indexing, so the TPU design splits that hash map in two:

* this **KeyIndex** (host side): an open vocabulary mapping arbitrary uint64
  keys to dense slots, assigned lazily on first touch — the moral equivalent
  of ``dense_hash_map`` insertion.  Routing is shard-aware: a key's shard is
  decided by the same murmur-based HashFrag rule as the reference
  (hashfrag.h:51-55), and its slot lands in that shard's contiguous slot
  range, so row ``slot`` of the device-side table lives on the device that
  "owns" the key.
* the device-side **SparseTable** (sparse_table.py): dense ``(capacity, d)``
  arrays indexed by slot, row-sharded over the mesh.

Slot layout: ``slot = shard_id * capacity_per_shard + local_slot``.  With
``num_shards`` equal to the mesh's table-axis size, shard *i*'s range maps
exactly onto device *i*'s row slice.

Hybrid hot/cold placement (``transfer: hybrid``): an optional
``HotColdPartition`` reserves the FIRST ``n_hot`` slots of the unified slot
space for a frequency-ranked hot head that is replicated on every device
(Parallax, arXiv:1808.02621).  Tail keys keep the sharded layout above,
offset by ``n_hot``:

    slot < n_hot                → hot slot (replicated row, dense psum)
    slot = n_hot + shard*cap+l  → tail slot (hash-sharded row, all_to_all)

Hot-first was chosen so hot slots survive ``grow()`` and elastic restore
unchanged — ``n_hot`` is fixed at vocab build, while the tail layout is
re-derived from ``capacity_per_shard`` whenever it changes.
"""

from __future__ import annotations

from itertools import chain
from typing import Dict, Iterable, List, Optional, Tuple

import numpy as np

from swiftmpi_tpu.cluster.hashfrag import HashFrag
from swiftmpi_tpu.utils.hashing import get_hash_code_np


class CapacityError(RuntimeError):
    """A shard ran out of slots; raise rather than silently evict."""


def calibrate_hot_k(counts, mass_lo: float = 0.5, mass_hi: float = 0.8,
                    batch_rows: Optional[int] = None,
                    dense_ratio: float = 2.0) -> Tuple[int, float]:
    """Pick the hot-head size K from a descending frequency histogram.

    The CDF band [``mass_lo``, ``mass_hi``] bounds K to the head covering
    ~50-80% of token mass (the Zipf knee).  Within the band, a measured
    dense-vs-sparse crossover in the spirit of ``transfer/tpu.py:285``
    decides how far to push: replicating K rows costs one dense psum of K
    rows per step, while leaving them sharded costs ~``batch_rows *
    cdf[K-1]`` routed rows (the expected head hits per batch).  The dense
    head pays off while ``K <= dense_ratio * expected_head_hits`` — the
    same "dense once sparse volume passes half the dense size" rule the
    tpu backend applies to its DCN hop, applied per-partition.  Without a
    batch-size hint the conservative band floor ``k_lo`` is used.

    Returns ``(K, head_mass)`` where ``head_mass = cdf[K-1]``.
    """
    counts = np.asarray(counts, dtype=np.float64).ravel()
    if counts.size == 0:
        return 0, 0.0
    if np.any(np.diff(counts) > 0):
        counts = np.sort(counts)[::-1]
    total = counts.sum()
    if total <= 0:
        return 0, 0.0
    cdf = np.cumsum(counts) / total
    k_lo = int(np.searchsorted(cdf, mass_lo, side="left")) + 1
    k_hi = int(np.searchsorted(cdf, mass_hi, side="left")) + 1
    k_lo = max(1, min(k_lo, counts.size))
    k_hi = max(k_lo, min(k_hi, counts.size))
    k = k_lo
    if batch_rows:
        ks = np.arange(1, counts.size + 1, dtype=np.float64)
        ok = ((ks >= k_lo) & (ks <= k_hi)
              & (ks <= dense_ratio * float(batch_rows) * cdf))
        hits = np.flatnonzero(ok)
        if hits.size:
            k = int(hits[-1]) + 1
    return k, float(cdf[k - 1])


def window_wire_format(rows: int, capacity: int, row_bytes: int,
                       dense_ratio: float = 2.0,
                       expected_unique: Optional[float] = None,
                       quant: str = "off",
                       quant_row_bytes: Optional[int] = None,
                       quant_guard: float = 1.25,
                       sketch: bool = False) -> str:
    """Wire format for one coalesced push window.

    The same crossover rule :func:`calibrate_hot_k` applies to placement
    ("dense once sparse volume passes half the dense size", SparCML
    arXiv:1802.08021), applied per-window to the exchange representation:

      sparse volume = rows_on_wire x (4-byte index + row_bytes)
      dense volume  = capacity x row_bytes

    and the window densifies when ``sparse >= dense / dense_ratio``.
    ``rows`` is the window's flattened request count; ``expected_unique``
    (when the caller has a frequency histogram — see
    ``cluster.hashfrag.expected_unique_rows``) caps it at the rows the
    pre-exchange dedup will actually leave on the wire.  The decision is
    host-static so the compiled window program bakes in one format.

    With ``quant != "off"`` the decision widens from 2-way to 4-way
    (SparCML's quantized sparse streams, S2-Reducer's index-set
    compression) using per-format byte models over ``eff`` effective
    rows (``value_bytes = row_bytes - 4``, the index word removed):

      =========  =====================================================
      dense      ``capacity * row_bytes`` (unchanged 2-way gate, so
                 the sparse/dense boundary is bit-identical to quant
                 off)
      sparse     ``eff * (4 + row_bytes)`` — f32 (index, value) pairs;
                 lossless, the legacy representation
      bitmap     ``capacity / 8 + eff * value_bytes`` — one occupancy
                 bit per table row plus packed values; wins in the
                 mid-density band where index words cost more than the
                 mask; lossless
      sparse_q   ``eff * (4 + quant_row_bytes)`` — indices stay i32,
                 values ship quantized (int8 + per-bucket scale, or
                 bf16); LOSSY per window, repaired across windows by
                 error feedback
      =========  =====================================================

    ``sketch=True`` (the ``wire_sketch`` knob) arms a fifth, lossless
    rung — ``sparse_sketch``, S2 Reducer's counting-sketch index
    compression (transfer/sketch.py):

      ``sketch_base + eff * (1 + value_bytes)`` — a uint16 per-bucket
      occupancy sketch (``2 * ceil(capacity / 256)`` bytes) replaces
      both the index words and the bitmap mask; each row ships one
      uint8 in-bucket offset plus packed values.  Wins in the
      mid-density band between ``sparse`` (low density: 4-byte indices
      are cheap) and ``bitmap`` (high density: a 1-bit mask beats 1
      byte/row).

    Whenever the ladder extends past 2-way the sketch rung is PRICED
    (its volume lands in the evidence dict alongside the other four)
    but it can only WIN with ``sketch=True`` — so arming quantization
    alone leaves every historical decision bit-identical.

    The lossless minimum always beats sparse_q unless the quantized
    volume clears the **quantization-error guard**: sparse_q is picked
    only when ``q_vol * quant_guard <= lossless_vol`` (default 1.25 —
    never pay quantization error for a marginal byte win)."""
    decision, _ = price_window_formats(
        rows, capacity, row_bytes, dense_ratio=dense_ratio,
        expected_unique=expected_unique, quant=quant,
        quant_row_bytes=quant_row_bytes, quant_guard=quant_guard,
        sketch=sketch)
    return decision


def price_window_formats(rows: int, capacity: int, row_bytes: int,
                         dense_ratio: float = 2.0,
                         expected_unique: Optional[float] = None,
                         quant: str = "off",
                         quant_row_bytes: Optional[int] = None,
                         quant_guard: float = 1.25,
                         sketch: bool = False):
    """The :func:`window_wire_format` decision WITH its evidence: returns
    ``(decision, prices)`` where ``prices`` maps every candidate format
    that was actually priced to its modeled byte volume — the "why did
    this window densify" record the wire-tracing plane
    (:mod:`swiftmpi_tpu.obs.trace`) attaches to each trace record, and
    the pricing half of the TrafficPlan compiler
    (:mod:`swiftmpi_tpu.transfer.plan`).  The decision logic is
    byte-for-byte the one documented on :func:`window_wire_format`
    (which delegates here); with ``quant == "off"`` and ``sketch``
    unset only the 2-way sparse/dense pair is priced, so the candidate
    set itself records which rungs were even in play."""
    eff = float(min(rows, capacity))
    if expected_unique is not None:
        eff = min(eff, float(expected_unique))
    sparse_vol = eff * (4.0 + row_bytes)
    dense_vol = float(capacity) * row_bytes
    prices = {"sparse": sparse_vol, "dense": dense_vol}
    if sparse_vol * dense_ratio >= dense_vol:
        return "dense", prices
    if quant == "off" and not sketch:
        return "sparse", prices
    value_bytes = max(float(row_bytes) - 4.0, 0.0)
    bitmap_vol = capacity / 8.0 + eff * value_bytes
    prices["bitmap"] = bitmap_vol
    from swiftmpi_tpu.transfer.sketch import sketch_wire_bytes
    sketch_vol = sketch_wire_bytes(capacity, eff, value_bytes)
    prices["sparse_sketch"] = sketch_vol
    best, best_vol = "sparse", sparse_vol
    if bitmap_vol < best_vol:
        best, best_vol = "bitmap", bitmap_vol
    # the sketch rung is always PRICED past 2-way but only ELIGIBLE
    # when armed — quant-only configurations keep their exact
    # historical decisions
    if sketch and sketch_vol < best_vol:
        best, best_vol = "sparse_sketch", sketch_vol
    if quant != "off" and quant_row_bytes is not None:
        q_vol = eff * (4.0 + float(quant_row_bytes))
        prices["sparse_q"] = q_vol
        if q_vol * quant_guard <= best_vol:
            return "sparse_q", prices
    return best, prices


def price_hot_collectives(capacity: int, width_bytes: int,
                          touched_fraction: Optional[float],
                          sparse_ar_ratio: float = 2.0):
    """Collective crossover for a replicated/capacity-shaped reconcile
    (the hybrid hot plane's psum, the window path's dense rung):
    returns ``(decision, prices)`` with ``decision`` in ``{"psum",
    "sparse_allreduce"}`` and ``prices`` the modeled byte volume of
    each candidate — the evidence half, exactly like
    :func:`price_window_formats`.

    The byte models are the shared ones in
    :mod:`swiftmpi_tpu.transfer.sparse_allreduce` (so the pricer, the
    ledger booking and the budget gate agree by construction):

      psum             ``capacity * width_bytes`` — the full buffer,
                       no index stream
      sparse_allreduce ``touched * (4 + width_bytes)`` — the touched
                       (index, value) rows through Ok-Topk's
                       split-and-exchange, ``touched =
                       touched_fraction * capacity``

    and the SparCML-style threshold mirrors the window wire crossover:
    the dense psum keeps winning while ``sparse_vol * sparse_ar_ratio
    >= dense_vol`` (default ratio 2.0 — "densify once sparse volume
    passes half the dense size", arXiv:1802.08021).  With no
    ``touched_fraction`` signal (None — nothing observed the hot-touch
    density yet) the dense psum wins unconditionally: the sparse
    collective is only ever an EVIDENCED downgrade."""
    from swiftmpi_tpu.transfer.sparse_allreduce import (dense_psum_bytes,
                                                        sparse_ar_bytes)
    dense_vol = dense_psum_bytes(capacity, width_bytes)
    if touched_fraction is None:
        return "psum", {"psum": dense_vol}
    frac = min(max(float(touched_fraction), 0.0), 1.0)
    sparse_vol = sparse_ar_bytes(frac * capacity, width_bytes)
    prices = {"psum": dense_vol, "sparse_allreduce": sparse_vol}
    if sparse_vol * sparse_ar_ratio >= dense_vol:
        return "psum", prices
    return "sparse_allreduce", prices


class HotColdPartition:
    """Frequency split of the key space: hot head vs sharded cold tail.

    ``hot_keys[i]`` owns hot slot ``i`` — slot order IS frequency rank, so
    the split is deterministic under re-keying as long as the counts are
    (ties broken by key value in :meth:`from_counts`).  The partition is
    host-side routing metadata, the moral sibling of :class:`HashFrag`:
    hashfrag answers "which shard owns this tail key", the partition
    answers "is this key replicated, and at which hot slot".
    """

    def __init__(self, hot_keys):
        hot = np.asarray(hot_keys, dtype=np.uint64).ravel()
        if np.unique(hot).size != hot.size:
            raise ValueError("hot_keys must be distinct")
        self.hot_keys = hot
        self.n_hot = int(hot.size)
        self.head_mass: Optional[float] = None
        self._order = np.argsort(hot, kind="stable")
        self._sorted = hot[self._order]

    @classmethod
    def from_counts(cls, keys, counts, mass_lo: float = 0.5,
                    mass_hi: float = 0.8,
                    batch_rows: Optional[int] = None) -> "HotColdPartition":
        """Calibrate K from the measured frequency CDF and take the top-K
        keys by ``(-count, key)`` — the deterministic tie-break makes the
        hot set a pure function of the histogram, independent of input
        order (re-keying / vocab rebuild safety)."""
        keys = np.asarray(keys, dtype=np.uint64).ravel()
        counts = np.asarray(counts, dtype=np.int64).ravel()
        if keys.shape != counts.shape:
            raise ValueError("keys/counts length mismatch")
        order = np.lexsort((keys, -counts))
        k, mass = calibrate_hot_k(counts[order], mass_lo, mass_hi,
                                  batch_rows)
        part = cls(keys[order][:k])
        part.head_mass = mass
        return part

    def hot_slot(self, keys) -> np.ndarray:
        """Vectorized key → hot slot; -1 for tail keys."""
        arr = np.asarray(keys, dtype=np.uint64)
        flat = arr.ravel()
        out = np.full(flat.shape, -1, dtype=np.int64)
        if self.n_hot:
            pos = np.searchsorted(self._sorted, flat)
            pos_c = np.minimum(pos, self.n_hot - 1)
            match = self._sorted[pos_c] == flat
            out[match] = self._order[pos_c[match]]
        return out.reshape(arr.shape)

    def is_hot(self, keys) -> np.ndarray:
        return self.hot_slot(keys) >= 0

    def items(self) -> Iterable:
        """(key, hot_slot) pairs in hot-slot (frequency-rank) order."""
        return zip(self.hot_keys.tolist(), range(self.n_hot))

    def __eq__(self, other) -> bool:
        return (isinstance(other, HotColdPartition)
                and np.array_equal(self.hot_keys, other.hot_keys))

    def __repr__(self) -> str:  # pragma: no cover
        mass = (f", head_mass={self.head_mass:.3f}"
                if self.head_mass is not None else "")
        return f"HotColdPartition(n_hot={self.n_hot}{mass})"


class RepartitionPlan:
    """Row-movement recipe produced by :meth:`KeyIndex.repartition`.

    All arrays are parallel src/dst index pairs in the NEW layout's
    coordinate frames (hot arrays indexed by frequency rank, tail
    arrays by their shard-local row ``shard*capacity_per_shard+local``
    — the tail frame is repartition-invariant, only the unified-slot
    offset ``n_hot`` moves):

    * ``demote_src``/``demote_dst``: old hot rank → tail row, for keys
      leaving the hot head (their current replicated row is written
      back into the sharded tail so no update is lost).
    * ``hot_from_hot_src``/``hot_from_hot_dst``: old rank → new rank,
      for keys staying hot whose frequency rank moved.
    * ``hot_from_tail_src``/``hot_from_tail_dst``: tail row → new
      rank, for promoted keys that already own a materialized tail
      slot (its row seeds the new hot row; the tail slot stays
      allocated and simply goes dormant under the hot overlay).
      Promoted keys never touched before start from fresh init.
    """

    def __init__(self, old_n_hot: int, new_n_hot: int,
                 demote_src, demote_dst,
                 hot_from_hot_src, hot_from_hot_dst,
                 hot_from_tail_src, hot_from_tail_dst):
        self.old_n_hot = int(old_n_hot)
        self.new_n_hot = int(new_n_hot)
        self.demote_src = np.asarray(demote_src, np.int64)
        self.demote_dst = np.asarray(demote_dst, np.int64)
        self.hot_from_hot_src = np.asarray(hot_from_hot_src, np.int64)
        self.hot_from_hot_dst = np.asarray(hot_from_hot_dst, np.int64)
        self.hot_from_tail_src = np.asarray(hot_from_tail_src, np.int64)
        self.hot_from_tail_dst = np.asarray(hot_from_tail_dst, np.int64)

    @property
    def moved_rows(self) -> int:
        return int(self.demote_src.size + self.hot_from_hot_src.size
                   + self.hot_from_tail_src.size)

    def __repr__(self) -> str:  # pragma: no cover
        return (f"RepartitionPlan({self.old_n_hot}->{self.new_n_hot} hot, "
                f"demote={self.demote_src.size}, "
                f"stay={self.hot_from_hot_src.size}, "
                f"promote={self.hot_from_tail_src.size})")


class KeyIndex:
    def __init__(self, num_shards: int, capacity_per_shard: int,
                 hashfrag: Optional[HashFrag] = None,
                 partition: Optional[HotColdPartition] = None):
        self.num_shards = int(num_shards)
        self.capacity_per_shard = int(capacity_per_shard)
        self.hashfrag = hashfrag or HashFrag(num_shards)
        if self.hashfrag.num_shards != self.num_shards:
            raise ValueError("hashfrag shard count mismatch")
        self.partition = partition
        self.n_hot = partition.n_hot if partition is not None else 0
        self._slot_of: Dict[int, int] = {}
        self._next_local = np.zeros(self.num_shards, dtype=np.int64)
        self._keys_by_shard: List[List[int]] = [
            [] for _ in range(self.num_shards)]
        # Vectorized open-addressing mirror of _slot_of for the batch
        # lookup hot path (the dict stays authoritative for
        # introspection/insertion order).  Round-1 lookup was a per-key
        # python loop — at BASELINE config #3 scale (~1M-word vocab,
        # per-batch feature lookups) that loop dominated the host
        # pipeline.  Linear probing, power-of-two size, grown at 50% load.
        self._ht_size = 0
        self._ht_keys = np.empty(0, np.uint64)
        self._ht_slots = np.empty(0, np.int64)

    # -- vectorized hash table --------------------------------------------
    def _ht_grow(self, min_items: int) -> None:
        size = 1024
        while size < 2 * min_items:
            size *= 2
        self._ht_size = size
        self._ht_keys = np.zeros(size, np.uint64)
        self._ht_slots = np.full(size, -1, np.int64)
        if self._slot_of:
            keys = np.fromiter(self._slot_of.keys(), np.uint64,
                               len(self._slot_of))
            slots = np.fromiter(self._slot_of.values(), np.int64,
                                len(self._slot_of))
            self._ht_insert(keys, slots)

    def _ht_insert(self, keys: np.ndarray, slots: np.ndarray) -> None:
        """Vectorized insert of DISTINCT keys.  Claim rounds: every
        pending key probes its bucket; one winner per free bucket writes,
        everyone else advances one probe step."""
        mask = np.uint64(self._ht_size - 1)
        idx = get_hash_code_np(keys) & mask
        pending = np.arange(len(keys))
        while pending.size:
            cur = idx[pending].astype(np.int64)
            free = self._ht_slots[cur] < 0
            cand_pos = np.flatnonzero(free)
            if cand_pos.size:
                buckets, first = np.unique(cur[cand_pos],
                                           return_index=True)
                winners = pending[cand_pos[first]]
                self._ht_keys[buckets] = keys[winners]
                self._ht_slots[buckets] = slots[winners]
                won = np.zeros(len(keys), bool)
                won[winners] = True
                pending = pending[~won[pending]]
                if not pending.size:
                    break
            idx[pending] = (idx[pending] + np.uint64(1)) & mask

    def _ht_find(self, flat: np.ndarray) -> np.ndarray:
        """Vectorized probe: slots for present keys, -1 for absent."""
        out = np.full(flat.shape, -1, np.int64)
        if self._ht_size == 0:
            return out
        mask = np.uint64(self._ht_size - 1)
        idx = get_hash_code_np(flat) & mask
        active = np.arange(flat.size)
        while active.size:
            cur = idx[active].astype(np.int64)
            slots_at = self._ht_slots[cur]
            empty = slots_at < 0
            match = (~empty) & (self._ht_keys[cur] == flat[active])
            out[active[match]] = slots_at[match]
            cont = ~(empty | match)          # occupied by a different key
            active = active[cont]
            if active.size:
                idx[active] = (idx[active] + np.uint64(1)) & mask
        return out

    # -- core -------------------------------------------------------------
    def lookup(self, keys, create: bool = True) -> np.ndarray:
        """Map keys → slots; unknown keys get fresh slots in their owning
        shard when ``create`` (lazy init, reference accessmethod.h:63-70),
        else -1.  Fully vectorized (hash-probe batch lookup + batch slot
        assignment); the reference's scale mechanism for the same problem
        was a multithreaded gather_keys scan (word2vec.h:323-377).
        """
        keys = np.asarray(keys, dtype=np.uint64)
        flat = keys.ravel()
        out_flat = self._ht_find(flat)
        if self.partition is not None:
            # hot keys never enter the sharded tail: their slot is fixed
            # by frequency rank at vocab build, overlaying any miss
            hot = self.partition.hot_slot(flat)
            out_flat = np.where(hot >= 0, hot, out_flat)
        if create:
            miss_pos = np.flatnonzero(out_flat < 0)
            if miss_pos.size:
                out_flat[miss_pos] = self._create(flat[miss_pos])
        return out_flat.astype(np.int32).reshape(keys.shape)

    def _create(self, miss_keys: np.ndarray) -> np.ndarray:
        """Assign fresh slots to missing keys (first-touch order, like
        dict insertion); returns the slot for every position in
        ``miss_keys`` (duplicates resolve to one new slot)."""
        # de-duplicate keeping first-touch order (np.unique sorts; undo
        # via the first-occurrence indices)
        uniq_sorted, first, inv = np.unique(miss_keys, return_index=True,
                                            return_inverse=True)
        order = np.argsort(first, kind="stable")
        uniq = uniq_sorted[order]
        shards = self.hashfrag.to_shard_id(uniq).astype(np.int64)
        counts = np.bincount(shards, minlength=self.num_shards)
        over = self._next_local + counts > self.capacity_per_shard
        if over.any():
            s = int(np.flatnonzero(over)[0])
            raise CapacityError(
                f"shard {s} full ({self.capacity_per_shard} slots); "
                f"raise capacity_per_shard")
        # per-key local slot = next_local[shard] + occurrence index of its
        # shard so far (stable grouping preserves first-touch order)
        by_shard = np.argsort(shards, kind="stable")
        group_start = np.zeros(self.num_shards, np.int64)
        group_start[1:] = np.cumsum(counts)[:-1]
        occ = np.empty(len(uniq), np.int64)
        occ[by_shard] = np.arange(len(uniq)) - group_start[shards[by_shard]]
        locals_ = self._next_local[shards] + occ
        slots = self.n_hot + shards * self.capacity_per_shard + locals_
        self._next_local += counts
        # mirror into the dict (authoritative order/introspection) and ht
        self._slot_of.update(
            zip(uniq.tolist(), slots.tolist()))
        for s, k in zip(shards.tolist(), uniq.tolist()):
            self._keys_by_shard[s].append(k)
        if 2 * len(self._slot_of) >= self._ht_size:
            self._ht_grow(len(self._slot_of))   # re-inserts everything
        else:
            self._ht_insert(uniq, slots)
        # map back to per-position slots: inv indexes uniq_sorted; order
        # maps uniq_sorted -> uniq; invert it
        rank = np.empty(len(uniq), np.int64)
        rank[order] = np.arange(len(uniq))
        return slots[rank[inv]]

    def shard_of(self, keys) -> np.ndarray:
        return self.hashfrag.to_shard_id(keys)

    # -- introspection ----------------------------------------------------
    @property
    def capacity(self) -> int:
        """Tail (sharded) capacity — the row count of sharded arrays."""
        return self.num_shards * self.capacity_per_shard

    @property
    def total_capacity(self) -> int:
        """Hot + tail: the size of the unified slot space."""
        return self.n_hot + self.capacity

    def __len__(self) -> int:
        return self.n_hot + len(self._slot_of)

    def __contains__(self, key: int) -> bool:
        if self.partition is not None and \
                int(self.partition.hot_slot(np.uint64(key))) >= 0:
            return True
        return int(key) in self._slot_of

    def slot(self, key: int) -> int:
        if self.partition is not None:
            hs = int(self.partition.hot_slot(np.uint64(key)))
            if hs >= 0:
                return hs
        return self._slot_of[int(key)]

    def keys(self) -> Iterable[int]:
        if self.partition is None:
            return self._slot_of.keys()
        return chain(self.partition.hot_keys.tolist(), self._slot_of.keys())

    def items(self) -> Iterable:
        """(key, slot) pairs: hot pairs first (frequency-rank order),
        then tail pairs in insertion order."""
        if self.partition is None:
            return self._slot_of.items()
        return chain(self.partition.items(), self._slot_of.items())

    def shard_fill(self) -> np.ndarray:
        """Occupied slots per shard (load-balance introspection)."""
        return self._next_local.copy()

    # -- growth ------------------------------------------------------------
    def grow(self, new_capacity_per_shard: int) -> None:
        """Raise per-shard capacity, remapping every assigned slot to the
        new ``slot = shard * new_cap + local`` layout (locals, and hence
        per-shard insertion order, are preserved).  The device-side table
        must be re-laid-out to match — use ``SparseTable.grow``, which
        calls this."""
        new = int(new_capacity_per_shard)
        if new <= self.capacity_per_shard:
            raise ValueError(
                f"new capacity {new} must exceed {self.capacity_per_shard}")
        old = self.capacity_per_shard
        self.capacity_per_shard = new
        for key, slot in list(self._slot_of.items()):
            shard, local = divmod(slot - self.n_hot, old)
            self._slot_of[key] = self.n_hot + shard * new + local
        self._ht_grow(max(len(self._slot_of), 1))   # slot values changed

    # -- online re-partition ----------------------------------------------
    def repartition(self, new_partition: Optional[HotColdPartition]
                    ) -> RepartitionPlan:
        """Swap the hot/cold frequency split in place, preserving every
        key's identity: keys leaving the head get (or reuse) tail slots,
        keys entering it take their frequency-rank hot slot, and every
        existing tail slot keeps its shard-local row — only the unified
        offset ``n_hot`` moves.  Returns the :class:`RepartitionPlan`
        the device-side table replays (``SparseTable.repartition``).

        Atomic against capacity failure: the demoted keys' shard
        occupancy is validated BEFORE any mutation, so a
        :class:`CapacityError` leaves the index exactly as it was."""
        old = self.partition
        old_hot = (old.hot_keys if old is not None
                   else np.empty(0, np.uint64))
        new_hot = (new_partition.hot_keys if new_partition is not None
                   else np.empty(0, np.uint64))
        old_n_hot, new_n_hot = int(old_hot.size), int(new_hot.size)
        in_new = (np.zeros(old_hot.shape, bool) if new_partition is None
                  else new_partition.is_hot(old_hot))
        demoted = old_hot[~in_new]              # rank order preserved
        demote_src = np.flatnonzero(~in_new)
        # capacity precheck for demoted keys with no tail slot yet —
        # BEFORE any state changes (repartition must be all-or-nothing)
        have = self._ht_find(demoted) if demoted.size else \
            np.empty(0, np.int64)
        missing = demoted[have < 0]
        if missing.size:
            shards = self.hashfrag.to_shard_id(missing).astype(np.int64)
            counts = np.bincount(shards, minlength=self.num_shards)
            over = self._next_local + counts > self.capacity_per_shard
            if over.any():
                s = int(np.flatnonzero(over)[0])
                raise CapacityError(
                    f"repartition needs {int(counts[s])} tail slots on "
                    f"full shard {s} ({self.capacity_per_shard} slots); "
                    "grow the table first")
        # -- mutation starts: shift tail slots to the new hot offset
        delta = new_n_hot - old_n_hot
        if delta:
            for key in self._slot_of:
                self._slot_of[key] += delta
        self.partition = new_partition
        self.n_hot = new_n_hot
        self._ht_grow(max(len(self._slot_of), 1))   # slot values changed
        # demoted keys: reuse existing tail slots, create the rest (the
        # precheck guarantees _create cannot fail here)
        if demoted.size:
            demote_slots = self._ht_find(demoted)
            miss_pos = np.flatnonzero(demote_slots < 0)
            if miss_pos.size:
                demote_slots[miss_pos] = self._create(demoted[miss_pos])
            demote_dst = demote_slots - new_n_hot   # tail-local rows
        else:
            demote_dst = np.empty(0, np.int64)
        # keys staying hot: old rank -> new rank
        stayed_src = np.flatnonzero(in_new)
        stayed_dst = (new_partition.hot_slot(old_hot[in_new])
                      if stayed_src.size else np.empty(0, np.int64))
        # promoted keys with a materialized tail slot: seed from it
        if new_n_hot:
            was_hot = (old.is_hot(new_hot) if old is not None
                       else np.zeros(new_hot.shape, bool))
            promoted = new_hot[~was_hot]
            tail_slots = self._ht_find(promoted)
            seeded = tail_slots >= 0
            hot_from_tail_src = tail_slots[seeded] - new_n_hot
            hot_from_tail_dst = new_partition.hot_slot(promoted[seeded])
        else:
            hot_from_tail_src = np.empty(0, np.int64)
            hot_from_tail_dst = np.empty(0, np.int64)
        return RepartitionPlan(
            old_n_hot, new_n_hot, demote_src, demote_dst,
            stayed_src, stayed_dst, hot_from_tail_src, hot_from_tail_dst)

    # -- checkpoint restore ------------------------------------------------
    def restore(self, keys, slots) -> None:
        """Rebuild the index from saved (key, slot) pairs, preserving the
        ``slot = shard * capacity_per_shard + local`` layout invariant."""
        self._slot_of.clear()
        self._next_local[:] = 0
        for lst in self._keys_by_shard:
            lst.clear()
        per = self.capacity_per_shard
        for key, slot in zip(np.asarray(keys, np.uint64).tolist(),
                             np.asarray(slots, np.int64).tolist()):
            if int(slot) < self.n_hot:
                # hot pair: the partition owns the mapping — validate it
                # round-trips (a checkpoint written under a different
                # frequency split must fail loudly, not scramble rows)
                if self.partition is None or \
                        int(self.partition.hot_slot(np.uint64(key))) \
                        != int(slot):
                    raise ValueError(
                        f"hot slot {slot} for key {key} does not match "
                        "the active HotColdPartition — rebuild the vocab "
                        "(and its partition) before restoring")
                continue
            shard, local = divmod(int(slot) - self.n_hot, per)
            if not (0 <= shard < self.num_shards):
                raise ValueError(f"slot {slot} outside table layout")
            self._slot_of[int(key)] = int(slot)
            self._keys_by_shard[shard].append(int(key))
            self._next_local[shard] = max(self._next_local[shard], local + 1)
        self._ht_grow(max(len(self._slot_of), 1))

    # -- elastic ownership (cross-process repartition, ISSUE 16) -----------
    def shard_rows(self, shard: int) -> Tuple[np.ndarray, np.ndarray]:
        """``(keys, slots)`` of every assigned tail key in ``shard``, in
        insertion order — the export manifest when the shard moves to
        another process: gather the table rows at ``slots``, encode them
        as a PR-10 delta keyed by ``keys``, and the receiver re-creates
        the keys in its own layout (slot values are process-local and
        never cross the wire)."""
        keys = np.asarray(self._keys_by_shard[int(shard)], np.int64)
        slots = np.asarray([self._slot_of[int(k)] for k in keys],
                           np.int64)
        return keys, slots

    def adopt_owner_map(self, owner_of_shard, epoch: int) -> None:
        """Adopt an elastic member table's shard->rank ownership
        (cluster/membership.py).  The map is advisory routing state —
        it does not move any local rows itself (the ElasticWorker /
        transfer layer ships the deltas) — but its epoch is guarded:
        adopting an older epoch than the one already applied means this
        process is acting on a stale world view, which is exactly the
        split-brain the epoch protocol exists to prevent."""
        from swiftmpi_tpu.cluster.membership import StaleEpochError
        owner = tuple(int(r) for r in owner_of_shard)
        if len(owner) != self.num_shards:
            raise ValueError(
                f"owner map covers {len(owner)} shards; this index "
                f"routes {self.num_shards}")
        cur = getattr(self, "owner_epoch", -1)
        if int(epoch) < cur:
            raise StaleEpochError(
                f"adopt_owner_map: epoch {epoch} regressed below "
                f"adopted epoch {cur}")
        # epoch-guard: regression raises StaleEpochError above — the
        # ownership state below only ever moves forward in epoch
        self.shard_owner = owner
        self.owner_epoch = int(epoch)

    def owner_moves(self, new_owner, rank: int
                    ) -> Tuple[Dict[int, List[int]], List[int]]:
        """Diff the adopted owner map against ``new_owner`` from
        ``rank``'s seat: returns ``(outbound, inbound)`` where
        ``outbound`` maps destination rank -> the local shards to
        export there (each with :meth:`shard_rows`) and ``inbound`` is
        the shards arriving.  Raises if no map was adopted yet."""
        old = getattr(self, "shard_owner", None)
        if old is None:
            raise ValueError("owner_moves: no owner map adopted yet")
        new = tuple(int(r) for r in new_owner)
        if len(new) != len(old):
            raise ValueError(
                f"owner map length changed: {len(old)} -> {len(new)}")
        outbound: Dict[int, List[int]] = {}
        inbound: List[int] = []
        for s, (o, n) in enumerate(zip(old, new)):
            if o == n:
                continue
            if o == rank:
                outbound.setdefault(n, []).append(s)
            elif n == rank:
                inbound.append(s)
        return outbound, inbound
