"""Host-side key → dense-slot index for the sharded parameter table.

The reference stores parameters in a ``dense_hash_map<key, value>`` per
server shard (`/root/reference/src/parameter/sparsetable.h:17-149`) and
creates rows lazily on first pull (accessmethod.h:63-70).  XLA wants static
shapes and integer indexing, so the TPU design splits that hash map in two:

* this **KeyIndex** (host side): an open vocabulary mapping arbitrary uint64
  keys to dense slots, assigned lazily on first touch — the moral equivalent
  of ``dense_hash_map`` insertion.  Routing is shard-aware: a key's shard is
  decided by the same murmur-based HashFrag rule as the reference
  (hashfrag.h:51-55), and its slot lands in that shard's contiguous slot
  range, so row ``slot`` of the device-side table lives on the device that
  "owns" the key.
* the device-side **SparseTable** (sparse_table.py): dense ``(capacity, d)``
  arrays indexed by slot, row-sharded over the mesh.

Slot layout: ``slot = shard_id * capacity_per_shard + local_slot``.  With
``num_shards`` equal to the mesh's table-axis size, shard *i*'s range maps
exactly onto device *i*'s row slice.
"""

from __future__ import annotations

from typing import Dict, Iterable, List, Optional

import numpy as np

from swiftmpi_tpu.cluster.hashfrag import HashFrag


class CapacityError(RuntimeError):
    """A shard ran out of slots; raise rather than silently evict."""


class KeyIndex:
    def __init__(self, num_shards: int, capacity_per_shard: int,
                 hashfrag: Optional[HashFrag] = None):
        self.num_shards = int(num_shards)
        self.capacity_per_shard = int(capacity_per_shard)
        self.hashfrag = hashfrag or HashFrag(num_shards)
        if self.hashfrag.num_shards != self.num_shards:
            raise ValueError("hashfrag shard count mismatch")
        self._slot_of: Dict[int, int] = {}
        self._next_local = np.zeros(self.num_shards, dtype=np.int64)
        self._keys_by_shard: List[List[int]] = [
            [] for _ in range(self.num_shards)]

    # -- core -------------------------------------------------------------
    def lookup(self, keys, create: bool = True) -> np.ndarray:
        """Map keys → slots; unknown keys get fresh slots in their owning
        shard when ``create`` (lazy init, reference accessmethod.h:63-70),
        else -1.
        """
        keys = np.asarray(keys, dtype=np.uint64)
        out = np.empty(keys.shape, dtype=np.int32)
        flat = keys.ravel()
        out_flat = out.ravel()
        misses: List[int] = []
        miss_pos: List[int] = []
        for i, k in enumerate(flat.tolist()):
            slot = self._slot_of.get(k)
            if slot is None:
                misses.append(k)
                miss_pos.append(i)
                out_flat[i] = -1
            else:
                out_flat[i] = slot
        if misses and create:
            # de-duplicate while keeping first-touch order
            uniq = list(dict.fromkeys(misses))
            shards = self.hashfrag.to_shard_id(
                np.asarray(uniq, dtype=np.uint64))
            for k, s in zip(uniq, shards.tolist()):
                local = int(self._next_local[s])
                if local >= self.capacity_per_shard:
                    raise CapacityError(
                        f"shard {s} full ({self.capacity_per_shard} slots); "
                        f"raise capacity_per_shard")
                self._next_local[s] = local + 1
                self._slot_of[k] = s * self.capacity_per_shard + local
                self._keys_by_shard[s].append(k)
            for i in miss_pos:
                out_flat[i] = self._slot_of[int(flat[i])]
        return out

    def shard_of(self, keys) -> np.ndarray:
        return self.hashfrag.to_shard_id(keys)

    # -- introspection ----------------------------------------------------
    @property
    def capacity(self) -> int:
        return self.num_shards * self.capacity_per_shard

    def __len__(self) -> int:
        return len(self._slot_of)

    def __contains__(self, key: int) -> bool:
        return int(key) in self._slot_of

    def slot(self, key: int) -> int:
        return self._slot_of[int(key)]

    def keys(self) -> Iterable[int]:
        return self._slot_of.keys()

    def items(self) -> Iterable:
        """(key, slot) pairs in insertion order per shard."""
        return self._slot_of.items()

    def shard_fill(self) -> np.ndarray:
        """Occupied slots per shard (load-balance introspection)."""
        return self._next_local.copy()

    # -- growth ------------------------------------------------------------
    def grow(self, new_capacity_per_shard: int) -> None:
        """Raise per-shard capacity, remapping every assigned slot to the
        new ``slot = shard * new_cap + local`` layout (locals, and hence
        per-shard insertion order, are preserved).  The device-side table
        must be re-laid-out to match — use ``SparseTable.grow``, which
        calls this."""
        new = int(new_capacity_per_shard)
        if new <= self.capacity_per_shard:
            raise ValueError(
                f"new capacity {new} must exceed {self.capacity_per_shard}")
        old = self.capacity_per_shard
        self.capacity_per_shard = new
        for key, slot in list(self._slot_of.items()):
            shard, local = divmod(slot, old)
            self._slot_of[key] = shard * new + local

    # -- checkpoint restore ------------------------------------------------
    def restore(self, keys, slots) -> None:
        """Rebuild the index from saved (key, slot) pairs, preserving the
        ``slot = shard * capacity_per_shard + local`` layout invariant."""
        self._slot_of.clear()
        self._next_local[:] = 0
        for lst in self._keys_by_shard:
            lst.clear()
        per = self.capacity_per_shard
        for key, slot in zip(np.asarray(keys, np.uint64).tolist(),
                             np.asarray(slots, np.int64).tolist()):
            shard, local = divmod(int(slot), per)
            if not (0 <= shard < self.num_shards):
                raise ValueError(f"slot {slot} outside table layout")
            self._slot_of[int(key)] = int(slot)
            self._keys_by_shard[shard].append(int(key))
            self._next_local[shard] = max(self._next_local[shard], local + 1)
