"""Row-sharded dense parameter table in HBM.

TPU-native equivalent of the reference server store
(`/root/reference/src/parameter/sparsetable.h:17-149`): instead of
``shard_num`` dense_hash_maps behind RWLocks in a server process, the table
is a pytree of dense ``(capacity, dim)`` arrays living sharded across device
HBM, indexed by the dense slots a host-side KeyIndex assigns.  The
reference's two-level routing (key → server via hashfrag, key → shard via
murmur % shard_num) collapses into the KeyIndex slot layout: shard *i* owns
slot range ``[i*cap, (i+1)*cap)``, which is exactly device *i*'s row slice
under a ``PartitionSpec(axis)`` sharding.

Lazy row init (accessmethod.h:63-70: create + ``init_param`` on first pull)
becomes eager whole-capacity initialization with the same per-row
distribution: untouched rows are never observed, so eager-random ≡
lazy-random in all observable behavior, and the device never round-trips to
the host to materialize a row.

The table *state* is a plain ``{field: jax.Array}`` dict — a pytree that
training steps close over, donate, and return updated; the ``SparseTable``
object is the host-side handle (spec, mesh placement, key index).
"""

from __future__ import annotations

from typing import Dict, Optional

import jax
import jax.numpy as jnp
import numpy as np
from jax.sharding import Mesh, NamedSharding, PartitionSpec

from swiftmpi_tpu.cluster.mesh import MODEL_AXIS
from swiftmpi_tpu.parameter.access import AccessMethod
from swiftmpi_tpu.parameter.key_index import KeyIndex

TableState = Dict[str, jax.Array]


class SparseTable:
    def __init__(self, access: AccessMethod, key_index: KeyIndex,
                 mesh: Optional[Mesh] = None, axis: str = MODEL_AXIS,
                 seed: int = 0):
        self.access = access
        self.key_index = key_index
        self.mesh = mesh
        self.axis = axis
        self.seed = int(seed)
        if mesh is not None:
            axis_size = mesh.shape[axis]
            if key_index.num_shards % axis_size:
                raise ValueError(
                    f"num_shards={key_index.num_shards} must be a multiple "
                    f"of mesh axis {axis!r} size {axis_size}")
        self.state: TableState = self._init_state()

    # -- construction -----------------------------------------------------
    def row_sharding(self) -> Optional[NamedSharding]:
        if self.mesh is None:
            return None
        return NamedSharding(self.mesh, PartitionSpec(self.axis))

    def _init_state(self) -> TableState:
        cap = self.key_index.capacity
        fields = self.access.fields

        def init_all(key):
            out = {}
            for name, fs in sorted(fields.items()):
                key, sub = jax.random.split(key)
                out[name] = fs.init(sub, (cap, fs.dim)).astype(fs.dtype)
            return out

        sharding = self.row_sharding()
        if sharding is None:
            return jax.jit(init_all)(jax.random.key(self.seed))
        shardings = {name: sharding for name in fields}
        return jax.jit(init_all, out_shardings=shardings)(
            jax.random.key(self.seed))

    # -- growth ------------------------------------------------------------
    def grow(self, new_capacity_per_shard: Optional[int] = None) -> None:
        """Re-lay-out the table at a larger per-shard capacity (default
        2x), preserving every occupied row (params AND optimizer state)
        and freshly initializing the new slots.

        The reference never needs this — ``dense_hash_map`` grows by
        itself (sparsetable.h) — but dense static-shape HBM arrays don't,
        so growth is an explicit re-shard: old rows scatter into their new
        ``shard * new_cap + local`` positions in one jitted remap (no
        donation — both layouts coexist during the scatter, so budget one
        extra copy of the table).  Mesh sharding is preserved (num_shards
        is unchanged, so per-device shard ranges still line up)."""
        ki = self.key_index
        old_per = ki.capacity_per_shard
        new_per = int(new_capacity_per_shard or 2 * old_per)
        items = list(ki.items())
        old_slots = np.asarray([s for _, s in items], np.int64)
        ki.grow(new_per)                      # remaps key -> new slot
        # same remap the index applied, vectorized: shard and local parts
        # are preserved, only the stride changes
        new_slots = (old_slots // old_per) * new_per + old_slots % old_per

        fields = self.access.fields
        sharding = self.row_sharding()
        new_cap = ki.capacity
        # fresh init stream for the enlarged arrays: a different fold per
        # growth so re-grown slots never repeat earlier row inits
        self.seed += 1

        def remap(old_state, old_slots, new_slots, key):
            out = {}
            for name, fs in sorted(fields.items()):
                key, sub = jax.random.split(key)
                arr = fs.init(sub, (new_cap, fs.dim)).astype(fs.dtype)
                if len(items):
                    arr = arr.at[new_slots].set(
                        old_state[name][old_slots])
                out[name] = arr
            return out

        # no donation: the enlarged outputs can't reuse the smaller input
        # buffers anyway, and both copies must coexist during the scatter
        jitted = jax.jit(
            remap,
            out_shardings=None if sharding is None
            else {name: sharding for name in fields})
        self.state = jitted(self.state, jnp.asarray(old_slots),
                            jnp.asarray(new_slots),
                            jax.random.key(self.seed))

    # -- device-level row access ------------------------------------------
    def gather(self, slots) -> TableState:
        """Rows for ``slots`` across pull-visible fields (device op)."""
        slots = jnp.asarray(slots)
        return {f: jnp.take(self.state[f], slots, axis=0)
                for f in self.access.pull_fields}

    def gather_all_fields(self, slots) -> TableState:
        slots = jnp.asarray(slots)
        return {f: jnp.take(self.state[f], slots, axis=0)
                for f in self.access.fields}

    # -- host-level introspection -----------------------------------------
    @property
    def capacity(self) -> int:
        return self.key_index.capacity

    @property
    def num_rows(self) -> int:
        """Occupied rows (reference SparseTable::size, sparsetable.h:135)."""
        return len(self.key_index)

    def rows_as_numpy(self) -> Dict[str, np.ndarray]:
        from swiftmpi_tpu.cluster.bootstrap import host_array

        return {f: host_array(v) for f, v in self.state.items()}

    def __repr__(self) -> str:  # pragma: no cover
        return (f"SparseTable(fields={list(self.access.fields)}, "
                f"capacity={self.capacity}, rows={self.num_rows}, "
                f"sharded={self.mesh is not None})")
